// Native host runtime for open_simulator_tpu.
//
// The reference's host layer is compiled Go (CGO_ENABLED=0 — SURVEY §2.4):
// its ingestion/accounting hot loops (resource.Quantity parsing in
// pkg/utils/utils.go:642-667, the scheduler cache bookkeeping) run at native
// speed. This module is the equivalent compiled layer for the TPU build's
// host plane, exposed to Python over a C ABI via ctypes:
//
//   osim_parse_quantity_one — Kubernetes resource.Quantity parsing
//     (suffixes n/u/m/k/M/G/T/P/E, Ki..Ei, e/E exponents) into exact
//     canonical int64 units (milli and base, each under ceil and floor
//     rounding), matching utils/quantity.py:parse_quad bit for bit on every
//     value that fits int64. Values it cannot represent exactly return 0 and
//     the caller falls back to the exact-Fraction Python path.
//
//   osim_hash_rows — 128-bit per-row feature hashing for grouped
//     scheduling's identical-pod detection (ops/grouped.py:_row_signature).
//
// Build: `make -C open_simulator_tpu/native` (plain g++, no deps); the
// Python loader also builds on demand and degrades to pure Python when no
// compiler is available.

#include <cstdint>
#include <cstring>

extern "C" {

typedef unsigned __int128 u128;

// Saturating/checked helpers -------------------------------------------------

static inline bool mul_overflow_u128(u128 a, u128 b, u128 *out) {
  if (a != 0 && b > (u128)-1 / a) return true;
  *out = a * b;
  return false;
}

static const u128 INT64_MAX_U = (u128)INT64_MAX;

// Parse one quantity string into milli/base values under both ceil and floor
// rounding (pod requests round up, node allocatable rounds down —
// core/objects.py:_canon_resources). Returns 1 on success, 0 when the string
// is invalid or out of int64 range.
static int parse_one(const char *s, int64_t len, int64_t *milli_ceil,
                     int64_t *milli_floor, int64_t *base_ceil,
                     int64_t *base_floor) {
  const char *p = s;
  const char *end = s + len;
  // strip ASCII whitespace (Python str.strip parity)
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r' ||
                     *p == '\f' || *p == '\v'))
    p++;
  while (end > p && (end[-1] == ' ' || end[-1] == '\t' || end[-1] == '\n' ||
                     end[-1] == '\r' || end[-1] == '\f' || end[-1] == '\v'))
    end--;
  if (p == end) return 0;

  bool neg = false;
  if (*p == '+' || *p == '-') {
    neg = (*p == '-');
    p++;
  }

  // mantissa: digits [. digits]; at least one digit total
  u128 mant = 0;
  int frac_digits = 0;
  bool any_digit = false;
  bool overflow = false;
  while (p < end && *p >= '0' && *p <= '9') {
    any_digit = true;
    if (mant > ((u128)-1 - (*p - '0')) / 10) overflow = true;
    mant = mant * 10 + (u128)(*p - '0');
    p++;
  }
  if (p < end && *p == '.') {
    p++;
    while (p < end && *p >= '0' && *p <= '9') {
      any_digit = true;
      // keep at most 30 fractional digits; beyond that they cannot change
      // the ceil of a milli value for any suffix we accept, but we must
      // still know whether a nonzero tail exists for correct rounding
      if (frac_digits < 30) {
        if (mant > ((u128)-1 - (*p - '0')) / 10) overflow = true;
        mant = mant * 10 + (u128)(*p - '0');
        frac_digits++;
      } else if (*p != '0') {
        // nonzero beyond precision: force round-up by adding 1 ulp later
        overflow = true;  // rare; punt to exact Python path
      }
      p++;
    }
  }
  if (!any_digit || overflow) return 0;

  // suffix or exponent
  u128 mult_num = 1;
  u128 mult_den = 1;
  if (p < end) {
    char c = *p;
    if (c == 'e' || c == 'E') {
      p++;
      bool eneg = false;
      if (p < end && (*p == '+' || *p == '-')) {
        eneg = (*p == '-');
        p++;
      }
      if (p == end) return 0;
      int ev = 0;
      while (p < end && *p >= '0' && *p <= '9') {
        ev = ev * 10 + (*p - '0');
        if (ev > 40) return 0;  // out of int64 range anyway; exact path
        p++;
      }
      if (p != end) return 0;
      for (int i = 0; i < ev; i++) {
        if (eneg) {
          if (mul_overflow_u128(mult_den, 10, &mult_den)) return 0;
        } else if (mul_overflow_u128(mult_num, 10, &mult_num)) {
          return 0;
        }
      }
    } else {
      // binary suffixes Ki..Ei and decimal n u m k M G T P E
      static const u128 KI = 1024;
      u128 bin = 0;
      if (end - p == 2 && p[1] == 'i') {
        switch (p[0]) {
          case 'K': bin = KI; break;
          case 'M': bin = KI * KI; break;
          case 'G': bin = KI * KI * KI; break;
          case 'T': bin = KI * KI * KI * KI; break;
          case 'P': bin = KI * KI * KI * KI * KI; break;
          case 'E': bin = KI * KI * KI * KI * KI * KI; break;
          default: return 0;
        }
        mult_num = bin;
      } else if (end - p == 1) {
        switch (p[0]) {
          case 'n': mult_den = 1000000000ull; break;
          case 'u': mult_den = 1000000ull; break;
          case 'm': mult_den = 1000ull; break;
          case 'k': mult_num = 1000ull; break;
          case 'M': mult_num = 1000000ull; break;
          case 'G': mult_num = 1000000000ull; break;
          case 'T': mult_num = 1000000000000ull; break;
          case 'P': mult_num = 1000000000000000ull; break;
          case 'E': mult_num = 1000000000000000000ull; break;
          default: return 0;
        }
      } else {
        return 0;
      }
    }
  }

  // value = mant * mult_num / (mult_den * 10^frac_digits)
  // 10^frac_digits can exceed u128 for 30 digits? 10^30 < 2^100, ok; combined
  // with mult_den (<=1e9) still < 2^128.
  u128 den = mult_den;
  for (int i = 0; i < frac_digits; i++) {
    if (mul_overflow_u128(den, 10, &den)) return 0;
  }

  u128 num;
  if (mul_overflow_u128(mant, mult_num, &num)) return 0;

  // |value| = num/den. For positive v: ceil = q + (r?1:0), floor = q.
  // For negative v: ceil(-num/den) = -q, floor(-num/den) = -(q + (r?1:0)).
  u128 q = num / den;
  u128 r = num % den;
  u128 up = r ? q + 1 : q;
  if (up > INT64_MAX_U) return 0;
  *base_ceil = neg ? -(int64_t)q : (int64_t)up;
  *base_floor = neg ? -(int64_t)up : (int64_t)q;

  u128 num_m;
  if (mul_overflow_u128(num, 1000, &num_m)) return 0;
  u128 qm = num_m / den;
  u128 rm = num_m % den;
  u128 upm = rm ? qm + 1 : qm;
  if (upm > INT64_MAX_U) return 0;
  *milli_ceil = neg ? -(int64_t)qm : (int64_t)upm;
  *milli_floor = neg ? -(int64_t)upm : (int64_t)qm;
  return 1;
}

// Scalar entry point for the lru-cached single-string path (cheap ctypes
// call: four byref int64 outputs, no array marshalling).
int osim_parse_quantity_one(const char *s, int64_t len, int64_t *milli_ceil,
                            int64_t *milli_floor, int64_t *base_ceil,
                            int64_t *base_floor) {
  return parse_one(s, len, milli_ceil, milli_floor, base_ceil, base_floor);
}

// 128-bit row hashing ---------------------------------------------------------
// splitmix64-based mixing over 8-byte chunks with two independent seeds; used
// only to detect runs of identical pod rows, where a collision between
// ADJACENT differing rows would merge two groups. Two independent 64-bit
// streams make that probability negligible (~2^-128 per pair).

static inline uint64_t mix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ull;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
  return x ^ (x >> 31);
}

void osim_hash_rows(const uint8_t *data, int64_t n_rows, int64_t row_bytes,
                    uint64_t *out /* [n_rows*2] */) {
  for (int64_t i = 0; i < n_rows; i++) {
    const uint8_t *row = data + i * row_bytes;
    uint64_t h1 = 0x243f6a8885a308d3ull;  // pi digits: arbitrary fixed seeds
    uint64_t h2 = 0x13198a2e03707344ull;
    int64_t j = 0;
    for (; j + 8 <= row_bytes; j += 8) {
      uint64_t chunk;
      memcpy(&chunk, row + j, 8);
      h1 = mix64(h1 ^ chunk);
      h2 = mix64(h2 + chunk * 0x9e3779b97f4a7c15ull);
    }
    if (j < row_bytes) {
      uint64_t chunk = 0;
      memcpy(&chunk, row + j, row_bytes - j);
      h1 = mix64(h1 ^ chunk);
      h2 = mix64(h2 + chunk * 0x9e3779b97f4a7c15ull);
    }
    out[i * 2] = mix64(h1 ^ (uint64_t)row_bytes);
    out[i * 2 + 1] = mix64(h2 ^ (uint64_t)row_bytes);
  }
}

}  // extern "C"
