"""ctypes loader for the native host runtime (osim_native.cpp).

Degrades gracefully: if the shared library is missing it is compiled on
demand with g++ (the toolchain baked into the image); if that fails, every
entry point reports unavailable and callers keep their pure-Python paths.
The reference's host layer is compiled Go — this is the TPU build's
equivalent compiled layer for host-side hot loops (SURVEY §2.4).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libosim_native.so")
_SRC = os.path.join(_DIR, "osim_native.cpp")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        proc = subprocess.run(
            ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", _SRC, "-o", _SO],
            capture_output=True,
            timeout=120,
        )
        return proc.returncode == 0 and os.path.exists(_SO)
    except (OSError, subprocess.SubprocessError):
        return False


def load() -> Optional[ctypes.CDLL]:
    """The shared library, building it on first use; None when unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        # double-checked locking: the unlocked fast-path read above pairs
        # with these writes, but both writes happen under _lock and a stale
        # fast-path read only costs a harmless second trip into the lock
        _tried = True  # osim: audit-ok[race]
        if not os.path.exists(_SO) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.osim_hash_rows.argtypes = [
            np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
            ctypes.c_int64,
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS"),
        ]
        lib.osim_hash_rows.restype = None
        lib.osim_parse_quantity_one.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.osim_parse_quantity_one.restype = ctypes.c_int
        # publish under _lock; the unlocked reader sees either None (and
        # takes the lock) or the fully-initialized CDLL
        _lib = lib  # osim: audit-ok[race]
        return _lib


def available() -> bool:
    return load() is not None


def parse_quantity_one(s: str) -> Optional[Tuple[int, int, int, int]]:
    """Scalar fast path: (milli_ceil, milli_floor, base_ceil, base_floor), or
    None when unavailable / the value needs the exact Python path."""
    lib = load()
    if lib is None:
        return None
    b = s.encode()
    mc = ctypes.c_int64()
    mf = ctypes.c_int64()
    bc = ctypes.c_int64()
    bf = ctypes.c_int64()
    if not lib.osim_parse_quantity_one(
        b, len(b),
        ctypes.byref(mc), ctypes.byref(mf), ctypes.byref(bc), ctypes.byref(bf),
    ):
        return None
    return mc.value, mf.value, bc.value, bf.value


def hash_rows(data: np.ndarray) -> Optional[np.ndarray]:
    """128-bit hash per row of a 2-D uint8 array -> uint64[n, 2], or None
    when the native library is unavailable."""
    lib = load()
    if lib is None:
        return None
    data = np.ascontiguousarray(data, np.uint8)
    n, row_bytes = data.shape
    out = np.zeros((n, 2), np.uint64)
    lib.osim_hash_rows(data, n, row_bytes, out)
    return out
