"""Minimal text-table rendering (replaces the reference's pterm tables)."""

from __future__ import annotations

from typing import List, Sequence


def render_table(headers: Sequence[str], rows: Sequence[Sequence[str]]) -> str:
    cols = len(headers)
    widths = [len(str(h)) for h in headers]
    str_rows: List[List[str]] = []
    for row in rows:
        cells = [str(c) for c in row] + [""] * (cols - len(row))
        str_rows.append(cells)
        for i in range(cols):
            widths[i] = max(widths[i], len(cells[i]))

    def line(cells: Sequence[str]) -> str:
        return "| " + " | ".join(c.ljust(widths[i]) for i, c in enumerate(cells)) + " |"

    sep = "|" + "|".join("-" * (w + 2) for w in widths) + "|"
    out = [line([str(h) for h in headers]), sep]
    out.extend(line(r) for r in str_rows)
    return "\n".join(out)


# --- terminal coloring (the pterm-colored-tables analog) --------------------

import re as _re

_PCT_RE = _re.compile(r"\b(\d+(?:\.\d+)?)%")


def colorize_report(text: str) -> str:
    """ANSI-color a rendered report for terminal display (parity: the
    reference's pterm color tables; its DisablePTerm-when-writing-to-file
    maps to the caller only colorizing tty output). Utilization percentages
    go green < 50%, yellow < 80%, red >= 80%; section headers are bold."""

    def pct(m: "_re.Match[str]") -> str:
        v = float(m.group(1))
        code = "32" if v < 50.0 else ("33" if v < 80.0 else "31")
        return f"\x1b[{code}m{m.group(0)}\x1b[0m"

    out = []
    for line in text.split("\n"):
        if line.startswith("=== "):
            out.append(f"\x1b[1m{line}\x1b[0m")
        else:
            out.append(_PCT_RE.sub(pct, line))
    return "\n".join(out)
