"""Minimal text-table rendering (replaces the reference's pterm tables)."""

from __future__ import annotations

from typing import List, Sequence


def render_table(headers: Sequence[str], rows: Sequence[Sequence[str]]) -> str:
    cols = len(headers)
    widths = [len(str(h)) for h in headers]
    str_rows: List[List[str]] = []
    for row in rows:
        cells = [str(c) for c in row] + [""] * (cols - len(row))
        str_rows.append(cells)
        for i in range(cols):
            widths[i] = max(widths[i], len(cells[i]))

    def line(cells: Sequence[str]) -> str:
        return "| " + " | ".join(c.ljust(widths[i]) for i, c in enumerate(cells)) + " |"

    sep = "|" + "|".join("-" * (w + 2) for w in widths) + "|"
    out = [line([str(h) for h in headers]), sep]
    out.extend(line(r) for r in str_rows)
    return "\n".join(out)
