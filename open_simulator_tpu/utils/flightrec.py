"""Crash flight recorder: the last N spans/journal keys, dumped on failure.

Postmortems of wedged bench rounds keep asking the same three questions —
what was the process *doing* (spans), what had it *promised* (journal
records), and what had it *counted* (metrics) — right before the watchdog
fired / SIGTERM landed / the chaos plan aborted the apply / an exception
nobody caught unwound the stack. This module keeps an always-on bounded
ring of exactly that evidence and serializes it as ONE correlated JSON
artifact when any of those four triggers fires:

* **spans** — every finished root span tree feeds the ring via
  `tracing._record_flight` (compact summary: name, duration, trace/span
  IDs, meta — not the whole subtree);
* **journal event keys** — `durable/journal.RunJournal.append` notes each
  committed record's (event, seq, run_dir) plus the trace ID active on the
  appending thread, so a dump's journal notes join against the WAL on
  `seq` and against the spans on `trace_id`;
* **metric deltas** — counter/histogram movement since the recorder's
  baseline (lazily snapshotted at first record), so the dump shows what
  changed during the window, not the process's whole life.

Recording is a deque append under a lock — cheap enough to stay on in
every hot path. Dumping never raises: a flight recorder that can crash
the crashing process is worse than none.

Import direction: tracing feeds this module through a lazy import, and
this module reads trace IDs back through a lazy import of tracing — no
top-level cycle. The dump writes through `durable.journal.atomic_write`
(also lazy) so a crash mid-dump can't leave a torn artifact.

Env knobs: OSIM_FLIGHT_EVENTS (ring size, default 512) and
OSIM_FLIGHT_DIR (dump directory; falls back to the dump call's run_dir
argument, then the current directory). See docs/observability.md.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional

DEFAULT_RING = 512

_lock = threading.Lock()
_events: "deque[dict]" = deque(maxlen=DEFAULT_RING)
_baseline: Optional[Dict[str, dict]] = None
_dump_seq = 0
_hooks_installed = False
_prev_sys_hook = None
_prev_threading_hook = None


def _ring_size() -> int:
    raw = os.environ.get("OSIM_FLIGHT_EVENTS", "").strip()
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            pass
    return DEFAULT_RING


def _snapshot_metrics() -> Dict[str, dict]:
    from . import metrics

    return metrics.REGISTRY.snapshot()


def _record(ev: dict) -> None:
    global _baseline, _events
    with _lock:
        if _baseline is None:
            try:
                _baseline = _snapshot_metrics()
            except Exception:  # pragma: no cover - metrics must not kill us
                _baseline = {}
        size = _ring_size()
        if _events.maxlen != size:
            _events = deque(_events, maxlen=size)
        _events.append(ev)


def _current_trace_id() -> Optional[str]:
    try:
        from . import tracing

        return tracing.current_trace_id()
    except Exception:  # pragma: no cover
        return None


def record_span(root_dict: dict) -> None:
    """One finished root span tree (called by tracing on root close).
    Kept compact: identity + timing + meta, not the whole subtree."""
    _record(
        {
            "kind": "span",
            "ts": root_dict.get("start"),
            "name": root_dict.get("name"),
            "trace_id": root_dict.get("trace_id"),
            "span_id": root_dict.get("span_id"),
            "parent_id": root_dict.get("parent_id"),
            "duration_s": root_dict.get("duration_s"),
            "meta": root_dict.get("meta") or {},
        }
    )


def record_journal(event: str, seq: int, run_dir: str) -> None:
    """One durably committed journal record's key (called by
    RunJournal.append, post-fsync). `trace_id` is whatever trace the
    appending thread was inside — the correlation key of the dump."""
    _record(
        {
            "kind": "journal",
            "ts": round(time.time(), 6),
            "event": event,
            "seq": seq,
            "run_dir": run_dir,
            "trace_id": _current_trace_id(),
        }
    )


def note(kind: str, **payload: Any) -> None:
    """Free-form marker (e.g. a chaos rule firing) stamped with the active
    trace ID."""
    ev = {"kind": kind, "ts": round(time.time(), 6),
          "trace_id": _current_trace_id()}
    ev.update(payload)
    _record(ev)


# ---------------------------------------------------------------------------
# Dump
# ---------------------------------------------------------------------------


def _metric_deltas(
    baseline: Dict[str, dict], current: Dict[str, dict]
) -> Dict[str, list]:
    """Per-family sample movement since the baseline; zero-delta samples are
    dropped so the dump shows only what moved during the window."""

    def _sample_key(s: dict) -> tuple:
        return tuple(sorted((s.get("labels") or {}).items()))

    out: Dict[str, list] = {}
    for family, snap in current.items():
        base_samples = {
            _sample_key(s): s
            for s in (baseline.get(family) or {}).get("samples", [])
        }
        moved = []
        for s in snap.get("samples", []):
            base = base_samples.get(_sample_key(s), {})
            delta: Dict[str, Any] = {"labels": s.get("labels") or {}}
            changed = False
            for fieldname in ("value", "count", "sum"):
                if fieldname in s:
                    d = s[fieldname] - base.get(fieldname, 0)
                    if d:
                        delta[fieldname] = d
                        changed = True
            if changed:
                moved.append(delta)
        if moved:
            out[family] = moved
    return out


def dump(
    reason: str,
    *,
    run_dir: Optional[str] = None,
    error: Optional[str] = None,
) -> Optional[str]:
    """Write the flight-recorder artifact; returns its path, or None when
    the write failed (logged, never raised). One artifact per trigger:
    flightrec-<reason>-<pid>-<n>.json under OSIM_FLIGHT_DIR, else
    `run_dir`, else the current directory."""
    global _dump_seq
    try:
        import json

        from ..durable.journal import atomic_write

        with _lock:
            events = list(_events)
            baseline = dict(_baseline or {})
            _dump_seq += 1
            seq = _dump_seq
        try:
            deltas = _metric_deltas(baseline, _snapshot_metrics())
        except Exception:  # pragma: no cover
            deltas = {}
        traces: Dict[str, List[dict]] = {}
        for ev in events:
            traces.setdefault(ev.get("trace_id") or "untraced", []).append(ev)
        artifact = {
            "kind": "flight-recorder",
            "reason": reason,
            "ts": round(time.time(), 6),
            "pid": os.getpid(),
            "error": error,
            "events": events,
            "traces": traces,
            "metrics_delta": deltas,
        }
        out_dir = (
            os.environ.get("OSIM_FLIGHT_DIR", "").strip()
            or run_dir
            or os.getcwd()
        )
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(
            out_dir, f"flightrec-{reason}-{os.getpid()}-{seq}.json"
        )
        atomic_write(path, json.dumps(artifact, sort_keys=True) + "\n")
        from .tracing import log

        log.warning("flight recorder: %s dump written to %s", reason, path)
        return path
    except Exception:  # pragma: no cover - never let the dump crash the crash
        try:
            from .tracing import log

            log.warning("flight recorder dump failed", exc_info=True)
        except Exception:
            pass
        return None


# ---------------------------------------------------------------------------
# Unhandled-crash hooks
# ---------------------------------------------------------------------------


def _sys_hook(exc_type, exc, tb) -> None:
    if not issubclass(exc_type, (KeyboardInterrupt, SystemExit)):
        dump(
            "crash",
            error="".join(
                traceback.format_exception_only(exc_type, exc)
            ).strip(),
        )
    if _prev_sys_hook is not None:
        _prev_sys_hook(exc_type, exc, tb)


def _threading_hook(args) -> None:
    if not issubclass(args.exc_type, (KeyboardInterrupt, SystemExit)):
        dump(
            "crash",
            error="".join(
                traceback.format_exception_only(args.exc_type, args.exc_value)
            ).strip(),
        )
    if _prev_threading_hook is not None:
        _prev_threading_hook(args)


def install_crash_hook() -> None:
    """Chain the flight-recorder dump into sys.excepthook and
    threading.excepthook (idempotent; previous hooks still run)."""
    global _hooks_installed, _prev_sys_hook, _prev_threading_hook
    with _lock:
        if _hooks_installed:
            return
        _hooks_installed = True
    _prev_sys_hook = sys.excepthook
    sys.excepthook = _sys_hook
    _prev_threading_hook = threading.excepthook
    threading.excepthook = _threading_hook


def events() -> List[dict]:
    """Current ring contents, oldest first (tests, /debug introspection)."""
    with _lock:
        return list(_events)


def reset() -> None:
    """Clear the ring, the metrics baseline, and the dump counter (test
    isolation). Crash hooks stay installed."""
    global _baseline, _dump_seq
    with _lock:
        _events.clear()
        _baseline = None
        _dump_seq = 0
