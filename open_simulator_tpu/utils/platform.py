"""Honor JAX_PLATFORMS in environments whose site hooks override it.

This image's sitecustomize registers the single-chip TPU tunnel as the
default platform *after* env processing, so `JAX_PLATFORMS=cpu simon apply
...` would silently still target the TPU — and hang whenever the tunnel is
down. jax.config.update is authoritative over the site hook, so entry points
call ensure_platform() before any jax computation to restore the documented
env-var semantics. (Same pattern as tests/conftest.py and the driver-facing
__graft_entry__.dryrun_multichip.)
"""

from __future__ import annotations

import os


def ensure_platform() -> None:
    """If JAX_PLATFORMS is set in the environment, make it stick."""
    plat = os.environ.get("JAX_PLATFORMS", "").strip()
    if not plat:
        return
    import jax

    if jax.config.jax_platforms != plat:
        jax.config.update("jax_platforms", plat)
