"""Honor JAX_PLATFORMS in environments whose site hooks override it.

This image's sitecustomize registers the single-chip TPU tunnel as the
default platform *after* env processing, so `JAX_PLATFORMS=cpu simon apply
...` would silently still target the TPU — and hang whenever the tunnel is
down. jax.config.update is authoritative over the site hook, so entry points
call ensure_platform() before any jax computation to restore the documented
env-var semantics. (Same pattern as tests/conftest.py and the driver-facing
__graft_entry__.dryrun_multichip.)
"""

from __future__ import annotations

import os


def ensure_platform() -> None:
    """If JAX_PLATFORMS is set in the environment, make it stick."""
    plat = os.environ.get("JAX_PLATFORMS", "").strip()
    if not plat:
        return
    import jax

    if jax.config.jax_platforms != plat:
        jax.config.update("jax_platforms", plat)


def _host_fingerprint() -> str:
    """Short stable id for this host's CPU feature set."""
    import hashlib
    import platform as _plat

    blob = _plat.machine()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    blob += line
                    break
    except OSError:
        pass
    return hashlib.blake2b(blob.encode(), digest_size=6).hexdigest()


def enable_compilation_cache() -> "str | None":
    """Persist XLA executables across processes (parity concern: the
    reference binary re-simulates a tweaked cluster interactively in seconds,
    apply.go:203-216 — repeat `simon apply` runs must not re-pay 30s+ of
    compilation). Directory override: OSIM_COMPILE_CACHE; empty disables.
    Returns the cache directory when enabled (the backend watchdog journals
    it on its warm-cache retry), else None."""
    path = os.environ.get(
        "OSIM_COMPILE_CACHE",
        os.path.join(
            os.path.expanduser("~"), ".cache", "open-simulator-tpu", "xla"
        ),
    )
    if not path:
        return None
    try:
        # Key the cache by a host-CPU fingerprint: XLA:CPU AOT executables
        # record the *compile* machine's feature set, and loading them on a
        # host with fewer features risks SIGILL (observed when a cache
        # written in an earlier round's container leaked into this one).
        # Same machine => same key, so the cross-process win is kept.
        path = os.path.join(path, _host_fingerprint())
        os.makedirs(path, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        # cache every executable, however fast the compile looked
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        return path
    except Exception:
        return None  # cache is an optimization — never fail an entry point over it


#: jax.monitoring event fired once per compile *request* — it wraps
#: compile_or_get_cached, so it fires whether XLA compiled or the persistent
#: cache served the executable (verified against jax 0.4.37 pxla.py).
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

#: fired exactly once per persistent-cache hit, *inside* the window the
#: duration event wraps. cold compiles = duration events - hit events.
PERSISTENT_HIT_EVENT = "/jax/compilation_cache/cache_hits"

_compile_listener_installed = False


def install_compile_listener() -> bool:
    """Mirror XLA backend compiles into the metrics registry.

    Registers jax.monitoring listeners that bump
    ``osim_compile_cache_total{event="backend_compile"}`` every time a
    compile request reaches XLA and ``{event="persistent_hit"}`` when the
    persistent cache served it (the duration event fires in both cases —
    only in-process jit cache hits skip it). One counter therefore tells
    the whole compile-cache story: ``hit``/``miss`` from the engine's own
    jit lookup caches, ``backend_compile``/``persistent_hit`` from XLA; a
    cold-compile regression shows up as backend_compile growing faster
    than persistent_hit. Idempotent; returns False when jax.monitoring is
    unavailable."""
    global _compile_listener_installed
    if _compile_listener_installed:
        return True
    try:
        from jax import monitoring
    except ImportError:
        return False

    from . import metrics

    def _on_event(event: str, duration: float, **kwargs) -> None:
        if event == BACKEND_COMPILE_EVENT:
            metrics.COMPILE_CACHE.inc(event="backend_compile")

    def _on_hit(event: str, **kwargs) -> None:
        if event == PERSISTENT_HIT_EVENT:
            metrics.COMPILE_CACHE.inc(event="persistent_hit")

    monitoring.register_event_duration_secs_listener(_on_event)
    monitoring.register_event_listener(_on_hit)
    # idempotence flag, set once during single-threaded platform init (or
    # inside a watchdog-guarded warmup whose supervisor blocks in
    # done.wait); a lost update would only double-register a counter
    # listener for the same monotonic metric
    _compile_listener_installed = True  # osim: audit-ok[race]
    return True


class CompileCounter:
    """Context manager counting XLA compile requests and persistent-cache
    hits over a code region via local jax.monitoring listeners.

    ``cold_compiles`` is the honest recompile metric: compile requests that
    the persistent cache did NOT absorb — the quantity ``simon warmup`` is
    meant to drive to zero for a warmed workload. Unregistration uses the
    private jax.monitoring helpers when present and degrades to a disarm
    flag otherwise (the listener list has no public remove API)."""

    def __init__(self) -> None:
        self.backend_compiles = 0
        self.persistent_hits = 0
        self._armed = False

    @property
    def cold_compiles(self) -> int:
        return max(0, self.backend_compiles - self.persistent_hits)

    def _on_duration(self, event: str, duration: float, **kwargs) -> None:
        if self._armed and event == BACKEND_COMPILE_EVENT:
            self.backend_compiles += 1

    def _on_event(self, event: str, **kwargs) -> None:
        if self._armed and event == PERSISTENT_HIT_EVENT:
            self.persistent_hits += 1

    def __enter__(self) -> "CompileCounter":
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(self._on_duration)
        monitoring.register_event_listener(self._on_event)
        self._armed = True
        return self

    def __exit__(self, *exc) -> None:
        self._armed = False
        try:
            from jax import monitoring

            monitoring._unregister_event_duration_listener_by_callback(
                self._on_duration
            )
        except Exception:
            pass
        try:
            from jax._src import monitoring as _mon

            _mon._unregister_event_listener_by_callback(self._on_event)
        except Exception:
            pass
