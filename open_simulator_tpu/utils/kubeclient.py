"""Minimal Kubernetes REST client + kubeconfig loader.

Parity target: `CreateClusterResourceFromClient`
(`/root/reference/pkg/simulator/simulator.go:503-601`) — snapshot a REAL
cluster as the simulation's starting state: nodes; non-DaemonSet-owned,
non-terminating Running pods then Pending pods; PDBs, Services,
StorageClasses, PVCs, ConfigMaps, DaemonSets.

The reference rides client-go; this is a dependency-free client over stdlib
urllib/ssl understanding the common kubeconfig auth shapes: cluster CA data,
client cert/key (inline *-data or file paths), and bearer tokens. Anything
beyond that (exec plugins, OIDC refresh) raises KubeClientError with a clear
message — this environment has no live cluster, so all paths are exercised by
tests against a stub API server.
"""

from __future__ import annotations

import base64
import json
import os
import ssl
import tempfile
import urllib.error
import urllib.request
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import yaml

from ..resilience import faults
from ..resilience.policy import RetryExhaustedError, RetryPolicy


class KubeClientError(Exception):
    pass


class TransientKubeError(KubeClientError):
    """An apiserver failure worth retrying: connection/timeout errors, HTTP
    5xx, or 429 Too Many Requests. Subclasses KubeClientError so exhausted
    retries surface through the existing error path."""


@dataclass
class KubeConfig:
    server: str
    ca_file: Optional[str] = None
    cert_file: Optional[str] = None
    key_file: Optional[str] = None
    token: Optional[str] = None
    insecure: bool = False


def _materialize(data_b64: Optional[str], path: Optional[str], suffix: str) -> Optional[str]:
    """Inline base64 *-data wins over the *-file path (kubectl precedence)."""
    if data_b64:
        fd, tmp = tempfile.mkstemp(prefix="osim-kube-", suffix=suffix)
        with os.fdopen(fd, "wb") as fh:
            fh.write(base64.b64decode(data_b64))
        return tmp
    return path


def load_kubeconfig(path: str, context: Optional[str] = None) -> KubeConfig:
    """Resolve the current (or named) context into connection settings."""
    try:
        with open(path) as fh:
            doc = yaml.safe_load(fh) or {}
    except OSError as e:
        raise KubeClientError(f"cannot read kubeconfig {path}: {e}")

    ctx_name = context or doc.get("current-context")
    if not ctx_name:
        raise KubeClientError(f"{path}: no current-context set")
    ctxs = {c.get("name"): c.get("context") or {} for c in doc.get("contexts") or []}
    if ctx_name not in ctxs:
        raise KubeClientError(f"{path}: context {ctx_name!r} not found")
    ctx = ctxs[ctx_name]

    clusters = {c.get("name"): c.get("cluster") or {} for c in doc.get("clusters") or []}
    users = {u.get("name"): u.get("user") or {} for u in doc.get("users") or []}
    cluster = clusters.get(ctx.get("cluster"))
    if cluster is None:
        raise KubeClientError(f"{path}: cluster {ctx.get('cluster')!r} not found")
    user = users.get(ctx.get("user"), {})

    server = cluster.get("server")
    if not server:
        raise KubeClientError(f"{path}: cluster has no server URL")

    token = user.get("token")
    if not token and user.get("exec"):
        raise KubeClientError(
            f"{path}: exec credential plugins are not supported by the "
            "built-in client; provide a token or client certificates"
        )
    return KubeConfig(
        server=server.rstrip("/"),
        ca_file=_materialize(
            cluster.get("certificate-authority-data"),
            cluster.get("certificate-authority"),
            ".crt",
        ),
        cert_file=_materialize(
            user.get("client-certificate-data"), user.get("client-certificate"), ".crt"
        ),
        key_file=_materialize(
            user.get("client-key-data"), user.get("client-key"), ".key"
        ),
        token=token,
        insecure=bool(cluster.get("insecure-skip-tls-verify")),
    )


class KubeClient:
    """GET-only API client: list_* helpers returning decoded items."""

    def __init__(
        self,
        cfg: KubeConfig,
        timeout: float = 30.0,
        policy: Optional[RetryPolicy] = None,
    ) -> None:
        self.cfg = cfg
        self.timeout = timeout
        # transient apiserver errors retry under an overall deadline so a
        # snapshot against a flapping apiserver degrades gracefully instead
        # of failing on the first blip (OSIM_RETRY_* env knobs apply)
        self.policy = (
            policy
            if policy is not None
            else RetryPolicy.from_env(deadline_s=60.0)
        )
        if cfg.server.startswith("https"):
            if cfg.insecure:
                ctx = ssl._create_unverified_context()
            else:
                ctx = ssl.create_default_context(cafile=cfg.ca_file)
            if cfg.cert_file:
                ctx.load_cert_chain(cfg.cert_file, cfg.key_file)
            self._ssl = ctx
        else:
            self._ssl = None

    @staticmethod
    def from_kubeconfig(
        path: str, context: Optional[str] = None, master: str = ""
    ) -> "KubeClient":
        """`master` overrides the kubeconfig's server URL (the reference's
        --master flag, cmd/server/options.go:14-17 -> BuildConfigFromFlags)."""
        cfg = load_kubeconfig(path, context)
        if master:
            cfg.server = master.rstrip("/")
        return KubeClient(cfg)

    def _get_once(
        self, api_path: str, timeout: Optional[float]
    ) -> Dict[str, Any]:
        url = f"{self.cfg.server}{api_path}"
        rule = faults.maybe_inject("kubeclient", api_path)
        body: Optional[bytes] = None
        try:
            if rule is not None:
                body = faults.apply_http_fault(rule, url)
            if body is None:
                req = urllib.request.Request(url)
                req.add_header("Accept", "application/json")
                if self.cfg.token:
                    req.add_header("Authorization", f"Bearer {self.cfg.token}")
                eff = self.timeout if timeout is None else min(timeout, self.timeout)
                with urllib.request.urlopen(
                    req, timeout=eff, context=self._ssl
                ) as resp:
                    body = resp.read()
        except urllib.error.HTTPError as e:
            # 5xx and 429 (apiserver overload/flow-control) are transient;
            # 4xx (bad auth, missing resource) will not heal with retries
            cls = (
                TransientKubeError
                if e.code >= 500 or e.code == 429
                else KubeClientError
            )
            raise cls(f"GET {api_path}: HTTP {e.code} {e.reason}")
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise TransientKubeError(f"GET {api_path}: {e}")
        try:
            return json.loads(body)
        except ValueError as e:
            # truncated/garbled payloads are transport-level and transient
            raise TransientKubeError(f"GET {api_path}: {e}")

    def get(self, api_path: str) -> Dict[str, Any]:
        try:
            return self.policy.execute(
                lambda t: self._get_once(api_path, t),
                retryable=(TransientKubeError,),
                target="kubeclient",
            )
        except RetryExhaustedError as e:
            raise KubeClientError(str(e))

    def list(self, api_path: str, kind: str) -> List[dict]:
        """List a resource; items get apiVersion/kind stamped back on (the
        API server omits them inside List responses)."""
        doc = self.get(api_path)
        items = doc.get("items") or []
        parts = api_path.lstrip("/").split("/")
        # /api/v1/...        -> "v1"
        # /apis/<g>/<v>/...  -> "<g>/<v>"
        api_version = parts[1] if parts[0] == "api" else f"{parts[1]}/{parts[2]}"
        for item in items:
            item.setdefault("apiVersion", api_version)
            item.setdefault("kind", kind)
        return items


def _owned_by_daemonset(pod: dict) -> bool:
    for ref in (pod.get("metadata") or {}).get("ownerReferences") or []:
        if ref.get("kind") == "DaemonSet":
            return True
    return False


def snapshot_cluster(client: KubeClient):
    """CreateClusterResourceFromClient parity: the decoded objects forming the
    simulation's initial state. Returns a ClusterResource."""
    from ..engine.simulator import ClusterResource

    objs: List[dict] = []
    objs.extend(client.list("/api/v1/nodes", "Node"))

    running: List[dict] = []
    pending: List[dict] = []
    for pod in client.list("/api/v1/pods?resourceVersion=0", "Pod"):
        meta = pod.get("metadata") or {}
        if _owned_by_daemonset(pod) or meta.get("deletionTimestamp"):
            continue  # workload pods are regenerated; DS pods re-expand
        phase = (pod.get("status") or {}).get("phase")
        if phase == "Running":
            running.append(pod)
        elif phase == "Pending":
            pending.append(pod)
    objs.extend(running)
    objs.extend(pending)  # pending after running (simulator.go:527-541)

    objs.extend(
        client.list(
            "/apis/policy/v1beta1/poddisruptionbudgets", "PodDisruptionBudget"
        )
    )
    objs.extend(client.list("/api/v1/services", "Service"))
    objs.extend(client.list("/apis/storage.k8s.io/v1/storageclasses", "StorageClass"))
    objs.extend(
        client.list("/api/v1/persistentvolumeclaims", "PersistentVolumeClaim")
    )
    objs.extend(client.list("/api/v1/configmaps", "ConfigMap"))
    objs.extend(client.list("/apis/apps/v1/daemonsets", "DaemonSet"))
    # the reference syncs StatefulSet + ReplicaSet listers too
    # (server.go:114-116): scale-apps resolves a Deployment's pods through
    # its owned ReplicaSets, so the snapshot must carry them
    objs.extend(client.list("/apis/apps/v1/statefulsets", "StatefulSet"))
    objs.extend(client.list("/apis/apps/v1/replicasets", "ReplicaSet"))
    return ClusterResource.from_objects(objs)


def create_cluster_resource_from_kubeconfig(
    path: str, context: Optional[str] = None, master: str = ""
):
    """Snapshot via a kubeconfig, a kubeconfig + master override, or a bare
    master URL alone (BuildConfigFromFlags accepts either — an anonymous
    client with just the apiserver URL is valid against unauthenticated
    endpoints)."""
    if path:
        return snapshot_cluster(KubeClient.from_kubeconfig(path, context, master))
    if master:
        return snapshot_cluster(
            KubeClient(KubeConfig(server=master.rstrip("/")))
        )
    raise KubeClientError("neither kubeconfig nor master URL supplied")
