"""Concurrency annotations shared by runtime code and `simon audit`.

`@guarded_by("lockname")` documents that every call of the decorated
function happens with the named module-level lock (or semaphore) already
held by the caller — the guard exists but is non-local, so the race
detector (analysis/races.py) cannot see it from the function body alone.
The decorator is a no-op at runtime beyond recording the lock name on the
function object; the audit pass trusts the annotation and treats the
function body as dominated by `with <lockname>`.

Keep this module dependency-free: runtime modules (server, resilience)
import it, and they must never import analysis/.
"""

from __future__ import annotations

from typing import Callable, TypeVar

F = TypeVar("F", bound=Callable)

#: Attribute the annotation stores the lock name under; analysis/races.py
#: reads the decorator syntactically, so the attribute only matters for
#: runtime introspection and tests.
GUARDED_BY_ATTR = "__osim_guarded_by__"


def guarded_by(lockname: str) -> Callable[[F], F]:
    """Assert that callers hold the module-level lock `lockname`.

    The name is the lock's module-level binding (e.g. ``"_busy"``), not an
    object reference — the audit pass matches it against the `with` /
    `acquire()` discipline it reconstructs from the AST.
    """
    if not lockname or not isinstance(lockname, str):
        raise ValueError("guarded_by() needs a non-empty lock name")

    def deco(fn: F) -> F:
        setattr(fn, GUARDED_BY_ATTR, lockname)
        return fn

    return deco
