"""Aggregated scheduler metrics: counters, gauges, histograms + Prometheus text.

The reference embeds the real kube-scheduler, whose `metrics` package is what
operators tune against (e2e scheduling duration, attempt counts, schedule
results).  This module is the TPU-port equivalent: a small, dependency-free,
thread-safe registry with the kube-scheduler metric names carried over under
the `osim_` prefix.

Parity table (ours -> kube-scheduler):

    osim_e2e_scheduling_duration_seconds  -> scheduler_e2e_scheduling_duration_seconds
    osim_pod_scheduling_attempts_total    -> scheduler_pod_scheduling_attempts
    osim_schedule_result_total{result=}   -> scheduler_schedule_attempts_total{result=}
    osim_filter_failure_total{reason=}    -> (per-plugin UnschedulableAndUnresolvable counts)
    osim_compile_cache_total{event=}      -> (no analogue: XLA jit-probe cache hit/miss)
    osim_encode_duration_seconds          -> (no analogue: cluster/pod -> device-array encode)

Exposure paths:
  * `GET /metrics` on the HTTP server (Prometheus text format 0.0.4);
  * `snapshot()` embedded in bench.py output JSON;
  * every `tracing.span()` observes into a histogram via `observe_span()`.

Hand-rolled on purpose: the image pins jax/numpy/pyyaml only, and the subset
of prometheus_client we need (labeled counter/gauge/histogram + text render)
is ~300 lines.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "REGISTRY",
    "observe_span",
]

_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# kube-scheduler's e2e duration buckets: exponential from 1ms, factor 2,
# 15 buckets (1ms .. ~16s).
DEFAULT_BUCKETS: Tuple[float, ...] = tuple(0.001 * 2 ** i for i in range(15))


def _escape_label_value(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _format_value(value: float) -> str:
    if value == math.inf:
        return "+Inf"
    if value == -math.inf:
        return "-Inf"
    if isinstance(value, float) and value.is_integer() and abs(value) < 2 ** 53:
        return str(int(value))
    return repr(float(value))


def _format_labels(
    labelnames: Sequence[str], labelvalues: Sequence[str], extra: str = ""
) -> str:
    """Render `{a="x",b="y"}` (or "" when there are no labels)."""
    parts = [
        '%s="%s"' % (n, _escape_label_value(v))
        for n, v in zip(labelnames, labelvalues)
    ]
    if extra:
        parts.append(extra)
    if not parts:
        return ""
    return "{" + ",".join(parts) + "}"


class _Metric:
    """Base: one metric family; children keyed by label-value tuples."""

    kind = "untyped"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str] = (),
        lock: Optional[threading.RLock] = None,
    ) -> None:
        if not _METRIC_NAME_RE.match(name):
            raise ValueError(f"invalid metric name: {name!r}")
        for ln in labelnames:
            if not _LABEL_NAME_RE.match(ln) or ln.startswith("__"):
                raise ValueError(f"invalid label name: {ln!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lock if lock is not None else threading.RLock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not self.labelnames:
            # Label-less metrics expose a sample immediately (a counter that
            # has never fired still renders as `name 0`).
            self._child(())

    # -- child management ---------------------------------------------------

    def _new_child(self) -> object:
        raise NotImplementedError

    def _child(self, key: Tuple[str, ...]) -> object:
        child = self._children.get(key)
        if child is None:
            child = self._children[key] = self._new_child()
        return child

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != "
                f"declared {sorted(self.labelnames)}"
            )
        return tuple(str(labels[n]) for n in self.labelnames)

    # -- rendering ----------------------------------------------------------

    def _sample_lines(self) -> Iterable[str]:
        raise NotImplementedError

    def render(self) -> str:
        with self._lock:
            lines = [
                f"# HELP {self.name} {_escape_help(self.help)}",
                f"# TYPE {self.name} {self.kind}",
            ]
            lines.extend(self._sample_lines())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def _new_child(self) -> list:
        return [0.0]

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if amount < 0:
            raise ValueError(f"{self.name}: counters cannot decrease")
        key = self._key(labels)
        with self._lock:
            self._child(key)[0] += amount

    def value(self, **labels: str) -> float:
        key = self._key(labels)
        with self._lock:
            child = self._children.get(key)
            return child[0] if child is not None else 0.0

    def _sample_lines(self) -> Iterable[str]:
        for key in sorted(self._children):
            yield "%s%s %s" % (
                self.name,
                _format_labels(self.labelnames, key),
                _format_value(self._children[key][0]),
            )

    def snapshot(self) -> dict:
        with self._lock:
            samples = [
                {"labels": dict(zip(self.labelnames, key)), "value": val[0]}
                for key, val in sorted(self._children.items())
            ]
        return {"type": self.kind, "help": self.help, "samples": samples}


class Gauge(Counter):
    kind = "gauge"

    def set(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._child(key)[0] = float(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._child(key)[0] += amount

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)


class _HistChild:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int) -> None:
        self.counts = [0] * n_buckets  # per-bucket (non-cumulative) counts
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
        lock: Optional[threading.RLock] = None,
    ) -> None:
        ordered = sorted(float(b) for b in buckets)
        if not ordered:
            raise ValueError(f"{name}: histogram needs at least one bucket")
        if ordered[-1] != math.inf:
            ordered.append(math.inf)
        self.buckets = tuple(ordered)
        super().__init__(name, help, labelnames, lock=lock)

    def _new_child(self) -> _HistChild:
        return _HistChild(len(self.buckets))

    def observe(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        value = float(value)
        # leftmost bucket whose upper bound contains the value
        idx = len(self.buckets) - 1
        for i, ub in enumerate(self.buckets):
            if value <= ub:
                idx = i
                break
        with self._lock:
            child = self._child(key)
            child.counts[idx] += 1
            child.sum += value
            child.count += 1

    def child_state(self, **labels: str) -> Tuple[List[int], float, int]:
        """(cumulative bucket counts, sum, count) — test/snapshot helper."""
        key = self._key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                return [0] * len(self.buckets), 0.0, 0
            cum, running = [], 0
            for c in child.counts:
                running += c
                cum.append(running)
            return cum, child.sum, child.count

    def _sample_lines(self) -> Iterable[str]:
        for key in sorted(self._children):
            child = self._children[key]
            running = 0
            for ub, c in zip(self.buckets, child.counts):
                running += c
                le = _format_labels(
                    self.labelnames, key, extra='le="%s"' % _format_value(ub)
                )
                yield "%s_bucket%s %d" % (self.name, le, running)
            plain = _format_labels(self.labelnames, key)
            yield "%s_sum%s %s" % (self.name, plain, _format_value(child.sum))
            yield "%s_count%s %d" % (self.name, plain, child.count)

    def snapshot(self) -> dict:
        with self._lock:
            samples = []
            for key, child in sorted(self._children.items()):
                running, cum = 0, []
                for c in child.counts:
                    running += c
                    cum.append(running)
                samples.append(
                    {
                        "labels": dict(zip(self.labelnames, key)),
                        "buckets": {
                            _format_value(ub): n
                            for ub, n in zip(self.buckets, cum)
                        },
                        "sum": child.sum,
                        "count": child.count,
                    }
                )
        return {"type": self.kind, "help": self.help, "samples": samples}


class MetricsRegistry:
    """Get-or-create registry; re-registering a name returns the existing
    metric (and raises if the kind or label set changed)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}

    def _register(self, cls, name, help, labelnames, **kwargs) -> _Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls or existing.labelnames != tuple(
                    labelnames
                ):
                    raise ValueError(
                        f"metric {name!r} already registered with a "
                        f"different kind or label set"
                    )
                return existing
            metric = cls(name, help, labelnames, lock=self._lock, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(
        self, name: str, help: str = "", labelnames: Sequence[str] = ()
    ) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(
        self, name: str, help: str = "", labelnames: Sequence[str] = ()
    ) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def histogram(
        self,
        name: str,
        help: str = "",
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._register(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4. The payload always ends
        with exactly one trailing newline (each family render is
        newline-terminated; an empty registry still yields "\\n") — the
        EOF-safety the text format requires of scrapable exports."""
        with self._lock:
            families = [self._metrics[n] for n in sorted(self._metrics)]
        out = "".join(f.render() for f in families)
        if not out.endswith("\n"):
            out += "\n"
        return out

    def snapshot(self, include_empty: bool = False) -> Dict[str, dict]:
        """JSON-friendly dump (embedded in bench.py output)."""
        with self._lock:
            families = sorted(self._metrics.items())
        out = {}
        for name, metric in families:
            snap = metric.snapshot()
            if not include_empty and not any(
                s.get("value") or s.get("count") for s in snap["samples"]
            ):
                continue
            out[name] = snap
        return out

    def reset(self) -> None:
        """Zero all samples, keep registrations (test isolation helper)."""
        with self._lock:
            for metric in self._metrics.values():
                metric._children.clear()
                if not metric.labelnames:
                    metric._child(())


REGISTRY = MetricsRegistry()

# ---------------------------------------------------------------------------
# Well-known scheduler metrics (kube-scheduler name parity where an analogue
# exists — see the parity table in the module docstring).
# ---------------------------------------------------------------------------

E2E_SCHEDULING = REGISTRY.histogram(
    "osim_e2e_scheduling_duration_seconds",
    "End-to-end simulate() duration (root span), seconds.",
)
ENCODE_DURATION = REGISTRY.histogram(
    "osim_encode_duration_seconds",
    "Pod/cluster -> device-array encode duration, seconds.",
)
SPAN_DURATION = REGISTRY.histogram(
    "osim_span_duration_seconds",
    "Duration of every tracing span, by span name, seconds.",
    labelnames=("span",),
)
SCHEDULING_ATTEMPTS = REGISTRY.counter(
    "osim_pod_scheduling_attempts_total",
    "Pods entering a scheduling pass (preemption retries count again).",
)
SCHEDULE_RESULT = REGISTRY.counter(
    "osim_schedule_result_total",
    "Final scheduling outcomes: scheduled, unscheduled, or preempted "
    "(victims evicted by a committed preemption).",
    labelnames=("result",),
)
COMPILE_CACHE = REGISTRY.counter(
    "osim_compile_cache_total",
    "Device-probe jit cache lookups (miss = new XLA compile).",
    labelnames=("event",),
)
EXPAND_CACHE = REGISTRY.counter(
    "osim_expand_cache_total",
    "Workload expand-cache lookups inside simulate().",
    labelnames=("event",),
)
FILTER_FAILURE = REGISTRY.counter(
    "osim_filter_failure_total",
    "Per-(pod,node) filter rejections for pods that failed to schedule, "
    "by kube failure-reason string.",
    labelnames=("reason",),
)
FAST_PATH = REGISTRY.counter(
    "osim_fast_path_total",
    "schedule_batch_fast strategy selections, by path.",
    labelnames=("path",),
)
PREEMPTION_ATTEMPTS = REGISTRY.counter(
    "osim_preemption_attempts_total",
    "Preemption attempts for unscheduled pods, by outcome.",
    labelnames=("outcome",),
)
EXTENDER_REQUESTS = REGISTRY.counter(
    "osim_extender_requests_total",
    "HTTP scheduler-extender round trips, by verb and outcome.",
    labelnames=("verb", "outcome"),
)
EXTENDER_DURATION = REGISTRY.histogram(
    "osim_extender_duration_seconds",
    "HTTP scheduler-extender round-trip duration, seconds, by verb and "
    "outcome (ok / error / circuit_open) — error and fail-fast paths cost "
    "real wall time too.",
    labelnames=("verb", "outcome"),
)
EXTENDER_INFLIGHT = REGISTRY.gauge(
    "osim_extender_inflight",
    "Per-pod extender HTTP chains currently in flight in the wave engine.",
)
EXTENDER_WAVE_SIZE = REGISTRY.histogram(
    "osim_extender_wave_size",
    "Pods per dispatched extender wave (real lanes, excluding bucket pad).",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256),
)
EXTENDER_WAVE_RESPILL = REGISTRY.counter(
    "osim_extender_wave_respill_total",
    "Wave pods respilled to the next wave after the commit-time feasibility "
    "recheck saw a mask changed by earlier commits.",
)
HTTP_REQUESTS = REGISTRY.counter(
    "osim_http_requests_total",
    "Simulator HTTP server responses, by path and status code.",
    labelnames=("path", "code"),
)
CAPACITY_PROBES = REGISTRY.counter(
    "osim_capacity_probe_total",
    "Capacity-planner simulate() probes (bracket + bisection).",
)
CAPACITY_NODES_ADDED = REGISTRY.gauge(
    "osim_capacity_plan_nodes_added",
    "Nodes added by the most recent capacity plan.",
)
APPLY_RUNS = REGISTRY.counter(
    "osim_apply_total",
    "simon-apply runs, by outcome.",
    labelnames=("outcome",),
)
RETRY_ATTEMPTS = REGISTRY.counter(
    "osim_retry_attempts_total",
    "Retries performed by resilience.RetryPolicy, by call target.",
    labelnames=("target",),
)
CIRCUIT_STATE = REGISTRY.gauge(
    "osim_circuit_state",
    "Per-endpoint circuit-breaker state (0=closed, 1=open, 2=half-open).",
    labelnames=("endpoint",),
)
EXTENDER_SKIPPED = REGISTRY.counter(
    "osim_extender_skipped_total",
    "Ignorable extenders skipped after an error or an open circuit breaker.",
    labelnames=("endpoint",),
)
SNAPSHOT_STALE = REGISTRY.counter(
    "osim_snapshot_stale_total",
    "Server requests served from a stale cluster snapshot after a refresh "
    "failure.",
)
FAULTS_INJECTED = REGISTRY.counter(
    "osim_faults_injected_total",
    "Faults injected by the resilience fault-injection harness.",
    labelnames=("target", "kind"),
)
SANITIZER_VIOLATIONS = REGISTRY.counter(
    "osim_sanitizer_violations_total",
    "checkify violations (NaN/OOB/div) caught by OSIM_SANITIZE=1 runs, by "
    "jit entry point.",
    labelnames=("entry",),
)
WATCHDOG_FIRED = REGISTRY.counter(
    "osim_watchdog_fired_total",
    "Watchdog deadlines that fired on a guarded call (backend acquisition, "
    "compile/execute), by stage.",
    labelnames=("stage",),
)
RUN_RESUMED = REGISTRY.counter(
    "osim_run_resumed_total",
    "Runs resumed from a journal (apply/bench --resume).",
)
PLAN_CHUNKS = REGISTRY.counter(
    "osim_plan_chunks_total",
    "Commit chunks executed by the chunked scenario driver "
    "(OSIM_COMMIT_CHUNK > 0).",
)
CHECKPOINT_BYTES = REGISTRY.counter(
    "osim_checkpoint_bytes",
    "Bytes atomically persisted in mid-plan carry snapshots.",
)
RESUME_CHUNKS_SKIPPED = REGISTRY.counter(
    "osim_resume_chunks_skipped_total",
    "Commit chunks a resumed plan restored from a snapshot instead of "
    "re-executing.",
)
COMMIT_ROUNDS = REGISTRY.histogram(
    "osim_commit_rounds",
    "Rounds to fixpoint per wave in the conflict-parallel wave commit "
    "engine (ops/wave.py). 2 is the floor: one round to decide, one to "
    "confirm; a wave that exhausts its round budget records the budget "
    "it burned before the serial fallback.",
    buckets=(1, 2, 3, 4, 6, 8, 12, 16, 24, 32),
)
WAVE_CONFLICTS = REGISTRY.counter(
    "osim_wave_conflicts_total",
    "Pod decisions revised between wave rounds (choice changes observed "
    "in rounds >= 2): each count is one pod whose tentative placement was "
    "disturbed by an earlier pod's commit and re-decided.",
)
WAVE_FALLBACKS = REGISTRY.counter(
    "osim_wave_fallbacks_total",
    "Waves re-run through the serial chunked kernel after failing to "
    "reach the fixpoint within the round budget (OSIM_WAVE_ROUNDS), by "
    "reason. The fallback is the oracle path: results stay byte-identical.",
    labelnames=("reason",),
)
DEVICE_LOST = REGISTRY.counter(
    "osim_device_lost_total",
    "Device-loss events seen by the chunked commit driver; handled=yes "
    "means the carry was restored from the last good snapshot and the plan "
    "continued.",
    labelnames=("handled",),
)
JOURNAL_EVENTS = REGISTRY.counter(
    "osim_journal_events_total",
    "Records durably committed to run journals, by event type.",
    labelnames=("event",),
)
ADMISSION_QUEUE_DEPTH = REGISTRY.gauge(
    "osim_admission_queue_depth",
    "Requests currently waiting in the server admission queue.",
)
REQUESTS_SHED = REGISTRY.counter(
    "osim_requests_shed_total",
    "Requests shed by admission control with a definite response "
    "(429/503 + Retry-After), by reason.",
    labelnames=("reason",),
)
REQUESTS_DROPPED = REGISTRY.counter(
    "osim_requests_dropped_total",
    "Requests dropped without a simulated or shed response (scheduler "
    "worker death) — any nonzero value is a failure, not degradation.",
)
COALESCED_BATCH = REGISTRY.histogram(
    "osim_coalesced_batch_size",
    "Requests answered by one coalesced simulate pass: mode=fanout counts "
    "identical-body waiters fanned out from one result (per coalesce key), "
    "mode=scenarios counts distinct-scenario bodies merged into one batched "
    "device call.",
    labelnames=("mode",),
    buckets=(1, 2, 4, 8, 16, 32, 64),
)
SCENARIOS_PER_CALL = REGISTRY.histogram(
    "osim_scenarios_per_call",
    "Scenarios evaluated by one batched (vmapped) device call; the sample "
    "count is the number of batched calls issued.",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128),
)
BATCH_SWEEP_DURATION = REGISTRY.histogram(
    "osim_batch_sweep_duration_seconds",
    "Wall-clock duration of one batched multi-scenario sweep call "
    "(capacity ladder/refinement or coalesced serving batch), seconds.",
)
REQUEST_LATENCY = REGISTRY.histogram(
    "osim_server_request_duration_seconds",
    "Admission-to-response latency of POST simulation requests, seconds.",
)
RESIDENT_DRIFT_REPAIRS = REGISTRY.counter(
    "osim_resident_drift_repairs_total",
    "Anti-entropy repairs (full re-encode) of the resident cluster state, by "
    "trigger: digest_mismatch (drift detector), torn_delta (partial apply), "
    "delta_budget (too many deltas since last full encode), disabled "
    "(OSIM_RESIDENT=0 forced degrade).",
    labelnames=("reason",),
)
RESIDENT_DELTAS = REGISTRY.counter(
    "osim_resident_deltas_total",
    "Deltas applied to the resident cluster state without a full re-encode, "
    "by kind (pod_usage = bind/unbind changed a node's free planes, "
    "node_row = a node object changed, node_added).",
    labelnames=("kind",),
)
RESIDENT_FALLBACKS = REGISTRY.counter(
    "osim_resident_fallbacks_total",
    "Requests or syncs that declined the resident fast path and re-encoded "
    "from scratch for a structural reason (node_removed, node_order, "
    "bucket_overflow, shape_growth, not_covering, disabled).",
    labelnames=("reason",),
)
RESIDENT_VERIFICATIONS = REGISTRY.counter(
    "osim_resident_verifications_total",
    "Drift-detector digest cross-checks of the resident state against a full "
    "re-encode, by outcome (ok | mismatch).",
    labelnames=("outcome",),
)
RESIDENT_EPOCH = REGISTRY.gauge(
    "osim_resident_epoch",
    "Current generation of the resident cluster state; bumps on every delta "
    "apply and every repair. Globally monotonic across re-serves.",
)
ADMISSION_FENCE = REGISTRY.counter(
    "osim_admission_fence_total",
    "Generation-fence decisions at admission dequeue: current = ticket ran "
    "against the epoch it was submitted under, rekeyed = the resident epoch "
    "moved between submit and dequeue so the ticket was re-keyed to prevent "
    "cross-generation coalescing.",
    labelnames=("outcome",),
)
LOOP_ITERATION = REGISTRY.histogram(
    "osim_loop_iteration_seconds",
    "Wall-clock duration of one continuous-batching scheduler-loop "
    "iteration (pack assembly + the device call + fan-out); the EWMA of "
    "this feeds Retry-After hints.",
)
PACK_LATENCY = REGISTRY.histogram(
    "osim_pack_latency_seconds",
    "Per-ticket time between admission and the moment its pack was taken "
    "by the scheduler loop — the queueing cost of continuous batching, "
    "excluding the device call itself.",
)
LANE_OCCUPANCY = REGISTRY.histogram(
    "osim_lane_occupancy_ratio",
    "Real scenario lanes over padded lanes (s_real / s_pad) per batched "
    "device call — how full the SCENARIO_BUCKET-padded shape ran.",
    buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0),
)
LOOP_FALLBACKS = REGISTRY.counter(
    "osim_loop_fallbacks_total",
    "Requests served per-request on the handler thread because the "
    "scheduler loop thread was not alive (degradation ladder, "
    "docs/serving.md) — correctness is preserved, batching is lost.",
)
JOBS = REGISTRY.counter(
    "osim_jobs_total",
    "Async jobs (POST /v1/jobs), by terminal outcome "
    "(completed | failed | rejected).",
    labelnames=("outcome",),
)
NODE_BUCKET = REGISTRY.gauge(
    "osim_node_bucket",
    "Node-axis ladder rung (padded node count, ops.encode.node_bucket) of "
    "the most recent encode or capacity-sweep device call — the shape the "
    "jit family compiled for.",
)
ENCODE_STAMPED_ROWS = REGISTRY.counter(
    "osim_encode_stamped_rows_total",
    "Node rows materialized by the template-stamping encode fast path (row "
    "broadcast of a previously-encoded identical node spec plus per-row "
    "name fixups) instead of the per-node Python encode loop.",
)
HBM_BYTES_PER_DEVICE = REGISTRY.gauge(
    "osim_hbm_bytes_per_device",
    "Bytes of cluster-state shards resident on each device after the most "
    "recent sharded placement (parallel.mesh.hbm_bytes_per_device) — under "
    "the 2-D (scenarios, nodes) mesh this stays ~1/node_devices of the "
    "replicated node-table footprint.",
    labelnames=("device",),
)
DEVICE_TIME = REGISTRY.gauge(
    "osim_device_time_seconds",
    "Device-side seconds of one warmed call of each audited jit entry, "
    "from the dispatch-gap analyzer's block_until_ready sandwich "
    "(utils/profiling.py): wall time between dispatch returning and the "
    "result becoming ready.",
    labelnames=("entry",),
)
DISPATCH_GAP = REGISTRY.gauge(
    "osim_dispatch_gap_ratio",
    "Host->device dispatch-gap fraction per audited jit entry: the share "
    "of the entry's wall time spent in host-side dispatch (trace-cache "
    "lookup, argument handling, enqueue) before the device could start — "
    "the device-idle fraction the profiling layer exists to expose.",
    labelnames=("entry",),
)

# Span names that map onto a dedicated kube-parity histogram; everything
# else lands only in osim_span_duration_seconds{span=...}.
_SPAN_HISTOGRAMS: Dict[str, Histogram] = {
    "simulate": E2E_SCHEDULING,
    "encode": ENCODE_DURATION,
}


def observe_span(name: str, seconds: float) -> None:
    """Feed one finished tracing span into the histograms.

    Called from tracing.span()'s finally block for *every* span, so the
    import direction is tracing -> metrics (metrics must never import
    tracing).
    """
    dedicated = _SPAN_HISTOGRAMS.get(name)
    if dedicated is not None:
        dedicated.observe(seconds)
    SPAN_DURATION.observe(seconds, span=name)
