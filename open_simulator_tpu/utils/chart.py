"""Built-in Helm chart rendering.

Parity target: `/root/reference/pkg/chart/chart.go` (ProcessChart →
load → installable check → render values {Chart, Release{Name=chart name,
Namespace=default, Revision=1, Service=Helm}, Values} → engine.Render → strip
NOTES.txt → SortManifests by InstallOrder). The reference links Helm v3 as a
library; this is a from-scratch renderer for the Go-template subset that
Kubernetes application charts actually use:

  - {{ .path.to.value }} / {{ $.rooted.path }} lookups with `-` trim markers
  - pipelines with the common helpers: default, quote, squote, upper, lower,
    trim, int, toString, indent, nindent, toYaml
  - block actions: if / else if / else / end, range (lists and dicts),
    with / end — nested arbitrarily
  - literals: "str", 'str', `str`, ints, floats, true/false/nil

Charts may be directories or .tgz archives; dependency charts under charts/
render recursively with subchart-scoped values (values.<name> overlaid onto
the subchart's own values, plus shared .Values.global). Templates using
constructs outside this subset raise ChartError with the offending action —
the apply layer falls back to a real `helm template` binary when present.
"""

from __future__ import annotations

import os
import re
import tarfile
import tempfile
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import yaml

NOTES_SUFFIX = "NOTES.txt"

INSTALL_ORDER = [
    "Namespace", "NetworkPolicy", "ResourceQuota", "LimitRange",
    "PodSecurityPolicy", "PodDisruptionBudget", "ServiceAccount", "Secret",
    "SecretList", "ConfigMap", "StorageClass", "PersistentVolume",
    "PersistentVolumeClaim", "CustomResourceDefinition", "ClusterRole",
    "ClusterRoleList", "ClusterRoleBinding", "ClusterRoleBindingList",
    "Role", "RoleList", "RoleBinding", "RoleBindingList", "Service",
    "DaemonSet", "Pod", "ReplicationController", "ReplicaSet", "Deployment",
    "HorizontalPodAutoscaler", "StatefulSet", "Job", "CronJob",
    "IngressClass", "Ingress", "APIService",
]
_ORDER_INDEX = {k: i for i, k in enumerate(INSTALL_ORDER)}


class ChartError(Exception):
    pass


@dataclass
class Chart:
    name: str
    metadata: Dict[str, Any]
    values: Dict[str, Any]
    templates: Dict[str, str]            # relative path -> text
    dependencies: List["Chart"] = field(default_factory=list)


def load_chart(path: str) -> Chart:
    """Load a chart from a directory or a .tgz archive. Everything is read
    into memory; extracted archives are removed before returning."""
    if os.path.isfile(path) and (path.endswith(".tgz") or path.endswith(".tar.gz")):
        tmp = tempfile.mkdtemp(prefix="osim-chart-")
        try:
            with tarfile.open(path, "r:gz") as tf:
                # "data" filter rejects traversal, link escapes, devices
                tf.extractall(tmp, filter="data")
            entries = [
                e for e in os.listdir(tmp) if os.path.isdir(os.path.join(tmp, e))
            ]
            if len(entries) != 1:
                raise ChartError(
                    f"chart archive must contain one root dir, got {entries}"
                )
            return _load_chart_dir(os.path.join(tmp, entries[0]))
        except (tarfile.TarError, OSError, UnicodeDecodeError, yaml.YAMLError) as e:
            raise ChartError(f"unreadable chart archive {path}: {e}")
        except TypeError as e:
            # tarfile's filter= kwarg is missing on old Python patch releases
            if "filter" in str(e):
                raise ChartError(f"tarfile filter unsupported: {e}")
            raise
        finally:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
    try:
        return _load_chart_dir(path)
    except (OSError, UnicodeDecodeError, yaml.YAMLError) as e:
        # surface as ChartError so render_chart's helm-binary fallback engages
        raise ChartError(f"unreadable chart {path}: {e}")


def _load_chart_dir(path: str) -> Chart:
    if not os.path.isdir(path):
        raise ChartError(f"chart path not found: {path}")

    meta_path = os.path.join(path, "Chart.yaml")
    if not os.path.exists(meta_path):
        raise ChartError(f"{path}: Chart.yaml not found")
    with open(meta_path) as fh:
        metadata = yaml.safe_load(fh) or {}
    ctype = metadata.get("type", "")
    if ctype not in ("", "application", None):
        # checkIfInstallable parity (chart.go:45-51)
        raise ChartError(f"{ctype} charts are not installable")

    values: Dict[str, Any] = {}
    vals_path = os.path.join(path, "values.yaml")
    if os.path.exists(vals_path):
        with open(vals_path) as fh:
            values = yaml.safe_load(fh) or {}

    templates: Dict[str, str] = {}
    tdir = os.path.join(path, "templates")
    if os.path.isdir(tdir):
        for root, _, files in os.walk(tdir):
            for f in sorted(files):
                full = os.path.join(root, f)
                rel = os.path.relpath(full, path)
                with open(full) as fh:
                    templates[rel] = fh.read()

    deps: List[Chart] = []
    cdir = os.path.join(path, "charts")
    if os.path.isdir(cdir):
        for entry in sorted(os.listdir(cdir)):
            sub = os.path.join(cdir, entry)
            if os.path.isdir(sub) or entry.endswith(".tgz"):
                deps.append(load_chart(sub))

    name = metadata.get("name") or os.path.basename(path.rstrip("/"))
    return Chart(
        name=name, metadata=metadata, values=values, templates=templates,
        dependencies=deps,
    )


# ---------------------------------------------------------------------------
# The template engine (Go text/template subset)
# ---------------------------------------------------------------------------

_ACTION_RE = re.compile(r"\{\{(-?)\s*(.*?)\s*(-?)\}\}", re.DOTALL)


@dataclass
class _Node:
    kind: str                 # text | action | if | range | with
    text: str = ""
    expr: str = ""
    body: list = field(default_factory=list)
    elifs: list = field(default_factory=list)   # [(expr, body), ...]
    else_body: Optional[list] = None


def _tokenize_with_positions(src: str):
    """[(kind, payload)]: kind 'text' or 'action'. Trim markers apply to
    adjacent text the way Go templates do ('{{-' eats preceding whitespace,
    '-}}' eats following whitespace)."""
    tokens: List[Tuple[str, str]] = []
    pos = 0
    pending_trim = False
    for m in _ACTION_RE.finditer(src):
        text = src[pos : m.start()]
        if pending_trim:
            text = text.lstrip(" \t\n\r")
        if m.group(1) == "-":
            text = text.rstrip(" \t\n\r")
        tokens.append(("text", text))
        tokens.append(("action", m.group(2)))
        pending_trim = m.group(3) == "-"
        pos = m.end()
    tail = src[pos:]
    if pending_trim:
        tail = tail.lstrip(" \t\n\r")
    tokens.append(("text", tail))
    return tokens


def _stop_word(payload: str) -> str:
    parts = payload.split(None, 1)
    return parts[0] if parts else ""


def _parse(tokens, i=0, stop=()):
    """Recursive-descent parse into a node list; returns (nodes, next_index,
    stop_payload). A block body that runs out of tokens before its terminator
    raises ChartError; a stray end/else at the top level does too."""
    nodes: List[_Node] = []
    while i < len(tokens):
        kind, payload = tokens[i]
        if kind == "text":
            if payload:
                nodes.append(_Node("text", text=payload))
            i += 1
            continue
        word = _stop_word(payload)
        if word in stop:
            return nodes, i, payload

        def block_body(j, allow_else=True):
            terms = ("end", "else") if allow_else else ("end",)
            body, j2, stop_payload = _parse(tokens, j, stop=terms)
            if not stop_payload:
                raise ChartError("unterminated block action (missing {{ end }})")
            return body, j2, stop_payload

        if word == "if":
            expr = payload[2:].strip()
            body, i, stop_payload = block_body(i + 1)
            node = _Node("if", expr=expr, body=body)
            while _stop_word(stop_payload) == "else":
                rest = stop_payload[4:].strip()
                if rest.startswith("if "):
                    sub_body, i, stop_payload = block_body(i + 1)
                    node.elifs.append((rest[3:].strip(), sub_body))
                else:
                    node.else_body, i, stop_payload = block_body(
                        i + 1, allow_else=False
                    )
                    break
            nodes.append(node)
            i += 1  # past 'end'
        elif word in ("range", "with"):
            expr = payload[len(word):].strip()
            body, i, stop_payload = block_body(i + 1)
            node = _Node(word, expr=expr, body=body)
            if _stop_word(stop_payload) == "else":
                node.else_body, i, _ = block_body(i + 1, allow_else=False)
            nodes.append(node)
            i += 1
        elif word in ("end", "else"):
            raise ChartError(f"unexpected {{{{ {word} }}}} outside a block")
        else:
            nodes.append(_Node("action", expr=payload))
            i += 1
    return nodes, i, ""


_STR_LIT = re.compile(r'^"((?:[^"\\]|\\.)*)"$|' r"^'((?:[^'\\]|\\.)*)'$|^`([^`]*)`$")

_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", '"': '"', "'": "'", "\\": "\\", "0": "\0"}


def _unescape(s: str) -> str:
    """Go string-literal escapes, unicode-safe (a bytes/unicode_escape round
    trip would mangle non-ASCII source characters)."""
    return re.sub(r"\\(.)", lambda m: _ESCAPES.get(m.group(1), m.group(1)), s)


class _Renderer:
    def __init__(self, root: Dict[str, Any]):
        self.root = root

    # -- expression evaluation ---------------------------------------------
    def _lookup(self, path: str, dot: Any) -> Any:
        base = self.root if path.startswith("$") else dot
        trimmed = path.lstrip("$")
        if trimmed in ("", "."):
            return base
        cur = base
        for part in trimmed.strip(".").split("."):
            if isinstance(cur, dict):
                cur = cur.get(part)
            else:
                cur = getattr(cur, part, None)
            if cur is None:
                return None
        return cur

    def _atom(self, tok: str, dot: Any) -> Any:
        m = _STR_LIT.match(tok)
        if m:
            s = next(g for g in m.groups() if g is not None)
            if tok.startswith("`"):
                return s  # raw string: no escapes
            return _unescape(s)
        if tok == "true":
            return True
        if tok == "false":
            return False
        if tok in ("nil", "null"):
            return None
        if re.fullmatch(r"[+-]?\d+", tok):
            return int(tok)
        if re.fullmatch(r"[+-]?\d*\.\d+", tok):
            return float(tok)
        if tok.startswith(".") or tok.startswith("$"):
            return self._lookup(tok, dot)
        raise ChartError(f"unsupported template expression: {tok!r}")

    def _call(self, fn: str, args: List[Any]) -> Any:
        if fn == "default":
            # default DEFAULT VALUE: VALUE if truthy else DEFAULT
            if len(args) != 2:
                raise ChartError("default expects 2 arguments")
            return args[1] if _truthy(args[1]) else args[0]
        if fn == "quote":
            return '"' + _to_string(args[0]).replace('"', '\\"') + '"'
        if fn == "squote":
            return "'" + _to_string(args[0]) + "'"
        if fn == "upper":
            return _to_string(args[0]).upper()
        if fn == "lower":
            return _to_string(args[0]).lower()
        if fn == "trim":
            return _to_string(args[0]).strip()
        if fn == "int":
            try:
                return int(float(args[0]))
            except (TypeError, ValueError):
                return 0
        if fn == "toString":
            return _to_string(args[0])
        if fn == "toYaml":
            return yaml.safe_dump(args[0], default_flow_style=False).rstrip("\n")
        if fn == "indent" or fn == "nindent":
            n, s = int(args[0]), _to_string(args[1])
            pad = " " * n
            body = "\n".join(pad + line for line in s.split("\n"))
            return ("\n" + body) if fn == "nindent" else body
        if fn == "not":
            return not _truthy(args[0])
        if fn in ("eq", "ne", "lt", "le", "gt", "ge"):
            a, b = args[0], args[1]
            try:
                return {
                    "eq": a == b, "ne": a != b, "lt": a < b,
                    "le": a <= b, "gt": a > b, "ge": a >= b,
                }[fn]
            except TypeError:
                return False
        if fn == "and":
            out = args[0]
            for a in args:
                if not _truthy(a):
                    return a
                out = a
            return out
        if fn == "or":
            for a in args:
                if _truthy(a):
                    return a
            return args[-1]
        raise ChartError(f"unsupported template function: {fn!r}")

    def _eval(self, expr: str, dot: Any) -> Any:
        expr = expr.strip()
        if not expr:
            return None
        # pipeline: split on | at top level (no parens support beyond one level)
        stages = _split_top(expr, "|")
        value: Any = None
        first = True
        for stage in stages:
            toks = _split_top(stage.strip(), " ")
            if not toks:
                continue
            head = toks[0]
            if first and (
                head.startswith(".") or head.startswith("$") or _STR_LIT.match(head)
                or head in ("true", "false", "nil", "null")
                or re.fullmatch(r"[+-]?\d+(\.\d+)?", head)
            ):
                if len(toks) != 1:
                    raise ChartError(f"unsupported template expression: {stage!r}")
                value = self._atom(head, dot)
            else:
                args = [self._atom(t, dot) for t in toks[1:]]
                if not first:
                    args.append(value)
                value = self._call(head, args)
            first = False
        return value

    # -- rendering ----------------------------------------------------------
    def render_nodes(self, nodes: List[_Node], dot: Any) -> str:
        out: List[str] = []
        for node in nodes:
            if node.kind == "text":
                out.append(node.text)
            elif node.kind == "action":
                word = node.expr.split(None, 1)[0] if node.expr else ""
                if word in ("define", "template", "include", "block"):
                    raise ChartError(
                        f"unsupported template action: {node.expr!r}"
                    )
                if node.expr.startswith("/*") or word == "":
                    continue  # comment
                val = self._eval(node.expr, dot)
                out.append(_to_string(val))
            elif node.kind == "if":
                if _truthy(self._eval(node.expr, dot)):
                    out.append(self.render_nodes(node.body, dot))
                else:
                    done = False
                    for cond, body in node.elifs:
                        if _truthy(self._eval(cond, dot)):
                            out.append(self.render_nodes(body, dot))
                            done = True
                            break
                    if not done and node.else_body is not None:
                        out.append(self.render_nodes(node.else_body, dot))
            elif node.kind == "range":
                coll = self._eval(node.expr, dot)
                items: List[Any]
                if isinstance(coll, dict):
                    items = [coll[k] for k in coll]
                elif isinstance(coll, (list, tuple)):
                    items = list(coll)
                else:
                    items = []
                if items:
                    for item in items:
                        out.append(self.render_nodes(node.body, item))
                elif node.else_body is not None:
                    out.append(self.render_nodes(node.else_body, dot))
            elif node.kind == "with":
                val = self._eval(node.expr, dot)
                if _truthy(val):
                    out.append(self.render_nodes(node.body, val))
                elif node.else_body is not None:
                    out.append(self.render_nodes(node.else_body, dot))
        return "".join(out)


def _split_top(s: str, sep: str) -> List[str]:
    """Split on sep outside quotes."""
    parts: List[str] = []
    cur: List[str] = []
    quote = ""
    for ch in s:
        if quote:
            cur.append(ch)
            if ch == quote:
                quote = ""
        elif ch in "\"'`":
            quote = ch
            cur.append(ch)
        elif ch == sep:
            if "".join(cur).strip():
                parts.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    if "".join(cur).strip():
        parts.append("".join(cur).strip())
    return parts


def _truthy(v: Any) -> bool:
    """Go template truthiness: false, 0, empty string/collection, nil."""
    if v is None or v is False:
        return False
    if isinstance(v, (int, float)) and v == 0:
        return False
    if isinstance(v, (str, list, dict, tuple)) and len(v) == 0:
        return False
    return True


def _to_string(v: Any) -> str:
    if v is None:
        return ""
    if v is True:
        return "true"
    if v is False:
        return "false"
    return str(v)


def render_template(src: str, context: Dict[str, Any]) -> str:
    tokens = _tokenize_with_positions(src)
    nodes, _, _ = _parse(tokens)
    return _Renderer(context).render_nodes(nodes, context)


# ---------------------------------------------------------------------------
# ProcessChart
# ---------------------------------------------------------------------------

def _coalesce(base: Dict[str, Any], overlay: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(base)
    for k, v in overlay.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _coalesce(out[k], v)
        else:
            out[k] = v
    return out


def _render_chart_files(
    chart: Chart, values: Dict[str, Any], release_name: str
) -> Dict[str, str]:
    ctx = {
        "Chart": chart.metadata,
        "Release": {
            # chart.go:27-61: the app name overwrites Chart.Metadata.Name
            # before rendering, so Release.Name is the APP name (also what
            # `helm template <name> <path>` does); ns/revision hardcoded
            "Name": release_name,
            "Namespace": "default",
            "Revision": 1,
            "Service": "Helm",
        },
        "Values": values,
    }
    files: Dict[str, str] = {}
    for rel, src in chart.templates.items():
        if rel.startswith(os.path.join("templates", "_")):
            continue  # partials unsupported; skipped unless referenced
        files[os.path.join(chart.name, rel)] = render_template(src, ctx)
    # dependencies: subchart values live under values.<subchart name>,
    # sharing .Values.global and the parent's release name
    for dep in chart.dependencies:
        sub_vals = _coalesce(dep.values, values.get(dep.name) or {})
        if "global" in values:
            sub_vals = _coalesce(sub_vals, {"global": values["global"]})
        files.update(_render_chart_files(dep, sub_vals, release_name))
    return files


def process_chart(path: str, release_name: Optional[str] = None) -> List[dict]:
    """Render a chart into decoded manifest objects in Helm install order
    (parity: chart.ProcessChart, pkg/chart/chart.go:27-118). release_name is
    the app name from the Simon config; defaults to the chart's own name."""
    chart = load_chart(path)
    files = _render_chart_files(
        chart, chart.values, release_name or chart.name
    )

    docs: List[Tuple[int, int, dict]] = []  # (order, seq, object)
    seq = 0
    for rel in sorted(files):
        if rel.endswith(NOTES_SUFFIX):
            continue
        content = files[rel]
        for doc in re.split(r"(?m)^---\s*$", content):
            if not doc.strip():
                continue
            try:
                obj = yaml.safe_load(doc)
            except yaml.YAMLError as e:
                raise ChartError(f"{rel}: rendered template is not YAML: {e}")
            if not isinstance(obj, dict) or not obj:
                continue
            kind = obj.get("kind", "")
            order = _ORDER_INDEX.get(kind, len(INSTALL_ORDER))
            docs.append((order, seq, obj))
            seq += 1
    docs.sort(key=lambda t: (t[0], t[1]))
    return [d for _, _, d in docs]
