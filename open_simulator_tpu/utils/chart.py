"""Built-in Helm chart rendering.

Parity target: `/root/reference/pkg/chart/chart.go` (ProcessChart →
load → installable check → render values {Chart, Release{Name=chart name,
Namespace=default, Revision=1, Service=Helm}, Values} → engine.Render → strip
NOTES.txt → SortManifests by InstallOrder). The reference links Helm v3 as a
library (`vendor/helm.sh/helm/v3/pkg/engine`); this is a from-scratch
renderer for the Go-template language as Kubernetes application charts use
it:

  - {{ .path.to.value }} / {{ $.rooted.path }} lookups with `-` trim markers
  - variables: {{ $x := expr }}, {{ $x = expr }}, {{ range $i, $v := ... }}
  - named templates: define / include / template / block — the full
    `helm create` scaffold (`_helpers.tpl`) renders natively
  - pipelines with parenthesized sub-expressions and the sprig/helm helpers
    charts actually call (printf, required, ternary, toJson, b64enc, hasKey,
    contains, trunc, trimSuffix, replace, index, dict/list, tpl, ...)
  - block actions: if / else if / else / end, range (lists, dicts in sorted
    key order, ints), with / end — nested arbitrarily
  - literals: "str", 'str', `str`, ints, floats, true/false/nil

Charts may be directories or .tgz archives; dependency charts under charts/
render recursively with subchart-scoped values (values.<name> overlaid onto
the subchart's own values, plus shared .Values.global). Named templates share
one namespace across the chart tree, parent definitions overriding subchart
ones (Helm override semantics). Nondeterministic helpers (randAlphaNum,
uuidv4, now) are intentionally unsupported — rendering is a pure function.
Templates using constructs outside this subset raise ChartError with the
offending action — the apply layer degrades that app to a render failure.
"""

from __future__ import annotations

import base64
import hashlib
import json
import math
import os
import posixpath
import re
import tarfile
import tempfile
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import yaml

NOTES_SUFFIX = "NOTES.txt"

INSTALL_ORDER = [
    "Namespace", "NetworkPolicy", "ResourceQuota", "LimitRange",
    "PodSecurityPolicy", "PodDisruptionBudget", "ServiceAccount", "Secret",
    "SecretList", "ConfigMap", "StorageClass", "PersistentVolume",
    "PersistentVolumeClaim", "CustomResourceDefinition", "ClusterRole",
    "ClusterRoleList", "ClusterRoleBinding", "ClusterRoleBindingList",
    "Role", "RoleList", "RoleBinding", "RoleBindingList", "Service",
    "DaemonSet", "Pod", "ReplicationController", "ReplicaSet", "Deployment",
    "HorizontalPodAutoscaler", "StatefulSet", "Job", "CronJob",
    "IngressClass", "Ingress", "APIService",
]
_ORDER_INDEX = {k: i for i, k in enumerate(INSTALL_ORDER)}


class ChartError(Exception):
    pass


@dataclass
class Chart:
    name: str
    metadata: Dict[str, Any]
    values: Dict[str, Any]
    templates: Dict[str, str]            # relative path -> text
    dependencies: List["Chart"] = field(default_factory=list)
    # non-template chart files (.Files): relative path -> bytes. Helm
    # excludes templates/, charts/, Chart.yaml and values.yaml.
    files: Dict[str, bytes] = field(default_factory=dict)


def load_chart(path: str) -> Chart:
    """Load a chart from a directory or a .tgz archive. Everything is read
    into memory; extracted archives are removed before returning."""
    if os.path.isfile(path) and (path.endswith(".tgz") or path.endswith(".tar.gz")):
        tmp = tempfile.mkdtemp(prefix="osim-chart-")
        try:
            with tarfile.open(path, "r:gz") as tf:
                # "data" filter rejects traversal, link escapes, devices
                tf.extractall(tmp, filter="data")
            entries = [
                e for e in os.listdir(tmp) if os.path.isdir(os.path.join(tmp, e))
            ]
            if len(entries) != 1:
                raise ChartError(
                    f"chart archive must contain one root dir, got {entries}"
                )
            return _load_chart_dir(os.path.join(tmp, entries[0]))
        except (tarfile.TarError, OSError, UnicodeDecodeError, yaml.YAMLError) as e:
            raise ChartError(f"unreadable chart archive {path}: {e}")
        except TypeError as e:
            # tarfile's filter= kwarg is missing on old Python patch releases
            if "filter" in str(e):
                raise ChartError(f"tarfile filter unsupported: {e}")
            raise
        finally:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
    try:
        return _load_chart_dir(path)
    except (OSError, UnicodeDecodeError, yaml.YAMLError) as e:
        # surface as ChartError so the apply layer records a per-app failure
        raise ChartError(f"unreadable chart {path}: {e}")


def _load_helmignore(path: str):
    """Parse .helmignore (gitignore-like: comments, blank lines, trailing
    '/' for directories, '!' negation; patterns without '/' match basenames
    at any depth). Returns [(regex, negate, dir_only)]."""
    rules = []
    p = os.path.join(path, ".helmignore")
    if not os.path.exists(p):
        return rules
    with open(p) as fh:
        for raw in fh:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            negate = line.startswith("!")
            if negate:
                line = line[1:]
            dir_only = line.endswith("/")
            line = line.rstrip("/")
            if not line:
                continue
            if "/" in line:
                rx = _glob_regex(line.lstrip("/"))
            else:
                # basename pattern: match at any depth
                rx = re.compile(
                    "^(?:.*/)?" + _glob_regex(line).pattern[1:]
                )
            rules.append((rx, negate, dir_only))
    return rules


def _helmignored(rel: str, rules, is_dir: bool) -> bool:
    ignored = False
    for rx, negate, dir_only in rules:
        if dir_only and not is_dir:
            continue
        if rx.match(rel):
            ignored = not negate
    return ignored


def _load_chart_dir(path: str) -> Chart:
    if not os.path.isdir(path):
        raise ChartError(f"chart path not found: {path}")

    meta_path = os.path.join(path, "Chart.yaml")
    if not os.path.exists(meta_path):
        raise ChartError(f"{path}: Chart.yaml not found")
    with open(meta_path) as fh:
        metadata = yaml.safe_load(fh) or {}
    ctype = metadata.get("type", "")
    if ctype not in ("", "application", None):
        # checkIfInstallable parity (chart.go:45-51)
        raise ChartError(f"{ctype} charts are not installable")

    values: Dict[str, Any] = {}
    vals_path = os.path.join(path, "values.yaml")
    if os.path.exists(vals_path):
        with open(vals_path) as fh:
            values = yaml.safe_load(fh) or {}

    templates: Dict[str, str] = {}
    tdir = os.path.join(path, "templates")
    if os.path.isdir(tdir):
        for root, _, files in os.walk(tdir):
            for f in sorted(files):
                full = os.path.join(root, f)
                rel = os.path.relpath(full, path)
                with open(full) as fh:
                    templates[rel] = fh.read()

    deps: List[Chart] = []
    cdir = os.path.join(path, "charts")
    if os.path.isdir(cdir):
        for entry in sorted(os.listdir(cdir)):
            sub = os.path.join(cdir, entry)
            if os.path.isdir(sub) or entry.endswith(".tgz"):
                deps.append(load_chart(sub))

    # .Files: everything but templates/, charts/, the chart metadata, and
    # whatever .helmignore excludes (Helm's loader filters those before the
    # engine ever sees them)
    ignore = _load_helmignore(path)
    files: Dict[str, bytes] = {}
    for root, dirs, names in os.walk(path):
        rel_root = os.path.relpath(root, path)
        if rel_root == ".":
            dirs[:] = [d for d in dirs if d not in ("templates", "charts")]
        dirs[:] = [
            d
            for d in dirs
            if not _helmignored(
                os.path.normpath(os.path.join(rel_root, d)).replace(os.sep, "/"),
                ignore, is_dir=True,
            )
        ]
        for f in sorted(names):
            rel = os.path.normpath(os.path.join(rel_root, f)).replace(os.sep, "/")
            if rel in ("Chart.yaml", "values.yaml", "Chart.lock",
                       ".helmignore"):
                continue
            if _helmignored(rel, ignore, is_dir=False):
                continue
            with open(os.path.join(root, f), "rb") as fh:
                files[rel] = fh.read()

    name = metadata.get("name") or os.path.basename(path.rstrip("/"))
    return Chart(
        name=name, metadata=metadata, values=values, templates=templates,
        dependencies=deps, files=files,
    )


# ---------------------------------------------------------------------------
# The template engine (Go text/template + the sprig subset Helm charts use)
# ---------------------------------------------------------------------------

# Quote-aware action lexer: a `}}` inside a string literal does not end the
# action (Go's lexer behaves the same), so {{ tpl "{{ .x }}" . }} parses.
# Comments are matched as an unparsed unit first — an apostrophe inside
# {{/* don't */}} is not an open quote.
_ACTION_RE = re.compile(
    r"\{\{(-?)\s*("
    r"/\*.*?\*/"
    r"|(?:[^\"'`}]|\"(?:[^\"\\]|\\.)*\"|'(?:[^'\\]|\\.)*'|`[^`]*`|\}(?!\}))*?"
    r")\s*(-?)\}\}",
    re.DOTALL,
)


@dataclass
class _Node:
    kind: str                 # text | action | if | range | with | define | block
    text: str = ""
    expr: str = ""
    body: list = field(default_factory=list)
    elifs: list = field(default_factory=list)   # [(expr, body), ...]
    else_body: Optional[list] = None


def _tokenize_with_positions(src: str):
    """[(kind, payload)]: kind 'text' or 'action'. Trim markers apply to
    adjacent text the way Go templates do ('{{-' eats preceding whitespace,
    '-}}' eats following whitespace)."""
    tokens: List[Tuple[str, str]] = []
    pos = 0
    pending_trim = False
    for m in _ACTION_RE.finditer(src):
        text = src[pos : m.start()]
        if pending_trim:
            text = text.lstrip(" \t\n\r")
        if m.group(1) == "-":
            text = text.rstrip(" \t\n\r")
        tokens.append(("text", text))
        tokens.append(("action", m.group(2)))
        pending_trim = m.group(3) == "-"
        pos = m.end()
    tail = src[pos:]
    if pending_trim:
        tail = tail.lstrip(" \t\n\r")
    tokens.append(("text", tail))
    return tokens


def _stop_word(payload: str) -> str:
    parts = payload.split(None, 1)
    return parts[0] if parts else ""


def _parse(tokens, i=0, stop=()):
    """Recursive-descent parse into a node list; returns (nodes, next_index,
    stop_payload). A block body that runs out of tokens before its terminator
    raises ChartError; a stray end/else at the top level does too."""
    nodes: List[_Node] = []
    while i < len(tokens):
        kind, payload = tokens[i]
        if kind == "text":
            if payload:
                nodes.append(_Node("text", text=payload))
            i += 1
            continue
        word = _stop_word(payload)
        if word in stop:
            return nodes, i, payload

        def block_body(j, allow_else=True):
            terms = ("end", "else") if allow_else else ("end",)
            body, j2, stop_payload = _parse(tokens, j, stop=terms)
            if not stop_payload:
                raise ChartError("unterminated block action (missing {{ end }})")
            return body, j2, stop_payload

        if word == "if":
            expr = payload[2:].strip()
            body, i, stop_payload = block_body(i + 1)
            node = _Node("if", expr=expr, body=body)
            while _stop_word(stop_payload) == "else":
                rest = stop_payload[4:].strip()
                if rest.startswith("if "):
                    sub_body, i, stop_payload = block_body(i + 1)
                    node.elifs.append((rest[3:].strip(), sub_body))
                else:
                    node.else_body, i, stop_payload = block_body(
                        i + 1, allow_else=False
                    )
                    break
            nodes.append(node)
            i += 1  # past 'end'
        elif word in ("range", "with"):
            expr = payload[len(word):].strip()
            body, i, stop_payload = block_body(i + 1)
            node = _Node(word, expr=expr, body=body)
            if _stop_word(stop_payload) == "else":
                node.else_body, i, _ = block_body(i + 1, allow_else=False)
            nodes.append(node)
            i += 1
        elif word in ("define", "block"):
            expr = payload[len(word):].strip()
            body, i, _ = block_body(i + 1, allow_else=False)
            nodes.append(_Node(word, expr=expr, body=body))
            i += 1
        elif word in ("end", "else"):
            raise ChartError(f"unexpected {{{{ {word} }}}} outside a block")
        else:
            nodes.append(_Node("action", expr=payload))
            i += 1
    return nodes, i, ""


_STR_LIT = re.compile(r'^"((?:[^"\\]|\\.)*)"$|' r"^'((?:[^'\\]|\\.)*)'$|^`([^`]*)`$")

_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", '"': '"', "'": "'", "\\": "\\", "0": "\0"}


def _unescape(s: str) -> str:
    """Go string-literal escapes, unicode-safe (a bytes/unicode_escape round
    trip would mangle non-ASCII source characters)."""
    return re.sub(r"\\(.)", lambda m: _ESCAPES.get(m.group(1), m.group(1)), s)


def _literal_string(tok: str) -> str:
    m = _STR_LIT.match(tok.strip())
    if not m:
        raise ChartError(f"expected a string literal, got {tok!r}")
    s = next(g for g in m.groups() if g is not None)
    return s if tok.strip().startswith("`") else _unescape(s)


_EXPR_TOK = re.compile(
    r'"(?:[^"\\]|\\.)*"'      # double-quoted string
    r"|'(?:[^'\\]|\\.)*'"     # single-quoted string
    r"|`[^`]*`"               # raw string
    r"|[()|]"                 # parens, pipe
    r"|[^\s()|]+"             # atom (path, variable, number, ident)
)


def _tokenize_expr(expr: str) -> List[str]:
    return _EXPR_TOK.findall(expr)


class _Scope:
    """Template variable scope chain. `dollar` is Go's `$`: the dot the
    current template execution started with (not the innermost block's)."""

    __slots__ = ("vars", "parent", "dollar")

    def __init__(self, parent: Optional["_Scope"] = None, dollar: Any = None):
        self.vars: Dict[str, Any] = {}
        self.parent = parent
        self.dollar = parent.dollar if (parent is not None and dollar is None) else dollar

    def lookup(self, name: str) -> Any:
        s: Optional[_Scope] = self
        while s is not None:
            if name in s.vars:
                return s.vars[name]
            s = s.parent
        raise ChartError(f"undefined variable ${name}")

    def declare(self, name: str, val: Any) -> None:
        self.vars[name] = val

    def assign(self, name: str, val: Any) -> None:
        s: Optional[_Scope] = self
        while s is not None:
            if name in s.vars:
                s.vars[name] = val
                return
            s = s.parent
        raise ChartError(f"assignment to undeclared variable ${name}")


_NOPIPE = object()       # sentinel: no piped-in value yet
_MAX_TEMPLATE_DEPTH = 60    # nested include/template invocations; far past any
                            # real chart, and low enough that the guard fires
                            # before Python's own interpreter recursion limit


_VAR_DECL_RE = re.compile(r"^\$([A-Za-z_]\w*)\s*(:=|=)\s*(.+)$", re.DOTALL)
_RANGE_DECL_RE = re.compile(
    r"^(\$[A-Za-z_]\w*)\s*(?:,\s*(\$[A-Za-z_]\w*)\s*)?:=\s*(.+)$", re.DOTALL
)


class _Renderer:
    def __init__(self, templates: Optional[Dict[str, List[_Node]]] = None):
        self.templates: Dict[str, List[_Node]] = templates if templates is not None else {}
        self.depth = 0

    # -- value lookup -------------------------------------------------------
    def _navigate(self, cur: Any, parts: List[str]) -> Any:
        for part in parts:
            if not part:
                continue
            if isinstance(cur, dict):
                cur = cur.get(part)
            else:
                # Attribute access is restricted to the template-safe method
                # surface (e.g. APIVersions.Has) — field access on a scalar is
                # an error in Go templates, and an open getattr would leak
                # Python internals ({{ .Values.x.__class__ }}) into manifests.
                safe = getattr(type(cur), "__template_safe__", ())
                if part in safe:
                    cur = getattr(cur, part)
                else:
                    raise ChartError(
                        f"cannot access field {part!r} on "
                        f"{_go_kind(cur)} value"
                    )
            if cur is None:
                return None
        return cur

    def _lookup(self, path: str, dot: Any, scope: _Scope) -> Any:
        if path.startswith("$"):
            rest = path[1:]
            if rest == "" or rest == ".":
                return scope.dollar
            if rest.startswith("."):
                return self._navigate(scope.dollar, rest.strip(".").split("."))
            # $name or $name.a.b
            name, _, tail = rest.partition(".")
            base = scope.lookup(name)
            return self._navigate(base, tail.split(".")) if tail else base
        if path in (".",):
            return dot
        return self._navigate(dot, path.strip(".").split("."))

    def _atom(self, tok: str, dot: Any, scope: _Scope) -> Any:
        m = _STR_LIT.match(tok)
        if m:
            s = next(g for g in m.groups() if g is not None)
            if tok.startswith("`"):
                return s  # raw string: no escapes
            return _unescape(s)
        if tok == "true":
            return True
        if tok == "false":
            return False
        if tok in ("nil", "null"):
            return None
        if re.fullmatch(r"[+-]?\d+", tok):
            return int(tok)
        if re.fullmatch(r"[+-]?\d*\.\d+", tok):
            return float(tok)
        if tok.startswith(".") or tok.startswith("$"):
            return self._lookup(tok, dot, scope)
        raise ChartError(f"unsupported template expression: {tok!r}")

    # -- pipeline evaluation ------------------------------------------------
    def _eval(self, expr: str, dot: Any, scope: _Scope) -> Any:
        toks = _tokenize_expr(expr)
        val, pos = self._pipeline(toks, 0, dot, scope)
        if pos != len(toks):
            raise ChartError(f"trailing tokens in expression: {expr!r}")
        return val

    def _pipeline(self, toks: List[str], i: int, dot: Any, scope: _Scope):
        value: Any = _NOPIPE
        while True:
            value, i = self._command(toks, i, dot, scope, piped=value)
            if i < len(toks) and toks[i] == "|":
                i += 1
                continue
            break
        return value, i

    def _command(self, toks: List[str], i: int, dot: Any, scope: _Scope, piped: Any):
        parts: List[Tuple[str, Any]] = []   # ("tok", str) | ("val", value)
        while i < len(toks) and toks[i] not in ("|", ")"):
            if toks[i] == "(":
                v, i = self._pipeline(toks, i + 1, dot, scope)
                if i >= len(toks) or toks[i] != ")":
                    raise ChartError("unbalanced parentheses in expression")
                i += 1
                parts.append(("val", v))
            else:
                parts.append(("tok", toks[i]))
                i += 1
        if not parts:
            if piped is not _NOPIPE:
                return piped, i
            raise ChartError("empty command in pipeline")

        def resolve(part: Tuple[str, Any]) -> Any:
            return part[1] if part[0] == "val" else self._atom(part[1], dot, scope)

        kind, head = parts[0]
        is_fn = (
            kind == "tok"
            and not head.startswith((".", "$"))
            and not _STR_LIT.match(head)
            and head not in ("true", "false", "nil", "null")
            and not re.fullmatch(r"[+-]?\d+(\.\d+)?", head)
        )
        if is_fn:
            args = [resolve(p) for p in parts[1:]]
            if piped is not _NOPIPE:
                args.append(piped)
            return self._call(head, args, dot, scope), i
        def finish(value: Any) -> Any:
            """Resolve a terminal command value: Go auto-invokes niladic
            methods, and a piped-in value becomes the method's argument
            (`"f.txt" | .Files.Get`). Piping into a non-callable errors."""
            if callable(value):
                try:
                    return value(piped) if piped is not _NOPIPE else value()
                except TypeError as e:
                    raise ChartError(f"template method call failed: {e}")
            if piped is not _NOPIPE:
                raise ChartError(f"cannot pipe into non-function {head!r}")
            return value

        if len(parts) > 1:
            # method invocation: .Capabilities.APIVersions.Has "apps/v1"
            target = resolve(parts[0])
            if callable(target):
                args = [resolve(p) for p in parts[1:]]
                if piped is not _NOPIPE:
                    args.append(piped)
                return target(*args), i
            if (
                parts[0][0] == "val"   # ONLY a parenthesized result — a
                                       # plain `.a .b` stays an error like Go
                and len(parts) == 2
                and parts[1][0] == "tok"
                and parts[1][1].startswith(".")
            ):
                # field/method access on a parenthesized result:
                # (.Files.Glob "x").AsConfig
                return finish(
                    self._navigate(target, parts[1][1].strip(".").split("."))
                ), i
            raise ChartError(
                f"unsupported template expression: {' '.join(str(p[1]) for p in parts)!r}"
            )
        return finish(resolve(parts[0])), i

    # -- named templates ----------------------------------------------------
    def exec_template(self, name: str, dot: Any) -> str:
        nodes = self.templates.get(name)
        if nodes is None:
            raise ChartError(f"template {name!r} not defined")
        if self.depth >= _MAX_TEMPLATE_DEPTH:
            raise ChartError(f"template recursion too deep at {name!r}")
        self.depth += 1
        try:
            # fresh scope: `$` inside a template is the dot it was called with
            return self.render_nodes(nodes, dot, _Scope(dollar=dot))
        finally:
            self.depth -= 1

    # -- function library ---------------------------------------------------
    def _call(self, fn: str, args: List[Any], dot: Any, scope: _Scope) -> Any:
        if fn == "default":
            # default DEFAULT VALUE: VALUE if truthy else DEFAULT
            if len(args) != 2:
                raise ChartError("default expects 2 arguments")
            return args[1] if _truthy(args[1]) else args[0]
        if fn == "quote":
            return " ".join(
                '"' + _to_string(a).replace("\\", "\\\\").replace('"', '\\"') + '"'
                for a in args
            )
        if fn == "squote":
            return " ".join("'" + _to_string(a) + "'" for a in args)
        if fn == "upper":
            return _to_string(args[0]).upper()
        if fn == "lower":
            return _to_string(args[0]).lower()
        if fn == "title":
            return re.sub(
                r"\b\w", lambda m: m.group(0).upper(), _to_string(args[0])
            )
        if fn == "trim":
            return _to_string(args[0]).strip()
        if fn == "trimAll":
            return _to_string(args[1]).strip(_to_string(args[0]))
        if fn == "int" or fn == "int64":
            try:
                return int(float(args[0]))
            except (TypeError, ValueError):
                return 0
        if fn == "float64":
            try:
                return float(args[0])
            except (TypeError, ValueError):
                return 0.0
        if fn == "toString":
            return _to_string(args[0])
        if fn == "toYaml":
            return yaml.safe_dump(args[0], default_flow_style=False).rstrip("\n")
        if fn == "fromYaml":
            try:
                return yaml.safe_load(_to_string(args[0])) or {}
            except yaml.YAMLError:
                return {}
        if fn == "toJson":
            return json.dumps(args[0], separators=(",", ":"))
        if fn == "fromJson":
            try:
                return json.loads(_to_string(args[0]))
            except (ValueError, TypeError):
                return {}
        if fn == "indent" or fn == "nindent":
            n, s = int(args[0]), _to_string(args[1])
            pad = " " * n
            body = "\n".join(pad + line for line in s.split("\n"))
            return ("\n" + body) if fn == "nindent" else body
        if fn == "not":
            return not _truthy(args[0])
        if fn in ("eq", "ne", "lt", "le", "gt", "ge"):
            # Go text/template basicKind semantics (funcs.go): nil and
            # non-basic values (maps, slices) have no comparison kind —
            # "invalid type for comparison"; mismatched kinds (int vs
            # string, int vs float) are "incompatible types for
            # comparison"; ordering additionally rejects bools. None of
            # these silently compare false the way loose Python would.
            a = args[0]
            k1 = _basic_kind(a)
            if k1 is None:
                raise ChartError(f"{fn}: invalid type for comparison")
            if fn == "eq":
                # Go's eq loop short-circuits: it returns true at the first
                # matching pair WITHOUT inspecting later args' kinds
                # (funcs.go eq) — `eq 1 1 "x"` is true, `eq 1 "x" 1` errors
                for b in args[1:]:
                    k2 = _basic_kind(b)
                    if k2 is None:
                        raise ChartError(
                            f"{fn}: invalid type for comparison"
                        )
                    if k1 != k2:
                        raise ChartError(
                            f"{fn}: incompatible types for comparison"
                        )
                    if a == b:
                        return True
                return False
            b = args[1]
            k2 = _basic_kind(b)
            if k2 is None:
                raise ChartError(f"{fn}: invalid type for comparison")
            if k1 != k2:
                raise ChartError(f"{fn}: incompatible types for comparison")
            if fn == "ne":
                return a != b
            if k1 == "bool":
                raise ChartError(f"{fn}: invalid type for comparison")
            return {"lt": a < b, "le": a <= b, "gt": a > b, "ge": a >= b}[fn]
        if fn == "and":
            out = args[0]
            for a in args:
                if not _truthy(a):
                    return a
                out = a
            return out
        if fn == "or":
            for a in args:
                if _truthy(a):
                    return a
            return args[-1]
        # -- sprig string helpers ------------------------------------------
        if fn == "printf":
            return _go_sprintf(_to_string(args[0]), args[1:])
        if fn in ("print", "println"):
            out = []
            prev_str = True
            for a in args:
                is_str = isinstance(a, str)
                if out and not (prev_str or is_str):
                    out.append(" ")   # Go fmt.Sprint: space between non-strings
                out.append(_to_string(a))
                prev_str = is_str
            return "".join(out) + ("\n" if fn == "println" else "")
        if fn == "contains":
            return _to_string(args[0]) in _to_string(args[1])
        if fn == "hasPrefix":
            return _to_string(args[1]).startswith(_to_string(args[0]))
        if fn == "hasSuffix":
            return _to_string(args[1]).endswith(_to_string(args[0]))
        if fn == "trunc":
            n, s = int(args[0]), _to_string(args[1])
            return s[n:] if n < 0 else s[:n]
        if fn == "trimSuffix":
            suf, s = _to_string(args[0]), _to_string(args[1])
            return s[: -len(suf)] if suf and s.endswith(suf) else s
        if fn == "trimPrefix":
            pre, s = _to_string(args[0]), _to_string(args[1])
            return s[len(pre):] if pre and s.startswith(pre) else s
        if fn == "replace":
            old, new, s = _to_string(args[0]), _to_string(args[1]), _to_string(args[2])
            return s.replace(old, new)
        if fn == "repeat":
            return _to_string(args[1]) * int(args[0])
        if fn == "join":
            sep = _to_string(args[0])
            coll = args[1] if isinstance(args[1], (list, tuple)) else [args[1]]
            return sep.join(_to_string(x) for x in coll)
        if fn == "splitList":
            return _to_string(args[1]).split(_to_string(args[0]))
        if fn == "split":
            parts = _to_string(args[1]).split(_to_string(args[0]))
            return {f"_{i}": p for i, p in enumerate(parts)}
        if fn == "base":
            return _go_path_base(_to_string(args[0]))
        if fn == "dir":
            return _go_path_dir(_to_string(args[0]))
        if fn == "ext":
            return _go_path_ext(_to_string(args[0]))
        if fn == "clean":
            return posixpath.normpath(_to_string(args[0])) if args[0] else "."
        if fn == "sha256sum":
            return hashlib.sha256(_to_string(args[0]).encode()).hexdigest()
        if fn == "b64enc":
            return base64.b64encode(_to_string(args[0]).encode()).decode()
        if fn == "b64dec":
            try:
                return base64.b64decode(_to_string(args[0]).encode()).decode()
            except Exception:
                return ""
        if fn == "kebabcase":
            s = re.sub(r"([a-z0-9])([A-Z])", r"\1-\2", _to_string(args[0]))
            return re.sub(r"[\s_]+", "-", s).lower()
        if fn == "snakecase":
            s = re.sub(r"([a-z0-9])([A-Z])", r"\1_\2", _to_string(args[0]))
            return re.sub(r"[\s-]+", "_", s).lower()
        if fn == "camelcase":
            return "".join(
                w[:1].upper() + w[1:]
                for w in re.split(r"[\s_-]+", _to_string(args[0]))
            )
        # -- control / validation ------------------------------------------
        if fn == "required":
            # required "message" VALUE (helm: error out when value is unset)
            if len(args) != 2:
                raise ChartError("required expects 2 arguments")
            if args[1] is None or args[1] == "":
                raise ChartError(f"required value missing: {_to_string(args[0])}")
            return args[1]
        if fn == "fail":
            raise ChartError(f"template fail: {_to_string(args[0])}")
        if fn == "ternary":
            # TRUE_VAL FALSE_VAL | ternary ... or ternary TRUE FALSE TEST
            if len(args) != 3:
                raise ChartError("ternary expects 3 arguments")
            return args[0] if _truthy(args[2]) else args[1]
        if fn == "empty":
            return not _truthy(args[0])
        if fn == "coalesce":
            for a in args:
                if _truthy(a):
                    return a
            return None
        if fn == "kindOf":
            return _go_kind(args[0])
        if fn == "kindIs":
            return _go_kind(args[1]) == _to_string(args[0])
        # -- collections ----------------------------------------------------
        if fn == "list":
            return list(args)
        if fn == "dict":
            if len(args) % 2:
                raise ChartError("dict expects an even number of arguments")
            return {
                _to_string(args[i]): args[i + 1] for i in range(0, len(args), 2)
            }
        if fn == "get":
            d = args[0] if isinstance(args[0], dict) else {}
            return d.get(_to_string(args[1]), "")
        if fn == "set":
            if not isinstance(args[0], dict):
                raise ChartError("set expects a dict")
            args[0][_to_string(args[1])] = args[2]
            return args[0]
        if fn == "unset":
            if isinstance(args[0], dict):
                args[0].pop(_to_string(args[1]), None)
            return args[0]
        if fn == "hasKey":
            return isinstance(args[0], dict) and _to_string(args[1]) in args[0]
        if fn == "keys":
            out: List[str] = []
            for a in args:
                if isinstance(a, dict):
                    out.extend(a.keys())
            return sorted(out)
        if fn == "values":
            out = []
            for a in args:
                if isinstance(a, dict):
                    out.extend(a[k] for k in sorted(a))
            return out
        if fn == "merge":
            # sprig merge MUTATES the destination in place (dest keys win,
            # sources only fill gaps) and returns it — charts rely on the
            # `{{ $_ := merge .Values.a .Values.b }}` idiom observing the
            # merge through .Values.a afterwards
            dest = args[0]
            if not isinstance(dest, dict):
                raise ChartError("merge expects a dict destination")

            def fill(dst: Dict[str, Any], src: Dict[str, Any]) -> None:
                for k, v in src.items():
                    if k not in dst:
                        dst[k] = v
                    elif isinstance(dst[k], dict) and isinstance(v, dict):
                        fill(dst[k], v)

            for a in args[1:]:
                if isinstance(a, dict):
                    fill(dest, a)
            return dest
        if fn == "index":
            cur = args[0]
            for key in args[1:]:
                if isinstance(cur, dict):
                    cur = cur.get(_to_string(key) if not isinstance(key, (int, float, bool)) else key)
                elif isinstance(cur, (list, tuple, str)):
                    try:
                        cur = cur[int(key)]
                    except (IndexError, ValueError, TypeError):
                        return None
                else:
                    return None
                if cur is None:
                    return None
            return cur
        if fn == "first":
            c = args[0]
            return c[0] if isinstance(c, (list, tuple)) and c else None
        if fn == "last":
            c = args[0]
            return c[-1] if isinstance(c, (list, tuple)) and c else None
        if fn == "rest":
            c = args[0]
            return list(c[1:]) if isinstance(c, (list, tuple)) else []
        if fn == "append":
            return (list(args[0]) if isinstance(args[0], (list, tuple)) else []) + [args[1]]
        if fn == "prepend":
            return [args[1]] + (list(args[0]) if isinstance(args[0], (list, tuple)) else [])
        if fn == "has":
            coll = args[1]
            return isinstance(coll, (list, tuple)) and args[0] in coll
        if fn == "len":
            try:
                return len(args[0])
            except TypeError:
                return 0
        if fn == "until":
            return list(range(int(args[0])))
        # -- arithmetic -----------------------------------------------------
        if fn in ("add", "sub", "mul", "div", "mod", "max", "min", "add1"):
            try:
                nums = [int(a) if float(a) == int(float(a)) else float(a) for a in args]
            except (TypeError, ValueError):
                raise ChartError(f"{fn}: non-numeric argument")
            if fn == "add":
                return sum(nums)
            if fn == "add1":
                return nums[0] + 1
            if fn == "sub":
                return nums[0] - nums[1]
            if fn == "mul":
                out3 = 1
                for n in nums:
                    out3 *= n
                return out3
            if fn == "div":
                if all(isinstance(n, int) for n in nums[:2]):
                    # Go int64 division truncates toward zero (-7/2 = -3),
                    # Python's // floors (-4) — correct the sign case
                    q = nums[0] // nums[1]
                    if q < 0 and q * nums[1] != nums[0]:
                        q += 1
                    return q
                return nums[0] / nums[1]
            if fn == "mod":
                if all(isinstance(n, int) for n in nums[:2]):
                    # Go % takes the dividend's sign (-7%2 = -1); derive from
                    # the truncated quotient (exact for big ints, no floats)
                    q = nums[0] // nums[1]
                    if q < 0 and q * nums[1] != nums[0]:
                        q += 1
                    return nums[0] - nums[1] * q
                return math.fmod(nums[0], nums[1])
            if fn == "max":
                return max(nums)
            return min(nums)
        if fn == "floor":
            return float(math.floor(float(args[0])))
        if fn == "ceil":
            return float(math.ceil(float(args[0])))
        if fn == "round":
            places = int(args[1]) if len(args) > 1 else 0
            return round(float(args[0]), places)
        # -- helm-specific --------------------------------------------------
        if fn == "include":
            name = _to_string(args[0])
            data = args[1] if len(args) > 1 else None
            return self.exec_template(name, data)
        if fn == "tpl":
            src = _to_string(args[0])
            ctx = args[1] if len(args) > 1 else dot
            toks = _tokenize_with_positions(src)
            nodes, _, _ = _parse(toks)
            if self.depth >= _MAX_TEMPLATE_DEPTH:
                raise ChartError("tpl recursion too deep")
            # Helm runs tpl against a per-invocation clone of the template
            # set: defines inside the rendered string must not leak into
            # (or override) the chart's own helpers.
            sub = _Renderer(dict(self.templates))
            _collect_defines(nodes, sub.templates)
            sub.depth = self.depth + 1
            return sub.render_nodes(nodes, ctx, _Scope(dollar=ctx))
        if fn == "lookup":
            return {}   # helm: empty when not connected to a cluster
        if fn in ("randAlphaNum", "randAlpha", "randNumeric", "randAscii",
                  "uuidv4", "now", "date", "genPrivateKey", "genCA",
                  "genSelfSignedCert", "genSignedCert", "derivePassword",
                  "htpasswd", "shuffle"):
            raise ChartError(
                f"nondeterministic template function {fn!r} is unsupported "
                "(rendering is a pure function of chart + values)"
            )
        raise ChartError(f"unsupported template function: {fn!r}")

    # -- rendering ----------------------------------------------------------
    def render_nodes(self, nodes: List[_Node], dot: Any, scope: _Scope) -> str:
        out: List[str] = []
        for node in nodes:
            if node.kind == "text":
                out.append(node.text)
            elif node.kind == "define":
                continue   # collected at parse time (_collect_defines)
            elif node.kind == "block":
                toks = _tokenize_expr(node.expr)
                if not toks:
                    raise ChartError("block action missing a template name")
                name = _literal_string(toks[0])
                rest = node.expr[node.expr.index(toks[0]) + len(toks[0]):].strip()
                arg = self._eval(rest, dot, scope) if rest else None
                out.append(self.exec_template(name, arg))
            elif node.kind == "action":
                expr = node.expr
                if expr.startswith("/*") or not expr:
                    continue  # comment
                m = _VAR_DECL_RE.match(expr)
                if m:
                    name, op, rhs = m.group(1), m.group(2), m.group(3)
                    val = self._eval(rhs, dot, scope)
                    if op == ":=":
                        scope.declare(name, val)
                    else:
                        scope.assign(name, val)
                    continue
                word = expr.split(None, 1)[0]
                if word == "template":
                    rest = expr[len("template"):].strip()
                    toks = _tokenize_expr(rest)
                    if not toks:
                        raise ChartError("template action missing a name")
                    name = _literal_string(toks[0])
                    tail = rest[rest.index(toks[0]) + len(toks[0]):].strip()
                    arg = self._eval(tail, dot, scope) if tail else None
                    out.append(self.exec_template(name, arg))
                    continue
                val = self._eval(expr, dot, scope)
                out.append(_to_string(val))
            elif node.kind == "if":
                child = _Scope(parent=scope)
                if _truthy(self._eval_cond(node.expr, dot, child)):
                    out.append(self.render_nodes(node.body, dot, child))
                else:
                    done = False
                    for cond, body in node.elifs:
                        if _truthy(self._eval_cond(cond, dot, child)):
                            out.append(self.render_nodes(body, dot, child))
                            done = True
                            break
                    if not done and node.else_body is not None:
                        out.append(self.render_nodes(node.else_body, dot, child))
            elif node.kind == "range":
                out.append(self._render_range(node, dot, scope))
            elif node.kind == "with":
                expr = node.expr
                var_name = None
                m = _RANGE_DECL_RE.match(expr)
                if m and m.group(2) is None:
                    var_name, expr = m.group(1)[1:], m.group(3)
                val = self._eval(expr, dot, scope)
                if _truthy(val):
                    child = _Scope(parent=scope)
                    if var_name is not None:
                        child.declare(var_name, val)
                    out.append(self.render_nodes(node.body, val, child))
                elif node.else_body is not None:
                    out.append(self.render_nodes(node.else_body, dot, _Scope(parent=scope)))
        return "".join(out)

    def _eval_cond(self, expr: str, dot: Any, scope: _Scope) -> Any:
        """An if/else-if condition may declare a variable visible in the
        block: {{ if $x := .Values.y }} (Go text/template semantics)."""
        m = _RANGE_DECL_RE.match(expr)
        if m and m.group(2) is None:
            val = self._eval(m.group(3), dot, scope)
            scope.declare(m.group(1)[1:], val)
            return val
        return self._eval(expr, dot, scope)

    def _render_range(self, node: _Node, dot: Any, scope: _Scope) -> str:
        expr = node.expr
        v1 = v2 = None
        m = _RANGE_DECL_RE.match(expr)
        if m:
            v1 = m.group(1)[1:]
            v2 = m.group(2)[1:] if m.group(2) else None
            expr = m.group(3)
        coll = self._eval(expr, dot, scope)
        pairs: List[Tuple[Any, Any]]   # (key-or-index, element)
        if isinstance(coll, _Files):
            # range over .Files / .Files.Glob yields (path, content)
            pairs = [
                (k, coll._files[k].decode(errors="replace"))
                for k in sorted(coll._files)
            ]
        elif isinstance(coll, dict):
            # Go templates visit maps in sorted key order
            pairs = [(k, coll[k]) for k in sorted(coll, key=_to_string)]
        elif isinstance(coll, (list, tuple)):
            pairs = list(enumerate(coll))
        elif isinstance(coll, int) and not isinstance(coll, bool):
            pairs = [(i, i) for i in range(coll)]
        else:
            pairs = []
        out: List[str] = []
        if pairs:
            for key, item in pairs:
                child = _Scope(parent=scope)
                if v1 is not None and v2 is not None:
                    child.declare(v1, key)
                    child.declare(v2, item)
                elif v1 is not None:
                    child.declare(v1, item)
                out.append(self.render_nodes(node.body, item, child))
        elif node.else_body is not None:
            out.append(self.render_nodes(node.else_body, dot, _Scope(parent=scope)))
        return "".join(out)


def _collect_defines(nodes: List[_Node], registry: Dict[str, List[_Node]]) -> None:
    """Hoist {{ define }} (and block) bodies into the shared template
    registry; later definitions override earlier ones, which — with subcharts
    collected before their parent — gives Helm's parent-overrides semantics."""
    for n in nodes:
        if n.kind in ("define", "block"):
            toks = _tokenize_expr(n.expr)
            if not toks:
                raise ChartError(f"{n.kind} action missing a template name")
            registry[_literal_string(toks[0])] = n.body
        _collect_defines(n.body, registry)
        for _, body in n.elifs:
            _collect_defines(body, registry)
        if n.else_body:
            _collect_defines(n.else_body, registry)


# Go `path` package semantics (sprig's base/dir/ext delegate to it), which
# differ from posixpath on edge inputs: Base("")=".", Base("a/")="a",
# Dir("a")=".", Ext(".bashrc")=".bashrc".

def _go_path_base(s: str) -> str:
    if not s:
        return "."
    s = s.rstrip("/")
    if not s:
        return "/"
    return s.rsplit("/", 1)[-1]


def _go_path_dir(s: str) -> str:
    if not s:
        return "."
    d = posixpath.dirname(s)
    if not d:
        return "/" if s.startswith("/") else "."
    return posixpath.normpath(d)


def _go_path_ext(s: str) -> str:
    dot = s.rfind(".")
    return s[dot:] if dot > s.rfind("/") else ""


def _glob_regex(pat: str):
    """Helm's Files.Glob semantics (gobwas/glob compiled with '/' as the
    separator): `*`/`?` do not cross path segments, `**` does."""
    out = []
    i = 0
    while i < len(pat):
        c = pat[i]
        if c == "*":
            if pat[i : i + 2] == "**":
                out.append(".*")
                i += 2
            else:
                out.append("[^/]*")
                i += 1
        elif c == "?":
            out.append("[^/]")
            i += 1
        elif c == "[":
            j = pat.find("]", i + 1)
            if j == -1:
                out.append(re.escape(c))
                i += 1
            else:
                out.append(pat[i : j + 1])
                i = j + 1
        else:
            out.append(re.escape(c))
            i += 1
    return re.compile("^" + "".join(out) + "$")


def _basic_kind(v: Any) -> Optional[str]:
    """text/template funcs.go basicKind: the comparison kind of a value, or
    None for nil and non-basic values (maps, slices) — bool checked before
    int because isinstance(True, int) holds in Python."""
    for t, k in ((bool, "bool"), (int, "int"), (float, "float"), (str, "string")):
        if isinstance(v, t):
            return k
    return None


def _go_kind(v: Any) -> str:
    if v is None:
        return "invalid"
    if isinstance(v, bool):
        return "bool"
    if isinstance(v, int):
        return "int"
    if isinstance(v, float):
        return "float64"
    if isinstance(v, str):
        return "string"
    if isinstance(v, (list, tuple)):
        return "slice"
    if isinstance(v, dict):
        return "map"
    return type(v).__name__


_FMT_RE = re.compile(r"%([-+ #0]*)(\d+)?(?:\.(\d+))?([a-zA-Z%])")


def _go_sprintf(fmt: str, args: List[Any]) -> str:
    """Go fmt.Sprintf for the verbs charts use: %s %v %q %d %f %g %e %x %X
    %o %b %t %c %%, with flags/width/precision."""
    out: List[str] = []
    pos = 0
    ai = 0

    def next_arg() -> Any:
        nonlocal ai
        if ai >= len(args):
            raise ChartError(f"printf: not enough arguments for format {fmt!r}")
        a = args[ai]
        ai += 1
        return a

    for m in _FMT_RE.finditer(fmt):
        out.append(fmt[pos : m.start()])
        pos = m.end()
        flags, width, prec, verb = m.groups()
        if verb == "%":
            out.append("%")
            continue
        spec = "%" + (flags or "") + (width or "") + (("." + prec) if prec else "")
        a = next_arg()
        if verb == "d":
            out.append((spec + "d") % int(a))
        elif verb in "oxX":
            out.append((spec + verb) % int(a))
        elif verb == "b":
            out.append(format(int(a), "b"))
        elif verb in "feEgG":
            out.append((spec + verb) % float(a))
        elif verb == "s":
            out.append((spec + "s") % _to_string(a))
        elif verb == "v":
            out.append((spec + "s") % _to_string(a))
        elif verb == "q":
            out.append(
                (spec + "s")
                % ('"' + _to_string(a).replace("\\", "\\\\").replace('"', '\\"') + '"')
            )
        elif verb == "t":
            out.append("true" if bool(a) else "false")
        elif verb == "c":
            out.append(chr(int(a)))
        else:
            raise ChartError(f"printf: unsupported verb %{verb}")
    out.append(fmt[pos:])
    return "".join(out)


def _truthy(v: Any) -> bool:
    """Go template truthiness: false, 0, empty string/collection, nil."""
    if v is None or v is False:
        return False
    if isinstance(v, (int, float)) and v == 0:
        return False
    if isinstance(v, (str, list, dict, tuple)) and len(v) == 0:
        return False
    return True


def _to_string(v: Any) -> str:
    if v is None:
        return ""
    if v is True:
        return "true"
    if v is False:
        return "false"
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        # Go prints whole floats from template arithmetic as "1e+06"-style
        # only at %e; default %v gives "1" for 1.0 via strconv shortest form
        return str(int(v))
    return str(v)


# Helper misuse (wrong arg types/counts, div-by-zero) surfaces as ChartError
# so one bad chart degrades per-app instead of aborting the run with a
# Python traceback.
_RENDER_RUNTIME_ERRORS = (
    ValueError, TypeError, ZeroDivisionError, IndexError, KeyError,
    AttributeError, OverflowError,
)


def render_template(src: str, context: Dict[str, Any]) -> str:
    """Render a standalone template string (defines inside `src` are
    available to include/template within it)."""
    tokens = _tokenize_with_positions(src)
    nodes, _, _ = _parse(tokens)
    registry: Dict[str, List[_Node]] = {}
    _collect_defines(nodes, registry)
    r = _Renderer(registry)
    try:
        return r.render_nodes(nodes, context, _Scope(dollar=context))
    except _RENDER_RUNTIME_ERRORS as e:
        raise ChartError(f"template runtime error: {e!r}")


# ---------------------------------------------------------------------------
# ProcessChart
# ---------------------------------------------------------------------------

def _coalesce(base: Dict[str, Any], overlay: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(base)
    for k, v in overlay.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _coalesce(out[k], v)
        else:
            out[k] = v
    return out


class _Files:
    """`.Files` (helm.sh/helm/v3/pkg/engine files.go): access to the chart's
    non-template files from templates. Method surface matches Helm's —
    Get/GetBytes/Glob/Lines/AsConfig/AsSecrets."""

    __template_safe__ = (
        "Get", "GetBytes", "Glob", "Lines", "AsConfig", "AsSecrets",
    )

    def __init__(self, files: Dict[str, bytes]):
        self._files = dict(files)

    def Get(self, name: Any) -> str:                 # noqa: N802
        data = self._files.get(_to_string(name))
        return data.decode(errors="replace") if data is not None else ""

    def GetBytes(self, name: Any) -> bytes:          # noqa: N802
        return self._files.get(_to_string(name), b"")

    def Glob(self, pattern: Any) -> "_Files":        # noqa: N802
        rx = _glob_regex(_to_string(pattern))
        return _Files(
            {k: v for k, v in self._files.items() if rx.match(k)}
        )

    def Lines(self, name: Any) -> List[str]:         # noqa: N802
        text = self.Get(name)
        return text.splitlines() if text else []

    def AsConfig(self) -> str:                       # noqa: N802
        """Basename -> file content, as YAML (for `data:` of a ConfigMap)."""
        out = {
            posixpath.basename(k): v.decode(errors="replace")
            for k, v in sorted(self._files.items())
        }
        return yaml.safe_dump(out, default_flow_style=False).rstrip("\n") if out else ""

    def AsSecrets(self) -> str:                      # noqa: N802
        out = {
            posixpath.basename(k): base64.b64encode(v).decode()
            for k, v in sorted(self._files.items())
        }
        return yaml.safe_dump(out, default_flow_style=False).rstrip("\n") if out else ""


class _APIVersions(list):
    """`.Capabilities.APIVersions` with the `.Has` method templates call."""

    __template_safe__ = ("Has",)

    def Has(self, v: Any) -> bool:   # noqa: N802 — Go method name
        return _to_string(v) in self


# The API surface of the vendored scheduler's Kubernetes (v1.20.5) — what the
# reference's Helm engine would report when rendering offline.
_CAPABILITIES: Dict[str, Any] = {
    "KubeVersion": {
        "Version": "v1.20.5", "GitVersion": "v1.20.5",
        "Major": "1", "Minor": "20",
    },
    "APIVersions": _APIVersions([
        "v1", "apps/v1", "batch/v1", "batch/v1beta1", "autoscaling/v1",
        "autoscaling/v2beta2", "networking.k8s.io/v1",
        "networking.k8s.io/v1beta1", "policy/v1beta1",
        "rbac.authorization.k8s.io/v1", "storage.k8s.io/v1",
        "scheduling.k8s.io/v1", "apiextensions.k8s.io/v1",
    ]),
    "HelmVersion": {"Version": "v3.9.4"},
}


def _chart_meta_ctx(metadata: Dict[str, Any]) -> Dict[str, Any]:
    """Helm exposes Chart.yaml fields capitalized (.Chart.Name, .Chart.Version,
    .Chart.AppVersion); keep the raw keys too for backward compatibility."""
    ctx = dict(metadata)
    for k, v in metadata.items():
        if isinstance(k, str) and k:
            ctx[k[0].upper() + k[1:]] = v
    return ctx


def _parse_chart_tree(
    chart: Chart,
    registry: Dict[str, List[_Node]],
    parsed: List[Tuple[Chart, str, List[_Node]]],
) -> None:
    """Parse every template file in the chart tree, hoisting defines into the
    shared registry. Subcharts first so parent definitions override (Helm's
    template-override semantics), and each file also registers under its
    chart-relative path (`mychart/templates/deployment.yaml`) so
    `include (print $.Template.BasePath "/x.yaml") .` works."""
    for dep in chart.dependencies:
        _parse_chart_tree(dep, registry, parsed)
    for rel, src in chart.templates.items():
        tokens = _tokenize_with_positions(src)
        nodes, _, _ = _parse(tokens)
        _collect_defines(nodes, registry)
        registry[posixpath.join(chart.name, rel.replace(os.sep, "/"))] = nodes
        parsed.append((chart, rel, nodes))


def _render_parsed(
    chart: Chart,
    values: Dict[str, Any],
    release_name: str,
    renderer: _Renderer,
    parsed_by_chart: Dict[int, List[Tuple[str, List[_Node]]]],
) -> Dict[str, str]:
    ctx_base = {
        "Chart": _chart_meta_ctx(chart.metadata),
        "Release": {
            # chart.go:27-61: the app name overwrites Chart.Metadata.Name
            # before rendering, so Release.Name is the APP name (also what
            # `helm template <name> <path>` does); ns/revision hardcoded
            "Name": release_name,
            "Namespace": "default",
            "Revision": 1,
            "Service": "Helm",
        },
        "Values": values,
        "Capabilities": _CAPABILITIES,
        "Files": _Files(chart.files),
    }
    files: Dict[str, str] = {}
    for rel, nodes in parsed_by_chart.get(id(chart), []):
        if os.path.basename(rel).startswith("_"):
            continue  # partials: defines only, never rendered as manifests
        tpl_name = posixpath.join(chart.name, rel.replace(os.sep, "/"))
        ctx = dict(ctx_base)
        ctx["Template"] = {
            "Name": tpl_name,
            "BasePath": posixpath.join(chart.name, "templates"),
        }
        try:
            files[os.path.join(chart.name, rel)] = renderer.render_nodes(
                nodes, ctx, _Scope(dollar=ctx)
            )
        except _RENDER_RUNTIME_ERRORS as e:
            raise ChartError(f"{tpl_name}: template runtime error: {e!r}")
    # dependencies: subchart values live under values.<subchart name>,
    # sharing .Values.global and the parent's release name
    for dep in chart.dependencies:
        sub_vals = _coalesce(dep.values, values.get(dep.name) or {})
        if "global" in values:
            sub_vals = _coalesce(sub_vals, {"global": values["global"]})
        files.update(
            _render_parsed(dep, sub_vals, release_name, renderer, parsed_by_chart)
        )
    return files


def process_chart(path: str, release_name: Optional[str] = None) -> List[dict]:
    """Render a chart into decoded manifest objects in Helm install order
    (parity: chart.ProcessChart, pkg/chart/chart.go:27-118). release_name is
    the app name from the Simon config; defaults to the chart's own name."""
    from ..resilience import faults

    rule = faults.maybe_inject("chart", release_name or path)
    if rule is not None:
        faults.apply_chart_fault(rule, release_name or path)
    chart = load_chart(path)
    if release_name:
        # chart.go:23: `chartRequested.Metadata.Name = name` — the app name
        # overwrites the top-level chart's own name BEFORE rendering, so
        # .Chart.Name (and the scaffold helpers built on it) see the app name.
        chart.name = release_name
        chart.metadata = dict(chart.metadata)
        chart.metadata["name"] = release_name

    registry: Dict[str, List[_Node]] = {}
    parsed: List[Tuple[Chart, str, List[_Node]]] = []
    _parse_chart_tree(chart, registry, parsed)
    parsed_by_chart: Dict[int, List[Tuple[str, List[_Node]]]] = {}
    for ch, rel, nodes in parsed:
        parsed_by_chart.setdefault(id(ch), []).append((rel, nodes))

    renderer = _Renderer(registry)
    files = _render_parsed(
        chart, chart.values, release_name or chart.name, renderer, parsed_by_chart
    )

    docs: List[Tuple[int, int, dict]] = []  # (order, seq, object)
    seq = 0
    for rel in sorted(files):
        if rel.endswith(NOTES_SUFFIX):
            continue
        content = files[rel]
        for doc in re.split(r"(?m)^---\s*$", content):
            if not doc.strip():
                continue
            try:
                obj = yaml.safe_load(doc)
            except yaml.YAMLError as e:
                raise ChartError(f"{rel}: rendered template is not YAML: {e}")
            if not isinstance(obj, dict) or not obj:
                continue
            kind = obj.get("kind", "")
            order = _ORDER_INDEX.get(kind, len(INSTALL_ORDER))
            docs.append((order, seq, obj))
            seq += 1
    docs.sort(key=lambda t: (t[0], t[1]))
    return [d for _, _, d in docs]
