"""Bounded keep-alive HTTP connection pools for extender I/O.

The serial extender path paid a fresh TCP dial per request
(`urllib.request.urlopen` builds and tears down a connection every call);
under the wave engine (engine/extender_wave.py) dozens of requests per wave
would each pay that dial. One pool per (scheme, host, port) endpoint holds at
most `OSIM_EXTENDER_POOL` persistent `http.client` connections; checkout
blocks when all are in flight, so per-endpoint concurrency is bounded by the
knob, not by the caller's thread count. A kept-alive socket the server closed
between requests is redialed transparently (one retry on the same logical
request — the stale-socket race is indistinguishable from it on the client
side, and the extender verbs riding the pool are idempotent).

Thread-safety: pool internals are guarded by a per-pool Condition; the
endpoint registry mirrors the resilience breaker registry
(`_pools` under `_pools_lock`).
"""

from __future__ import annotations

import http.client
import os
import socket
import threading
import urllib.parse
from typing import Dict, List, Optional, Tuple

DEFAULT_POOL_SIZE = 8


def configured_pool_size() -> int:
    """OSIM_EXTENDER_POOL: max persistent connections per extender endpoint
    (and the wave engine's HTTP worker count). Floor 1."""
    try:
        n = int(os.environ.get("OSIM_EXTENDER_POOL", "") or DEFAULT_POOL_SIZE)
    except ValueError:
        n = DEFAULT_POOL_SIZE
    return max(1, n)


def keepalive_enabled() -> bool:
    """OSIM_EXTENDER_KEEPALIVE: 0 routes extender HTTP through the legacy
    fresh-connection-per-request transport (`urllib.request.urlopen`) instead
    of these pools — the transport escape hatch for proxies or servers that
    misbehave on persistent connections, and the bench's `legacy_serial`
    baseline."""
    return os.environ.get("OSIM_EXTENDER_KEEPALIVE", "1") != "0"


class HTTPConnectionPool:
    """At most `size` persistent connections to one endpoint."""

    def __init__(
        self,
        scheme: str,
        host: str,
        port: Optional[int],
        size: int,
    ) -> None:
        self.scheme = scheme
        self.host = host
        self.port = port
        self.size = max(1, size)
        self._cond = threading.Condition(threading.Lock())
        self._idle: List[http.client.HTTPConnection] = []
        self._live = 0        # checked out + idle
        self.created = 0      # connections dialed over the pool's lifetime
        self.requests = 0     # round trips served

    def _new_conn(self) -> http.client.HTTPConnection:
        cls = (
            http.client.HTTPSConnection
            if self.scheme == "https"
            else http.client.HTTPConnection
        )
        self.created += 1
        return cls(self.host, self.port)

    def _checkout(self) -> http.client.HTTPConnection:
        with self._cond:
            while not self._idle and self._live >= self.size:
                self._cond.wait()
            if self._idle:
                return self._idle.pop()  # LIFO keeps sockets warm
            self._live += 1
            return self._new_conn()

    def _checkin(self, conn: http.client.HTTPConnection) -> None:
        with self._cond:
            self._idle.append(conn)
            self._cond.notify()

    def _drop(self, conn: http.client.HTTPConnection) -> None:
        try:
            conn.close()
        except Exception:
            pass
        with self._cond:
            self._live -= 1
            self._cond.notify()

    def _roundtrip(
        self,
        conn: http.client.HTTPConnection,
        method: str,
        path: str,
        body: Optional[bytes],
        headers: Dict[str, str],
        timeout: Optional[float],
    ) -> Tuple[int, str, bytes]:
        conn.timeout = timeout
        if conn.sock is None:
            conn.connect()
        if conn.sock is not None:
            conn.sock.settimeout(timeout)
            try:
                # http.client writes headers and body as separate segments;
                # without TCP_NODELAY, Nagle holds the second until the
                # peer's delayed ACK (~40ms per round trip on keep-alive
                # connections)
                conn.sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
            except OSError:
                pass
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        data = resp.read()
        if resp.will_close:
            # HTTP/1.0 peer or Connection: close — next use redials
            conn.close()
        return resp.status, resp.reason, data

    def request(
        self,
        method: str,
        path: str,
        body: Optional[bytes],
        headers: Dict[str, str],
        timeout: Optional[float] = None,
    ) -> Tuple[int, str, bytes]:
        """One round trip: (status, reason, response body). Transport
        failures raise OSError/http.client.HTTPException; the connection is
        dropped from the pool so the next request dials fresh.

        When the calling thread is inside a trace (utils/tracing.py), the
        request automatically carries the W3C `traceparent` header so the
        far side can continue the same trace. An explicit header from the
        caller wins; an explicitly EMPTY one suppresses the header entirely
        (for callers that know the surrounding span is not a trace worth
        propagating)."""
        if "traceparent" not in headers:
            from . import tracing

            tp = tracing.current_traceparent()
            if tp is not None:
                headers = dict(headers)
                headers["traceparent"] = tp
        elif not headers["traceparent"]:
            headers = dict(headers)
            del headers["traceparent"]
        conn = self._checkout()
        try:
            try:
                out = self._roundtrip(
                    conn, method, path, body, headers, timeout
                )
            except (
                http.client.RemoteDisconnected,
                http.client.CannotSendRequest,
                BrokenPipeError,
                ConnectionResetError,
            ):
                # stale keep-alive socket: redial once (http.client
                # auto-reconnects after close())
                conn.close()
                with self._cond:
                    self.created += 1
                out = self._roundtrip(
                    conn, method, path, body, headers, timeout
                )
        except BaseException:
            self._drop(conn)
            raise
        with self._cond:
            self.requests += 1
        self._checkin(conn)
        return out

    def stats(self) -> Dict[str, int]:
        with self._cond:
            return {
                "size": self.size,
                "live": self._live,
                "idle": len(self._idle),
                "created": self.created,
                "requests": self.requests,
            }

    def close(self) -> None:
        with self._cond:
            idle, self._idle = self._idle, []
            self._live -= len(idle)
        for conn in idle:
            try:
                conn.close()
            except Exception:
                pass


# ---------------------------------------------------------------------------
# Endpoint-keyed registry, mirroring resilience.policy._breakers: extender
# objects are rebuilt per simulate() call, so warm connections must live
# OUTSIDE them to survive across pods, waves, and capacity-search probes.
# ---------------------------------------------------------------------------

_pools: Dict[Tuple[str, str, Optional[int]], HTTPConnectionPool] = {}
_pools_lock = threading.Lock()


def pool_for(url: str) -> Tuple[HTTPConnectionPool, str]:
    """Get-or-create the endpoint pool for `url`; returns (pool, request
    path). Pool size comes from OSIM_EXTENDER_POOL at creation."""
    parts = urllib.parse.urlsplit(url)
    key = (parts.scheme, parts.hostname or "", parts.port)
    with _pools_lock:
        pool = _pools.get(key)
        if pool is None:
            pool = _pools[key] = HTTPConnectionPool(
                parts.scheme, parts.hostname or "", parts.port,
                size=configured_pool_size(),
            )
    path = parts.path or "/"
    if parts.query:
        path = f"{path}?{parts.query}"
    return pool, path


def reset_pools() -> None:
    """Close every pooled connection and drop the registry (test isolation;
    respects a changed OSIM_EXTENDER_POOL on next use)."""
    with _pools_lock:
        pools = list(_pools.values())
        _pools.clear()
    for p in pools:
        p.close()


def pool_stats() -> Dict[str, Dict[str, int]]:
    """endpoint -> counters for every registered pool (debugging, tests)."""
    with _pools_lock:
        items = sorted(
            (f"{scheme}://{host}:{port}", pool)
            for (scheme, host, port), pool in _pools.items()
        )
    return {ep: pool.stats() for ep, pool in items}
