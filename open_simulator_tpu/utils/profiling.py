"""Device-time profiling: jax.profiler capture + the dispatch-gap analyzer.

The span layer (utils/tracing.py) times host phases; what it cannot see is
how much of a phase the *device* was actually busy — the evidence the
wave-commit and capture-round work needs ("how idle is the device during
the serial commit scan?"). Two tools, both dependency-free beyond jax:

* **Device trace capture** (`capture_device_trace`): a thin wrapper over
  `jax.profiler.start_trace`/`stop_trace` writing a Perfetto-loadable
  device trace into a run directory. Exposed as `simon profile <cmd>` and
  `GET /debug/profile?ms=` on the server. Failures degrade to an
  `{"ok": false}` report — profiling must never take the run down.

* **Dispatch-gap analyzer** (`analyze_dispatch_gaps`): for each audited
  jit entry (engine/warmup.registry_captures — the same capture list the
  audit/warmup/preflight gates prove over), time a warmed call with the
  block_until_ready sandwich:

      t0 -- fn(*args) returns ------- t1 -- block_until_ready ------- t2

  `t1-t0` is host dispatch time (trace-cache lookup, arg handling,
  enqueue), `t2-t1` is the device-side remainder the host then waits out.
  The *dispatch-gap ratio* `dispatch/total` is the fraction of the
  entry's wall time the device sat idle waiting for the host — the
  per-entry number published as `osim_dispatch_gap_ratio{entry=}` next to
  `osim_device_time_seconds{entry=}`, surfaced in bench.py segments as
  `device_time_ms`/`dispatch_gap_ratio`, and emitted as `device:<entry>`
  spans so OSIM_TRACE_FILE exports carry device evidence alongside host
  spans.

Donation caveat: entries that donate buffers consume their inputs, so the
analyzer re-copies donated args per timed call (the registry's stored args
stay live — the same discipline as jaxpr_audit._snapshot_donated).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Dict, List, Optional, Sequence

from . import metrics
from .tracing import log, span

__all__ = [
    "EntryTiming",
    "DispatchGapReport",
    "analyze_dispatch_gaps",
    "capture_device_trace",
    "profiler_available",
]


def profiler_available() -> bool:
    try:
        import jax.profiler  # noqa: F401

        return True
    except Exception:  # pragma: no cover - jax is a hard dep in-tree
        return False


def capture_device_trace(
    out_dir: str, duration_ms: float = 1000.0, fn=None
) -> Dict[str, Any]:
    """Capture a jax.profiler device trace into `out_dir` — around `fn()`
    when given, else for `duration_ms` of wall time. Returns a report dict
    ({"ok": bool, "trace_dir": ..., "seconds": ...}, plus "error" on
    failure); never raises."""
    import jax

    report: Dict[str, Any] = {"ok": False, "trace_dir": out_dir}
    t0 = time.perf_counter()
    try:
        os.makedirs(out_dir, exist_ok=True)
        jax.profiler.start_trace(out_dir)
    except Exception as e:
        report["error"] = str(e)
        return report
    err: Optional[str] = None
    try:
        with span("device-profile", out_dir=out_dir):
            if fn is not None:
                fn()
            else:
                time.sleep(max(float(duration_ms), 0.0) / 1000.0)
    except Exception as e:
        # the workload blew up, not the profiler — still stop the trace
        # (below) so the partial capture is readable, and report not raise
        err = f"{type(e).__name__}: {e}"
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception as e:
            report["error"] = err or str(e)
            return report
    if err is not None:
        report["error"] = err
        return report
    report["ok"] = True
    report["seconds"] = round(time.perf_counter() - t0, 4)
    return report


@dataclasses.dataclass
class EntryTiming:
    """Block-until-ready sandwich timing of one warmed jit entry (best of
    `repeats` runs, so a GC pause can't smear the gap ratio)."""

    name: str
    dispatch_ms: float  # host time until dispatch returned (the gap)
    device_ms: float    # dispatch-return -> block_until_ready return
    total_ms: float
    gap_ratio: float    # dispatch_ms / total_ms, in [0, 1]
    repeats: int

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "dispatch_ms": round(self.dispatch_ms, 4),
            "device_ms": round(self.device_ms, 4),
            "total_ms": round(self.total_ms, 4),
            "gap_ratio": round(self.gap_ratio, 4),
            "repeats": self.repeats,
        }


@dataclasses.dataclass
class DispatchGapReport:
    entries: List[EntryTiming]
    seconds: float

    @property
    def device_time_ms(self) -> float:
        return round(sum(e.device_ms for e in self.entries), 4)

    @property
    def dispatch_gap_ratio(self) -> float:
        """Aggregate gap: total dispatch time over total wall time across
        every timed entry (NOT a mean of ratios — a 2 µs entry must not
        outvote a 20 ms one)."""
        total = sum(e.total_ms for e in self.entries)
        if total <= 0:
            return 0.0
        return round(sum(e.dispatch_ms for e in self.entries) / total, 4)

    def to_dict(self) -> dict:
        return {
            "entries": [e.to_dict() for e in self.entries],
            "seconds": round(self.seconds, 4),
            "device_time_ms": self.device_time_ms,
            "dispatch_gap_ratio": self.dispatch_gap_ratio,
        }

    def render_text(self) -> str:
        lines = [
            f"dispatch-gap analysis: {len(self.entries)} entries in "
            f"{self.seconds:.2f}s — device {self.device_time_ms:.2f} ms, "
            f"aggregate gap ratio {self.dispatch_gap_ratio:.3f}"
        ]
        for e in sorted(self.entries, key=lambda e: -e.device_ms):
            lines.append(
                f"  {e.name:28s} device {e.device_ms:8.3f} ms  "
                f"dispatch {e.dispatch_ms:7.3f} ms  gap {e.gap_ratio:.3f}"
            )
        return "\n".join(lines)


def _fresh_args(cap) -> tuple:
    """Per-call argument tuple: donated argnums are re-copied so a donating
    entry can be timed repeatedly without consuming the registry's stored
    canonical args."""
    import jax

    donated = set(getattr(cap.fn, "__osim_donate_argnums__", ()) or ())
    if not donated:
        return cap.args
    return tuple(
        jax.tree.map(lambda a: a.copy() if hasattr(a, "dtype") else a, arg)
        if i in donated
        else arg
        for i, arg in enumerate(cap.args)
    )


def analyze_dispatch_gaps(
    names: Optional[Sequence[str]] = None,
    repeats: int = 2,
    captures: Optional[Sequence[Any]] = None,
) -> DispatchGapReport:
    """Time every audited jit entry at its canonical shapes and derive
    per-entry device ms + dispatch-gap fraction.

    `names` filters the registry (audit names like
    "ops.fast:schedule_scenarios"); `captures` injects a prepared capture
    list (tests; anything with .name/.fn/.args/.kwargs works). Each entry
    is warmed once outside the timed window, then sandwiched `repeats`
    times, keeping the fastest run. Publishes
    osim_device_time_seconds{entry=} / osim_dispatch_gap_ratio{entry=} and
    emits a `device:<entry>` span per entry."""
    import jax

    if captures is None:
        from ..engine.warmup import registry_captures

        captures = registry_captures(names)
    repeats = max(1, int(repeats))
    t_start = time.perf_counter()
    entries: List[EntryTiming] = []
    with span("dispatch-gap-analysis", entries=len(captures)):
        for cap in captures:
            # warm outside the timed window: compile (first call in a cold
            # process) must never be billed as dispatch gap
            jax.block_until_ready(cap.fn(*_fresh_args(cap), **cap.kwargs))
            best = None
            with span(f"device:{cap.name}", entry=cap.name) as dev_span:
                for _ in range(repeats):
                    args = _fresh_args(cap)
                    t0 = time.perf_counter()
                    out = cap.fn(*args, **cap.kwargs)
                    t1 = time.perf_counter()
                    jax.block_until_ready(out)
                    t2 = time.perf_counter()
                    if best is None or (t2 - t0) < best[2]:
                        best = (t1 - t0, t2 - t1, t2 - t0)
                dispatch_s, device_s, total_s = best
                gap = dispatch_s / total_s if total_s > 0 else 0.0
                dev_span.meta.update(
                    device_ms=round(device_s * 1e3, 4),
                    dispatch_ms=round(dispatch_s * 1e3, 4),
                    gap_ratio=round(gap, 4),
                )
            entries.append(
                EntryTiming(
                    name=cap.name,
                    dispatch_ms=dispatch_s * 1e3,
                    device_ms=device_s * 1e3,
                    total_ms=total_s * 1e3,
                    gap_ratio=gap,
                    repeats=repeats,
                )
            )
            metrics.DEVICE_TIME.set(device_s, entry=cap.name)
            metrics.DISPATCH_GAP.set(gap, entry=cap.name)
    report = DispatchGapReport(
        entries=entries, seconds=time.perf_counter() - t_start
    )
    log.debug("dispatch-gap analysis:\n%s", report.render_text())
    return report
