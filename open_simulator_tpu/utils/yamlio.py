"""YAML/JSON ingestion: recursive file walker + multi-document decode.

Parity: the reference walks directories recursively collecting .yaml/.yml files
(`/root/reference/pkg/utils/utils.go:43-70`), splits multi-doc manifests via
Helm's SplitManifests and decodes through the scheme codec
(`utils.go:73-87`, `pkg/simulator/utils.go:233-275`). We use PyYAML's
safe_load_all and keep decoded objects as dicts classified by `kind`.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Tuple

import yaml

# The kinds GetObjectFromYamlContent understands (pkg/simulator/utils.go:233-275);
# the cluster/app builders warn on anything else.
SUPPORTED_KINDS = {
    "Pod",
    "Deployment",
    "ReplicaSet",
    "StatefulSet",
    "DaemonSet",
    "Job",
    "CronJob",
    "Node",
    "Service",
    "PersistentVolumeClaim",
    "StorageClass",
    "PodDisruptionBudget",
    "ConfigMap",
}


def walk_files(path: str, exts: Tuple[str, ...]) -> List[str]:
    """All files under path (or path itself) with one of the extensions, sorted
    for determinism."""
    if os.path.isfile(path):
        return [path] if path.endswith(exts) else []
    found: List[str] = []
    for root, dirs, files in os.walk(path):
        dirs.sort()
        for f in sorted(files):
            if f.endswith(exts):
                found.append(os.path.join(root, f))
    return found


def load_yaml_documents(text: str) -> List[dict]:
    docs = []
    for doc in yaml.safe_load_all(text):
        if isinstance(doc, dict) and doc.get("kind"):
            docs.append(doc)
    return docs


def objects_from_directory(path: str) -> List[dict]:
    """Decode every YAML object under a directory (recursively)."""
    objs: List[dict] = []
    for f in walk_files(path, (".yaml", ".yml")):
        with open(f, "r") as fh:
            objs.extend(load_yaml_documents(fh.read()))
    return objs


def objects_from_yaml_contents(contents: List[str]) -> List[dict]:
    objs: List[dict] = []
    for text in contents:
        objs.extend(load_yaml_documents(text))
    return objs


def json_files_by_stem(path: str) -> Dict[str, str]:
    """Map file basename (sans extension) → raw JSON text; used to match
    node-local-storage specs to node names (pkg/simulator/utils.go:385-401)."""
    out: Dict[str, str] = {}
    for f in walk_files(path, (".json",)):
        stem = os.path.splitext(os.path.basename(f))[0]
        with open(f, "r") as fh:
            text = fh.read()
        try:
            json.loads(text)
        except json.JSONDecodeError:
            continue
        out[stem] = text
    return out


def group_by_kind(objs: List[dict]) -> Dict[str, List[dict]]:
    grouped: Dict[str, List[dict]] = {}
    for o in objs:
        grouped.setdefault(o.get("kind", ""), []).append(o)
    return grouped
