"""Timing spans + logging: the observability subsystem.

Parity targets:
  - `utils.LogIfLong` / the 1 s slow-Simulate trace threshold
    (`/root/reference/pkg/simulator/core.go:72-73`, `simulator.go:511-521`):
    here every root span that exceeds OSIM_SLOW_TRACE (default 1.0 s) logs its
    whole subtree at WARNING.
  - the `LogLevel` env handling (`cmd/simon/simon.go:46-66`): init_logging()
    maps LogLevel ∈ {debug, info, warn, error} onto the stdlib logger.
  - per-pod progress output (`simulator.go:311-321`): the engine emits a
    per-batch progress line at DEBUG (per-pod printing would serialize the
    batched device path — the batch line carries the same information).
  - pprof on the server (`pkg/server/server.go:152`): the /debug/timings
    endpoint serves recent span trees as JSON.

Spans nest via a thread-local stack; finished roots are kept in a bounded
ring buffer for the server endpoint. Overhead when disabled is two clock
reads per span — safe to leave in hot host paths (device time is measured
as host wall time around blocking calls, which is what a user can act on).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from contextlib import contextmanager
from typing import List, Optional

log = logging.getLogger("osim")

SLOW_TRACE_S = float(os.environ.get("OSIM_SLOW_TRACE", "1.0"))
_HISTORY_MAX = 64


class Span:
    __slots__ = ("name", "start", "end", "children", "meta")

    def __init__(self, name: str) -> None:
        self.name = name
        self.start = time.time()
        self.end: Optional[float] = None
        self.children: List["Span"] = []
        self.meta: dict = {}

    @property
    def duration(self) -> float:
        return (self.end or time.time()) - self.start

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "duration_s": round(self.duration, 4),
        }
        if self.meta:
            d["meta"] = self.meta
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d

    def render(self, indent: int = 0) -> str:
        lines = [f"{'  ' * indent}{self.name}: {self.duration * 1e3:.1f} ms"
                 + (f" {self.meta}" if self.meta else "")]
        for c in self.children:
            lines.append(c.render(indent + 1))
        return "\n".join(lines)


class _Tracer(threading.local):
    def __init__(self) -> None:
        self.stack: List[Span] = []


_tracer = _Tracer()
_history: List[dict] = []
_history_lock = threading.Lock()


@contextmanager
def span(name: str, **meta):
    """Time a phase. Nested spans build a tree; when a ROOT span closes it is
    recorded for /debug/timings, logged at DEBUG, and escalated to WARNING
    with its full subtree when slower than OSIM_SLOW_TRACE seconds (the
    LogIfLong analog)."""
    s = Span(name)
    if meta:
        s.meta.update(meta)
    parent = _tracer.stack[-1] if _tracer.stack else None
    if parent is not None:
        parent.children.append(s)
    _tracer.stack.append(s)
    try:
        yield s
    finally:
        s.end = time.time()
        _tracer.stack.pop()
        if parent is None:
            with _history_lock:
                _history.append(s.to_dict())
                del _history[:-_HISTORY_MAX]
            if s.duration > SLOW_TRACE_S:
                log.warning("slow trace (> %.1fs):\n%s", SLOW_TRACE_S, s.render())
            else:
                log.debug("trace:\n%s", s.render())


def recent_timings() -> List[dict]:
    """Recent root span trees, oldest first (the /debug/timings payload)."""
    with _history_lock:
        return list(_history)


def progress(fmt: str, *args) -> None:
    """Per-batch progress line (the reference's per-pod report.Progress,
    simulator.go:311-321, lifted to batch granularity)."""
    log.debug(fmt, *args)


_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}


def init_logging(default: str = "info") -> None:
    """Honor the LogLevel env exactly like cmd/simon/simon.go:46-66 (invalid
    values fall back to the default, case-insensitive)."""
    level = _LEVELS.get(os.environ.get("LogLevel", default).strip().lower())
    if level is None:
        level = _LEVELS[default]
    logging.basicConfig(
        level=level,
        format="%(asctime)s %(levelname)-7s %(name)s: %(message)s",
    )
    log.setLevel(level)
