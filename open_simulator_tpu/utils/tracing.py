"""Timing spans + logging: the observability subsystem.

Parity targets:
  - `utils.LogIfLong` / the 1 s slow-Simulate trace threshold
    (`/root/reference/pkg/simulator/core.go:72-73`, `simulator.go:511-521`):
    here every root span that exceeds OSIM_SLOW_TRACE (default 1.0 s) logs its
    whole subtree at WARNING.
  - the `LogLevel` env handling (`cmd/simon/simon.go:46-66`): init_logging()
    maps LogLevel ∈ {debug, info, warn, error} onto the stdlib logger.
  - per-pod progress output (`simulator.go:311-321`): the engine emits a
    per-batch progress line at DEBUG (per-pod printing would serialize the
    batched device path — the batch line carries the same information).
  - pprof on the server (`pkg/server/server.go:152`): the /debug/timings
    endpoint serves recent span trees as JSON.

Spans nest via a thread-local stack; finished roots are kept in a bounded
ring buffer for the server endpoint (size: OSIM_SPAN_HISTORY, default 64).
Every finished span also feeds the metrics histograms (utils/metrics.py),
and when OSIM_TRACE_FILE is set, finished root trees are exported as Chrome
trace events (load the file in Perfetto / chrome://tracing). Overhead when
disabled is two clock reads per span — safe to leave in hot host paths
(device time is measured as host wall time around blocking calls, which is
what a user can act on).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from contextlib import contextmanager
from typing import List, Optional

from . import metrics

log = logging.getLogger("osim")

SLOW_TRACE_S = float(os.environ.get("OSIM_SLOW_TRACE", "1.0"))
_HISTORY_DEFAULT = 64


def _history_max() -> int:
    """Ring-buffer size for /debug/timings; OSIM_SPAN_HISTORY overrides the
    default of 64 so long bench runs can keep full histories. Read per root
    close (cheap) so tests and long-lived servers can change it on the fly."""
    raw = os.environ.get("OSIM_SPAN_HISTORY", "").strip()
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            log.warning("ignoring non-integer OSIM_SPAN_HISTORY=%r", raw)
    return _HISTORY_DEFAULT


class Span:
    __slots__ = ("name", "start", "end", "children", "meta")

    def __init__(self, name: str) -> None:
        self.name = name
        self.start = time.time()
        self.end: Optional[float] = None
        self.children: List["Span"] = []
        self.meta: dict = {}

    @property
    def duration(self) -> float:
        return (self.end or time.time()) - self.start

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "start": round(self.start, 6),
            "duration_s": round(self.duration, 4),
        }
        if self.meta:
            d["meta"] = self.meta
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d

    def render(self, indent: int = 0) -> str:
        lines = [f"{'  ' * indent}{self.name}: {self.duration * 1e3:.1f} ms"
                 + (f" {self.meta}" if self.meta else "")]
        for c in self.children:
            lines.append(c.render(indent + 1))
        return "\n".join(lines)


class _Tracer(threading.local):
    def __init__(self) -> None:
        self.stack: List[Span] = []


_tracer = _Tracer()
_history: List[dict] = []
_history_lock = threading.Lock()


@contextmanager
def span(name: str, **meta):
    """Time a phase. Nested spans build a tree; every finished span observes
    into the metrics histograms, and when a ROOT span closes it is recorded
    for /debug/timings, exported to OSIM_TRACE_FILE (if set), logged at
    DEBUG, and escalated to WARNING with its full subtree when slower than
    OSIM_SLOW_TRACE seconds (the LogIfLong analog)."""
    s = Span(name)
    if meta:
        s.meta.update(meta)
    parent = _tracer.stack[-1] if _tracer.stack else None
    if parent is not None:
        parent.children.append(s)
    _tracer.stack.append(s)
    try:
        yield s
    finally:
        s.end = time.time()
        _tracer.stack.pop()
        metrics.observe_span(s.name, s.end - s.start)
        if parent is None:
            with _history_lock:
                _history.append(s.to_dict())
                del _history[:-_history_max()]
            _maybe_export_trace(s)
            if s.duration > SLOW_TRACE_S:
                log.warning("slow trace (> %.1fs):\n%s", SLOW_TRACE_S, s.render())
            else:
                log.debug("trace:\n%s", s.render())


def recent_timings() -> List[dict]:
    """Recent root span trees, oldest first (the /debug/timings payload)."""
    with _history_lock:
        return list(_history)


# ---------------------------------------------------------------------------
# Chrome trace-event export (OSIM_TRACE_FILE)
# ---------------------------------------------------------------------------
#
# Each finished root span tree is flattened into "X" (complete) events with
# epoch-microsecond `ts` and `dur`, and the whole accumulated event list is
# rewritten to the file — roots are rare (one per simulate call), so the
# rewrite is cheap and the file is valid JSON after every root, even if the
# process dies mid-run. Epoch microseconds stay below 2^53, so `ts` survives
# the JSON double round trip.

_trace_lock = threading.Lock()
_trace_events: List[dict] = []
_TRACE_MAX_EVENTS = 250_000  # backstop for long-lived servers
_trace_overflow_logged = False


def _span_events(s: Span, pid: int, tid: int, out: List[dict]) -> None:
    ev = {
        "name": s.name,
        "cat": "osim",
        "ph": "X",
        "ts": s.start * 1e6,
        "dur": max(s.duration, 0.0) * 1e6,
        "pid": pid,
        "tid": tid,
    }
    if s.meta:
        ev["args"] = dict(s.meta)
    out.append(ev)
    for c in s.children:
        _span_events(c, pid, tid, out)


def _maybe_export_trace(root: Span) -> None:
    path = os.environ.get("OSIM_TRACE_FILE", "").strip()
    if not path:
        return
    global _trace_overflow_logged
    events: List[dict] = []
    _span_events(root, os.getpid(), threading.get_ident(), events)
    with _trace_lock:
        if len(_trace_events) + len(events) > _TRACE_MAX_EVENTS:
            if not _trace_overflow_logged:
                _trace_overflow_logged = True
                log.warning(
                    "OSIM_TRACE_FILE: dropping events beyond %d; "
                    "restart the process to start a fresh trace",
                    _TRACE_MAX_EVENTS,
                )
            return
        _trace_events.extend(events)
        payload = {"traceEvents": list(_trace_events),
                   "displayTimeUnit": "ms"}
        try:
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        except OSError as exc:
            log.warning("OSIM_TRACE_FILE write failed: %s", exc)


def reset_trace_events() -> None:
    """Drop accumulated trace events (test isolation / manual truncation)."""
    global _trace_overflow_logged
    with _trace_lock:
        _trace_events.clear()
        _trace_overflow_logged = False


def progress(fmt: str, *args) -> None:
    """Per-batch progress line (the reference's per-pod report.Progress,
    simulator.go:311-321, lifted to batch granularity)."""
    log.debug(fmt, *args)


_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}

_FORMAT = "%(asctime)s %(levelname)-7s %(name)s: %(message)s"
_log_handler: Optional[logging.Handler] = None


def init_logging(default: str = "info") -> None:
    """Honor the LogLevel env exactly like cmd/simon/simon.go:46-66 (invalid
    values fall back to the default, case-insensitive).

    Idempotent: `logging.basicConfig` is a no-op once any root handler
    exists (e.g. under pytest, or on a second serve() call), which used to
    silently ignore LogLevel changes. The `osim` logger now owns a single
    dedicated stderr handler whose level tracks LogLevel on every call;
    propagation stays on so root-level capture (pytest caplog) still works.
    """
    level = _LEVELS.get(os.environ.get("LogLevel", default).strip().lower())
    if level is None:
        level = _LEVELS[default]
    global _log_handler
    if _log_handler is None:
        _log_handler = logging.StreamHandler()
        _log_handler.setFormatter(logging.Formatter(_FORMAT))
        log.addHandler(_log_handler)
    _log_handler.setLevel(level)
    log.setLevel(level)
