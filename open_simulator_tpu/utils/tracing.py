"""Timing spans + logging: the observability subsystem.

Parity targets:
  - `utils.LogIfLong` / the 1 s slow-Simulate trace threshold
    (`/root/reference/pkg/simulator/core.go:72-73`, `simulator.go:511-521`):
    here every root span that exceeds OSIM_SLOW_TRACE (default 1.0 s) logs its
    whole subtree at WARNING.
  - the `LogLevel` env handling (`cmd/simon/simon.go:46-66`): init_logging()
    maps LogLevel ∈ {debug, info, warn, error} onto the stdlib logger.
  - per-pod progress output (`simulator.go:311-321`): the engine emits a
    per-batch progress line at DEBUG (per-pod printing would serialize the
    batched device path — the batch line carries the same information).
  - pprof on the server (`pkg/server/server.go:152`): the /debug/timings
    endpoint serves recent span trees as JSON.

Spans nest via a thread-local stack; finished roots are kept in a bounded
ring buffer for the server endpoint (size: OSIM_SPAN_HISTORY, default 64).
Every finished span also feeds the metrics histograms (utils/metrics.py),
and when OSIM_TRACE_FILE is set, finished root trees are exported as Chrome
trace events (load the file in Perfetto / chrome://tracing). Overhead when
disabled is two clock reads per span — safe to leave in hot host paths
(device time is measured as host wall time around blocking calls, which is
what a user can act on).

Cross-thread propagation: every span carries explicit trace/span IDs. A
`TraceContext` snapshot of the active span (`current_context()`) can cross a
queue or a thread-pool boundary and be re-activated on the far side with
`activate(ctx)` — the next root span opened there becomes a *child by ID*
of the captured span, so one request's work stays one connected trace even
though each thread keeps its own span stack. The wire form is the W3C
`traceparent` header (`TraceContext.to_traceparent` /
`TraceContext.from_traceparent`); packed/coalesced lanes that share one
execution record their relationship as span *links* (`Span.add_link`)
instead of a parent edge. All IDs ride the Chrome-trace export as event
args, so an exported file is reconnectable offline.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
from contextlib import contextmanager
from typing import List, Optional

from . import metrics

log = logging.getLogger("osim")

SLOW_TRACE_S = float(os.environ.get("OSIM_SLOW_TRACE", "1.0"))
_HISTORY_DEFAULT = 64


def _history_max() -> int:
    """Ring-buffer size for /debug/timings; OSIM_SPAN_HISTORY overrides the
    default of 64 so long bench runs can keep full histories. Read per root
    close (cheap) so tests and long-lived servers can change it on the fly."""
    raw = os.environ.get("OSIM_SPAN_HISTORY", "").strip()
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            log.warning("ignoring non-integer OSIM_SPAN_HISTORY=%r", raw)
    return _HISTORY_DEFAULT


def _new_trace_id() -> str:
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


class TraceContext:
    """Immutable (trace_id, span_id) snapshot — the part of a span's
    identity that can cross a thread, a queue, or a process boundary.
    Captured at enqueue (`current_context()`), re-activated at dequeue
    (`activate(ctx)`), and serialized on the wire as a W3C traceparent."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str) -> None:
        self.trace_id = trace_id
        self.span_id = span_id

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, TraceContext)
            and self.trace_id == other.trace_id
            and self.span_id == other.span_id
        )

    def __hash__(self) -> int:
        return hash((self.trace_id, self.span_id))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"TraceContext({self.trace_id}, {self.span_id})"

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    # -- W3C traceparent (version 00, sampled flag always set) --------------

    _TRACEPARENT_RE = re.compile(
        r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
    )

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    @classmethod
    def from_traceparent(cls, header: Optional[str]) -> Optional["TraceContext"]:
        """Parse a traceparent header; malformed/absent/all-zero IDs return
        None (the request simply starts a fresh trace)."""
        if not header:
            return None
        m = cls._TRACEPARENT_RE.match(header.strip().lower())
        if m is None:
            return None
        version, trace_id, span_id = m.group(1), m.group(2), m.group(3)
        if version == "ff" or trace_id == "0" * 32 or span_id == "0" * 16:
            return None
        return cls(trace_id, span_id)


class Span:
    __slots__ = (
        "name", "start", "end", "children", "meta",
        "trace_id", "span_id", "parent_id", "links",
    )

    def __init__(self, name: str) -> None:
        self.name = name
        self.start = time.time()
        self.end: Optional[float] = None
        self.children: List["Span"] = []
        self.meta: dict = {}
        self.trace_id: str = ""
        self.span_id: str = _new_span_id()
        self.parent_id: Optional[str] = None
        self.links: List[dict] = []

    @property
    def duration(self) -> float:
        return (self.end or time.time()) - self.start

    def context(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id)

    def add_link(self, ctx) -> None:
        """Record a non-parent relationship to another span (a packed lane
        pointing at its pack's execution span and vice versa). Accepts a
        TraceContext or another Span."""
        self.links.append(
            {"trace_id": ctx.trace_id, "span_id": ctx.span_id}
        )

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "start": round(self.start, 6),
            "duration_s": round(self.duration, 4),
            "trace_id": self.trace_id,
            "span_id": self.span_id,
        }
        if self.parent_id:
            d["parent_id"] = self.parent_id
        if self.links:
            d["links"] = [dict(ln) for ln in self.links]
        if self.meta:
            d["meta"] = self.meta
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d

    def render(self, indent: int = 0) -> str:
        lines = [f"{'  ' * indent}{self.name}: {self.duration * 1e3:.1f} ms"
                 + (f" {self.meta}" if self.meta else "")]
        for c in self.children:
            lines.append(c.render(indent + 1))
        return "\n".join(lines)


class _Tracer(threading.local):
    def __init__(self) -> None:
        self.stack: List[Span] = []
        # remote parent context re-activated on this thread (activate());
        # the next ROOT span opened here becomes its child by ID
        self.remote: Optional[TraceContext] = None


_tracer = _Tracer()
_history: List[dict] = []
_history_lock = threading.Lock()


@contextmanager
def span(name: str, **meta):
    """Time a phase. Nested spans build a tree; every finished span observes
    into the metrics histograms, and when a ROOT span closes it is recorded
    for /debug/timings, exported to OSIM_TRACE_FILE (if set), logged at
    DEBUG, and escalated to WARNING with its full subtree when slower than
    OSIM_SLOW_TRACE seconds (the LogIfLong analog)."""
    s = Span(name)
    if meta:
        s.meta.update(meta)
    parent = _tracer.stack[-1] if _tracer.stack else None
    if parent is not None:
        parent.children.append(s)
        s.trace_id = parent.trace_id
        s.parent_id = parent.span_id
    elif _tracer.remote is not None:
        # cross-thread continuation: a local root, but a child by ID of the
        # context captured on the submitting thread
        s.trace_id = _tracer.remote.trace_id
        s.parent_id = _tracer.remote.span_id
    else:
        s.trace_id = _new_trace_id()
    _tracer.stack.append(s)
    try:
        yield s
    finally:
        s.end = time.time()
        _tracer.stack.pop()
        metrics.observe_span(s.name, s.end - s.start)
        if parent is None:
            root_dict = s.to_dict()
            with _history_lock:
                _history.append(root_dict)
                del _history[:-_history_max()]
            _record_flight(root_dict)
            _maybe_export_trace(s)
            if s.duration > SLOW_TRACE_S:
                log.warning("slow trace (> %.1fs):\n%s", SLOW_TRACE_S, s.render())
            else:
                log.debug("trace:\n%s", s.render())


@contextmanager
def activate(ctx: Optional[TraceContext]):
    """Re-activate a captured TraceContext on the current thread: root spans
    opened inside become children by ID of the captured span. `None` is a
    no-op, so call sites can pass an optional context unconditionally."""
    if ctx is None:
        yield
        return
    prev = _tracer.remote
    _tracer.remote = ctx
    try:
        yield
    finally:
        _tracer.remote = prev


def current_span() -> Optional[Span]:
    """The innermost open span on this thread, if any."""
    return _tracer.stack[-1] if _tracer.stack else None


def current_context() -> Optional[TraceContext]:
    """Snapshot of the active span (or the re-activated remote context when
    no span is open) for crossing a thread/queue boundary; None when this
    thread is not inside any trace."""
    if _tracer.stack:
        return _tracer.stack[-1].context()
    return _tracer.remote


def current_trace_id() -> Optional[str]:
    ctx = current_context()
    return ctx.trace_id if ctx is not None else None


def current_traceparent() -> Optional[str]:
    """The W3C traceparent header for outbound HTTP, or None when the
    calling thread is not inside any trace (never mints a fresh ID — a
    header nobody can correlate is noise)."""
    ctx = current_context()
    return ctx.to_traceparent() if ctx is not None else None


def _record_flight(root_dict: dict) -> None:
    """Feed the finished root into the crash flight recorder (always-on
    bounded ring, utils/flightrec.py). Lazy import: flightrec reads trace
    IDs back through this module, so neither imports the other at top."""
    try:
        from . import flightrec

        flightrec.record_span(root_dict)
    except Exception:  # pragma: no cover - recorder must never break tracing
        pass


def recent_timings() -> List[dict]:
    """Recent root span trees, oldest first (the /debug/timings payload)."""
    with _history_lock:
        return list(_history)


# ---------------------------------------------------------------------------
# Chrome trace-event export (OSIM_TRACE_FILE)
# ---------------------------------------------------------------------------
#
# Each finished root span tree is flattened into "X" (complete) events with
# epoch-microsecond `ts` and `dur`, and the whole accumulated event list is
# rewritten to the file — roots are rare (one per simulate call), so the
# rewrite is cheap and the file is valid JSON after every root, even if the
# process dies mid-run. Epoch microseconds stay below 2^53, so `ts` survives
# the JSON double round trip. Every event carries its span's trace/span/
# parent IDs (and links) as args, so the exported file stays one connected,
# offline-reconnectable tree per request.

_trace_lock = threading.Lock()
_trace_events: List[dict] = []
_TRACE_MAX_EVENTS = 250_000  # default backstop for long-lived servers
_trace_overflow_logged = False


def _trace_max_events() -> int:
    """OSIM_TRACE_MAX_EVENTS overrides the 250k default event cap; read per
    export so long-lived servers can be resized without a restart."""
    raw = os.environ.get("OSIM_TRACE_MAX_EVENTS", "").strip()
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            log.warning("ignoring non-integer OSIM_TRACE_MAX_EVENTS=%r", raw)
    return _TRACE_MAX_EVENTS


def _span_events(s: Span, pid: int, tid: int, out: List[dict]) -> None:
    args = dict(s.meta) if s.meta else {}
    args["trace_id"] = s.trace_id
    args["span_id"] = s.span_id
    if s.parent_id:
        args["parent_id"] = s.parent_id
    if s.links:
        args["links"] = [dict(ln) for ln in s.links]
    ev = {
        "name": s.name,
        "cat": "osim",
        "ph": "X",
        "ts": s.start * 1e6,
        "dur": max(s.duration, 0.0) * 1e6,
        "pid": pid,
        "tid": tid,
        "args": args,
    }
    out.append(ev)
    for c in s.children:
        _span_events(c, pid, tid, out)


def _maybe_export_trace(root: Span) -> None:
    path = os.environ.get("OSIM_TRACE_FILE", "").strip()
    if not path:
        return
    global _trace_overflow_logged
    events: List[dict] = []
    _span_events(root, os.getpid(), threading.get_ident(), events)
    with _trace_lock:
        cap = _trace_max_events()
        _trace_events.extend(events)
        overflow = len(_trace_events) - cap
        if overflow > 0:
            # oldest-first rotation: the newest spans are the ones a live
            # incident needs; the rotated-out prefix is already on disk in
            # the previous rewrite anyway
            del _trace_events[:overflow]
            if not _trace_overflow_logged:
                _trace_overflow_logged = True
                log.warning(
                    "OSIM_TRACE_FILE: event cap %d reached; rotating oldest "
                    "events out (set OSIM_TRACE_MAX_EVENTS to resize)",
                    cap,
                )
        payload = {"traceEvents": list(_trace_events),
                   "displayTimeUnit": "ms"}
        try:
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        except OSError as exc:
            log.warning("OSIM_TRACE_FILE write failed: %s", exc)


def reset_trace_events() -> None:
    """Drop accumulated trace events (test isolation / manual truncation)."""
    global _trace_overflow_logged
    with _trace_lock:
        _trace_events.clear()
        _trace_overflow_logged = False


def progress(fmt: str, *args) -> None:
    """Per-batch progress line (the reference's per-pod report.Progress,
    simulator.go:311-321, lifted to batch granularity)."""
    log.debug(fmt, *args)


_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}

_FORMAT = "%(asctime)s %(levelname)-7s %(name)s: %(message)s"
_log_handler: Optional[logging.Handler] = None


def init_logging(default: str = "info") -> None:
    """Honor the LogLevel env exactly like cmd/simon/simon.go:46-66 (invalid
    values fall back to the default, case-insensitive).

    Idempotent: `logging.basicConfig` is a no-op once any root handler
    exists (e.g. under pytest, or on a second serve() call), which used to
    silently ignore LogLevel changes. The `osim` logger now owns a single
    dedicated stderr handler whose level tracks LogLevel on every call;
    propagation stays on so root-level capture (pytest caplog) still works.
    """
    level = _LEVELS.get(os.environ.get("LogLevel", default).strip().lower())
    if level is None:
        level = _LEVELS[default]
    global _log_handler
    if _log_handler is None:
        _log_handler = logging.StreamHandler()
        _log_handler.setFormatter(logging.Formatter(_FORMAT))
        log.addHandler(_log_handler)
    _log_handler.setLevel(level)
    log.setLevel(level)
