"""Kubernetes resource.Quantity parsing and formatting.

Host-side equivalent of `k8s.io/apimachinery/pkg/api/resource.Quantity` as used
throughout the reference (e.g. `/root/reference/pkg/utils/utils.go:642-667` for
per-node request totals and `pkg/simulator/plugin/simon.go:45-68` for scoring).

We keep quantities as exact integers in a canonical base unit:
  - cpu-like quantities: millivalue (1 cpu == 1000)
  - everything else: the plain value in its base unit (bytes for memory).
Parsing supports suffixes m, k/M/G/T/P/E, Ki/Mi/Gi/Ti/Pi/Ei and e/E exponents,
mirroring the accepted forms of the upstream Quantity grammar.
"""

from __future__ import annotations

from fractions import Fraction
import functools
import math
import re

_BINARY = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}
_DECIMAL = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 1000),
    "": 1,
    "k": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
}

_QTY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<num>\d+(?:\.\d*)?|\.\d+)"
    r"(?:(?P<suffix>Ki|Mi|Gi|Ti|Pi|Ei|[numkMGTPE])|[eE](?P<exp>[+-]?\d+))?$"
)


def parse_quantity(value) -> Fraction:
    """Parse a Kubernetes quantity (str/int/float) into an exact Fraction."""
    if isinstance(value, bool):
        raise ValueError(f"invalid quantity: {value!r}")
    if isinstance(value, int):
        return Fraction(value)
    if isinstance(value, float):
        return Fraction(value).limit_denominator(10**9)
    if not isinstance(value, str):
        raise ValueError(f"invalid quantity: {value!r}")
    s = value.strip()
    m = _QTY_RE.match(s)
    if not m:
        raise ValueError(f"invalid quantity: {value!r}")
    num = Fraction(m.group("num"))
    if m.group("exp") is not None:
        num *= Fraction(10) ** int(m.group("exp"))
    else:
        suffix = m.group("suffix") or ""
        if suffix in _BINARY:
            num *= _BINARY[suffix]
        else:
            num *= _DECIMAL[suffix]
    if m.group("sign") == "-":
        num = -num
    return num


def _native_parse_one(s: str):
    """(milli_ceil, milli_floor, base_ceil, base_floor) via the compiled
    parser (native/osim_native.cpp), or None when the library is unavailable
    or the value needs the exact path."""
    try:
        from ..native import parse_quantity_one
    except ImportError:
        return None
    return parse_quantity_one(s)


@functools.lru_cache(maxsize=131072)
def parse_quad(s: str) -> tuple:
    """(milli_ceil, milli_floor, base_ceil, base_floor) for a quantity string.
    Quantity strings repeat massively across pod templates, so this cache plus
    the native parser turns the ingestion hot loop from ~5µs/value into
    ~50ns/value (native cold parse: ~0.2µs)."""
    native = _native_parse_one(s)
    if native is not None:
        return native
    q = parse_quantity(s)
    m, b = q * 1000, q
    return (
        int(math.ceil(m)),
        int(math.floor(m)),
        int(math.ceil(b)),
        int(math.floor(b)),
    )


def parse_milli(value) -> int:
    """Parse a quantity and return it in milli-units, rounding up (cpu)."""
    if isinstance(value, str):
        return parse_quad(value)[0]
    return int(math.ceil(parse_quantity(value) * 1000))


def parse_int(value) -> int:
    """Parse a quantity and return the integer base value, rounding up."""
    if isinstance(value, str):
        return parse_quad(value)[2]
    return int(math.ceil(parse_quantity(value)))


def format_milli(milli: int) -> str:
    """Render a milli-quantity the way kubectl does (e.g. 1500m, 2)."""
    if milli % 1000 == 0:
        return str(milli // 1000)
    return f"{milli}m"


def format_bytes(n: int) -> str:
    """Render bytes with the largest clean binary suffix (parity with kubectl)."""
    for suffix in ("Ei", "Pi", "Ti", "Gi", "Mi", "Ki"):
        unit = _BINARY[suffix]
        if n != 0 and n % unit == 0:
            return f"{n // unit}{suffix}"
    return str(n)
