"""simon CLI: apply / server / lint / audit / preflight / prove / version /
gen-doc.

Parity: `/root/reference/cmd/` (cobra commands → argparse subcommands):
  apply   -f/--simon-config, --output-file, -i/--interactive, --use-greed,
          --extended-resources (cmd/apply/apply.go:27-32)
  server  --port (cmd/server/*; the reference binds a real cluster via
          kubeconfig — ours serves simulations over snapshots)
  version (cmd/version/version.go)
  gen-doc (cmd/doc/generate_markdown.go)
"""

from __future__ import annotations

import argparse
import os
import sys

VERSION = "0.1.0"


def _add_apply(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser(
        "apply", help="simulate deploying applications",
        description="simulate deploying applications",
    )
    p.add_argument("-f", "--simon-config", required=True, help="path of simon config")
    p.add_argument(
        "--default-scheduler-config", default="",
        help="KubeSchedulerConfiguration YAML merged with simon's plugin set",
    )
    p.add_argument("--output-file", default="", help="write the report to a file")
    p.add_argument(
        "-i", "--interactive", action="store_true",
        help="reference-style interactive add-node loop",
    )
    p.add_argument(
        "--no-auto-plan", action="store_true",
        help="disable the automatic add-node capacity search",
    )
    p.add_argument(
        "--use-greed", action="store_true",
        help="order pods by descending dominant resource share before "
        "scheduling (GreedQueue; the reference declares this flag but never "
        "wires it — here it works)",
    )
    p.add_argument(
        "--extended-resources", default="",
        help="comma list: gpu,open-local (extended report views)",
    )
    p.add_argument(
        "--devices", type=int, default=1,
        help="shard the node axis across this many JAX devices "
        "(0 = all visible devices; 1 = single-device, the default)",
    )
    p.add_argument(
        "--metrics-file", default="",
        help="after the run, write the scheduler metrics snapshot "
        "(counters/histograms, see docs/observability.md) as JSON here",
    )
    p.add_argument(
        "--run-dir", default="",
        help="journal the run into this directory (durable checkpoint: "
        "every capacity trial is committed as it completes, see "
        "docs/durability.md)",
    )
    p.add_argument(
        "--resume", nargs="?", const=True, default=False, metavar="RUN_DIR",
        help="resume a journaled run: completed trials replay from the "
        "journal instead of re-simulating (RUN_DIR defaults to --run-dir)",
    )


def _add_runs(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser(
        "runs",
        help="list, inspect, and resume journaled runs",
        description=(
            "Operate on durable run journals (docs/durability.md). "
            "`list` shows every journaled run under the runs root "
            "(OSIM_RUNS_DIR or ~/.cache/open-simulator-tpu/runs, or --root); "
            "`show` prints one run's summary and journal; `resume` re-runs "
            "an interrupted apply from its journal, re-simulating only "
            "trials the crashed run never committed."
        ),
    )
    p.add_argument(
        "action", choices=("list", "show", "resume"),
        help="list all runs / show one run / resume an interrupted apply",
    )
    p.add_argument(
        "run_dir", nargs="?", default="",
        help="run directory (required for show/resume)",
    )
    p.add_argument(
        "--root", default="",
        help="runs root for `list` (default: OSIM_RUNS_DIR or "
        "~/.cache/open-simulator-tpu/runs)",
    )
    p.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="output format",
    )
    p.add_argument(
        "-f", "--simon-config", default="",
        help="resume: config path override (default: the journaled one)",
    )


def _run_runs(args) -> int:
    import json as _json

    from ..durable import default_runs_root, list_runs, replay, summarize_run

    if args.action == "list":
        rows = list_runs(args.root or default_runs_root())
        if args.format == "json":
            print(_json.dumps(rows, indent=2, sort_keys=True))
            return 0
        if not rows:
            print(f"no journaled runs under {args.root or default_runs_root()}")
            return 0
        hdr = f"{'RUN':<28} {'KIND':<6} {'STATUS':<18} {'TRIALS':>6} {'SEGS':>4} {'DEVICE':<14} PATH"
        print(hdr)
        for r in rows:
            flag = " (cpu-fallback)" if r["fallback"] == "cpu" else ""
            print(
                f"{r['name']:<28} {r['kind']:<6} {r['status']:<18} "
                f"{r['trials']:>6} {r['segments']:>4} "
                f"{(r['device'] or '?'):<14} {r['run_dir']}{flag}"
            )
        return 0

    if not args.run_dir:
        print(f"error: `runs {args.action}` needs a run directory", file=sys.stderr)
        return 1
    summary = summarize_run(args.run_dir)
    if not summary["events"]:
        print(f"error: no journal found in {args.run_dir}", file=sys.stderr)
        return 1

    if args.action == "show":
        events = replay(args.run_dir)
        if args.format == "json":
            print(_json.dumps({"summary": summary, "events": events},
                              indent=2, sort_keys=True))
            return 0
        for k in ("run_dir", "kind", "config", "status", "outcome", "device",
                  "fallback", "events", "trials", "segments", "resumes",
                  "watchdogs"):
            print(f"{k:>10}: {summary[k]}")
        print("journal:")
        for e in events:
            extra = {k: v for k, v in e.items() if k not in ("seq", "ts", "event")}
            print(f"  [{e['seq']:>4}] {e['event']:<18} {_json.dumps(extra, sort_keys=True)}")
        return 0

    # resume: apply and sweep runs are resumable from the CLI (bench has its
    # own entry point: `python bench.py --resume RUN_DIR`)
    if summary["kind"] not in ("apply", "sweep"):
        print(
            f"error: run {args.run_dir} is kind={summary['kind'] or '?'}; "
            "`simon runs resume` handles apply and sweep runs — resume "
            "bench runs with `python bench.py --resume RUN_DIR`",
            file=sys.stderr,
        )
        return 1
    config_path = args.simon_config or summary["config"]
    if not config_path:
        print(
            "error: the journal records no config path; pass -f/--simon-config",
            file=sys.stderr,
        )
        return 1
    if summary["kind"] == "sweep":
        import argparse as _argparse

        start = (replay(args.run_dir) or [{}])[0]
        return _run_sweep(_argparse.Namespace(
            simon_config=config_path, capacity=True, node_counts="",
            use_greed=bool(start.get("use_greed")), format="text",
            run_dir=args.run_dir, resume=True,
        ))
    from ..api.config import SimonConfig
    from ..engine.apply import ApplyError, run_apply

    try:
        cfg = SimonConfig.load(config_path)
        outcome = run_apply(
            cfg, run_dir=args.run_dir, resume=True, config_path=config_path
        )
    except (ApplyError, ValueError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 0 if not outcome.result.unscheduled else 2


def _add_sweep(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser(
        "sweep",
        help="batched multi-scenario simulation (one vmapped device call)",
        description=(
            "Evaluate many what-if scenarios of one simon config through "
            "the batched scenario engine (docs/batching.md): every lane "
            "shares the encoded cluster and one compiled program, so a "
            "whole sweep costs one (or log-few) device calls instead of "
            "one simulation per scenario. `--node-counts` compares cluster "
            "sizes (each lane keeps only the first N nodes); `--capacity` "
            "runs the batched minimum-node capacity search against the "
            "config's newNode candidate, with the same journal/resume "
            "contract as `simon apply` (docs/durability.md)."
        ),
    )
    p.add_argument(
        "-f", "--simon-config", required=True, help="path of simon config"
    )
    p.add_argument(
        "--node-counts", default="",
        help="comma list of node counts; one scenario per count, each "
        "keeping only the first N cluster nodes (e.g. 4,8,16)",
    )
    p.add_argument(
        "--capacity", action="store_true",
        help="batched capacity search: minimum clones of the config's "
        "newNode so everything schedules (plan_capacity sweep_mode=batched)",
    )
    p.add_argument(
        "--use-greed", action="store_true",
        help="order pods by descending dominant resource share "
        "(forces the serial fallback for node-count sweeps: greed ordering "
        "depends on the lane's node set)",
    )
    p.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="output format",
    )
    p.add_argument(
        "--run-dir", default="",
        help="journal a --capacity sweep into this directory (each batched "
        "call commits a `sweep` record with all lane verdicts)",
    )
    p.add_argument(
        "--resume", action="store_true",
        help="resume a journaled --capacity sweep: journaled sweep records "
        "replay with zero re-run scenarios",
    )


def _run_sweep(args) -> int:
    import json as _json
    import time as _time

    from ..api.config import SimonConfig
    from ..engine.apply import (
        ApplyError,
        build_apps,
        build_cluster,
        load_new_node,
    )
    from ..engine.simulator import Scenario, simulate_batch

    try:
        cfg = SimonConfig.load(args.simon_config)
        cluster = build_cluster(cfg)
        apps = build_apps(cfg)
    except (ApplyError, ValueError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1

    if args.capacity:
        from ..engine.capacity import plan_capacity

        new_node = load_new_node(cfg)
        if new_node is None:
            print(
                "error: --capacity needs a newNode candidate in the config",
                file=sys.stderr,
            )
            return 1
        journal = None
        if args.run_dir:
            from ..durable import RunJournal

            journal = RunJournal.open(args.run_dir)
            if args.resume:
                journal.append("run_resume")
            else:
                journal.append(
                    "run_start", kind="sweep",
                    simon_config=args.simon_config,
                    use_greed=bool(args.use_greed),
                )
        elif args.resume:
            print("error: --resume needs --run-dir", file=sys.stderr)
            return 1
        t0 = _time.monotonic()
        plan = plan_capacity(
            cluster, apps, new_node, use_greed=args.use_greed,
            journal=journal, resume=args.resume, sweep_mode="batched",
        )
        wall = _time.monotonic() - t0
        if journal is not None:
            import os as _os

            from ..durable import atomic_write
            from ..engine.apply import placement_digest

            journal.append(
                "run_end",
                outcome="ok" if plan is not None else "does_not_fit",
                nodes_added=plan.nodes_added if plan else -1,
            )
            # timestamp-free snapshot (mirrors run_apply's outcome.json):
            # a SIGKILL'd-then-resumed sweep must byte-match an
            # uninterrupted one — the crash-resume smoke `cmp`s these
            atomic_write(
                _os.path.join(journal.run_dir, "outcome.json"),
                _json.dumps(
                    {
                        "outcome": "ok" if plan else "does_not_fit",
                        "kind": "sweep",
                        "nodes_added": plan.nodes_added if plan else -1,
                        "attempts": plan.attempts if plan else 0,
                        "batched_calls": plan.batched_calls if plan else 0,
                        "retries": plan.retries if plan else 0,
                        "unscheduled": (
                            len(plan.result.unscheduled) if plan else -1
                        ),
                        "placement_digest": (
                            placement_digest(plan.result) if plan else ""
                        ),
                    },
                    indent=2,
                    sort_keys=True,
                )
                + "\n",
            )
            journal.close()
        if plan is None:
            print("capacity sweep failed: workload does not fit", file=sys.stderr)
            return 2
        doc = {
            "nodes_added": plan.nodes_added,
            "attempts": plan.attempts,
            "batched_calls": plan.batched_calls,
            "retries": plan.retries,
            "wall_s": round(wall, 3),
        }
        if args.format == "json":
            print(_json.dumps(doc, indent=2, sort_keys=True))
        else:
            print(
                f"capacity sweep: add {plan.nodes_added} x {new_node.name} "
                f"({plan.attempts} scenario verdicts in "
                f"{plan.batched_calls} batched call(s), {wall:.2f}s)"
            )
        return 0

    try:
        counts = [
            int(s) for s in args.node_counts.split(",") if s.strip()
        ]
    except ValueError:
        print(
            f"error: --node-counts must be a comma list of integers, got "
            f"{args.node_counts!r}", file=sys.stderr,
        )
        return 1
    if not counts:
        print(
            "error: pass --node-counts or --capacity (nothing to sweep)",
            file=sys.stderr,
        )
        return 1
    scenarios = [Scenario(name=f"nodes-{k}", node_count=k) for k in counts]
    t0 = _time.monotonic()
    try:
        results = simulate_batch(
            cluster, apps, scenarios, use_greed=args.use_greed
        )
    except ValueError as e:  # e.g. a count outside [0, n_nodes]
        print(f"error: {e}", file=sys.stderr)
        return 1
    wall = _time.monotonic() - t0
    rows = []
    for sc, res in zip(scenarios, results):
        placed = sum(len(st.pods) for st in res.node_status)
        rows.append({
            "scenario": sc.name,
            "nodes": sc.node_count,
            "pods_placed": placed,
            "unscheduled": len(res.unscheduled),
        })
    if args.format == "json":
        print(_json.dumps(
            {"scenarios": rows, "wall_s": round(wall, 3)},
            indent=2, sort_keys=True,
        ))
    else:
        print(f"{'SCENARIO':<16} {'NODES':>6} {'PLACED':>8} {'UNSCHEDULED':>12}")
        for r in rows:
            print(
                f"{r['scenario']:<16} {r['nodes']:>6} {r['pods_placed']:>8} "
                f"{r['unscheduled']:>12}"
            )
        print(f"{len(rows)} scenario(s) in {wall:.2f}s (one batched sweep)")
    return 0 if all(r["unscheduled"] == 0 for r in rows) else 2


def _add_lint(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser(
        "lint",
        help="static analysis: AST purity/shape/dtype rules + jaxpr audit",
        description=(
            "Run the static-analysis subsystem over the installed package: "
            "the AST lint rules (tracer coercions, impure reads, dtype "
            "drift, unbucketed jit shapes) and, unless --no-jaxpr, the "
            "jaxpr auditor + recompile guard that trace the fast-path "
            "kernels on canonical bucketed shapes. Exit 0 = clean. See "
            "docs/static-analysis.md."
        ),
    )
    p.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="output format (json is the machine-readable CI artifact)",
    )
    p.add_argument(
        "--rules", default="",
        help="comma list of AST rule ids to run (default: all); "
        "see `simon lint --list-rules`",
    )
    p.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalogue and exit",
    )
    p.add_argument(
        "--no-jaxpr", action="store_true",
        help="skip the jaxpr auditor (pure-AST mode: no jax import, "
        "suitable for pre-commit hooks)",
    )
    p.add_argument(
        "--no-recompile-guard", action="store_true",
        help="skip the capacity-sweep recompile guard (the slowest stage)",
    )


def _add_chaos(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser(
        "chaos",
        help="run an apply under a deterministic fault plan and report "
        "degraded vs failed behavior",
        description=(
            "Install a fault-injection plan (docs/resilience.md), run the "
            "same simulation as `simon apply`, and print a deterministic "
            "report: which faults fired, what degraded (retries, skipped "
            "ignorable extenders, stale snapshots, failed app renders), and "
            "what failed outright (unscheduled pods, aborted runs). The "
            "report is byte-identical across runs with the same plan seed. "
            "Exit 0 when the simulation completed — even degraded; 1 when "
            "it aborted."
        ),
    )
    p.add_argument("-f", "--simon-config", required=True, help="path of simon config")
    p.add_argument(
        "--fault-plan", default="",
        help="fault plan YAML path (default: the OSIM_FAULT_PLAN env var)",
    )
    p.add_argument(
        "--default-scheduler-config", default="",
        help="KubeSchedulerConfiguration YAML merged with simon's plugin set",
    )
    p.add_argument(
        "--capacity", action="store_true",
        help="mid-plan-kill scenario: run a chunked capacity sweep under "
        "the plan's device faults (chunk_kill SIGKILLs a subprocess "
        "mid-chunk, device_lost is recovered in place), resume it, and "
        "prove the resumed placements byte-match a clean reference "
        "(docs/durability.md)",
    )
    p.add_argument(
        "--run-dir", default="",
        help="--capacity: journal the faulted sweep here (default: a "
        "temporary directory, removed afterwards)",
    )


def _run_chaos(args) -> int:
    import io as _io

    from ..api.config import SimonConfig
    from ..engine.apply import ApplyError, run_apply
    from ..resilience import faults
    from ..resilience.policy import breaker_states, reset_breakers
    from ..utils import metrics

    try:
        plan = (
            faults.FaultPlan.load(args.fault_plan)
            if args.fault_plan
            else faults.FaultPlan.from_env()
        )
    except faults.FaultInjectionError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if plan is None:
        print(
            "error: no fault plan (pass --fault-plan or set OSIM_FAULT_PLAN)",
            file=sys.stderr,
        )
        return 1

    if args.capacity:
        return _run_chaos_capacity(args, plan)

    # A clean slate makes the report a pure function of (config, plan seed):
    # same seed in -> byte-identical report out.
    metrics.REGISTRY.reset()
    reset_breakers()
    injector = faults.install_plan(plan)
    aborted = ""
    outcome = None
    try:
        cfg = SimonConfig.load(args.simon_config)
        outcome = run_apply(
            cfg,
            out=_io.StringIO(),  # the chaos report replaces the apply report
            scheduler_config=args.default_scheduler_config,
        )
    except (ApplyError, ValueError, OSError) as e:
        aborted = str(e)
        # chaos abort = the run died under injected faults: dump the flight
        # recorder so the abort leaves the same post-mortem artifact a real
        # crash would (utils/flightrec.py)
        try:
            from ..utils import flightrec

            flightrec.dump("chaos-abort", error=aborted)
        except Exception:
            pass
    finally:
        faults.uninstall_plan()

    def total(counter) -> int:
        snap = counter.snapshot()
        return int(sum(s["value"] for s in snap["samples"]))

    lines = ["simon chaos report", "=================="]
    lines.append(f"fault plan: seed={plan.seed}, {len(plan.rules)} rule(s)")
    for i, r in enumerate(injector.summary(), 1):
        lines.append(
            f"  rule {i}: target={r['target']} op={r['op'] or '*'} "
            f"kind={r['kind']} -> injected {r['injected']} of "
            f"{r['matched']} matched call(s)"
        )
    if aborted:
        lines.append(f"outcome: failed — apply aborted: {aborted}")
        print("\n".join(lines))
        return 1

    retries = total(metrics.RETRY_ATTEMPTS)
    skips = total(metrics.EXTENDER_SKIPPED)
    stale = total(metrics.SNAPSHOT_STALE)
    # Overload accounting (docs/serving.md): shedding is the admission
    # queue WORKING — every shed client got a definite 429/503 with a
    # Retry-After, so it is degradation; a drop (no response at all) is the
    # failure mode admission control exists to prevent.
    shed = total(metrics.REQUESTS_SHED)
    dropped = total(metrics.REQUESTS_DROPPED)
    # Resident-state self-healing (engine/resident.py): a repair is the
    # anti-entropy loop WORKING — the drifted/torn state was re-encoded from
    # the source of truth before answering, so it counts as degradation,
    # never as failure.
    repairs = total(metrics.RESIDENT_DRIFT_REPAIRS)
    failed_apps = sorted(fa.name for fa in outcome.failed_apps)
    not_closed = sorted(
        ep for ep, state in breaker_states().items() if state != "closed"
    )
    unscheduled = outcome.result.unscheduled
    degraded = bool(
        retries or skips or stale or failed_apps or not_closed or shed
        or repairs
    )

    lines.append("degraded:")
    lines.append(
        "  apps failed to render: "
        + (f"{len(failed_apps)} ({', '.join(failed_apps)})" if failed_apps else "0")
    )
    lines.append(f"  retries performed: {retries}")
    lines.append(f"  ignorable extenders skipped: {skips}")
    lines.append(f"  stale snapshots served: {stale}")
    lines.append(f"  requests shed with Retry-After: {shed}")
    lines.append(f"  resident drift repairs: {repairs}")
    lines.append(
        "  circuit breakers not closed: "
        + (", ".join(not_closed) if not_closed else "none")
    )
    lines.append("failed:")
    lines.append(f"  unscheduled pods: {len(unscheduled)}")
    for reason in sorted({u.reason for u in unscheduled}):
        lines.append(f"    reason: {reason}")
    lines.append(f"  requests dropped without response: {dropped}")
    if unscheduled:
        lines.append(
            "outcome: failed — pods went unscheduled under the fault plan"
        )
    elif dropped:
        lines.append(
            "outcome: failed — requests were dropped without a response"
        )
    elif degraded:
        lines.append("outcome: degraded — simulation completed under faults")
    else:
        lines.append("outcome: clean — no degradation observed")
    print("\n".join(lines))
    return 0


def _fault_plan_doc(plan) -> dict:
    """Serialize a FaultPlan back to its YAML schema (only non-default
    fields), so chaos can hand the exact plan to a subprocess via
    OSIM_FAULT_PLAN."""
    rules = []
    for r in plan.rules:
        doc: dict = {"target": r.target, "kind": r.kind}
        if r.op:
            doc["op"] = r.op
        if r.times is not None:
            doc["times"] = r.times
        if r.after:
            doc["after"] = r.after
        if r.probability != 1.0:
            doc["probability"] = r.probability
        if r.latency_s:
            doc["latency_s"] = r.latency_s
        if r.status != 503:
            doc["status"] = r.status
        if r.body:
            doc["body"] = r.body
        rules.append(doc)
    return {"seed": plan.seed, "rules": rules}


def _run_chaos_capacity(args, plan) -> int:
    """`simon chaos --capacity`: the mid-plan-kill scenario.

    Three legs: (1) a clean in-process chunked capacity sweep banks the
    reference placement digest; (2) the same sweep runs journaled in a
    subprocess under the fault plan — `chunk_kill` SIGKILLs it mid-chunk
    (the child cannot report anything; its journal and snapshots are the
    evidence), `device_lost` is recovered inside the child from its last
    good carry; (3) a killed run is resumed in-process (faults OFF —
    resume must work on a healthy host) and the final placement digest is
    compared byte-for-byte with the reference. Degraded-not-failed means:
    faults fired, the plan still landed, and the digests match (exit 0)."""
    import contextlib as _ctx
    import io as _io
    import json as _json
    import os as _os
    import shutil as _shutil
    import subprocess as _sp
    import tempfile as _tf

    import yaml as _yaml

    from ..api.config import SimonConfig
    from ..engine.apply import (
        ApplyError,
        build_apps,
        build_cluster,
        load_new_node,
        placement_digest,
    )
    from ..engine.capacity import plan_capacity
    from ..durable import replay
    from ..resilience.policy import reset_breakers
    from ..utils import metrics

    chunk = _os.environ.get("OSIM_COMMIT_CHUNK", "").strip() or "8"
    every = _os.environ.get("OSIM_CKPT_EVERY", "").strip() or "2"
    # the device-loss leg runs against the wave engine by default (the
    # new hot path): one wave per chunk record, rollback to the last
    # good wave, and the resumed digest still byte-matches the clean
    # reference. OSIM_WAVE_COMMIT=0 in the environment keeps the serial
    # chunked driver for comparison.
    wave = _os.environ.get("OSIM_WAVE_COMMIT", "").strip() or "1"
    metrics.REGISTRY.reset()
    reset_breakers()

    run_dir = args.run_dir or _tf.mkdtemp(prefix="simon-chaos-capacity-")
    cleanup = not args.run_dir
    saved = {
        k: _os.environ.get(k)
        for k in ("OSIM_COMMIT_CHUNK", "OSIM_CKPT_EVERY", "OSIM_WAVE_COMMIT")
    }
    _os.environ["OSIM_COMMIT_CHUNK"] = chunk
    _os.environ["OSIM_CKPT_EVERY"] = every
    _os.environ["OSIM_WAVE_COMMIT"] = wave
    try:
        try:
            cfg = SimonConfig.load(args.simon_config)
            cluster = build_cluster(cfg)
            apps = build_apps(cfg)
            new_node = load_new_node(cfg)
        except (ApplyError, ValueError, OSError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        if new_node is None:
            print(
                "error: chaos --capacity needs a newNode candidate in the "
                "config", file=sys.stderr,
            )
            return 1
        ref = plan_capacity(cluster, apps, new_node, sweep_mode="batched")
        if ref is None:
            print(
                "error: reference capacity sweep found no fitting plan",
                file=sys.stderr,
            )
            return 1
        ref_digest = placement_digest(ref.result)

        plan_path = _os.path.join(run_dir, "fault-plan.yaml")
        with open(plan_path, "w") as fh:
            _yaml.safe_dump(_fault_plan_doc(plan), fh, sort_keys=True)
        env = dict(_os.environ)
        env["OSIM_FAULT_PLAN"] = plan_path
        child = _sp.run(
            [sys.executable, "-m", "open_simulator_tpu.cli.main", "sweep",
             "--capacity", "-f", args.simon_config, "--run-dir", run_dir],
            env=env, stdout=_sp.DEVNULL, stderr=_sp.DEVNULL,
        )
        killed = child.returncode in (137, -9)
        if killed:
            import argparse as _argparse

            with _ctx.redirect_stdout(_io.StringIO()):
                rc = _run_sweep(_argparse.Namespace(
                    simon_config=args.simon_config, capacity=True,
                    node_counts="", use_greed=False, format="text",
                    run_dir=run_dir, resume=True,
                ))
            if rc != 0:
                print(
                    f"error: resume of the killed sweep failed (rc {rc})",
                    file=sys.stderr,
                )
                return 1
        elif child.returncode != 0:
            print(
                f"error: faulted sweep exited rc {child.returncode} "
                "(expected 0, or SIGKILL from a chunk_kill rule)",
                file=sys.stderr,
            )
            return 1

        try:
            with open(_os.path.join(run_dir, "outcome.json")) as fh:
                outcome = _json.load(fh)
        except (OSError, ValueError):
            outcome = {}
        got_digest = str(outcome.get("placement_digest", ""))

        events = replay(run_dir)
        n_chunk_records = sum(
            1 for e in events if e.get("event") == "plan_chunk"
        )

        def total(counter) -> int:
            snap = counter.snapshot()
            return int(sum(s["value"] for s in snap["samples"]))

        skipped = total(metrics.RESUME_CHUNKS_SKIPPED)
        art_kinds: dict = {}
        last_note = None
        for name in sorted(_os.listdir(run_dir)):
            if not name.startswith("flightrec-"):
                continue
            try:
                with open(_os.path.join(run_dir, name)) as fh:
                    doc = _json.load(fh)
            except (OSError, ValueError):
                continue
            reason = str(doc.get("reason", "?"))
            art_kinds[reason] = art_kinds.get(reason, 0) + 1
            for ev in doc.get("events", []):
                if ev.get("kind") in ("plan-restore", "device-lost"):
                    last_note = ev

        lines = ["simon chaos report", "=================="]
        lines.append(f"fault plan: seed={plan.seed}, {len(plan.rules)} rule(s)")
        for i, r in enumerate(plan.rules, 1):
            lines.append(
                f"  rule {i}: target={r.target} op={r.op or '*'} "
                f"kind={r.kind}"
            )
        lines.append(
            "scenario: chunked capacity sweep "
            f"(OSIM_COMMIT_CHUNK={chunk}, snapshot every {every} chunk(s), "
            f"engine={'wave' if wave != '0' else 'serial'})"
        )
        lines.append("degraded:")
        lines.append(
            "  faulted run: "
            + ("killed mid-plan (SIGKILL), resumed from checkpoint"
               if killed else
               "completed — device faults recovered in place")
        )
        lines.append(f"  plan_chunk records journaled: {n_chunk_records}")
        lines.append(f"  chunks restored from snapshot on resume: {skipped}")
        lines.append(
            f"  device_lost recoveries: {art_kinds.get('device-lost', 0)}"
        )
        if last_note is not None:
            where = last_note.get("restored_to", last_note.get("chunk"))
            lines.append(
                f"  last good chunk: {where} "
                f"(carry digest {last_note.get('digest')})"
            )
        lines.append(
            "  flight artifacts: "
            + (", ".join(f"{k}:{v}" for k, v in sorted(art_kinds.items()))
               or "none")
        )
        lines.append("failed:")
        match = bool(got_digest) and got_digest == ref_digest
        lines.append(
            "  placement digest vs clean reference: "
            + ("match" if match else "MISMATCH")
        )
        if not n_chunk_records:
            lines.append(
                "outcome: failed — the chunked commit driver never engaged "
                "(workload too small for OSIM_COMMIT_CHUNK?)"
            )
            print("\n".join(lines))
            return 1
        if not match:
            lines.append(
                "outcome: failed — resumed placements diverge from the "
                "clean reference"
            )
            print("\n".join(lines))
            return 1
        lines.append(
            "outcome: degraded — plan survived the device fault(s); "
            "placements byte-identical to the clean run"
        )
        print("\n".join(lines))
        return 0
    finally:
        for k, v in saved.items():
            if v is None:
                _os.environ.pop(k, None)
            else:
                _os.environ[k] = v
        if cleanup:
            _shutil.rmtree(run_dir, ignore_errors=True)


def _add_audit(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser(
        "audit",
        help="semantic verification: concurrency race detector + jaxpr "
        "numeric-invariant prover",
        description=(
            "Run the semantic audit passes: the lock-discipline race "
            "detector over thread-reachable code (server handlers, thread "
            "targets, signal handlers) and the abstract interpreter that "
            "re-traces every registered jit entry point, proving mask "
            "outputs stay in {0,1}, score plugins stay in [0,100], and no "
            "NaN can reach a selection primitive. Deterministic output; "
            "exit 0 = clean. The runtime companion is OSIM_SANITIZE=1. "
            "See docs/static-analysis.md."
        ),
    )
    p.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="output format (json is the machine-readable CI artifact)",
    )
    p.add_argument(
        "--no-races", action="store_true",
        help="skip the concurrency race detector",
    )
    p.add_argument(
        "--no-invariants", action="store_true",
        help="skip the jaxpr invariant prover (pure-AST mode: no jax "
        "import, suitable for pre-commit hooks)",
    )
    p.add_argument(
        "--memory", action="store_true",
        help="also run the compact memory/collective slice of the "
        "preflight matrix (canonical rung, host-available meshes); the "
        "full matrix with budget diff lives under `simon preflight`",
    )


def _run_audit(args) -> int:
    from ..analysis.audit import run_semantic_audit

    if not args.no_invariants or args.memory:
        # the invariant and memory passes trace jitted entries — pin the
        # platform the same way apply/server do before jax initializes
        from ..utils.platform import ensure_platform
        from ..utils.tracing import init_logging

        init_logging()
        ensure_platform()
    report = run_semantic_audit(
        races=not args.no_races,
        invariants=not args.no_invariants,
        memory=args.memory,
    )
    if args.format == "json":
        print(report.to_json())
    else:
        print(report.render_text())
    return 0 if report.ok else 1


def _add_interleave(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser(
        "interleave",
        help="deterministic concurrency model checker over the serving & "
        "durability protocols",
        description=(
            "Stateless model checker: run the real AdmissionQueue / "
            "SchedulerLoop / session-LRU / RunJournal / CircuitBreaker "
            "code under cooperative shim sync primitives (one runnable "
            "thread at a time, a yield at every acquire/release/wait/"
            "journal append) and exhaustively explore every interleaving "
            "of each small-scope protocol scenario within a context-"
            "switch bound, pruned by sleep-set DPOR. Safety invariants "
            "(no lost/double-dispatched ticket, fence-epoch monotonicity, "
            "no double session checkout, journal prefix-closure under "
            "crash, breaker state-machine legality) and semantic-deadlock "
            "freedom are checked on every schedule; a violation exits 1 "
            "with a ddmin-minimized, replayable schedule. "
            "See docs/static-analysis.md."
        ),
    )
    p.add_argument(
        "scenario", nargs="*", metavar="SCENARIO",
        help="scenarios to explore (default: all; see --format=json "
        "output or docs/static-analysis.md for the catalog: admission, "
        "fence, session, journal, breaker)",
    )
    p.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="output format (json is the machine-readable CI artifact)",
    )
    p.add_argument(
        "--quick", action="store_true",
        help="CI quick mode: preemption bound 1 and a smaller run budget "
        "(exhaustive within those bounds, still deterministic)",
    )
    p.add_argument(
        "--seed", type=int, default=0,
        help="exploration-order seed; same seed => byte-identical report "
        "(default: 0)",
    )
    p.add_argument(
        # keep in sync with analysis.interleave.MUTATIONS (validated
        # there too; static here so the parser stays import-light)
        "--mutate",
        choices=("double-checkout", "double-probe", "fence-regression",
                 "lost-ticket", "torn-checkpoint"),
        default=None,
        help="seeded protocol-bug injection: run the mutation's scenario "
        "with a deliberately-broken protocol; the checker must catch and "
        "minimize it (proves the checker)",
    )
    p.add_argument(
        "--replay", default=None, metavar="PATH",
        help="execute exactly one schedule from a violation's JSON "
        "schedule file instead of exploring (the concurrency-fix "
        "regression vehicle)",
    )
    p.add_argument(
        "--schedule-out", default=None, metavar="PATH",
        help="write the first violation's minimized schedule JSON here "
        "(replayable via --replay)",
    )
    p.add_argument(
        "--preemptions", type=int, default=None, metavar="N",
        help="context-switch bound override (default: 2; --quick: 1)",
    )
    p.add_argument(
        "--max-runs", type=int, default=None, metavar="N",
        help="per-scenario interleaving budget override (default: 60000; "
        "--quick: 8000)",
    )
    p.add_argument(
        "--max-steps", type=int, default=None, metavar="N",
        help="per-run scheduling-decision cap (default: 500)",
    )
    p.add_argument(
        "--no-dpor", action="store_true",
        help="disable sleep-set partial-order reduction (cross-check "
        "mode: slower, must reach the same verdicts)",
    )


def _run_interleave(args) -> int:
    import json as _json

    from ..analysis import interleave

    replay = None
    if args.replay:
        with open(args.replay) as fh:
            replay = _json.load(fh)
    try:
        report = interleave.run_interleave(
            args.scenario or None,
            seed=args.seed,
            quick=args.quick,
            mutate=args.mutate,
            preemptions=args.preemptions,
            max_runs=args.max_runs,
            max_steps=args.max_steps,
            use_dpor=not args.no_dpor,
            replay=replay,
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if args.schedule_out:
        for sc in report.scenarios:
            if sc.violations:
                sched = interleave._schedule_dict(
                    sc.violations[0], report.seed, report.mutate
                )
                with open(args.schedule_out, "w") as fh:
                    _json.dump(sched, fh, indent=2, sort_keys=True)
                    fh.write("\n")
                break
    if args.format == "json":
        print(_json.dumps(report.to_dict(), indent=2, sort_keys=True))
    else:
        print(report.render_text())
    return 0 if report.ok else 1


def _add_check(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser(
        "check",
        help="umbrella static gate: lint + audit + preflight + interleave "
        "in one SARIF 2.1.0 report",
        description=(
            "Run every static pass the repo ships — `simon lint` "
            "(syntactic contracts), `simon audit` (race detector + jaxpr "
            "invariant prover), `simon preflight` (HBM/collective budget "
            "diff), `simon interleave` (concurrency model checker) — and "
            "emit one SARIF 2.1.0 document with a run per producer, "
            "ready for a CI annotation step (e.g. "
            "github/codeql-action/upload-sarif). Exit 1 if any pass "
            "fails. Individual passes can be skipped; `--no-invariants "
            "--no-preflight` keeps the gate pure-AST + model checking "
            "(no jax import, no compiles)."
        ),
    )
    p.add_argument(
        "--format", choices=("sarif", "json", "text"), default="sarif",
        help="sarif (default) = one SARIF 2.1.0 document; json/text = "
        "the concatenated native reports",
    )
    p.add_argument(
        "--output", default=None, metavar="PATH",
        help="write the report here instead of stdout",
    )
    p.add_argument(
        "--quick", action="store_true",
        help="bound the interleave pass to its CI quick budget",
    )
    p.add_argument("--no-lint", action="store_true",
                   help="skip the lint pass")
    p.add_argument("--no-races", action="store_true",
                   help="skip the race-detector pass")
    p.add_argument("--no-invariants", action="store_true",
                   help="skip the jaxpr invariant prover (no jax import)")
    p.add_argument("--no-preflight", action="store_true",
                   help="skip the preflight budget diff (no compiles)")
    p.add_argument("--no-interleave", action="store_true",
                   help="skip the concurrency model checker")


def _run_check(args) -> int:
    import json as _json
    import os as _os

    from ..analysis import sarif as sarif_mod

    if not args.no_invariants or not args.no_preflight:
        # these passes trace/lower jitted entries — pin the platform the
        # same way `simon audit` / `simon preflight` do
        from ..utils.platform import ensure_platform
        from ..utils.tracing import init_logging

        init_logging()
        ensure_platform()

    runs = []
    texts = []
    native = {}
    ok = True

    if not args.no_lint:
        from ..analysis.lint import run_lint

        lint_report = run_lint()
        ok = ok and not lint_report.active
        runs.append(sarif_mod.lint_run(lint_report))
        native["lint"] = _json.loads(lint_report.to_json())
        texts.append(lint_report.render_text())

    if not (args.no_races and args.no_invariants):
        from ..analysis.audit import run_semantic_audit

        audit_report = run_semantic_audit(
            races=not args.no_races,
            invariants=not args.no_invariants,
            memory=False,
        )
        ok = ok and audit_report.ok
        runs.append(sarif_mod.audit_run(audit_report))
        native["audit"] = audit_report.to_dict()
        texts.append(audit_report.render_text())

    if not args.no_preflight:
        from ..analysis.budget import BudgetBook
        from ..analysis.hlo_audit import run_preflight

        budgets = "budgets/preflight.json"
        book = BudgetBook.load(budgets) if _os.path.exists(budgets) else None
        pf_report = run_preflight(book=book)
        pf_report.budgets_path = budgets
        ok = ok and pf_report.ok
        runs.append(sarif_mod.preflight_run(pf_report))
        native["preflight"] = pf_report.to_dict()
        texts.append(pf_report.render_text())

    if not args.no_interleave:
        from ..analysis import interleave

        il_report = interleave.run_interleave(quick=args.quick)
        ok = ok and il_report.ok
        runs.append(sarif_mod.interleave_run(il_report))
        native["interleave"] = il_report.to_dict()
        texts.append(il_report.render_text())

    if args.format == "sarif":
        out = _json.dumps(
            sarif_mod.sarif_document(runs), indent=2, sort_keys=True
        )
    elif args.format == "json":
        out = _json.dumps(
            {"ok": ok, "passes": native}, indent=2, sort_keys=True
        )
    else:
        texts.append(f"check: {'ok' if ok else 'FAILED'}")
        out = "\n".join(texts)
    if args.output:
        with open(args.output, "w") as fh:
            fh.write(out + "\n")
        print(f"wrote {args.output}", file=sys.stderr)
    else:
        print(out)
    return 0 if ok else 1


def _add_preflight(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser(
        "preflight",
        help="static HBM budgets + collective census over lowered programs",
        description=(
            "Pre-flight program auditor: lower-and-compile every audited "
            "jit entry at each node-ladder rung x mesh shape (on forced "
            "host devices), extract per-device argument/output/temp/peak "
            "bytes from compiled.memory_analysis() cross-checked against "
            "the shape-arithmetic estimator, census the HLO collectives "
            "(failing on node-table replication or collectives in lane-"
            "parallel programs), re-run entries under jax.transfer_guard, "
            "and diff everything against the checked-in budget book. The "
            "plan_1m_100k configuration gets a machine-checked fits-in-"
            "HBM verdict at mesh 1x4 — all without executing a single "
            "lowered program. See docs/static-analysis.md."
        ),
    )
    p.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="output format (json is the machine-readable CI artifact)",
    )
    p.add_argument(
        "--budgets", default="budgets/preflight.json",
        help="budget book to diff against (default: budgets/preflight.json)",
    )
    p.add_argument(
        "--write-budgets", action="store_true",
        help="rewrite the budget book from this run's measurements instead "
        "of diffing — the only sanctioned way to admit a memory or "
        "collective change",
    )
    p.add_argument(
        "--rungs", default="",
        help="comma-separated node-ladder rungs (default: 64,128)",
    )
    p.add_argument(
        "--meshes", default="",
        help="comma-separated mesh tags like 1,2x1,2x2 (default); meshes "
        "needing more devices than available are skipped and reported",
    )
    p.add_argument(
        "--entries", default="",
        help="comma-separated audit names (e.g. ops.fast:schedule_scenarios)"
        " to restrict the matrix; default: every captured entry",
    )
    p.add_argument(
        "--no-transfers", action="store_true",
        help="skip the transfer-guard audit (the one pass that executes "
        "programs; without it the preflight is fully static)",
    )
    p.add_argument(
        "--no-verdict", action="store_true",
        help="skip the plan_1m_100k fits-in-HBM verdict compile",
    )
    p.add_argument(
        "--hbm-gib", type=float, default=32.0,
        help="per-device HBM budget for the verdict (default: 32 GiB)",
    )


def _run_preflight(args) -> int:
    import json as _json
    import os as _os

    from ..analysis.budget import BudgetBook
    from ..analysis.hlo_audit import run_preflight

    book = None
    if not args.write_budgets and _os.path.exists(args.budgets):
        book = BudgetBook.load(args.budgets)
    rungs = [int(r) for r in args.rungs.split(",") if r.strip()] or None
    meshes = [m.strip() for m in args.meshes.split(",") if m.strip()] or None
    entries = [e.strip() for e in args.entries.split(",") if e.strip()] or None
    report = run_preflight(
        rungs=rungs, meshes=meshes, entries=entries, book=book,
        transfers=not args.no_transfers, verdict=not args.no_verdict,
        hbm_gib=args.hbm_gib,
    )
    report.budgets_path = args.budgets
    if args.write_budgets:
        base = (
            BudgetBook.load(args.budgets)
            if _os.path.exists(args.budgets) else None
        )
        report.to_book(base).save(args.budgets)
        print(f"wrote {args.budgets}", file=sys.stderr)
    if args.format == "json":
        print(_json.dumps(report.to_dict(), indent=2, sort_keys=True))
    else:
        print(report.render_text())
    return 0 if report.ok else 1


def _add_prove(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser(
        "prove",
        help="exhaustive small-scope semantics check against the pure "
        "oracle + commit-order contract verification",
        description=(
            "Small-scope semantics prover: enumerate EVERY scheduling "
            "universe in a bounded family (4 node slots x 5 pod slots "
            "drawn from a quantized catalog — 151,875 distinct universes), "
            "run the real ops.fast:schedule_universes engine over all of "
            "them in a handful of identically-shaped vmapped device calls, "
            "and diff every placement, reason code, GPU assignment and "
            "final carry against the independent pure-numpy oracle "
            "(analysis/oracle.py). Full runs also verify the canonical "
            "commit-order contract (budgets/commit_contract.json) that "
            "the conflict-parallel wave commit must reproduce; any "
            "divergence exits 1 with a minimized counterexample universe. "
            "See docs/static-analysis.md."
        ),
    )
    p.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="output format (json is the machine-readable CI artifact)",
    )
    p.add_argument(
        "--contract", default=None, metavar="PATH",
        help="contract artifact to verify "
        "(default: budgets/commit_contract.json)",
    )
    p.add_argument(
        "--write-contract", action="store_true",
        help="bank this run's placement digest as the canonical contract "
        "instead of verifying — the only sanctioned way to admit a "
        "commit-order change (refused over a diverging corpus)",
    )
    p.add_argument(
        "--smoke", type=int, default=None, metavar="N",
        help="check only N universes strided across the corpus (engine vs "
        "oracle only; the digest is sample-dependent, so no contract "
        "verdict)",
    )
    p.add_argument(
        "--chunk", type=int, default=None, metavar="S",
        help="universes per device call (default: 25608 — six calls, one "
        "compile for the full corpus)",
    )
    p.add_argument(
        "--mutate", choices=("tiebreak", "nocommit"), default=None,
        help="seeded commit-rule fault injection: run a deliberately-wrong "
        "engine variant; the checker must exit nonzero with a minimized "
        "counterexample (proves the prover)",
    )
    p.add_argument(
        "--engine", choices=("serial", "wave"), default="serial",
        help="scheduling engine to prove: the serial scan "
        "(ops.fast:schedule_universes, default) or the conflict-parallel "
        "wave engine (ops/wave.py) — both must reproduce the SAME banked "
        "placement digest; a passing wave run is its admission proof "
        "under the commit-order contract",
    )


def _run_prove(args) -> int:
    import json as _json

    from ..analysis import semantics

    report = semantics.run_prove(
        contract_path=args.contract or semantics.CONTRACT_PATH,
        write=args.write_contract,
        smoke=args.smoke,
        chunk=args.chunk or semantics.DEFAULT_CHUNK,
        mutate=args.mutate,
        engine=args.engine,
        progress=(
            (lambda done, total: print(
                f"prove: {done}/{total} universes", file=sys.stderr
            ))
            if args.format == "text" else None
        ),
    )
    if args.format == "json":
        print(_json.dumps(report.to_dict(), indent=2, sort_keys=True))
    else:
        print(report.render_text())
    return 0 if report.ok else 1


def _add_warmup(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser(
        "warmup",
        help="AOT-compile every audited jit entry into the persistent "
        "compilation cache",
        description=(
            "Compile lifecycle as a phase, not a side effect: enumerate "
            "the audited jit entries (the same set the jaxpr audit proves "
            "over) at their canonical bucketed shapes, drive each through "
            "trace().lower().compile(), and rehearse the full capacity "
            "sweep so every program the engine needs lands in the "
            "persistent compilation cache (OSIM_COMPILE_CACHE) before "
            "anything is being timed or deadlined. A later process "
            "sharing the cache then pays zero cold compiles — "
            "`simon warmup --check` asserts exactly that and exits "
            "nonzero otherwise. See docs/performance.md."
        ),
    )
    p.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="output format (json is the machine-readable CI artifact)",
    )
    p.add_argument(
        "--no-sweep", action="store_true",
        help="skip the capacity-sweep rehearsal (registry entries only; "
        "the zero-cold-compile guarantee then covers only the audited "
        "registry programs)",
    )
    p.add_argument(
        "--check", action="store_true",
        help="run the warm-start check instead of warming: re-run the "
        "full capacity sweep and demand ZERO cold compiles (exit 1 "
        "otherwise); run after `simon warmup` in a process sharing "
        "OSIM_COMPILE_CACHE",
    )


def _run_warmup(args) -> int:
    import json as _json

    if args.check:
        from ..analysis.jaxpr_audit import warm_start_check

        result = warm_start_check()
    else:
        from ..engine.warmup import run_warmup

        result = run_warmup(include_sweep=not args.no_sweep)
    if args.format == "json":
        print(_json.dumps(result.to_dict(), indent=2, sort_keys=True))
    else:
        print(result.render_text())
    return 0 if result.ok else 1


def _add_profile(sub: argparse._SubParsersAction) -> None:
    p = sub.add_parser(
        "profile",
        help="capture a device trace and/or run the dispatch-gap analyzer",
        description=(
            "Device-time profiling (docs/observability.md). With a trailing "
            "simon command (`simon profile -- apply -f cfg.yaml`), run it "
            "under a jax.profiler device trace written to --out "
            "(Perfetto/TensorBoard-loadable). Without a command — or with "
            "--gaps — time every audited jit entry at its canonical shapes "
            "with the block_until_ready sandwich and report per-entry "
            "device time plus the dispatch-gap ratio (the fraction of wall "
            "time the device sat idle waiting for the host), published as "
            "osim_device_time_seconds / osim_dispatch_gap_ratio."
        ),
    )
    p.add_argument(
        "--out", default="",
        help="device-trace output directory (default: "
        "<runs root>/device-profile)",
    )
    p.add_argument(
        "--gaps", action="store_true",
        help="also run the dispatch-gap analyzer after the traced command "
        "(implied when no command is given)",
    )
    p.add_argument(
        "--entries", default="",
        help="comma-separated audit entry names to analyze (default: every "
        "registry entry; names as in `simon audit`)",
    )
    p.add_argument(
        "--repeats", type=int, default=3,
        help="timed repeats per entry, keeping the fastest (default 3)",
    )
    p.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="output format (json is the machine-readable artifact)",
    )
    p.add_argument(
        "cmd", nargs=argparse.REMAINDER, metavar="command",
        help="simon command to run under the device trace, e.g. "
        "`simon profile -- apply -f cfg.yaml`",
    )


def _run_profile(args) -> int:
    import json as _json

    from ..durable import default_runs_root
    from ..utils.profiling import analyze_dispatch_gaps, capture_device_trace

    cmd = [c for c in args.cmd if c != "--"]
    report: dict = {}
    rc = 0
    if cmd:
        out_dir = args.out or os.path.join(
            default_runs_root(), "device-profile"
        )
        rc_box: list = []
        report["trace"] = capture_device_trace(
            out_dir, fn=lambda: rc_box.append(main(cmd))
        )
        rc = rc_box[0] if rc_box else 1
        if not report["trace"].get("ok"):
            rc = rc or 1
    gaps = None
    if args.gaps or not cmd:
        names = [
            s.strip() for s in args.entries.split(",") if s.strip()
        ] or None
        try:
            gaps = analyze_dispatch_gaps(names=names, repeats=args.repeats)
        except KeyError as e:
            print(f"error: unknown audit entry {e}", file=sys.stderr)
            return 1
        report["dispatch_gaps"] = gaps.to_dict()
    if args.format == "json":
        print(_json.dumps(report, indent=2, sort_keys=True))
    else:
        if "trace" in report:
            t = report["trace"]
            state = "ok" if t.get("ok") else f"failed: {t.get('error')}"
            print(f"device trace ({state}): {t.get('trace_dir')}")
        if gaps is not None:
            print(gaps.render_text())
    return rc


def _run_lint(args) -> int:
    import json as _json

    from ..analysis import iter_rules, run_lint

    if args.list_rules:
        for rid, doc in iter_rules():
            print(f"{rid}: {doc}")
        return 0
    only = [r.strip() for r in args.rules.split(",") if r.strip()] or None
    known = {rid for rid, _ in iter_rules()}
    unknown = set(only or ()) - known
    if unknown:
        print(f"error: unknown rule(s) {sorted(unknown)}", file=sys.stderr)
        return 1
    report = run_lint(only_rules=only)
    audit = guard = None
    if not args.no_jaxpr:
        from ..utils.platform import ensure_platform

        ensure_platform()
        from ..analysis.jaxpr_audit import run_audit, run_recompile_guard

        audit = run_audit()
        if not args.no_recompile_guard:
            guard = run_recompile_guard()
    ok = (
        not report.active
        and (audit is None or audit.ok)
        and (guard is None or guard.ok)
    )
    if args.format == "json":
        doc = _json.loads(report.to_json())
        doc["jaxpr_audit"] = audit.to_dict() if audit is not None else None
        doc["recompile_guard"] = guard.to_dict() if guard is not None else None
        doc["ok"] = ok
        print(_json.dumps(doc, indent=2, sort_keys=True))
    else:
        print(report.render_text())
        if audit is not None:
            print(audit.render_text())
        if guard is not None:
            print(guard.render_text())
    return 0 if ok else 1


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    parser = argparse.ArgumentParser(
        prog="simon",
        description="TPU-native cluster scheduling simulator (open-simulator capabilities)",
    )
    sub = parser.add_subparsers(dest="command")
    _add_apply(sub)
    _add_audit(sub)
    _add_chaos(sub)
    _add_check(sub)
    _add_interleave(sub)
    _add_lint(sub)
    _add_preflight(sub)
    _add_profile(sub)
    _add_prove(sub)
    _add_runs(sub)
    _add_sweep(sub)
    _add_warmup(sub)
    ps = sub.add_parser(
        "server", help="run the REST simulation service",
        description="run the REST simulation service",
    )
    ps.add_argument("--port", type=int, default=9998)
    ps.add_argument(
        "--kubeconfig", default="",
        help="snapshot this cluster per request when the request body carries "
        "no cluster spec",
    )
    ps.add_argument(
        "--master", default="",
        help="apiserver URL overriding the kubeconfig's server "
        "(cmd/server/options.go parity)",
    )
    ps.add_argument(
        "--queue-depth", type=int, default=None,
        help="admission queue depth before 429 shedding "
        "(default: OSIM_SERVER_QUEUE_DEPTH or 16; docs/serving.md)",
    )
    ps.add_argument(
        "--pack-window-ms", type=float, default=None,
        help="upper bound on how long the scheduler loop holds a PARTIAL "
        "pack open for stragglers; lone requests and full packs always "
        "dispatch immediately (default: OSIM_SERVER_PACK_WINDOW_MS or 0)",
    )
    ps.add_argument(
        "--coalesce-ms", type=float, default=None,
        help="DEPRECATED alias for --pack-window-ms (the fixed coalescing "
        "window became the pack-window upper bound of the continuous-"
        "batching loop; OSIM_SERVER_COALESCE_MS still works, with a "
        "warning — see docs/serving.md migration note)",
    )
    ps.add_argument(
        "--default-deadline-ms", type=float, default=None,
        help="deadline applied to requests without an X-Osim-Deadline-Ms "
        "header (default: OSIM_SERVER_DEFAULT_DEADLINE_MS or 0 = none)",
    )
    sub.add_parser(
        "version", help="print version", description="print version"
    )
    pd = sub.add_parser(
        "gen-doc", help="generate CLI markdown docs",
        description="generate CLI markdown docs",
    )
    pd.add_argument("--output-dir", default="./docs/commandline")

    args = parser.parse_args(argv)
    if args.command == "preflight" or (
        args.command == "audit" and getattr(args, "memory", False)
    ) or (
        args.command == "check" and not getattr(args, "no_preflight", False)
    ):
        # the mesh matrix (2x1/2x2) and the 1x4 verdict need multiple
        # devices; force host devices BEFORE jax initializes (no-op when
        # the caller already set the flag or runs on real hardware)
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    if args.command in (
        "apply", "chaos", "server", "runs", "sweep", "warmup", "preflight",
        "profile", "prove",
    ):
        from ..utils.platform import enable_compilation_cache, ensure_platform
        from ..utils.tracing import init_logging

        init_logging()  # LogLevel env, parity: cmd/simon/simon.go:46-66
        ensure_platform()
        enable_compilation_cache()
        # crash flight recorder: any unhandled crash of a device-touching
        # command dumps the recent-span/metric/journal ring first
        # (utils/flightrec.py; idempotent, safe under nested main() calls)
        from ..utils import flightrec

        flightrec.install_crash_hook()
    if args.command in ("apply", "server", "runs", "sweep"):
        # honor OSIM_FAULT_PLAN for non-chaos entry points too (chaos does
        # its own install): docs/resilience.md promises env-driven plans,
        # and the crash-resume smoke injects its deterministic SIGKILL into
        # a plain `simon apply` this way
        from ..resilience import faults

        try:
            plan = faults.FaultPlan.from_env()
        except faults.FaultInjectionError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        if plan is not None:
            faults.install_plan(plan)
    if args.command == "version":
        print(f"simon-tpu version {VERSION}")
        return 0
    if args.command == "chaos":
        return _run_chaos(args)
    if args.command == "runs":
        return _run_runs(args)
    if args.command == "audit":
        return _run_audit(args)
    if args.command == "check":
        return _run_check(args)
    if args.command == "interleave":
        return _run_interleave(args)
    if args.command == "preflight":
        return _run_preflight(args)
    if args.command == "lint":
        return _run_lint(args)
    if args.command == "sweep":
        return _run_sweep(args)
    if args.command == "warmup":
        return _run_warmup(args)
    if args.command == "profile":
        return _run_profile(args)
    if args.command == "prove":
        return _run_prove(args)
    if args.command == "gen-doc":
        return _gen_doc(parser, args.output_dir)
    if args.command == "server":
        from ..server.server import serve

        return serve(
            port=args.port,
            kubeconfig=args.kubeconfig,
            master=args.master,
            queue_depth=args.queue_depth,
            coalesce_ms=args.coalesce_ms,
            pack_window_ms=args.pack_window_ms,
            default_deadline_ms=args.default_deadline_ms,
        )
    if args.command == "apply":
        from ..api.config import SimonConfig
        from ..engine.apply import ApplyError, run_apply

        try:
            cfg = SimonConfig.load(args.simon_config)
            out = open(args.output_file, "w") if args.output_file else None
            try:
                ext = (
                    [s.strip() for s in args.extended_resources.split(",") if s.strip()]
                    if args.extended_resources
                    else None
                )
                unknown = set(ext or ()) - {"gpu", "open-local"}
                if unknown:
                    raise ApplyError(
                        f"--extended-resources: unknown resource(s) "
                        f"{sorted(unknown)}; expected gpu, open-local"
                    )
                run_dir = args.run_dir or (
                    args.resume if isinstance(args.resume, str) else ""
                )
                if args.resume and not run_dir:
                    raise ApplyError(
                        "--resume needs a run dir (inline or --run-dir)"
                    )
                outcome = run_apply(
                    cfg,
                    interactive=args.interactive,
                    auto_plan=not args.no_auto_plan,
                    out=out,
                    scheduler_config=args.default_scheduler_config,
                    use_greed=args.use_greed,
                    devices=args.devices,
                    extended_resources=ext,
                    run_dir=run_dir,
                    resume=bool(args.resume),
                    config_path=args.simon_config,
                )
            finally:
                if out is not None:
                    out.close()
            if args.metrics_file:
                import json

                from ..utils.metrics import REGISTRY

                with open(args.metrics_file, "w") as fh:
                    json.dump(REGISTRY.snapshot(), fh, indent=2)
        except (ApplyError, ValueError, OSError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        return 0 if not outcome.result.unscheduled else 2
    parser.print_help()
    return 0


def _gen_doc(parser: argparse.ArgumentParser, output_dir: str) -> int:
    """Markdown docs, one file per command like cobra's doc generator
    (parity: cmd/doc/generate_markdown.go:38 — GenMarkdownTree emits
    simon.md + simon_<sub>.md with cross-links)."""
    os.makedirs(output_dir, exist_ok=True)
    sub_actions = [
        a for a in parser._actions
        if isinstance(a, argparse._SubParsersAction)
    ]
    commands = dict(sub_actions[0].choices) if sub_actions else {}

    written = []
    root = os.path.join(output_dir, "simon.md")
    with open(root, "w") as fh:
        fh.write("## simon\n\n")
        fh.write(f"{parser.description}\n\n")
        fh.write("```\n" + parser.format_help() + "```\n\n")
        if commands:
            fh.write("### SEE ALSO\n\n")
            for name, sp in commands.items():
                help_line = (sp.description or "").strip()
                fh.write(
                    f"* [simon {name}](simon_{name}.md)"
                    + (f" — {help_line}" if help_line else "")
                    + "\n"
                )
    written.append(root)

    for name, sp in commands.items():
        path = os.path.join(output_dir, f"simon_{name}.md")
        with open(path, "w") as fh:
            fh.write(f"## simon {name}\n\n")
            if sp.description:
                fh.write(f"{sp.description}\n\n")
            fh.write("```\n" + sp.format_help() + "```\n\n")
            fh.write("### SEE ALSO\n\n* [simon](simon.md)\n")
        written.append(path)
    for path in written:
        print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
