"""Pure-Python scheduling predicates — the host-side reference semantics.

These mirror the vendored kube-scheduler plugin predicates
(`/root/reference/vendor/k8s.io/kubernetes/pkg/scheduler/framework/plugins/*`)
and serve three roles:
 1. DaemonSet eligibility during pod synthesis (parity with the daemon
    controller `Predicates`, `vendor/.../daemon/daemon_controller.go:1251`).
 2. The oracle that tests the TPU kernels in `ops/` against.
 3. Fallback path for constructs the tensor encoding cannot express.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from .objects import (
    LabelSelector,
    LabelSelectorRequirement,
    Node,
    NodeSelectorTerm,
    Pod,
    Taint,
    Toleration,
)


def _match_expression(labels: Dict[str, str], e: LabelSelectorRequirement) -> bool:
    val = labels.get(e.key)
    if e.operator == "In":
        return val is not None and val in e.values
    if e.operator == "NotIn":
        return val is None or val not in e.values
    if e.operator == "Exists":
        return val is not None
    if e.operator == "DoesNotExist":
        return val is None
    if e.operator in ("Gt", "Lt"):
        if val is None or not e.values:
            return False
        try:
            lhs, rhs = int(val), int(e.values[0])
        except ValueError:
            return False
        return lhs > rhs if e.operator == "Gt" else lhs < rhs
    return False


def match_label_selector(selector: Optional[LabelSelector], labels: Dict[str, str]) -> bool:
    """metav1.LabelSelector semantics; a nil selector matches nothing, an empty
    selector matches everything (upstream labels.Selector behavior)."""
    if selector is None:
        return False
    for k, v in selector.match_labels.items():
        if labels.get(k) != v:
            return False
    for e in selector.match_expressions:
        if not _match_expression(labels, e):
            return False
    return True


def match_node_selector_term(term: NodeSelectorTerm, labels: Dict[str, str]) -> bool:
    """One NodeSelectorTerm: AND of its expressions. Empty term matches nothing
    (parity with upstream nodeaffinity helpers)."""
    if not term.match_expressions:
        return False
    return all(_match_expression(labels, e) for e in term.match_expressions)


def match_node_affinity(pod: Pod, node: Node) -> bool:
    """Required node affinity + plain nodeSelector (NodeAffinity filter plugin)."""
    labels = node.meta.labels
    for k, v in pod.node_selector.items():
        if labels.get(k) != v:
            return False
    terms = pod.affinity.node_required
    if terms:
        if not any(match_node_selector_term(t, labels) for t in terms):
            return False
    return True


def toleration_tolerates(t: Toleration, taint: Taint) -> bool:
    """Upstream Toleration.ToleratesTaint: an empty key matches every taint key;
    an empty operator means Equal."""
    if t.effect and t.effect != taint.effect:
        return False
    if t.key and t.key != taint.key:
        return False
    if t.operator == "Exists":
        return True
    if t.operator in ("", "Equal"):
        return t.value == taint.value
    return False


def tolerations_tolerate_taint(tolerations: Iterable[Toleration], taint: Taint) -> bool:
    return any(toleration_tolerates(t, taint) for t in tolerations)


_WILDCARD_IPS = ("", "0.0.0.0")


def ports_conflict(
    want: Iterable[tuple], used: Iterable[tuple]
) -> bool:
    """NodePorts conflict oracle (vendored node_ports.go Fits): two
    (protocol, port, hostIP) entries clash iff protocol and port match and
    either hostIP is the wildcard or they are equal."""
    for wp, wport, wip in want:
        for up, uport, uip in used:
            if wp != up or wport != uport:
                continue
            if wip in _WILDCARD_IPS or uip in _WILDCARD_IPS or wip == uip:
                return True
    return False


def untolerated_taint(pod_tolerations: List[Toleration], node: Node) -> Optional[Taint]:
    """First NoSchedule/NoExecute taint not tolerated (TaintToleration filter)."""
    for taint in node.taints:
        if taint.effect not in ("NoSchedule", "NoExecute"):
            continue
        if not tolerations_tolerate_taint(pod_tolerations, taint):
            return taint
    return None


def count_intolerable_prefer_no_schedule(pod: Pod, node: Node) -> int:
    """TaintToleration score input: intolerable PreferNoSchedule taints."""
    n = 0
    for taint in node.taints:
        if taint.effect == "PreferNoSchedule":
            if not tolerations_tolerate_taint(pod.tolerations, taint):
                n += 1
    return n


def node_affinity_preferred_score(pod: Pod, node: Node) -> int:
    """Sum of matching preferred node-affinity term weights (NodeAffinity score)."""
    total = 0
    for pref in pod.affinity.node_preferred:
        if match_node_selector_term(pref.preference, node.meta.labels):
            total += pref.weight
    return total


def fits_resources(pod: Pod, free: Dict[str, int]) -> List[str]:
    """NodeResourcesFit: returns the list of insufficient resource names."""
    bad = []
    for name, req in pod.requests.items():
        if req <= 0:
            continue
        if req > free.get(name, 0):
            bad.append(name)
    return bad


def daemonset_should_run(pod: Pod, node: Node) -> bool:
    """Should a DaemonSet pod run on this node?

    Parity with `utils.NodeShouldRunPod` / the daemon controller Predicates
    (`/root/reference/pkg/utils/utils.go:325-366`): node affinity + taints with
    the auto-added unschedulable toleration. Resources are NOT checked here —
    the scheduler decides that later.
    """
    if pod.node_name and pod.node_name != node.name:
        return False
    if not match_node_affinity(pod, node):
        return False
    tols = list(pod.tolerations) + [
        Toleration(key="node.kubernetes.io/unschedulable", operator="Exists", effect="NoSchedule"),
        Toleration(key="node.kubernetes.io/not-ready", operator="Exists", effect="NoExecute"),
        Toleration(key="node.kubernetes.io/unreachable", operator="Exists", effect="NoExecute"),
        Toleration(key="node.kubernetes.io/disk-pressure", operator="Exists", effect="NoSchedule"),
        Toleration(key="node.kubernetes.io/memory-pressure", operator="Exists", effect="NoSchedule"),
        Toleration(key="node.kubernetes.io/pid-pressure", operator="Exists", effect="NoSchedule"),
    ]
    return untolerated_taint(tols, node) is None
