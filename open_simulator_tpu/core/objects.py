"""K8s-lite object model: the host-side representation of cluster state.

This replaces the reference's reliance on `k8s.io/api/core/v1` typed objects and
the fake clientset object store (`/root/reference/pkg/simulator/simulator.go:103`,
`vendor/k8s.io/client-go/kubernetes/fake`). We keep lightweight dataclasses with
only the scheduling-relevant fields, plus the original decoded dict in `raw` so
reports and round-tripping stay faithful.

All resource amounts are canonicalized at parse time:
  cpu            -> millicores (int)
  memory, ephemeral-storage, hugepages-*  -> bytes (int)
  pods and extended resources (counts)    -> plain int
"""

from __future__ import annotations

from dataclasses import dataclass, field
import math
from typing import Dict, List, Optional, Tuple

from ..utils.quantity import parse_quad, parse_quantity

# Canonical resource names (mirrors corev1.ResourceName constants).
CPU = "cpu"
MEMORY = "memory"
PODS = "pods"
EPHEMERAL_STORAGE = "ephemeral-storage"

# simon annotation/label names (parity: /root/reference/pkg/type/const.go:12-43)
ANNO_WORKLOAD_KIND = "simon/workload-kind"
ANNO_WORKLOAD_NAME = "simon/workload-name"
ANNO_WORKLOAD_NAMESPACE = "simon/workload-namespace"
ANNO_NODE_LOCAL_STORAGE = "simon/node-local-storage"
ANNO_POD_LOCAL_STORAGE = "simon/pod-local-storage"
ANNO_NODE_GPU_SHARE = "simon/node-gpu-share"
ANNO_POD_PROVISIONER = "simon/pod-provisioner"
LABEL_NEW_NODE = "simon/new-node"
LABEL_APP_NAME = "simon/app-name"

# open-gpu-share annotation keys (parity: pkg/type/open-gpu-share/utils/const.go:4-8)
ANNO_GPU_MEM_POD = "alibabacloud.com/gpu-mem"
ANNO_GPU_COUNT_POD = "alibabacloud.com/gpu-count"
ANNO_GPU_INDEX = "alibabacloud.com/gpu-index"
ANNO_GPU_COUNT_NODE = "alibabacloud.com/gpu-count"
ANNO_GPU_MODEL_NODE = "alibabacloud.com/gpu-card-model"
RESOURCE_GPU_COUNT = "alibabacloud.com/gpu-count"

DEFAULT_SCHEDULER = "default-scheduler"

# open-local / yoda storage-class name table (parity: pkg/utils/const.go:3-17).
# LVM membership mirrors GetPodLocalPVCs (pkg/utils/utils.go:598-607): only the
# two LVM class names route to the VG path; every other known class is an
# exclusive-device request.
LVM_SC_NAMES = {"open-local-lvm", "yoda-lvm-default"}
SSD_SC_NAMES = {
    "open-local-device-ssd",
    "open-local-mountpoint-ssd",
    "yoda-mountpoint-ssd",
    "yoda-device-ssd",
}
HDD_SC_NAMES = {
    "open-local-device-hdd",
    "open-local-mountpoint-hdd",
    "yoda-mountpoint-hdd",
    "yoda-device-hdd",
}


def _canon_resources(res: Optional[dict], round_up: bool) -> Dict[str, int]:
    """Canonicalize a resource map. round_up for requests (conservative: a pod
    never claims less than it asked), down for node allocatable."""
    out: Dict[str, int] = {}
    if not res:
        return out
    rounder = math.ceil if round_up else math.floor
    for name, val in res.items():
        if isinstance(val, str):
            # cached/native fast path (utils.quantity.parse_quad)
            mc, mf, bc, bf = parse_quad(val)
            if name == CPU:
                out[str(name)] = mc if round_up else mf
            else:
                out[str(name)] = bc if round_up else bf
            continue
        q = parse_quantity(val)
        if name == CPU:
            q *= 1000
        out[str(name)] = int(rounder(q))
    return out


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    owner_kind: str = ""
    owner_name: str = ""

    @staticmethod
    def from_dict(d: Optional[dict]) -> "ObjectMeta":
        d = d or {}
        owner_kind = owner_name = ""
        owners = d.get("ownerReferences") or []
        if owners:
            owner_kind = owners[0].get("kind", "")
            owner_name = owners[0].get("name", "")
        return ObjectMeta(
            name=d.get("name", "") or d.get("generateName", ""),
            namespace=d.get("namespace") or "default",
            labels=dict(d.get("labels") or {}),
            annotations={k: str(v) for k, v in (d.get("annotations") or {}).items()},
            owner_kind=owner_kind,
            owner_name=owner_name,
        )


@dataclass
class Toleration:
    key: str = ""          # empty key + Exists tolerates everything
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""       # empty matches all effects

    @staticmethod
    def from_dict(d: dict) -> "Toleration":
        # An empty operator means Equal (vendored toleration.go ToleratesTaint).
        return Toleration(
            key=d.get("key", "") or "",
            operator=d.get("operator") or "Equal",
            value=str(d.get("value", "") or ""),
            effect=d.get("effect", "") or "",
        )


@dataclass
class Taint:
    key: str
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute

    @staticmethod
    def from_dict(d: dict) -> "Taint":
        return Taint(
            key=d.get("key", ""),
            value=str(d.get("value", "") or ""),
            effect=d.get("effect", "NoSchedule"),
        )


@dataclass
class LabelSelectorRequirement:
    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: List[str] = field(default_factory=list)


@dataclass
class LabelSelector:
    """metav1.LabelSelector: matchLabels AND matchExpressions."""
    match_labels: Dict[str, str] = field(default_factory=dict)
    match_expressions: List[LabelSelectorRequirement] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Optional[dict]) -> Optional["LabelSelector"]:
        if d is None:
            return None
        exprs = [
            LabelSelectorRequirement(
                key=e.get("key", ""),
                operator=e.get("operator", "In"),
                values=[str(v) for v in (e.get("values") or [])],
            )
            for e in (d.get("matchExpressions") or [])
        ]
        return LabelSelector(
            match_labels={k: str(v) for k, v in (d.get("matchLabels") or {}).items()},
            match_expressions=exprs,
        )

    def key(self) -> Tuple:
        """Hashable identity used to dedupe selectors during tensorization."""
        return (
            tuple(sorted(self.match_labels.items())),
            tuple((e.key, e.operator, tuple(e.values)) for e in self.match_expressions),
        )


@dataclass
class NodeSelectorTerm:
    """One term: AND of requirements over labels (and fields, which we fold in)."""
    match_expressions: List[LabelSelectorRequirement] = field(default_factory=list)

    @staticmethod
    def from_dict(d: dict) -> "NodeSelectorTerm":
        exprs = []
        for part in ("matchExpressions", "matchFields"):
            for e in d.get(part) or []:
                key = e.get("key", "")
                if part == "matchFields" and key == "metadata.name":
                    key = "kubernetes.io/hostname"  # field selector on name ~ hostname label
                exprs.append(
                    LabelSelectorRequirement(
                        key=key,
                        operator=e.get("operator", "In"),
                        values=[str(v) for v in (e.get("values") or [])],
                    )
                )
        return NodeSelectorTerm(match_expressions=exprs)


@dataclass
class PreferredSchedulingTerm:
    weight: int
    preference: NodeSelectorTerm


@dataclass
class PodAffinityTerm:
    selector: Optional[LabelSelector]
    topology_key: str
    namespaces: List[str] = field(default_factory=list)

    @staticmethod
    def from_dict(d: dict) -> "PodAffinityTerm":
        return PodAffinityTerm(
            selector=LabelSelector.from_dict(d.get("labelSelector")),
            topology_key=d.get("topologyKey", ""),
            namespaces=list(d.get("namespaces") or []),
        )


@dataclass
class WeightedPodAffinityTerm:
    weight: int
    term: PodAffinityTerm


@dataclass
class Affinity:
    # node affinity
    node_required: List[NodeSelectorTerm] = field(default_factory=list)   # OR of terms
    node_preferred: List[PreferredSchedulingTerm] = field(default_factory=list)
    # pod (anti) affinity
    pod_required: List[PodAffinityTerm] = field(default_factory=list)
    pod_preferred: List[WeightedPodAffinityTerm] = field(default_factory=list)
    anti_required: List[PodAffinityTerm] = field(default_factory=list)
    anti_preferred: List[WeightedPodAffinityTerm] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Optional[dict]) -> "Affinity":
        a = Affinity()
        if not d:
            return a
        na = d.get("nodeAffinity") or {}
        req = na.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
        a.node_required = [
            NodeSelectorTerm.from_dict(t) for t in (req.get("nodeSelectorTerms") or [])
        ]
        a.node_preferred = [
            PreferredSchedulingTerm(
                weight=int(t.get("weight", 1)),
                preference=NodeSelectorTerm.from_dict(t.get("preference") or {}),
            )
            for t in (na.get("preferredDuringSchedulingIgnoredDuringExecution") or [])
        ]
        for src, req_dst, pref_dst in (
            ("podAffinity", "pod_required", "pod_preferred"),
            ("podAntiAffinity", "anti_required", "anti_preferred"),
        ):
            pa = d.get(src) or {}
            setattr(
                a,
                req_dst,
                [
                    PodAffinityTerm.from_dict(t)
                    for t in (pa.get("requiredDuringSchedulingIgnoredDuringExecution") or [])
                ],
            )
            setattr(
                a,
                pref_dst,
                [
                    WeightedPodAffinityTerm(
                        weight=int(t.get("weight", 1)),
                        term=PodAffinityTerm.from_dict(t.get("podAffinityTerm") or {}),
                    )
                    for t in (pa.get("preferredDuringSchedulingIgnoredDuringExecution") or [])
                ],
            )
        return a

    def empty(self) -> bool:
        return not (
            self.node_required
            or self.node_preferred
            or self.pod_required
            or self.pod_preferred
            or self.anti_required
            or self.anti_preferred
        )


@dataclass
class TopologySpreadConstraint:
    max_skew: int
    topology_key: str
    when_unsatisfiable: str  # DoNotSchedule | ScheduleAnyway
    selector: Optional[LabelSelector]

    @staticmethod
    def from_dict(d: dict) -> "TopologySpreadConstraint":
        return TopologySpreadConstraint(
            max_skew=int(d.get("maxSkew", 1)),
            topology_key=d.get("topologyKey", ""),
            when_unsatisfiable=d.get("whenUnsatisfiable", "DoNotSchedule"),
            selector=LabelSelector.from_dict(d.get("labelSelector")),
        )


def pod_requests_from_spec(spec: dict) -> Dict[str, int]:
    """Effective pod resource requests.

    max(sum(app containers), max(init containers)) + overhead — the formula from
    kubectl's resourcehelper.PodRequestsAndLimits used by the reference at
    `pkg/simulator/plugin/simon.go:46` and `pkg/algo/greed.go:55`.
    """
    total: Dict[str, int] = {}
    for c in spec.get("containers") or []:
        for name, v in _canon_resources((c.get("resources") or {}).get("requests"), True).items():
            total[name] = total.get(name, 0) + v
    for c in spec.get("initContainers") or []:
        for name, v in _canon_resources((c.get("resources") or {}).get("requests"), True).items():
            if v > total.get(name, 0):
                total[name] = v
    for name, v in _canon_resources(spec.get("overhead"), True).items():
        total[name] = total.get(name, 0) + v
    return total


def pod_limits_from_spec(spec: dict) -> Dict[str, int]:
    total: Dict[str, int] = {}
    for c in spec.get("containers") or []:
        for name, v in _canon_resources((c.get("resources") or {}).get("limits"), True).items():
            total[name] = total.get(name, 0) + v
    for c in spec.get("initContainers") or []:
        for name, v in _canon_resources((c.get("resources") or {}).get("limits"), True).items():
            if v > total.get(name, 0):
                total[name] = v
    return total


# ---------------------------------------------------------------------------
# Open-Local storage model (parity: utils.NodeStorage/Volume/VolumeRequest,
# pkg/utils/utils.go:510-530, and the open-local cache types
# vendor/github.com/alibaba/open-local/pkg/scheduler/algorithm/cache/types.go:50-65)
# ---------------------------------------------------------------------------

def _parse_int_lenient(v, default: int = 0) -> int:
    try:
        return int(str(v))
    except (TypeError, ValueError):
        return default


def _parse_bool_lenient(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).strip().lower() == "true"


@dataclass
class LocalVG:
    """A shared LVM volume group (SharedResource: json name/capacity/requested,
    capacity & requested serialized as strings)."""
    name: str
    capacity: int       # bytes
    requested: int = 0  # bytes already committed

    @staticmethod
    def from_dict(d: dict) -> "LocalVG":
        return LocalVG(
            name=str(d.get("name", "")),
            capacity=_parse_int_lenient(d.get("capacity")),
            requested=_parse_int_lenient(d.get("requested")),
        )


@dataclass
class LocalDevice:
    """An exclusive block device (ExclusiveResource: json name/device/capacity/
    mediaType/isAllocated, the booleans serialized as strings)."""
    name: str
    capacity: int            # bytes
    media_type: str = "hdd"  # "ssd" | "hdd"
    is_allocated: bool = False

    @staticmethod
    def from_dict(d: dict) -> "LocalDevice":
        return LocalDevice(
            name=str(d.get("device") or d.get("name") or ""),
            capacity=_parse_int_lenient(d.get("capacity")),
            media_type=str(d.get("mediaType", "hdd")).lower(),
            is_allocated=_parse_bool_lenient(d.get("isAllocated")),
        )


@dataclass
class NodeLocalStorage:
    """Decoded simon/node-local-storage annotation (utils.GetNodeStorage,
    pkg/utils/utils.go:527-539)."""
    vgs: List[LocalVG] = field(default_factory=list)
    devices: List[LocalDevice] = field(default_factory=list)

    @staticmethod
    def from_json(s: str) -> Optional["NodeLocalStorage"]:
        import json

        try:
            d = json.loads(s)
        except (ValueError, TypeError):
            return None
        if not isinstance(d, dict):
            return None
        return NodeLocalStorage(
            vgs=[LocalVG.from_dict(v) for v in d.get("vgs") or [] if isinstance(v, dict)],
            devices=[
                LocalDevice.from_dict(v)
                for v in d.get("devices") or []
                if isinstance(v, dict)
            ],
        )


@dataclass
class LocalVolume:
    """One entry of the simon/pod-local-storage VolumeRequest (utils.Volume:
    size serialized as string, kind in {LVM,SSD,HDD}, scName)."""
    size: int      # bytes
    kind: str
    sc_name: str
    vg_name: str = ""  # optional explicit VG (open-local's SC-parameter path)

    @property
    def is_lvm(self) -> bool:
        return self.sc_name in LVM_SC_NAMES

    @property
    def media_type(self) -> str:
        """Media type of a device request. The reference resolves it from the
        StorageClass parameters (GetMediaTypeFromPVC); simon's SC name table
        encodes it in the name, so we resolve from the name with the declared
        volume kind as fallback."""
        if self.sc_name in SSD_SC_NAMES or "ssd" in self.sc_name:
            return "ssd"
        if self.sc_name in HDD_SC_NAMES or "hdd" in self.sc_name:
            return "hdd"
        return "ssd" if self.kind.upper() == "SSD" else "hdd"


@dataclass
class Pod:
    meta: ObjectMeta
    requests: Dict[str, int] = field(default_factory=dict)
    limits: Dict[str, int] = field(default_factory=dict)
    node_name: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Affinity = field(default_factory=Affinity)
    tolerations: List[Toleration] = field(default_factory=list)
    spread_constraints: List[TopologySpreadConstraint] = field(default_factory=list)
    scheduler_name: str = DEFAULT_SCHEDULER
    priority: int = 0
    preemption_policy: str = "PreemptLowerPriority"
    phase: str = "Pending"
    # (protocol, port, hostIP); hostIP "" or "0.0.0.0" = wildcard
    host_ports: List[Tuple[str, int, str]] = field(default_factory=list)
    pvc_names: List[str] = field(default_factory=list)
    raw: dict = field(default_factory=dict)

    @staticmethod
    def from_dict(d: dict) -> "Pod":
        meta = ObjectMeta.from_dict(d.get("metadata"))
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        # NodePorts filter parity: app containers only (vendored node_ports.go:64
        # iterates pod.Spec.Containers, not initContainers).
        host_ports: List[Tuple[str, int, str]] = []
        host_network = bool(spec.get("hostNetwork"))
        for c in spec.get("containers") or []:
            for p in c.get("ports") or []:
                hp = p.get("hostPort", 0)
                cp = p.get("containerPort", 0)
                port = hp or (cp if host_network else 0)
                if port:
                    host_ports.append(
                        (p.get("protocol", "TCP"), int(port), p.get("hostIP", "") or "")
                    )
        pvcs = [
            v["persistentVolumeClaim"]["claimName"]
            for v in (spec.get("volumes") or [])
            if isinstance(v, dict) and v.get("persistentVolumeClaim")
        ]
        return Pod(
            meta=meta,
            requests=pod_requests_from_spec(spec),
            limits=pod_limits_from_spec(spec),
            node_name=spec.get("nodeName", "") or "",
            node_selector={k: str(v) for k, v in (spec.get("nodeSelector") or {}).items()},
            affinity=Affinity.from_dict(spec.get("affinity")),
            tolerations=[Toleration.from_dict(t) for t in (spec.get("tolerations") or [])],
            spread_constraints=[
                TopologySpreadConstraint.from_dict(t)
                for t in (spec.get("topologySpreadConstraints") or [])
            ],
            scheduler_name=spec.get("schedulerName") or DEFAULT_SCHEDULER,
            priority=int(spec.get("priority") or 0),
            preemption_policy=spec.get("preemptionPolicy") or "PreemptLowerPriority",
            phase=status.get("phase", "Pending"),
            host_ports=host_ports,
            pvc_names=pvcs,
            raw=d,
        )

    @property
    def key(self) -> str:
        return f"{self.meta.namespace}/{self.meta.name}"

    def gpu_mem_request(self) -> int:
        """Per-GPU memory request in bytes. The annotation is a resource
        quantity like `1024Mi` (parity: GetGpuMemoryFromPodAnnotation,
        pkg/type/open-gpu-share/utils/pod.go:57-67)."""
        v = self.meta.annotations.get(ANNO_GPU_MEM_POD)
        if v is None:
            return 0
        try:
            return int(parse_quantity(str(v)))
        except ValueError:
            return 0

    def gpu_count_request(self) -> int:
        """GPU count from the open-gpu-share annotation (parity:
        GetGpuCountFromPodAnnotation, utils/pod.go:69-79 — defaults to 0, so a
        gpu-mem-only pod is unschedulable everywhere, exactly like the
        reference's AllocateGpuId bailing on reqGpuNum <= 0)."""
        v = self.meta.annotations.get(ANNO_GPU_COUNT_POD)
        try:
            if v is not None and int(v) >= 0:  # reference rejects negatives
                return int(v)
        except ValueError:
            pass
        return 0

    def local_volumes(self) -> Tuple[List["LocalVolume"], List["LocalVolume"]]:
        """(lvm_volumes, device_volumes) from the simon/pod-local-storage
        annotation (parity: utils.GetPodLocalPVCs, pkg/utils/utils.go:580-625:
        kind must be LVM/SSD/HDD; the two LVM storage-class names route to the
        VG path, everything else is an exclusive device)."""
        import json

        s = self.meta.annotations.get(ANNO_POD_LOCAL_STORAGE)
        if not s:
            return [], []
        try:
            d = json.loads(s)
        except (ValueError, TypeError):
            return [], []
        lvm: List[LocalVolume] = []
        dev: List[LocalVolume] = []
        for v in (d.get("volumes") or []) if isinstance(d, dict) else []:
            if not isinstance(v, dict):
                continue
            kind = str(v.get("kind", ""))
            if kind not in ("LVM", "SSD", "HDD"):
                continue  # unsupported volume kind — reference logs and skips
            vol = LocalVolume(
                size=_parse_int_lenient(v.get("size")),
                kind=kind,
                sc_name=str(v.get("scName") or v.get("storageClassName") or ""),
                vg_name=str(v.get("vgName", "")),
            )
            (lvm if vol.is_lvm else dev).append(vol)
        return lvm, dev

    def gpu_index_ids(self) -> List[int]:
        """Allocated device ids from the gpu-index annotation, e.g. "2-3-4" ->
        [2,3,4] (parity: GpuIdStrToIntList, utils/pod.go:102-116). Duplicated
        ids are legal: the multi-GPU allocator may pack several shares onto one
        device (gpunodeinfo.go:271-283)."""
        v = self.meta.annotations.get(ANNO_GPU_INDEX)
        if not v:
            return []
        try:
            return [int(x) for x in str(v).split("-")]
        except ValueError:
            return []


@dataclass
class Node:
    meta: ObjectMeta
    allocatable: Dict[str, int] = field(default_factory=dict)
    capacity: Dict[str, int] = field(default_factory=dict)
    taints: List[Taint] = field(default_factory=list)
    unschedulable: bool = False
    raw: dict = field(default_factory=dict)

    @staticmethod
    def from_dict(d: dict) -> "Node":
        meta = ObjectMeta.from_dict(d.get("metadata"))
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        node = Node(
            meta=meta,
            allocatable=_canon_resources(status.get("allocatable"), False),
            capacity=_canon_resources(status.get("capacity"), False),
            taints=[Taint.from_dict(t) for t in (spec.get("taints") or [])],
            unschedulable=bool(spec.get("unschedulable")),
            raw=d,
        )
        # Ensure the hostname label exists (kubelet guarantees it in practice).
        node.meta.labels.setdefault("kubernetes.io/hostname", meta.name)
        return node

    @property
    def name(self) -> str:
        return self.meta.name

    def gpu_total_mem(self) -> int:
        """Total GPU memory in bytes from status.capacity (parity:
        GetTotalGpuMemory, pkg/type/open-gpu-share/utils/node.go:11-17)."""
        return self.capacity.get(ANNO_GPU_MEM_POD, 0)

    def gpu_count(self) -> int:
        """Number of physical GPUs from status.capacity (parity:
        GetGpuCountInNode, utils/node.go:20-26)."""
        return self.capacity.get(RESOURCE_GPU_COUNT, 0)

    def gpu_mem_per_device(self) -> int:
        """Per-device memory in bytes (parity: DeviceInfo totalGpuMem =
        nodeGpuMem / gpuCount, pkg/type/open-gpu-share/cache/deviceinfo.go)."""
        c = self.gpu_count()
        return self.gpu_total_mem() // c if c > 0 else 0

    def local_storage(self) -> Optional[NodeLocalStorage]:
        """Decoded simon/node-local-storage annotation, or None when the node
        has no local storage (parity: utils.GetNodeStorage/GetNodeCache,
        pkg/utils/utils.go:527-563)."""
        s = self.meta.annotations.get(ANNO_NODE_LOCAL_STORAGE)
        if not s:
            return None
        return NodeLocalStorage.from_json(s)
