"""Workload → Pod expansion (pod synthesis).

Parity targets in the reference:
  - Deployment→ReplicaSet→Pods    /root/reference/pkg/utils/utils.go:132-171
  - Job / CronJob                 utils.go:173-217
  - StatefulSet (+ volumeClaimTemplates → local-storage annotation) utils.go:219-292
  - DaemonSet (per-node eligibility via daemon-controller Predicates) utils.go:325-366
  - MakeValidPod normalization    utils.go:378-463
  - pod name = "<owner>-<rand10>" (STS renamed "<name>-<ordinal>") utils.go:311-313

Randomized suffixes are generated from a seeded RNG so simulations are
deterministic (the reference uses k8s rand.String(10); determinism there is
irrelevant because names never affect placement).
"""

from __future__ import annotations

import copy
import json
import random
import string
from typing import Dict, List, Optional

from .objects import (
    ANNO_POD_LOCAL_STORAGE,
    ANNO_WORKLOAD_KIND,
    ANNO_WORKLOAD_NAME,
    ANNO_WORKLOAD_NAMESPACE,
    HDD_SC_NAMES,
    LVM_SC_NAMES,
    SSD_SC_NAMES,
    Node,
    Pod,
)
from .matcher import daemonset_should_run
from ..utils.quantity import parse_int

# Workload kind strings (parity: pkg/type/const.go workload kinds)
DEPLOYMENT = "Deployment"
REPLICASET = "ReplicaSet"
STATEFULSET = "StatefulSet"
DAEMONSET = "DaemonSet"
JOB = "Job"
CRONJOB = "CronJob"
POD = "Pod"

WORKLOAD_KINDS = {DEPLOYMENT, REPLICASET, STATEFULSET, DAEMONSET, JOB, CRONJOB, POD}

_rng = random.Random(0x51B0)


def _clone_pod(proto: Pod, name: str) -> Pod:
    """Cheap per-replica clone of a parsed template pod.

    Replicas of one workload differ only in name: metadata (name + mutable
    label/annotation/request dicts) is fresh per clone, while the spec-derived
    immutable structures (affinity, tolerations, spread constraints, host
    ports) are shared — the engine never mutates those. This replaces the
    reference's per-replica template deep-copy (utils.go:139-150) and is what
    makes 100k-pod expansion a data-loader, not a bottleneck."""
    import dataclasses

    raw = dict(proto.raw)
    raw_meta = dict(raw.get("metadata") or {})
    raw_meta["name"] = name
    raw["metadata"] = raw_meta
    meta = dataclasses.replace(
        proto.meta,
        name=name,
        labels=dict(proto.meta.labels),
        annotations=dict(proto.meta.annotations),
    )
    return dataclasses.replace(
        proto,
        meta=meta,
        requests=dict(proto.requests),
        limits=dict(proto.limits),
        raw=raw,
    )


def reset_name_rng(seed: int = 0x51B0) -> None:
    _rng.seed(seed)


def _rand_suffix(n: int = 10) -> str:
    alphabet = string.ascii_lowercase + string.digits
    return "".join(_rng.choice(alphabet) for _ in range(n))


def _pod_dict_from_template(owner: dict, kind: str, template: dict) -> dict:
    meta = owner.get("metadata") or {}
    tmeta = template.get("metadata") or {}
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": f"{meta.get('name', 'pod')}-{_rand_suffix()}",
            "generateName": meta.get("name", ""),
            "namespace": meta.get("namespace") or "default",
            "labels": copy.deepcopy(tmeta.get("labels") or {}),
            "annotations": copy.deepcopy(tmeta.get("annotations") or {}),
            "ownerReferences": [
                {
                    "kind": kind,
                    "name": meta.get("name", ""),
                    "controller": True,
                }
            ],
        },
        "spec": copy.deepcopy(template.get("spec") or {}),
    }


def make_valid_pod_dict(pod: dict) -> dict:
    """MakeValidPod normalization (utils.go:378-463): defaults, strip env/
    volumeMounts/probes/pull-secrets, PVC volumes → hostPath, empty status."""
    pod = copy.deepcopy(pod)
    meta = pod.setdefault("metadata", {})
    meta.setdefault("labels", {})
    meta.setdefault("annotations", {})
    if not meta.get("namespace"):
        meta["namespace"] = "default"
    spec = pod.setdefault("spec", {})
    spec.setdefault("dnsPolicy", "ClusterFirst")
    spec.setdefault("restartPolicy", "Always")
    spec.setdefault("schedulerName", "default-scheduler")
    spec.pop("imagePullSecrets", None)
    for section in ("initContainers", "containers"):
        for c in spec.get(section) or []:
            c.setdefault("terminationMessagePolicy", "FallbackToLogsOnError")
            c.setdefault("imagePullPolicy", "IfNotPresent")
            sc = c.get("securityContext")
            if sc and "privileged" in sc:
                sc["privileged"] = False
            c.pop("volumeMounts", None)
            c.pop("env", None)
            if section == "containers":
                c.pop("livenessProbe", None)
                c.pop("readinessProbe", None)
                c.pop("startupProbe", None)
    for v in spec.get("volumes") or []:
        if isinstance(v, dict) and v.get("persistentVolumeClaim"):
            v.pop("persistentVolumeClaim")
            v["hostPath"] = {"path": "/tmp"}
    pod["status"] = {}
    return pod


def _add_workload_info(pod: dict, kind: str, name: str, namespace: str) -> dict:
    anns = pod["metadata"].setdefault("annotations", {})
    anns[ANNO_WORKLOAD_KIND] = kind
    anns[ANNO_WORKLOAD_NAME] = name
    anns[ANNO_WORKLOAD_NAMESPACE] = namespace or "default"
    return pod


def _storage_annotation(volume_claim_templates: List[dict]) -> Optional[str]:
    """volumeClaimTemplates → simon/pod-local-storage annotation (utils.go:246-292)."""
    volumes = []
    for pvc in volume_claim_templates or []:
        spec = pvc.get("spec") or {}
        sc = spec.get("storageClassName")
        size = parse_int(
            ((spec.get("resources") or {}).get("requests") or {}).get("storage", 0)
        )
        if sc in LVM_SC_NAMES:
            kind = "LVM"
        elif sc in SSD_SC_NAMES:
            kind = "SSD"
        elif sc in HDD_SC_NAMES:
            kind = "HDD"
        else:
            continue  # unsupported storage class — reference logs an error
        # Field names/stringly size match the reference's ffjson encoding of
        # utils.Volume (`json:"size,string"`, `json:"scName"`).
        volumes.append({"size": str(size), "kind": kind, "scName": sc})
    if not volumes:
        return None
    return json.dumps({"volumes": volumes})


def pods_from_workload(obj: dict, nodes: Optional[List[Node]] = None) -> List[Pod]:
    """Expand one decoded workload object into scheduling-ready Pods."""
    kind = obj.get("kind", "")
    meta = obj.get("metadata") or {}
    name = meta.get("name", "")
    namespace = meta.get("namespace") or "default"
    spec = obj.get("spec") or {}
    out: List[dict] = []

    if kind == POD:
        p = make_valid_pod_dict(obj)
        out.append(p)
    elif kind in (DEPLOYMENT, REPLICASET):
        # Deployment pods are annotated as ReplicaSet-owned (utils.go:132-135)
        return _expand_replicas(
            obj, REPLICASET, spec.get("template") or {},
            spec.get("replicas", 1), REPLICASET, name, namespace,
            name_fn=None,
        )
    elif kind == STATEFULSET:
        storage_ann = _storage_annotation(spec.get("volumeClaimTemplates") or [])
        return _expand_replicas(
            obj, STATEFULSET, spec.get("template") or {},
            spec.get("replicas", 1), STATEFULSET, name, namespace,
            name_fn=lambda ordinal: f"{name}-{ordinal}",
            # unconditional: volumeClaimTemplates are the source of truth for
            # the storage annotation, overriding any template-supplied value
            # (utils.go:246-292 always assigns)
            force_annotations=(
                {ANNO_POD_LOCAL_STORAGE: storage_ann} if storage_ann else None
            ),
        )
    elif kind == JOB:
        return _expand_replicas(
            obj, JOB, spec.get("template") or {},
            spec.get("completions", 1), JOB, name, namespace, name_fn=None,
        )
    elif kind == CRONJOB:
        job_spec = (spec.get("jobTemplate") or {}).get("spec") or {}
        return _expand_replicas(
            obj, JOB, job_spec.get("template") or {},
            job_spec.get("completions", 1), JOB, name, namespace,
            name_fn=None,
            extra_annotations={"cronjob.kubernetes.io/instantiate": "manual"},
        )
    elif kind == DAEMONSET:
        return daemonset_pods(obj, nodes or [])
    else:
        raise ValueError(f"unsupported workload kind: {kind}")
    return [Pod.from_dict(p) for p in out]


def _expand_replicas(
    owner: dict,
    owner_kind: str,
    template: dict,
    count,
    info_kind: str,
    name: str,
    namespace: str,
    name_fn,
    extra_annotations: Optional[Dict[str, str]] = None,
    force_annotations: Optional[Dict[str, str]] = None,
) -> List[Pod]:
    """Expand one template into `count` replica Pods: the first replica is
    fully synthesized + validated + parsed (the reference's MakeValidPod path,
    utils.go:139-171,378-463), the rest are cheap clones of that prototype —
    replicas are spec-identical by construction. extra_annotations are
    defaults (template wins); force_annotations always overwrite."""
    n = int(count if count is not None else 1)
    if n <= 0:
        return []
    d = make_valid_pod_dict(_pod_dict_from_template(owner, owner_kind, template))
    _add_workload_info(d, info_kind, name, namespace)
    if extra_annotations:
        for k, v in extra_annotations.items():
            d["metadata"]["annotations"].setdefault(k, v)
    if force_annotations:
        d["metadata"]["annotations"].update(force_annotations)
    if name_fn is not None:
        d["metadata"]["name"] = name_fn(0)
    proto = Pod.from_dict(d)
    pods = [proto]
    for i in range(1, n):
        pod_name = name_fn(i) if name_fn is not None else f"{name}-{_rand_suffix()}"
        pods.append(_clone_pod(proto, pod_name))
    return pods


def daemonset_pods(ds: dict, nodes: List[Node]) -> List[Pod]:
    """One pod per eligible node, pinned via required node affinity on the
    hostname — parity with NewDaemonPod/SetDaemonSetPodNodeNameByNodeAffinity
    (utils.go:338-366, 466-493)."""
    meta = ds.get("metadata") or {}
    name = meta.get("name", "")
    namespace = meta.get("namespace") or "default"
    template = (ds.get("spec") or {}).get("template") or {}
    pods: List[Pod] = []
    for node in nodes:
        d = _pod_dict_from_template(ds, DAEMONSET, template)
        spec = d["spec"]
        pin = {"key": "metadata.name", "operator": "In", "values": [node.name]}
        aff = spec.setdefault("affinity", {})
        node_aff = aff.setdefault("nodeAffinity", {})
        req = node_aff.setdefault("requiredDuringSchedulingIgnoredDuringExecution", {})
        terms = req.get("nodeSelectorTerms")
        if terms:
            # AND the node-name pin into every existing term, keeping the
            # template's matchExpressions (utils.go:806-813).
            for t in terms:
                t["matchFields"] = [pin]
        else:
            req["nodeSelectorTerms"] = [{"matchFields": [pin]}]
        p = make_valid_pod_dict(d)
        pod = Pod.from_dict(_add_workload_info(p, DAEMONSET, name, namespace))
        if daemonset_should_run(pod, node):
            pods.append(pod)
    return pods


def expected_pod_counts(objs: List[dict], nodes: List[Node]) -> Dict[str, int]:
    """Workload-conservation oracle: how many pods should each workload yield.

    Mirrors the checkResult oracle in the reference's core_test.go:364-591.
    An explicit replicas/completions of 0 counts as 0 (only a missing/None
    field defaults to 1, matching pods_from_workload).
    """

    def _count(value) -> int:
        return 1 if value is None else int(value)

    counts: Dict[str, int] = {}
    # Preserve the shared name RNG: the oracle must not perturb the names of
    # pods synthesized after it runs.
    rng_state = _rng.getstate()
    try:
        for obj in objs:
            kind = obj.get("kind", "")
            meta = obj.get("metadata") or {}
            key = f"{kind}/{meta.get('namespace') or 'default'}/{meta.get('name')}"
            spec = obj.get("spec") or {}
            if kind == POD:
                counts[key] = counts.get(key, 0) + 1
            elif kind in (DEPLOYMENT, REPLICASET, STATEFULSET):
                counts[key] = _count(spec.get("replicas", None))
            elif kind == JOB:
                counts[key] = _count(spec.get("completions", None))
            elif kind == CRONJOB:
                job_spec = (spec.get("jobTemplate") or {}).get("spec") or {}
                counts[key] = _count(job_spec.get("completions", None))
            elif kind == DAEMONSET:
                counts[key] = len(daemonset_pods(obj, nodes))
    finally:
        _rng.setstate(rng_state)
    return counts
