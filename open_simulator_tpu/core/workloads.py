"""Workload → Pod expansion (pod synthesis).

Parity targets in the reference:
  - Deployment→ReplicaSet→Pods    /root/reference/pkg/utils/utils.go:132-171
  - Job / CronJob                 utils.go:173-217
  - StatefulSet (+ volumeClaimTemplates → local-storage annotation) utils.go:219-292
  - DaemonSet (per-node eligibility via daemon-controller Predicates) utils.go:325-366
  - MakeValidPod normalization    utils.go:378-463
  - pod name = "<owner>-<rand10>" (STS renamed "<name>-<ordinal>") utils.go:311-313

Randomized suffixes are generated from a seeded RNG so simulations are
deterministic (the reference uses k8s rand.String(10); determinism there is
irrelevant because names never affect placement).
"""

from __future__ import annotations

import copy
import json
import random
import string
from typing import Dict, List, Optional

from .objects import (
    ANNO_POD_LOCAL_STORAGE,
    ANNO_WORKLOAD_KIND,
    ANNO_WORKLOAD_NAME,
    ANNO_WORKLOAD_NAMESPACE,
    HDD_SC_NAMES,
    LVM_SC_NAMES,
    SSD_SC_NAMES,
    Node,
    Pod,
)
from .matcher import daemonset_should_run
from ..utils.quantity import parse_int

# Workload kind strings (parity: pkg/type/const.go workload kinds)
DEPLOYMENT = "Deployment"
REPLICASET = "ReplicaSet"
STATEFULSET = "StatefulSet"
DAEMONSET = "DaemonSet"
JOB = "Job"
CRONJOB = "CronJob"
POD = "Pod"

WORKLOAD_KINDS = {DEPLOYMENT, REPLICASET, STATEFULSET, DAEMONSET, JOB, CRONJOB, POD}

_rng = random.Random(0x51B0)


def reset_name_rng(seed: int = 0x51B0) -> None:
    _rng.seed(seed)


def _rand_suffix(n: int = 10) -> str:
    alphabet = string.ascii_lowercase + string.digits
    return "".join(_rng.choice(alphabet) for _ in range(n))


def _pod_dict_from_template(owner: dict, kind: str, template: dict) -> dict:
    meta = owner.get("metadata") or {}
    tmeta = template.get("metadata") or {}
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": f"{meta.get('name', 'pod')}-{_rand_suffix()}",
            "generateName": meta.get("name", ""),
            "namespace": meta.get("namespace") or "default",
            "labels": copy.deepcopy(tmeta.get("labels") or {}),
            "annotations": copy.deepcopy(tmeta.get("annotations") or {}),
            "ownerReferences": [
                {
                    "kind": kind,
                    "name": meta.get("name", ""),
                    "controller": True,
                }
            ],
        },
        "spec": copy.deepcopy(template.get("spec") or {}),
    }


def make_valid_pod_dict(pod: dict) -> dict:
    """MakeValidPod normalization (utils.go:378-463): defaults, strip env/
    volumeMounts/probes/pull-secrets, PVC volumes → hostPath, empty status."""
    pod = copy.deepcopy(pod)
    meta = pod.setdefault("metadata", {})
    meta.setdefault("labels", {})
    meta.setdefault("annotations", {})
    if not meta.get("namespace"):
        meta["namespace"] = "default"
    spec = pod.setdefault("spec", {})
    spec.setdefault("dnsPolicy", "ClusterFirst")
    spec.setdefault("restartPolicy", "Always")
    spec.setdefault("schedulerName", "default-scheduler")
    spec.pop("imagePullSecrets", None)
    for section in ("initContainers", "containers"):
        for c in spec.get(section) or []:
            c.setdefault("terminationMessagePolicy", "FallbackToLogsOnError")
            c.setdefault("imagePullPolicy", "IfNotPresent")
            sc = c.get("securityContext")
            if sc and "privileged" in sc:
                sc["privileged"] = False
            c.pop("volumeMounts", None)
            c.pop("env", None)
            if section == "containers":
                c.pop("livenessProbe", None)
                c.pop("readinessProbe", None)
                c.pop("startupProbe", None)
    for v in spec.get("volumes") or []:
        if isinstance(v, dict) and v.get("persistentVolumeClaim"):
            v.pop("persistentVolumeClaim")
            v["hostPath"] = {"path": "/tmp"}
    pod["status"] = {}
    return pod


def _add_workload_info(pod: dict, kind: str, name: str, namespace: str) -> dict:
    anns = pod["metadata"].setdefault("annotations", {})
    anns[ANNO_WORKLOAD_KIND] = kind
    anns[ANNO_WORKLOAD_NAME] = name
    anns[ANNO_WORKLOAD_NAMESPACE] = namespace or "default"
    return pod


def _storage_annotation(volume_claim_templates: List[dict]) -> Optional[str]:
    """volumeClaimTemplates → simon/pod-local-storage annotation (utils.go:246-292)."""
    volumes = []
    for pvc in volume_claim_templates or []:
        spec = pvc.get("spec") or {}
        sc = spec.get("storageClassName")
        size = parse_int(
            ((spec.get("resources") or {}).get("requests") or {}).get("storage", 0)
        )
        if sc in LVM_SC_NAMES:
            kind = "LVM"
        elif sc in SSD_SC_NAMES:
            kind = "SSD"
        elif sc in HDD_SC_NAMES:
            kind = "HDD"
        else:
            continue  # unsupported storage class — reference logs an error
        # Field names/stringly size match the reference's ffjson encoding of
        # utils.Volume (`json:"size,string"`, `json:"scName"`).
        volumes.append({"size": str(size), "kind": kind, "scName": sc})
    if not volumes:
        return None
    return json.dumps({"volumes": volumes})


def pods_from_workload(obj: dict, nodes: Optional[List[Node]] = None) -> List[Pod]:
    """Expand one decoded workload object into scheduling-ready Pods."""
    kind = obj.get("kind", "")
    meta = obj.get("metadata") or {}
    name = meta.get("name", "")
    namespace = meta.get("namespace") or "default"
    spec = obj.get("spec") or {}
    out: List[dict] = []

    if kind == POD:
        p = make_valid_pod_dict(obj)
        out.append(p)
    elif kind in (DEPLOYMENT, REPLICASET):
        replicas = spec.get("replicas", 1)
        template = spec.get("template") or {}
        for _ in range(int(replicas if replicas is not None else 1)):
            p = make_valid_pod_dict(_pod_dict_from_template(obj, REPLICASET, template))
            # Deployment pods are annotated as ReplicaSet-owned (utils.go:132-135)
            out.append(_add_workload_info(p, REPLICASET, name, namespace))
    elif kind == STATEFULSET:
        replicas = spec.get("replicas", 1)
        template = spec.get("template") or {}
        storage_ann = _storage_annotation(spec.get("volumeClaimTemplates") or [])
        for ordinal in range(int(replicas if replicas is not None else 1)):
            p = make_valid_pod_dict(_pod_dict_from_template(obj, STATEFULSET, template))
            p["metadata"]["name"] = f"{name}-{ordinal}"
            _add_workload_info(p, STATEFULSET, name, namespace)
            if storage_ann:
                p["metadata"]["annotations"][ANNO_POD_LOCAL_STORAGE] = storage_ann
            out.append(p)
    elif kind == JOB:
        completions = spec.get("completions", 1)
        template = spec.get("template") or {}
        for _ in range(int(completions if completions is not None else 1)):
            p = make_valid_pod_dict(_pod_dict_from_template(obj, JOB, template))
            out.append(_add_workload_info(p, JOB, name, namespace))
    elif kind == CRONJOB:
        job_spec = (spec.get("jobTemplate") or {}).get("spec") or {}
        completions = job_spec.get("completions", 1)
        template = job_spec.get("template") or {}
        for _ in range(int(completions if completions is not None else 1)):
            p = make_valid_pod_dict(_pod_dict_from_template(obj, JOB, template))
            p["metadata"]["annotations"].setdefault(
                "cronjob.kubernetes.io/instantiate", "manual"
            )
            out.append(_add_workload_info(p, JOB, name, namespace))
    elif kind == DAEMONSET:
        return daemonset_pods(obj, nodes or [])
    else:
        raise ValueError(f"unsupported workload kind: {kind}")
    return [Pod.from_dict(p) for p in out]


def daemonset_pods(ds: dict, nodes: List[Node]) -> List[Pod]:
    """One pod per eligible node, pinned via required node affinity on the
    hostname — parity with NewDaemonPod/SetDaemonSetPodNodeNameByNodeAffinity
    (utils.go:338-366, 466-493)."""
    meta = ds.get("metadata") or {}
    name = meta.get("name", "")
    namespace = meta.get("namespace") or "default"
    template = (ds.get("spec") or {}).get("template") or {}
    pods: List[Pod] = []
    for node in nodes:
        d = _pod_dict_from_template(ds, DAEMONSET, template)
        spec = d["spec"]
        pin = {"key": "metadata.name", "operator": "In", "values": [node.name]}
        aff = spec.setdefault("affinity", {})
        node_aff = aff.setdefault("nodeAffinity", {})
        req = node_aff.setdefault("requiredDuringSchedulingIgnoredDuringExecution", {})
        terms = req.get("nodeSelectorTerms")
        if terms:
            # AND the node-name pin into every existing term, keeping the
            # template's matchExpressions (utils.go:806-813).
            for t in terms:
                t["matchFields"] = [pin]
        else:
            req["nodeSelectorTerms"] = [{"matchFields": [pin]}]
        p = make_valid_pod_dict(d)
        pod = Pod.from_dict(_add_workload_info(p, DAEMONSET, name, namespace))
        if daemonset_should_run(pod, node):
            pods.append(pod)
    return pods


def expected_pod_counts(objs: List[dict], nodes: List[Node]) -> Dict[str, int]:
    """Workload-conservation oracle: how many pods should each workload yield.

    Mirrors the checkResult oracle in the reference's core_test.go:364-591.
    An explicit replicas/completions of 0 counts as 0 (only a missing/None
    field defaults to 1, matching pods_from_workload).
    """

    def _count(value) -> int:
        return 1 if value is None else int(value)

    counts: Dict[str, int] = {}
    # Preserve the shared name RNG: the oracle must not perturb the names of
    # pods synthesized after it runs.
    rng_state = _rng.getstate()
    try:
        for obj in objs:
            kind = obj.get("kind", "")
            meta = obj.get("metadata") or {}
            key = f"{kind}/{meta.get('namespace') or 'default'}/{meta.get('name')}"
            spec = obj.get("spec") or {}
            if kind == POD:
                counts[key] = counts.get(key, 0) + 1
            elif kind in (DEPLOYMENT, REPLICASET, STATEFULSET):
                counts[key] = _count(spec.get("replicas", None))
            elif kind == JOB:
                counts[key] = _count(spec.get("completions", None))
            elif kind == CRONJOB:
                job_spec = (spec.get("jobTemplate") or {}).get("spec") or {}
                counts[key] = _count(job_spec.get("completions", None))
            elif kind == DAEMONSET:
                counts[key] = len(daemonset_pods(obj, nodes))
    finally:
        _rng.setstate(rng_state)
    return counts
