"""Apiserver-grade object validation (the essential subset).

Parity: the reference validates every synthesized pod and imported node with
the vendored apiserver validation before simulating — `utils.ValidatePod` /
`utils.ValidateNode` (`/root/reference/pkg/utils/utils.go:495-508`, backed by
`vendor/k8s.io/kubernetes/pkg/apis/core/validation`) — and fails the whole
simulation on the first invalid object. This module ports the checks that
matter for scheduling fidelity: metadata names/labels, container shape,
resource sanity, and selector validity; messages follow the apiserver's
`field.Error` style so diagnostics read the same.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional

from .objects import Node, Pod

_DNS1123_LABEL = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
_DNS1123_SUBDOMAIN = re.compile(
    r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*$"
)
_QUALIFIED_PART = re.compile(r"^[A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?$")
_LABEL_VALUE = re.compile(r"^([A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?)?$")

_LABEL_MSG = (
    "a lowercase RFC 1123 label must consist of lower case alphanumeric "
    "characters or '-', and must start and end with an alphanumeric character"
)
_SUBDOMAIN_MSG = (
    "a lowercase RFC 1123 subdomain must consist of lower case alphanumeric "
    "characters, '-' or '.', and must start and end with an alphanumeric "
    "character"
)


class ValidationError(ValueError):
    """Raised when an object fails apiserver-style validation."""


def _dns1123_label(value: str, max_len: int = 63) -> Optional[str]:
    if len(value) > max_len:
        return f"must be no more than {max_len} characters"
    if not _DNS1123_LABEL.match(value):
        return _LABEL_MSG
    return None


def _dns1123_subdomain(value: str) -> Optional[str]:
    if len(value) > 253:
        return "must be no more than 253 characters"
    if not _DNS1123_SUBDOMAIN.match(value):
        return _SUBDOMAIN_MSG
    return None


def _qualified_name(value: str) -> Optional[str]:
    parts = value.split("/")
    if len(parts) > 2:
        return "a qualified name must consist of a name and an optional prefix"
    if len(parts) == 2:
        prefix, name = parts
        if not prefix or _dns1123_subdomain(prefix) is not None:
            return "prefix part " + _SUBDOMAIN_MSG
    else:
        name = parts[0]
    if not name or len(name) > 63 or not _QUALIFIED_PART.match(name):
        return (
            "name part must consist of alphanumeric characters, '-', '_' or "
            "'.', and must start and end with an alphanumeric character"
        )
    return None


def _validate_labels(labels: Dict[str, str], path: str, errs: List[str]) -> None:
    for k, v in labels.items():
        msg = _qualified_name(k)
        if msg is not None:
            errs.append(f"{path}: Invalid value: {k!r}: {msg}")
        if len(v) > 63 or not _LABEL_VALUE.match(v):
            errs.append(
                f"{path}: Invalid value: {v!r}: a valid label value must be "
                "an empty string or consist of alphanumeric characters, '-', "
                "'_' or '.', and must start and end with an alphanumeric "
                "character"
            )


_RESTART_POLICIES = ("", "Always", "OnFailure", "Never")


def validate_pod(pod: Pod) -> List[str]:
    """Field errors for one pod; empty list = valid."""
    errs: List[str] = []
    name, namespace = pod.meta.name, pod.meta.namespace
    if not name:
        errs.append("metadata.name: Required value: name or generateName is required")
    else:
        msg = _dns1123_subdomain(name)
        if msg is not None:
            errs.append(f"metadata.name: Invalid value: {name!r}: {msg}")
    if not namespace:
        errs.append("metadata.namespace: Required value")
    else:
        msg = _dns1123_label(namespace)
        if msg is not None:
            errs.append(f"metadata.namespace: Invalid value: {namespace!r}: {msg}")
    _validate_labels(pod.meta.labels, "metadata.labels", errs)

    spec = (pod.raw.get("spec") or {}) if isinstance(pod.raw, dict) else {}
    containers = spec.get("containers")
    if not containers:
        errs.append("spec.containers: Required value")
        containers = []
    seen = set()
    for i, c in enumerate(containers):
        cname = (c or {}).get("name", "")
        if not cname:
            errs.append(f"spec.containers[{i}].name: Required value")
        else:
            msg = _dns1123_label(cname)
            if msg is not None:
                errs.append(
                    f"spec.containers[{i}].name: Invalid value: {cname!r}: {msg}"
                )
            if cname in seen:
                errs.append(
                    f"spec.containers[{i}].name: Duplicate value: {cname!r}"
                )
            seen.add(cname)
        if not (c or {}).get("image"):
            errs.append(f"spec.containers[{i}].image: Required value")

    policy = spec.get("restartPolicy", "")
    if policy not in _RESTART_POLICIES:
        errs.append(
            f"spec.restartPolicy: Unsupported value: {policy!r}: supported "
            'values: "Always", "OnFailure", "Never"'
        )

    for res, q in pod.requests.items():
        if q < 0:
            errs.append(
                f"spec.containers[0].resources.requests[{res}]: Invalid "
                f"value: must be greater than or equal to 0"
            )
    for res, q in pod.limits.items():
        if q < 0:
            errs.append(
                f"spec.containers[0].resources.limits[{res}]: Invalid value: "
                f"must be greater than or equal to 0"
            )
        req = pod.requests.get(res, 0)
        if q >= 0 and req > q:
            errs.append(
                f"spec.containers[0].resources.requests[{res}]: Invalid "
                f"value: must be less than or equal to {res} limit"
            )

    for k, v in pod.node_selector.items():
        msg = _qualified_name(k)
        if msg is not None:
            errs.append(f"spec.nodeSelector: Invalid value: {k!r}: {msg}")
    return errs


def validate_node(node: Node) -> List[str]:
    """Field errors for one node; empty list = valid."""
    errs: List[str] = []
    if not node.name:
        errs.append("metadata.name: Required value")
    else:
        msg = _dns1123_subdomain(node.name)
        if msg is not None:
            errs.append(f"metadata.name: Invalid value: {node.name!r}: {msg}")
    _validate_labels(node.meta.labels, "metadata.labels", errs)
    for res, q in node.allocatable.items():
        if q < 0:
            errs.append(
                f"status.allocatable[{res}]: Invalid value: must be greater "
                "than or equal to 0"
            )
    return errs


def check_pods(pods, where: str = "") -> None:
    """Raise ValidationError on the first invalid pod (the reference fails the
    whole Simulate on one invalid object, utils.go:60-67)."""
    for pod in pods:
        errs = validate_pod(pod)
        if errs:
            ctx = f" in {where}" if where else ""
            raise ValidationError(
                f"invalid pod {pod.key}{ctx}: " + "; ".join(errs)
            )


def check_nodes(nodes) -> None:
    for node in nodes:
        errs = validate_node(node)
        if errs:
            raise ValidationError(
                f"invalid node {node.name}: " + "; ".join(errs)
            )
