"""Pod-ordering queues applied before submission to the scheduler.

Parity: `/root/reference/pkg/algo/` —
  - AffinityQueue (affinity.go): pods with a nodeSelector first
  - TolerationQueue (toleration.go): pods with tolerations first
  - GreedQueue (greed.go): node-pinned pods first, then descending dominant
    cpu/memory share of the cluster total (`calculatePodShare` :50-67,
    `Share` :70-83)

ScheduleApp always applies affinity then toleration (simulator.go:238-241).
The reference's `--use-greed` flag exists but GreedQueue is never wired in
(dead option, SURVEY §2.1 #14); here the flag actually works — greed ordering
runs first, then the affinity/toleration stable sorts, so the reference's
default ordering is preserved within equal-share groups.

All sorts are STABLE (Python sorted), unlike Go's sort.Sort; the reference's
orderings are therefore reproduced deterministically rather than
arbitrarily-among-equals.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

from .objects import CPU, MEMORY, Node, Pod


def share(alloc: float, total: float) -> float:
    """algo.Share (greed.go:70-83)."""
    if total == 0:
        return 0.0 if alloc == 0 else 1.0
    return alloc / total


def pod_dominant_share(pod: Pod, totals: Dict[str, float]) -> float:
    """Max share over cpu/memory of the cluster totals (greed.go:50-67)."""
    if not pod.requests:
        return 0.0
    res = 0.0
    for name, total in totals.items():
        res = max(res, share(float(pod.requests.get(name, 0)), total))
    return res


def cluster_totals(nodes: Sequence[Node]) -> Dict[str, float]:
    """Cluster-wide allocatable cpu+memory (greed.go:16-32)."""
    return {
        CPU: float(sum(n.allocatable.get(CPU, 0) for n in nodes)),
        MEMORY: float(sum(n.allocatable.get(MEMORY, 0) for n in nodes)),
    }


def greed_sort(pods: Sequence[Pod], nodes: Sequence[Node]) -> List[Pod]:
    """GreedQueue order: node-pinned pods first, then descending dominant
    share (bigger pods first — worst-fit pairing with the Simon score)."""
    totals = cluster_totals(nodes)
    return sorted(
        pods,
        key=lambda p: (not p.node_name, -pod_dominant_share(p, totals)),
    )


def affinity_sort(pods: Sequence[Pod]) -> List[Pod]:
    """AffinityQueue: nodeSelector pods first (affinity.go:21-23)."""
    return sorted(pods, key=lambda p: not p.node_selector)


def toleration_sort(pods: Sequence[Pod]) -> List[Pod]:
    """TolerationQueue: tolerating pods first (toleration.go:19-21)."""
    return sorted(pods, key=lambda p: not p.tolerations)


def order_pods(
    pods: Sequence[Pod],
    nodes: Sequence[Node] = (),
    use_greed: bool = False,
) -> List[Pod]:
    """The ScheduleApp ordering: optional greed pass, then affinity, then
    toleration (stable, so later sorts only reorder across their own key)."""
    out = list(pods)
    if use_greed:
        out = greed_sort(out, nodes)
    out = affinity_sort(out)
    return toleration_sort(out)
