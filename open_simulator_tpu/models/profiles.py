"""Scheduler profiles: plugin enable/disable, weights, multi-profile configs.

Parity: the reference assembles a KubeSchedulerConfiguration programmatically —
default provider plugins + Simon/Open-Local/Open-Gpu-Share injected, DefaultBinder
disabled, PercentageOfNodesToScore pinned to 100
(`/root/reference/pkg/simulator/utils.go:304-381`) — optionally merged with a
user-supplied scheduler config file (`--default-scheduler-config`,
`cmd/apply/apply.go:28`), then hands every profile to scheduler.New
(`simulator.go:204-216`, WithProfiles...). Extenders in the user config are
wired the way the reference does (WithExtenders, simulator.go:215): parsed
into ExtenderConfig entries that the engine calls over HTTP between the
device filter mask and the score combine (engine/extenders.py).

A profile carries (a) the weight vector for the score kernels, (b) a
bool[NUM_FILTERS] filter-enable mask honoring the config's Filter
enable/disable lists, keyed by schedulerName. Kube plugin names map to kernel
names so user config files written for the reference keep working.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np
import yaml

from ..ops.kernels import DEFAULT_WEIGHTS, FILTER_PLUGIN_MAP, NUM_FILTERS

# kube plugin name -> kernel score name
PLUGIN_NAME_MAP = {
    "NodeResourcesLeastAllocated": "least_allocated",
    "NodeResourcesBalancedAllocation": "balanced_allocation",
    "NodeAffinity": "node_affinity",
    "TaintToleration": "taint_toleration",
    "PodTopologySpread": "topology_spread",
    "InterPodAffinity": "inter_pod_affinity",
    "NodePreferAvoidPods": "prefer_avoid_pods",
    "Simon": "simon",
    "Open-Local": "open_local",
    "Open-Gpu-Share": "gpu_share",
    # score-neutral in a fake cluster (no images, see SURVEY §2.2): accepted
    # and ignored so reference configs parse cleanly
    "ImageLocality": None,
    "NodeResourcesMostAllocated": None,
    "RequestedToCapacityRatio": None,
    "SelectorSpread": None,
    "DefaultPodTopologySpread": None,
}


@dataclass
class SchedulerProfile:
    scheduler_name: str = "default-scheduler"
    weights: Dict[str, float] = field(default_factory=lambda: dict(DEFAULT_WEIGHTS))
    # filter plugins enabled (index = kernels.F_*); Open-Local/Open-Gpu-Share
    # filters stay on regardless — the reference injects them after the user
    # config merge (utils.go:337-347)
    filters_enabled: List[bool] = field(
        default_factory=lambda: [True] * NUM_FILTERS
    )
    percentage_of_nodes_to_score: int = 100  # simon pins 100 (utils.go:370)

    def with_plugin(self, kube_name: str, weight: float = 1.0) -> "SchedulerProfile":
        kernel = PLUGIN_NAME_MAP.get(kube_name)
        if kernel:
            self.weights[kernel] = weight
        return self

    def without_plugin(self, kube_name: str) -> "SchedulerProfile":
        kernel = PLUGIN_NAME_MAP.get(kube_name)
        if kernel:
            self.weights[kernel] = 0.0
        return self

    def disable_filter(self, kube_name: str) -> "SchedulerProfile":
        idx = FILTER_PLUGIN_MAP.get(kube_name)
        if idx is not None:
            self.filters_enabled[idx] = False
        return self

    def enable_filter(self, kube_name: str) -> "SchedulerProfile":
        idx = FILTER_PLUGIN_MAP.get(kube_name)
        if idx is not None:
            self.filters_enabled[idx] = True
        return self

    def filter_on_array(self) -> Optional[np.ndarray]:
        """bool[NUM_FILTERS] for the kernels, or None when everything is on
        (keeps the unprofiled jit cache entries)."""
        if all(self.filters_enabled):
            return None
        return np.asarray(self.filters_enabled, bool)


_GO_DURATION_UNITS = {
    "ns": 1e-9, "us": 1e-6, "µs": 1e-6, "ms": 1e-3,
    "s": 1.0, "m": 60.0, "h": 3600.0,
}


def _parse_go_duration(s: str) -> Optional[float]:
    """metav1.Duration / Go time.ParseDuration subset: one or more
    (number)(unit) segments, e.g. "5s", "1m30s", "100ms". Returns seconds,
    or None when the string is not a valid duration."""
    import re as _re

    if not s:
        return None
    sign = 1.0
    if s[0] in "+-":
        sign = -1.0 if s[0] == "-" else 1.0
        s = s[1:]
        if not s:
            return None   # a bare sign is not a duration
    if s == "0":
        return 0.0   # the one unit-less form Go accepts
    total = 0.0
    pos = 0
    seg = _re.compile(r"([\d.]+)(ns|us|µs|ms|s|m|h)")
    while pos < len(s):
        m = seg.match(s, pos)
        if not m:
            return None
        try:
            total += float(m.group(1)) * _GO_DURATION_UNITS[m.group(2)]
        except ValueError:
            return None
        pos = m.end()
    return sign * total if total else 0.0


def _is_extended_resource_name(name: str) -> bool:
    """v1helper.IsExtendedResourceName (vendored helpers.go:37-61): a
    qualified name outside the *kubernetes.io/ namespace — never a native
    resource (cpu/memory/pods and anything containing "kubernetes.io/"),
    never a requests.-prefixed quota name."""
    if "/" not in name or "kubernetes.io/" in name:
        return False  # IsNativeResource
    if name.startswith("requests."):
        return False
    return True


@dataclass
class ExtenderConfig:
    """One `extenders:` entry of a KubeSchedulerConfiguration (parity:
    vendored KubeSchedulerConfiguration.Extenders → HTTPExtender,
    vendor/.../scheduler/core/extender.go:93-123). preemptVerb wires into the
    preemption pass (ProcessPreemption, engine/preemption.py). bindVerb is
    accepted but inert: simon disables DefaultBinder and binds through its
    own plugin."""

    url_prefix: str = ""
    filter_verb: str = ""
    prioritize_verb: str = ""
    preempt_verb: str = ""
    bind_verb: str = ""
    weight: int = 1
    enable_https: bool = False
    http_timeout_s: float = 30.0
    node_cache_capable: bool = False
    # resource names; empty = interested in every pod (extender.go:442-445)
    managed_resources: List[str] = field(default_factory=list)
    # managedResources[].ignoredByScheduler names: the reference adds these
    # to NodeResourcesFit's IgnoredResources for every profile
    # (vendor/.../scheduler/factory.go:105-130) so the in-tree resource fit
    # never rejects a pod for an extender-owned resource — the engine skips
    # encoding them into the fit tensors (ops/encode.Encoder).
    ignored_resources: List[str] = field(default_factory=list)
    ignorable: bool = False

    @staticmethod
    def from_dict(d: dict) -> "ExtenderConfig":
        timeout = d.get("httpTimeout")
        seconds = 30.0
        if isinstance(timeout, (int, float)):
            seconds = float(timeout)
        elif isinstance(timeout, str) and timeout:
            parsed = _parse_go_duration(timeout.strip())
            if parsed is None:
                raise ValueError(
                    f"extender httpTimeout: invalid duration {timeout!r}"
                )
            seconds = parsed
        if seconds < 0:
            # a Go http.Client with negative Timeout fails every request;
            # letting it through would crash urlopen(timeout<0)
            # mid-simulation instead of failing at parse time
            raise ValueError(
                f"extender httpTimeout: must not be negative, got {timeout!r}"
            )
        # httpTimeout: 0 is reference-valid (Go zero Timeout = no client
        # timeout); http_timeout_s=0.0 means "no timeout" in _send
        managed = [
            r for r in (d.get("managedResources") or []) if isinstance(r, dict)
        ]
        for r in managed:
            name = r.get("name", "")
            if name and not _is_extended_resource_name(name):
                # kube component-config validation requires managedResources
                # names to be extended resources (validation.go:149,
                # validateExtendedResourceName) — a native name like "cpu"
                # with ignoredByScheduler would disable the in-tree fit check
                raise ValueError(
                    f"extender managedResources: {name!r} is not an extended "
                    "resource name"
                )
        return ExtenderConfig(
            url_prefix=d.get("urlPrefix", "") or "",
            filter_verb=d.get("filterVerb", "") or "",
            prioritize_verb=d.get("prioritizeVerb", "") or "",
            preempt_verb=d.get("preemptVerb", "") or "",
            bind_verb=d.get("bindVerb", "") or "",
            weight=1 if d.get("weight") is None else int(d["weight"]),
            enable_https=bool(d.get("enableHTTPS")),
            http_timeout_s=seconds,
            node_cache_capable=bool(d.get("nodeCacheCapable")),
            managed_resources=[r.get("name", "") for r in managed],
            ignored_resources=[
                r.get("name", "")
                for r in managed
                if r.get("ignoredByScheduler") and r.get("name")
            ],
            ignorable=bool(d.get("ignorable")),
        )


@dataclass
class SchedulerConfig:
    """All profiles of one KubeSchedulerConfiguration, keyed by scheduler
    name. profiles[0] is the default profile (the reference forces
    Profiles[0].SchedulerName = default-scheduler, utils.go:318).
    `extenders` is config-global (shared by every profile), exactly like
    ComponentConfig.Extenders in the reference."""
    profiles: List[SchedulerProfile] = field(
        default_factory=lambda: [SchedulerProfile()]
    )
    extenders: List[ExtenderConfig] = field(default_factory=list)

    @property
    def default(self) -> SchedulerProfile:
        return self.profiles[0]

    # single-profile convenience accessors (most callers and the reference's
    # own examples use exactly one profile)
    @property
    def weights(self) -> Dict[str, float]:
        return self.default.weights

    @property
    def scheduler_name(self) -> str:
        return self.default.scheduler_name

    @property
    def percentage_of_nodes_to_score(self) -> int:
        return self.default.percentage_of_nodes_to_score

    def by_name(self) -> Dict[str, SchedulerProfile]:
        return {p.scheduler_name: p for p in self.profiles}


def default_profile() -> SchedulerProfile:
    """Default provider score weights + Simon at 1 (utils.go:304-368 plus
    algorithmprovider/registry.go:71-148)."""
    return SchedulerProfile()


def _apply_profile_doc(profile: SchedulerProfile, p: dict) -> None:
    plugins = p.get("plugins") or {}
    score = plugins.get("score") or {}
    for item in score.get("disabled") or []:
        name = item.get("name", "")
        if name == "*":
            for k in list(profile.weights):
                if k != "simon":  # simon is re-injected unconditionally
                    profile.weights[k] = 0.0
        else:
            profile.without_plugin(name)
    for item in score.get("enabled") or []:
        profile.with_plugin(item.get("name", ""), float(item.get("weight") or 1))
    filt = plugins.get("filter") or {}
    for item in filt.get("disabled") or []:
        name = item.get("name", "")
        if name == "*":
            for kube_name in FILTER_PLUGIN_MAP:
                profile.disable_filter(kube_name)
        else:
            profile.disable_filter(name)
    for item in filt.get("enabled") or []:
        profile.enable_filter(item.get("name", ""))


def load_scheduler_config(path: Optional[str]) -> SchedulerConfig:
    """Parse a KubeSchedulerConfiguration YAML into simon defaults.

    Mirrors InitKubeSchedulerConfiguration: every profile's score plugin
    enable/disable adjusts weights, filter enable/disable flips the filter
    mask, multiple profiles are kept keyed by schedulerName; simon's own
    plugins stay enabled regardless (the reference injects them after
    merging). Extenders parse into ExtenderConfig entries; a filter-less,
    prioritize-less extender (bind/preempt only) is rejected since nothing
    would ever call it."""
    cfg = SchedulerConfig()
    if not path:
        return cfg
    with open(path, "r") as fh:
        doc = yaml.safe_load(fh) or {}
    kind = doc.get("kind", "")
    if kind and kind != "KubeSchedulerConfiguration":
        raise ValueError(f"{path}: expected KubeSchedulerConfiguration, got {kind}")
    for e in doc.get("extenders") or []:
        ext = ExtenderConfig.from_dict(e or {})
        if not ext.url_prefix:
            raise ValueError(f"{path}: extender missing urlPrefix")
        if not ext.filter_verb and not ext.prioritize_verb and not ext.preempt_verb:
            raise ValueError(
                f"{path}: extender {ext.url_prefix}: neither filterVerb, "
                "prioritizeVerb nor preemptVerb set — nothing for the "
                "engine to call"
            )
        if ext.prioritize_verb and ext.weight <= 0:
            # kube's component-config validation: a prioritizing extender
            # must have a positive weight
            raise ValueError(
                f"{path}: extender {ext.url_prefix}: prioritizeVerb set "
                f"with non-positive weight {ext.weight}"
            )
        cfg.extenders.append(ext)
    profiles = doc.get("profiles") or [{}]
    names = [
        (p or {}).get("schedulerName", "default-scheduler") for p in profiles
    ]
    dupes = {n for n in names if names.count(n) > 1}
    if dupes:
        # kube's component-config validation rejects duplicate profile names
        raise ValueError(
            f"{path}: duplicate schedulerName(s) across profiles: "
            f"{sorted(dupes)}"
        )
    cfg.profiles = []
    for p in profiles:
        p = p or {}
        profile = default_profile()
        if p.get("schedulerName"):
            profile.scheduler_name = p["schedulerName"]
        _apply_profile_doc(profile, p)
        pct = doc.get("percentageOfNodesToScore")
        if pct:
            # accepted for config-compat; the TPU engine always scores all
            # nodes (simon pins 100 anyway)
            profile.percentage_of_nodes_to_score = int(pct)
        cfg.profiles.append(profile)
    return cfg
