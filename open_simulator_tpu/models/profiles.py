"""Scheduler profiles: which score plugins run, at what weight.

Parity: the reference assembles a KubeSchedulerConfiguration programmatically —
default provider plugins + Simon/Open-Local/Open-Gpu-Share injected, DefaultBinder
disabled, PercentageOfNodesToScore pinned to 100
(`/root/reference/pkg/simulator/utils.go:304-381`) — optionally merged with a
user-supplied scheduler config file (`--default-scheduler-config`,
`cmd/apply/apply.go:28`).

Here a profile is the weight vector handed to the score kernels; filters always
run (matching the default provider's filter set). Kube plugin names map to
kernel names so user config files written for the reference keep working.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

import yaml

from ..ops.kernels import DEFAULT_WEIGHTS

# kube plugin name -> kernel score name
PLUGIN_NAME_MAP = {
    "NodeResourcesLeastAllocated": "least_allocated",
    "NodeResourcesBalancedAllocation": "balanced_allocation",
    "NodeAffinity": "node_affinity",
    "TaintToleration": "taint_toleration",
    "PodTopologySpread": "topology_spread",
    "InterPodAffinity": "inter_pod_affinity",
    "NodePreferAvoidPods": "prefer_avoid_pods",
    "Simon": "simon",
    "Open-Local": "open_local",
    "Open-Gpu-Share": "gpu_share",
    # score-neutral in a fake cluster (no images, see SURVEY §2.2): accepted
    # and ignored so reference configs parse cleanly
    "ImageLocality": None,
    "NodeResourcesMostAllocated": None,
    "RequestedToCapacityRatio": None,
    "SelectorSpread": None,
    "DefaultPodTopologySpread": None,
}


@dataclass
class SchedulerProfile:
    scheduler_name: str = "default-scheduler"
    weights: Dict[str, float] = field(default_factory=lambda: dict(DEFAULT_WEIGHTS))
    percentage_of_nodes_to_score: int = 100  # simon pins 100 (utils.go:370)

    def with_plugin(self, kube_name: str, weight: float = 1.0) -> "SchedulerProfile":
        kernel = PLUGIN_NAME_MAP.get(kube_name)
        if kernel:
            self.weights[kernel] = weight
        return self

    def without_plugin(self, kube_name: str) -> "SchedulerProfile":
        kernel = PLUGIN_NAME_MAP.get(kube_name)
        if kernel:
            self.weights[kernel] = 0.0
        return self


def default_profile() -> SchedulerProfile:
    """Default provider score weights + Simon at 1 (utils.go:304-368 plus
    algorithmprovider/registry.go:71-148)."""
    return SchedulerProfile()


def load_scheduler_config(path: Optional[str]) -> SchedulerProfile:
    """Merge a KubeSchedulerConfiguration YAML into the simon defaults.

    Mirrors InitKubeSchedulerConfiguration: the user file's profile[0] score
    plugin enable/disable list adjusts weights; simon's own plugins stay
    enabled regardless (the reference injects them after merging)."""
    profile = default_profile()
    if not path:
        return profile
    with open(path, "r") as fh:
        doc = yaml.safe_load(fh) or {}
    kind = doc.get("kind", "")
    if kind and kind != "KubeSchedulerConfiguration":
        raise ValueError(f"{path}: expected KubeSchedulerConfiguration, got {kind}")
    profiles = doc.get("profiles") or [{}]
    p0 = profiles[0] or {}
    if p0.get("schedulerName"):
        profile.scheduler_name = p0["schedulerName"]
    plugins = p0.get("plugins") or {}
    score = plugins.get("score") or {}
    for item in score.get("disabled") or []:
        name = item.get("name", "")
        if name == "*":
            for k in list(profile.weights):
                if k != "simon":  # simon is re-injected unconditionally
                    profile.weights[k] = 0.0
        else:
            profile.without_plugin(name)
    for item in score.get("enabled") or []:
        profile.with_plugin(item.get("name", ""), float(item.get("weight") or 1))
    pct = doc.get("percentageOfNodesToScore")
    if pct:
        # accepted for config-compat; the TPU engine always scores all nodes
        # (simon pins 100 anyway)
        profile.percentage_of_nodes_to_score = int(pct)
    return profile
