"""Numeric-invariant abstract interpreter over captured jaxprs.

The lint engine (PR 2) proves *syntactic* jit hygiene; this pass proves the
*semantic* contracts the scheduler's correctness rests on, at the same
canonical bucketed shapes the jaxpr auditor traces:

  * masks stay {0,1}-valued (bool dtype all the way to the entry outputs),
  * every score plugin lands in [0,100] (kube's checkPluginScores contract),
  * no float output of any registered jit entry can be NaN,
  * the deliberate ``-inf * 0.0 → NaN`` sentinel pattern (fast.py's score
    lanes carry -inf on infeasible nodes) can never reach a selection point
    — argmax/argmin/reduce_max/reduce_min/sort operands are proven NaN-free,
    and
  * commit-carry resource counters (free CPU/mem, GPU memory, local-storage
    VG/device capacity) stay non-negative through every commit scan.

The last proof cannot come from the interval domain alone: a scan that
subtracts a request from ``free`` each step widens ``free.lo`` to -inf at
the fixpoint, because intervals cannot express the *relational* fact that
the decrement only fires where the feasibility filter held. Instead the
scan evaluator runs a structural **guarded-decrement matcher** over each
scan body: a float carry slot whose update is
``sub(carry_in, mul(convert(bool_guard), amount))`` is non-negative by
induction when the guard's backward slice contains a feasibility
comparison against that same carry slot (``req <= free + eps``). When the
compared quantity is syntactically the decrement amount the slot is
*proved* (``guard ⇒ amount ≤ slot + ε``, so ``slot ≥ -ε`` inductively);
when the slice ties the guard to the slot but not to the amount (the GPU
take path routes through an einsum the matcher does not chase) the slot is
reported *guarded* — the residual amount bound is exactly what the
exhaustive small-scope check (``simon prove``) discharges by running every
bounded universe through the real engine. Recognition is idiom-structural,
not a general theorem prover: an unguarded decrement of a float carry slot
is a finding (``commit-carry-nonneg``) unless the scan's final carry is
dropped — build_trajectory's virtual replay decrements unconditionally by
design (onehot ≡ 1) and its recorded rows are gated by the feasibility
masks stacked alongside them, so a dropped carry is classified ``virtual``
rather than flagged. Anything else the matcher cannot classify is reported
honestly as ``unrecognized`` rather than silently trusted.

Abstract domain — per-array, element-uniform::

    AVal = (lo, hi, pos_inf, neg_inf, nan, nonzero, kind)

``[lo, hi]`` bounds the *finite* values under real-number semantics
(float overflow/underflow are out of scope, which is sound for the proofs
above: they are about NaN production and value ranges after explicit
clips). Infinities are NOT encoded in the interval: ``pos_inf``/``neg_inf``
say "an element may be exactly ±inf", which is what the NaN transfer rules
need (``inf - inf``, ``0 * inf``, ``inf / inf``). Widening a bound to
±math.inf therefore means "finite but unknown magnitude" and does not set
the flags. ``nan`` is the taint bit; ``nonzero`` is the refinement the
safe-division idiom ``x / jnp.where(d == 0, 1.0, d)`` relies on (a
``select_n`` whose predicate is ``eq(d, 0)`` excludes 0 from the
not-equal branch).

Loops (``scan``) are handled by a join/widen fixpoint on the carry;
``pjit`` recurses. Any primitive without a transfer rule produces TOP and
an ``unhandled-primitive`` finding so the rule table cannot rot silently.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

NEG = float("-inf")
POS = float("inf")

#: primitives whose operands must be NaN-free: a NaN here corrupts which
#: lane gets *selected*, not just a value (the paper's placement-policy
#: correctness concern).
SELECTION_PRIMITIVES = frozenset(
    {"argmax", "argmin", "reduce_max", "reduce_min", "sort"}
)


# ---------------------------------------------------------------------------
# The domain
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class AVal:
    """Element-uniform abstraction of one array. See module docstring."""

    lo: float
    hi: float
    pos_inf: bool = False
    neg_inf: bool = False
    nan: bool = False
    nonzero: bool = False
    kind: str = "f"  # 'f' float / 'i' int / 'b' bool

    def flags(self) -> List[str]:
        out = []
        if self.pos_inf:
            out.append("+inf")
        if self.neg_inf:
            out.append("-inf")
        if self.nan:
            out.append("nan")
        if self.nonzero:
            out.append("nonzero")
        return out

    def describe(self) -> str:
        core = f"[{self.lo:g}, {self.hi:g}] {self.kind}"
        fl = self.flags()
        return core + (" {" + ",".join(fl) + "}" if fl else "")


def kind_of(dtype) -> str:
    d = np.dtype(dtype)
    if d == np.bool_:
        return "b"
    if np.issubdtype(d, np.integer):
        return "i"
    return "f"


def top(kind: str) -> AVal:
    if kind == "b":
        return AVal(0.0, 1.0, kind="b")
    return AVal(
        NEG, POS, pos_inf=(kind == "f"), neg_inf=(kind == "f"),
        nan=(kind == "f"), kind=kind,
    )


def const(v: float, kind: str = "f") -> AVal:
    return AVal(float(v), float(v), nonzero=(v != 0), kind=kind)


def from_concrete(x) -> AVal:
    """Abstraction of a concrete array (entry inputs, jaxpr consts)."""
    arr = np.asarray(x)
    kind = kind_of(arr.dtype)
    if arr.size == 0:
        return AVal(0.0, 0.0, kind=kind)
    if kind == "b":
        f = arr.astype(np.float64)
        return AVal(
            float(f.min()), float(f.max()), nonzero=bool(arr.all()), kind="b"
        )
    f = arr.astype(np.float64)
    nan = bool(np.isnan(f).any())
    pos_inf = bool((f == POS).any())
    neg_inf = bool((f == NEG).any())
    finite = f[np.isfinite(f)]
    lo = float(finite.min()) if finite.size else 0.0
    hi = float(finite.max()) if finite.size else 0.0
    nonzero = not bool((f == 0).any())
    return AVal(lo, hi, pos_inf, neg_inf, nan, nonzero, kind)


def _promote(a: AVal, b: AVal) -> str:
    ks = {a.kind, b.kind}
    if "f" in ks:
        return "f"
    if "i" in ks:
        return "i"
    return "b"


def join(a: AVal, b: AVal) -> AVal:
    return AVal(
        min(a.lo, b.lo),
        max(a.hi, b.hi),
        a.pos_inf or b.pos_inf,
        a.neg_inf or b.neg_inf,
        a.nan or b.nan,
        a.nonzero and b.nonzero,
        _promote(a, b),
    )


def widen(old: AVal, new: AVal) -> AVal:
    """Accelerate the scan fixpoint: any bound still moving goes to
    unknown-finite (±math.inf WITHOUT the inf flags — see module doc)."""
    return AVal(
        NEG if new.lo < old.lo else old.lo,
        POS if new.hi > old.hi else old.hi,
        old.pos_inf or new.pos_inf,
        old.neg_inf or new.neg_inf,
        old.nan or new.nan,
        old.nonzero and new.nonzero,
        _promote(old, new),
    )


def may_pos(a: AVal) -> bool:
    return a.hi > 0 or a.pos_inf


def may_neg(a: AVal) -> bool:
    return a.lo < 0 or a.neg_inf


def may_zero(a: AVal) -> bool:
    return (not a.nonzero) and a.lo <= 0 <= a.hi


def inf_any(a: AVal) -> bool:
    return a.pos_inf or a.neg_inf


# Bound arithmetic that never manufactures NaN: inf-inf / inf*0 at the
# BOUND level means "unknown", resolved toward the conservative side.
def _badd(x: float, y: float, side: int) -> float:
    r = x + y
    if math.isnan(r):
        return NEG if side < 0 else POS
    return r


def _bmul(x: float, y: float) -> float:
    if x == 0 or y == 0:
        return 0.0
    return x * y


def _bdiv(x: float, y: float, side: int) -> float:
    if x == 0:
        return 0.0
    if y == 0:  # callers exclude 0 from y's interval; defensive only
        return NEG if side < 0 else POS
    r = x / y
    if math.isnan(r):
        return NEG if side < 0 else POS
    return r


# ---------------------------------------------------------------------------
# Transfer rules
# ---------------------------------------------------------------------------

def _r_add(a: AVal, b: AVal) -> AVal:
    return AVal(
        _badd(a.lo, b.lo, -1),
        _badd(a.hi, b.hi, +1),
        a.pos_inf or b.pos_inf,
        a.neg_inf or b.neg_inf,
        a.nan or b.nan or (a.pos_inf and b.neg_inf) or (a.neg_inf and b.pos_inf),
        False,
        _promote(a, b),
    )


def _r_sub(a: AVal, b: AVal) -> AVal:
    return AVal(
        _badd(a.lo, -b.hi, -1),
        _badd(a.hi, -b.lo, +1),
        a.pos_inf or b.neg_inf,
        a.neg_inf or b.pos_inf,
        a.nan or b.nan or (a.pos_inf and b.pos_inf) or (a.neg_inf and b.neg_inf),
        False,
        _promote(a, b),
    )


def _r_mul(a: AVal, b: AVal) -> AVal:
    prods = (
        _bmul(a.lo, b.lo), _bmul(a.lo, b.hi),
        _bmul(a.hi, b.lo), _bmul(a.hi, b.hi),
    )
    # THE sentinel rule: ±inf times a possibly-zero factor is NaN.
    nan = (
        a.nan or b.nan
        or (inf_any(a) and may_zero(b))
        or (inf_any(b) and may_zero(a))
    )
    pos_inf = (
        (a.pos_inf and may_pos(b)) or (a.neg_inf and may_neg(b))
        or (b.pos_inf and may_pos(a)) or (b.neg_inf and may_neg(a))
    )
    neg_inf = (
        (a.pos_inf and may_neg(b)) or (a.neg_inf and may_pos(b))
        or (b.pos_inf and may_neg(a)) or (b.neg_inf and may_pos(a))
    )
    return AVal(
        min(prods), max(prods), pos_inf, neg_inf, nan,
        a.nonzero and b.nonzero, _promote(a, b),
    )


def _r_div(a: AVal, b: AVal) -> AVal:
    nan = (
        a.nan or b.nan
        or (may_zero(a) and may_zero(b))          # 0 / 0
        or (inf_any(a) and inf_any(b))            # inf / inf
    )
    if may_neg(b):  # denominator sign unknown: infs can land either side
        pos_inf = inf_any(a) or (may_zero(b) and (may_pos(a) or may_neg(a)))
        neg_inf = pos_inf
    else:
        pos_inf = a.pos_inf or (may_pos(a) and may_zero(b))
        neg_inf = a.neg_inf or (may_neg(a) and may_zero(b))
    if b.nonzero and (b.lo > 0 or b.hi < 0):
        quots = (
            _bdiv(a.lo, b.lo, -1), _bdiv(a.lo, b.hi, -1),
            _bdiv(a.hi, b.lo, +1), _bdiv(a.hi, b.hi, +1),
        )
        lo, hi = min(quots), max(quots)
    else:
        # 0 in (or arbitrarily near) the denominator range: unbounded
        lo, hi = NEG, POS
    return AVal(lo, hi, pos_inf, neg_inf, nan, False, _promote(a, b))


def _r_rem(a: AVal, b: AVal) -> AVal:
    m = max(abs(b.lo), abs(b.hi))
    lo = 0.0 if a.lo >= 0 and not a.neg_inf else -m
    hi = 0.0 if a.hi <= 0 and not a.pos_inf else m
    nan = a.nan or b.nan or inf_any(a) or (
        may_zero(b) and _promote(a, b) == "f"
    )
    return AVal(lo, hi, False, False, nan, False, _promote(a, b))


def _r_max(a: AVal, b: AVal) -> AVal:
    lo_cands = [max(a.lo, b.lo)]
    if a.neg_inf:
        lo_cands.append(b.lo)
    if b.neg_inf:
        lo_cands.append(a.lo)
    return AVal(
        min(lo_cands),
        max(a.hi, b.hi),
        a.pos_inf or b.pos_inf,
        a.neg_inf and b.neg_inf,
        a.nan or b.nan,
        False,
        _promote(a, b),
    )


def _r_min(a: AVal, b: AVal) -> AVal:
    hi_cands = [min(a.hi, b.hi)]
    if a.pos_inf:
        hi_cands.append(b.hi)
    if b.pos_inf:
        hi_cands.append(a.hi)
    return AVal(
        min(a.lo, b.lo),
        max(hi_cands),
        a.pos_inf and b.pos_inf,
        a.neg_inf or b.neg_inf,
        a.nan or b.nan,
        False,
        _promote(a, b),
    )


def _r_neg(a: AVal) -> AVal:
    return AVal(
        -a.hi, -a.lo, a.neg_inf, a.pos_inf, a.nan, a.nonzero, a.kind
    )


def _r_abs(a: AVal) -> AVal:
    if a.lo >= 0:
        lo, hi = a.lo, a.hi
    elif a.hi <= 0:
        lo, hi = -a.hi, -a.lo
    else:
        lo, hi = 0.0, max(-a.lo, a.hi)
    return AVal(lo, hi, inf_any(a), False, a.nan, a.nonzero, a.kind)


def _r_sign(a: AVal) -> AVal:
    lo = -1.0 if may_neg(a) else (0.0 if may_zero(a) else 1.0)
    hi = 1.0 if may_pos(a) else (0.0 if may_zero(a) else -1.0)
    return AVal(lo, hi, False, False, a.nan, False, a.kind)


def _r_floor(a: AVal) -> AVal:
    lo = a.lo if math.isinf(a.lo) else math.floor(a.lo)
    hi = a.hi if math.isinf(a.hi) else math.floor(a.hi)
    return AVal(lo, hi, a.pos_inf, a.neg_inf, a.nan, False, a.kind)


def _bool_out() -> AVal:
    return AVal(0.0, 1.0, kind="b")


def _sum_of(a: AVal, n: int) -> AVal:
    """Sum of exactly n elements each abstracted by `a`."""
    if n <= 0:
        return AVal(0.0, 0.0, kind=a.kind)
    return AVal(
        _bmul(float(n), a.lo) if a.lo < 0 else a.lo,
        _bmul(float(n), a.hi) if a.hi > 0 else a.hi,
        a.pos_inf,
        a.neg_inf,
        a.nan or (a.pos_inf and a.neg_inf),  # mixed ±inf sum
        False,
        a.kind,
    )


def _convert(a: AVal, new_kind: str) -> AVal:
    if new_kind == a.kind:
        return a
    if new_kind == "b":
        lo = 1.0 if a.nonzero else 0.0
        all_zero = a.lo == 0 == a.hi and not inf_any(a) and not a.nan
        return AVal(lo, 0.0 if all_zero else 1.0,
                    nonzero=a.nonzero, kind="b")
    if new_kind == "i":
        if a.nan or inf_any(a):
            return top("i")  # float->int of nan/inf is undefined
        lo = a.lo if math.isinf(a.lo) else math.floor(a.lo)
        hi = a.hi if math.isinf(a.hi) else math.ceil(a.hi)
        return AVal(lo, hi, nonzero=a.nonzero, kind="i")
    return dataclasses.replace(a, kind="f")


# ---------------------------------------------------------------------------
# Findings / reports
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True, order=True)
class InvariantFinding:
    entry: str
    kind: str       # nan-output | selection-taint | score-range | unhandled-primitive
    primitive: str
    path: str       # eqn path, e.g. "scan[17]/eqn3"
    message: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


#: verdict ladder for one float carry slot of one scan, strongest first.
CARRY_PROVED = "proved"              # guard ⇒ amount ≤ slot + ε
CARRY_GUARDED = "guarded"            # bool guard tied to slot, amount not
CARRY_NON_DECREASING = "non-decreasing"
CARRY_UNCHANGED = "unchanged"
CARRY_UNRECOGNIZED = "unrecognized"  # update shape outside the idiom set
CARRY_VIRTUAL = "virtual"            # unguarded, but the final carry is
                                     # dropped: a replay carry, not state
CARRY_UNGUARDED = "unguarded"        # decrement with no bool guard: finding


@dataclasses.dataclass(frozen=True, order=True)
class CommitCarryReport:
    """Non-negativity verdict for one float carry slot of one scan."""

    path: str    # eqn path of the scan, e.g. "eqn0/scan"
    slot: int
    shape: str
    verdict: str
    detail: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class _Scope:
    """Per-jaxpr def-use environment. `alias` links this jaxpr's invars back
    to the caller's atoms (pjit inlining), so dataflow facts like "this
    select_n's predicate is eq(d, 0)" survive the _where sub-jaxpr split."""

    __slots__ = ("def_of", "alias")

    def __init__(self) -> None:
        self.def_of: Dict = {}
        self.alias: Dict = {}


# ---------------------------------------------------------------------------
# Guarded-decrement matcher helpers (commit-carry non-negativity)
# ---------------------------------------------------------------------------

#: primitives that forward their first operand's values unchanged — the
#: matcher looks straight through them when resolving atom identity.
_SHAPE_PRIMS = frozenset(
    {"broadcast_in_dim", "reshape", "squeeze", "copy", "transpose",
     "stop_gradient"}
)

#: feasibility comparisons; eq/ne deliberately excluded (an equality on a
#: resource counter does not bound a decrement).
_ORDER_COMPARISONS = frozenset({"gt", "ge", "lt", "le"})


def _chase(defs: Dict, atom, literal_t):
    """Resolve an atom through value-preserving shape primitives."""
    while not isinstance(atom, literal_t):
        q = defs.get(atom)
        if q is None or q.primitive.name not in _SHAPE_PRIMS:
            return atom
        atom = q.invars[0]
    return atom


def _chase_eps(defs: Dict, atom, literal_t):
    """Like _chase, but also through add/sub with a literal operand — the
    commit filters compare against ``free + _EPS``, and the slop term must
    not hide the carry slot from the matcher."""
    while True:
        if isinstance(atom, literal_t):
            return atom
        q = defs.get(atom)
        if q is None:
            return atom
        name = q.primitive.name
        if name in _SHAPE_PRIMS:
            atom = q.invars[0]
            continue
        if name in ("add", "sub"):
            a, b = (_chase(defs, x, literal_t) for x in q.invars)
            if isinstance(b, literal_t):
                atom = q.invars[0]
                continue
            if name == "add" and isinstance(a, literal_t):
                atom = q.invars[1]
                continue
        return atom


def _mul_factors(defs: Dict, atom, literal_t) -> List:
    """Flatten a (possibly nested) product into its factor atoms, each
    resolved through shape primitives."""
    atom = _chase(defs, atom, literal_t)
    q = defs.get(atom) if not isinstance(atom, literal_t) else None
    if q is not None and q.primitive.name == "mul":
        return (_mul_factors(defs, q.invars[0], literal_t)
                + _mul_factors(defs, q.invars[1], literal_t))
    return [atom]


def _guard_origin(defs: Dict, factor, literal_t):
    """If `factor` is a {0,1}-valued guard (a bool converted to the carry
    dtype), return the underlying bool var; else None."""
    if isinstance(factor, literal_t):
        return None
    q = defs.get(factor)
    if (
        q is not None
        and q.primitive.name == "convert_element_type"
        and np.dtype(q.invars[0].aval.dtype) == np.bool_
    ):
        g = _chase(defs, q.invars[0], literal_t)
        return None if isinstance(g, literal_t) else g
    return None


def _comparisons_in_slice(defs: Dict, roots: Sequence, literal_t) -> List:
    """All order-comparison eqns in the backward slice of `roots` (the
    transitive defs of the guard inside one scan body)."""
    seen_vars = set()
    seen_eqns: Dict[int, object] = {}
    stack = list(roots)
    while stack:
        v = stack.pop()
        if v in seen_vars:
            continue
        seen_vars.add(v)
        q = defs.get(v)
        if q is None:
            continue
        if id(q) not in seen_eqns:
            seen_eqns[id(q)] = q
            for a in q.invars:
                if not isinstance(a, literal_t) and a in defs:
                    stack.append(a)
    return [q for q in seen_eqns.values()
            if q.primitive.name in _ORDER_COMPARISONS]


def _aval_of(env: Dict, atom, literal_t) -> AVal:
    if isinstance(atom, literal_t):
        return from_concrete(atom.val)
    got = env.get(atom)
    if got is not None:
        return got
    return top(kind_of(atom.aval.dtype))


# ---------------------------------------------------------------------------
# The interpreter
# ---------------------------------------------------------------------------

class Interpreter:
    MAX_FIXPOINT_ITERS = 8
    WIDEN_AFTER = 2

    def __init__(self, entry: str) -> None:
        self.entry = entry
        self._findings: Dict[Tuple, InvariantFinding] = {}
        self._record = True
        self.carry_proofs: List[CommitCarryReport] = []

    # -- findings -----------------------------------------------------------

    def finding(self, kind: str, primitive: str, path: str, message: str):
        if not self._record:
            return
        key = (kind, primitive, path)
        if key not in self._findings:
            self._findings[key] = InvariantFinding(
                self.entry, kind, primitive, path, message
            )

    @property
    def findings(self) -> List[InvariantFinding]:
        return sorted(self._findings.values())

    # -- jaxpr walking ------------------------------------------------------

    def run_closed(self, closed, in_avals: Sequence[AVal], path: str = "",
                   alias: Optional[Dict] = None,
                   env_out: Optional[Dict] = None) -> List[AVal]:
        consts = [from_concrete(c) for c in closed.consts]
        return self.run_jaxpr(closed.jaxpr, consts, in_avals, path, alias,
                              env_out)

    def run_jaxpr(self, jaxpr, const_avals: Sequence[AVal],
                  in_avals: Sequence[AVal], path: str = "",
                  alias: Optional[Dict] = None,
                  env_out: Optional[Dict] = None) -> List[AVal]:
        import jax

        literal_t = jax.core.Literal
        dropvar_t = getattr(jax.core, "DropVar", ())
        env: Dict = {}
        scope = _Scope()
        if alias:
            scope.alias = alias

        for v, a in zip(jaxpr.constvars, const_avals):
            env[v] = a
        for v, a in zip(jaxpr.invars, in_avals):
            env[v] = a

        def read(atom) -> AVal:
            if isinstance(atom, literal_t):
                return from_concrete(atom.val)
            return env[atom]

        for idx, eqn in enumerate(jaxpr.eqns):
            here = f"{path}eqn{idx}"
            ins = [read(x) for x in eqn.invars]
            outs = self.eval_eqn(eqn, ins, here, scope)
            for v, out in zip(eqn.outvars, outs):
                if not isinstance(v, dropvar_t):
                    env[v] = out
                    scope.def_of[v] = eqn

        if env_out is not None:
            env_out.update(env)
        return [read(v) for v in jaxpr.outvars]

    # -- eqn dispatch -------------------------------------------------------

    def eval_eqn(self, eqn, ins: List[AVal], path: str,
                 scope: _Scope) -> List[AVal]:
        name = eqn.primitive.name

        if name in SELECTION_PRIMITIVES:
            self._check_selection(eqn, ins, path)

        if name == "pjit":
            sub = eqn.params["jaxpr"]
            child_alias = {
                v: (scope, a)
                for v, a in zip(sub.jaxpr.invars, eqn.invars)
            }
            return self.run_closed(
                sub, ins,
                path=f"{path}/{eqn.params.get('name', 'pjit')}/",
                alias=child_alias,
            )
        if name == "scan":
            return self._eval_scan(eqn, ins, path)
        if name == "select_n":
            return [self._eval_select_n(eqn, ins, scope)]

        rule = _RULES.get(name)
        if rule is None:
            self.finding(
                "unhandled-primitive", name, path,
                f"no transfer rule for primitive '{name}'; result widened "
                "to TOP",
            )
            return [top(kind_of(v.aval.dtype)) for v in eqn.outvars]
        return rule(self, eqn, ins)

    def _check_selection(self, eqn, ins: List[AVal], path: str) -> None:
        name = eqn.primitive.name
        n_keys = eqn.params.get("num_keys", len(ins)) if name == "sort" else 1
        for i, a in enumerate(ins[:n_keys] if name == "sort" else ins[:1]):
            if a.nan:
                self.finding(
                    "selection-taint", name, path,
                    f"operand {i} of {name} may be NaN "
                    f"({a.describe()}): a poisoned lane can steal the "
                    "selection",
                )

    # -- select_n with eq/ne refinement ------------------------------------

    def _eval_select_n(self, eqn, ins: List[AVal], scope: _Scope) -> AVal:
        cases = list(ins[1:])
        refined = self._refine_select(eqn, scope)
        if refined is not None:
            cases[refined] = dataclasses.replace(cases[refined], nonzero=True)
        out = cases[0]
        for c in cases[1:]:
            out = join(out, c)
        return out

    @staticmethod
    def _resolve(atom, scope: Optional[_Scope]):
        """Canonical (atom, scope) pair: look through broadcast/reshape/copy
        chains and across pjit boundaries via the scope alias links."""
        import jax

        while True:
            if isinstance(atom, jax.core.Literal) or scope is None:
                return atom, None
            d = scope.def_of.get(atom)
            if d is not None and d.primitive.name in (
                "broadcast_in_dim", "reshape", "squeeze", "copy",
            ):
                atom = d.invars[0]
                continue
            if d is None and atom in scope.alias:
                scope, atom = scope.alias[atom]
                continue
            return atom, scope

    def _refine_select(self, eqn, scope: _Scope) -> Optional[int]:
        """`where(d == 0, k, d)` lowers to `select_n(eq(d,0), d, k)`: on the
        case-0 (pred false) branch d != 0. Symmetrically for ne on case 1.
        Returns the case index to mark nonzero when the branch operand is
        the compared variable and the comparand is exactly 0."""
        import jax

        if len(eqn.invars) != 3:
            return None
        pred_atom, pred_scope = self._resolve(eqn.invars[0], scope)
        if pred_scope is None:
            return None
        pred_def = pred_scope.def_of.get(pred_atom)
        if pred_def is None or pred_def.primitive.name not in ("eq", "ne"):
            return None
        case_idx = 0 if pred_def.primitive.name == "eq" else 1
        lhs = self._resolve(pred_def.invars[0], pred_scope)
        rhs = self._resolve(pred_def.invars[1], pred_scope)
        case_src = self._resolve(eqn.invars[1 + case_idx], scope)

        def lit_zero(res) -> bool:
            a = res[0]
            return isinstance(a, jax.core.Literal) and bool(
                np.all(np.asarray(a.val) == 0)
            )

        for var_side, lit_side in ((lhs, rhs), (rhs, lhs)):
            if (
                lit_zero(lit_side)
                and case_src[0] is var_side[0]
                and case_src[1] is var_side[1]
            ):
                return case_idx
        return None

    # -- scan fixpoint ------------------------------------------------------

    def _eval_scan(self, eqn, ins: List[AVal], path: str) -> List[AVal]:
        body = eqn.params["jaxpr"]
        n_const = eqn.params["num_consts"]
        n_carry = eqn.params["num_carry"]
        consts = list(ins[:n_const])
        carry = list(ins[n_const:n_const + n_carry])
        xs = list(ins[n_const + n_carry:])

        prev_record = self._record
        self._record = False  # findings only on the final, sound pass
        try:
            for it in range(self.MAX_FIXPOINT_ITERS):
                outs = self.run_closed(body, consts + carry + xs)
                new_carry = [join(c, o) for c, o in zip(outs[:n_carry], carry)]
                if new_carry == carry:
                    break
                if it >= self.WIDEN_AFTER:
                    new_carry = [
                        widen(c, n) for c, n in zip(carry, new_carry)
                    ]
                carry = new_carry
            else:
                carry = [
                    top(c.kind) if c.kind != "b" else _bool_out()
                    for c in carry
                ]
        finally:
            self._record = prev_record

        env_map: Dict = {}
        outs = self.run_closed(body, consts + carry + xs,
                               path=f"{path}/scan/", env_out=env_map)
        if self._record:
            self._check_commit_carry(eqn, env_map, f"{path}/scan")
        final_carry = [join(c, o) for c, o in zip(outs[:n_carry], carry)]
        return final_carry + outs[n_carry:]

    # -- commit-carry non-negativity (guarded-decrement matcher) ------------

    def _check_commit_carry(self, eqn, env: Dict, path: str) -> None:
        """Classify every float carry slot of one scan body. See module
        docstring: structural recognition of the commit idiom, with the
        amount bound on *guarded* slots discharged by ``simon prove``."""
        import jax

        body = eqn.params["jaxpr"].jaxpr
        n_const = eqn.params["num_consts"]
        n_carry = eqn.params["num_carry"]
        defs: Dict = {}
        for q in body.eqns:
            for v in q.outvars:
                defs[v] = q
        carry_vars = list(body.invars[n_const:n_const + n_carry])

        dropvar_t = getattr(jax.core, "DropVar", ())
        for slot, (cv, ov) in enumerate(zip(carry_vars,
                                            body.outvars[:n_carry])):
            if kind_of(cv.aval.dtype) != "f":
                continue
            verdict, detail = self._classify_carry_slot(
                defs, env, cv, ov, jax.core.Literal
            )
            if verdict == CARRY_UNGUARDED and isinstance(
                eqn.outvars[slot], dropvar_t
            ):
                # the final carry never escapes the scan: this is a
                # virtual-commit replay (build_trajectory's onehot ≡ 1
                # evolution), not committed cluster state. Negative values
                # are reachable by design and gated by the feasibility
                # masks recorded alongside them.
                verdict = CARRY_VIRTUAL
                detail = ("unconditional decrement, but the final carry is "
                          "dropped — a virtual replay carry whose rows are "
                          "gated by recorded feasibility masks downstream")
            self.carry_proofs.append(CommitCarryReport(
                path, slot, cv.aval.str_short(), verdict, detail
            ))
            if verdict == CARRY_UNGUARDED:
                self.finding(
                    "commit-carry-nonneg", "scan", f"{path}/carry{slot}",
                    f"carry slot {slot} ({cv.aval.str_short()}): {detail}",
                )

    def _classify_carry_slot(self, defs, env, cv, ov, literal_t
                             ) -> Tuple[str, str]:
        out_atom = _chase(defs, ov, literal_t)
        if out_atom is cv:
            return CARRY_UNCHANGED, "carry slot is threaded through unchanged"
        q = defs.get(out_atom)
        if q is None:
            return (CARRY_UNRECOGNIZED,
                    "carry output rebinds a different input; not the commit "
                    "idiom")

        if q.primitive.name == "add":
            sides = [_chase(defs, a, literal_t) for a in q.invars]
            if cv not in sides:
                return (CARRY_UNRECOGNIZED,
                        f"update is add() but neither operand is the carry "
                        f"slot")
            inc = q.invars[1 - sides.index(cv)]
            av = _aval_of(env, inc, literal_t)
            if av.lo >= 0 and not av.neg_inf and not av.nan:
                return (CARRY_NON_DECREASING,
                        f"update adds a provably non-negative increment "
                        f"({av.describe()})")
            return (CARRY_UNRECOGNIZED,
                    f"update adds an increment the domain cannot bound "
                    f"below 0 ({av.describe()})")

        if q.primitive.name != "sub":
            return (CARRY_UNRECOGNIZED,
                    f"update primitive '{q.primitive.name}' is outside the "
                    f"guarded-decrement idiom")
        if _chase(defs, q.invars[0], literal_t) is not cv:
            return (CARRY_UNRECOGNIZED,
                    "sub() minuend is not the carry slot itself")

        dec = q.invars[1]
        factors = _mul_factors(defs, dec, literal_t)
        guards, amounts = [], []
        for f in factors:
            g = _guard_origin(defs, f, literal_t)
            (guards if g is not None else amounts).append(
                g if g is not None else f
            )
        if not guards:
            av = _aval_of(env, dec, literal_t)
            if av.hi <= 0 and not av.pos_inf and not av.nan:
                return (CARRY_NON_DECREASING,
                        f"unconditional sub of a non-positive amount "
                        f"({av.describe()})")
            return (CARRY_UNGUARDED,
                    "decrement has no {0,1} bool-derived guard factor; the "
                    "slot can go negative whenever the amount exceeds it")

        # the guard's backward slice: does a feasibility comparison tie the
        # guard to this carry slot (and, ideally, to the decrement amount)?
        tied_to_slot = False
        tied_to_amount = False
        for comp in _comparisons_in_slice(defs, guards, literal_t):
            sides = [_chase_eps(defs, a, literal_t) for a in comp.invars]
            for i in (0, 1):
                if sides[i] is cv:
                    tied_to_slot = True
                    other = sides[1 - i]
                    if any(other is a for a in amounts):
                        tied_to_amount = True
        if tied_to_slot and tied_to_amount:
            return (CARRY_PROVED,
                    "guard ⇒ decrement amount ≤ slot + ε (feasibility "
                    "comparison on this slot vs the amount is in the "
                    "guard's slice): slot ≥ -ε by induction")
        if tied_to_slot:
            return (CARRY_GUARDED,
                    "bool guard's slice compares this slot against a bound, "
                    "but the decrement amount is not syntactically the "
                    "compared quantity; residual discharged by simon prove")
        return (CARRY_GUARDED,
                "decrement is {0,1}-guarded but no comparison on this slot "
                "was found in the guard's slice; non-negativity rests on "
                "the small-scope exhaustive check (simon prove)")


# ---------------------------------------------------------------------------
# Rule table
# ---------------------------------------------------------------------------

def _binary(fn: Callable[[AVal, AVal], AVal]):
    return lambda interp, eqn, ins: [fn(ins[0], ins[1])]


def _unary(fn: Callable[[AVal], AVal]):
    return lambda interp, eqn, ins: [fn(ins[0])]


def _identity(interp, eqn, ins):
    return [ins[0]]


def _join_all(interp, eqn, ins):
    out = ins[0]
    for a in ins[1:]:
        out = join(out, a)
    return [out]


def _bool_rule(interp, eqn, ins):
    return [_bool_out()]


def _logic_rule(interp, eqn, ins):
    if all(a.kind == "b" for a in ins):
        return [_bool_out()]
    return [top("i")]  # bitwise on ints: no precision needed here


def _r_convert(interp, eqn, ins):
    return [_convert(ins[0], kind_of(eqn.params["new_dtype"]))]


def _r_iota(interp, eqn, ins):
    n = eqn.params["shape"][eqn.params["dimension"]]
    return [AVal(0.0, float(max(n - 1, 0)),
                 kind=kind_of(eqn.params["dtype"]))]


def _reduced_count(eqn) -> int:
    shape = eqn.invars[0].aval.shape
    n = 1
    for ax in eqn.params["axes"]:
        n *= shape[ax]
    return n


def _r_reduce_sum(interp, eqn, ins):
    return [_sum_of(ins[0], _reduced_count(eqn))]


def _r_reduce_minmax(keep: str):
    def rule(interp, eqn, ins):
        a = ins[0]
        if _reduced_count(eqn) == 0:
            # reduce over an empty axis yields the monoid identity
            ident = NEG if keep == "max" else POS
            return [AVal(0.0, 0.0, pos_inf=(ident == POS),
                         neg_inf=(ident == NEG), kind=a.kind)]
        return [dataclasses.replace(a, nonzero=False)]

    return rule


def _r_cumsum(interp, eqn, ins):
    n = eqn.invars[0].aval.shape[eqn.params["axis"]]
    a = ins[0]
    s = _sum_of(a, max(n, 1))
    # a prefix sum of k<=n terms: bounds include the 1-term case too
    return [AVal(min(s.lo, a.lo, 0.0) if n > 1 else s.lo,
                 max(s.hi, a.hi, 0.0) if n > 1 else s.hi,
                 s.pos_inf, s.neg_inf, s.nan, False, a.kind)]


def _r_dot_general(interp, eqn, ins):
    (lc, _), _ = eqn.params["dimension_numbers"]
    shape = eqn.invars[0].aval.shape
    n = 1
    for ax in lc:
        n *= shape[ax]
    return [_sum_of(_r_mul(ins[0], ins[1]), n)]


def _r_argminmax(interp, eqn, ins):
    axes = eqn.params["axes"]
    shape = eqn.invars[0].aval.shape
    n = 1
    for ax in axes:
        n *= shape[ax]
    return [AVal(0.0, float(max(n - 1, 0)),
                 kind=kind_of(eqn.params["index_dtype"]))]


def _r_sort(interp, eqn, ins):
    return [dataclasses.replace(a, nonzero=a.nonzero) for a in ins]


def _r_gather(interp, eqn, ins):
    out = ins[0]
    if "FILL" in str(eqn.params.get("mode", "")).upper():
        out = join(out, const(0.0, out.kind))
    return [out]


def _r_scatter(interp, eqn, ins):
    return [join(ins[0], ins[2])]


def _r_scatter_add(interp, eqn, ins):
    op, upd = ins[0], ins[2]
    # an unknown number of updates may hit one slot: only the direction
    # updates cannot push survives as a bound
    lo = NEG if may_neg(upd) else op.lo
    hi = POS if may_pos(upd) else op.hi
    nan = op.nan or upd.nan or (
        (op.pos_inf or upd.pos_inf) and (op.neg_inf or upd.neg_inf)
    )
    return [AVal(lo, hi, op.pos_inf or upd.pos_inf,
                 op.neg_inf or upd.neg_inf, nan, False, op.kind)]


def _r_clamp(interp, eqn, ins):
    mn, x, mx = ins
    return [_r_min(_r_max(x, mn), mx)]


def _r_dynamic_update_slice(interp, eqn, ins):
    return [join(ins[0], ins[1])]


def _r_is_finite(interp, eqn, ins):
    return [_bool_out()]


def _r_bitcast(interp, eqn, ins):
    """Reinterpreting bits severs every numeric relationship between input
    and output (an f32 in [0,1] bitcast to u32 spans almost the whole u32
    range), so the only sound transfer is TOP of the OUTPUT kind. That stays
    precise where it matters: a bitcast to an integer kind cannot introduce
    inf/NaN, which is exactly what ops.delta's digest_fold relies on."""
    return [top(kind_of(eqn.params["new_dtype"]))]


_RULES: Dict[str, Callable] = {
    "add": _binary(_r_add),
    "sub": _binary(_r_sub),
    "mul": _binary(_r_mul),
    "div": _binary(_r_div),
    "rem": _binary(_r_rem),
    "max": _binary(_r_max),
    "min": _binary(_r_min),
    "neg": _unary(_r_neg),
    "abs": _unary(_r_abs),
    "sign": _unary(_r_sign),
    "floor": _unary(_r_floor),
    "clamp": _r_clamp,
    "eq": _bool_rule,
    "ne": _bool_rule,
    "ge": _bool_rule,
    "gt": _bool_rule,
    "le": _bool_rule,
    "lt": _bool_rule,
    "is_finite": _r_is_finite,
    "and": _logic_rule,
    "or": _logic_rule,
    "xor": _logic_rule,
    "not": _logic_rule,
    "reduce_and": _bool_rule,
    "reduce_or": _bool_rule,
    "reduce_sum": _r_reduce_sum,
    "reduce_max": _r_reduce_minmax("max"),
    "reduce_min": _r_reduce_minmax("min"),
    "cumsum": _r_cumsum,
    "dot_general": _r_dot_general,
    "argmax": _r_argminmax,
    "argmin": _r_argminmax,
    "sort": _r_sort,
    "iota": _r_iota,
    "convert_element_type": _r_convert,
    "bitcast_convert_type": _r_bitcast,
    "broadcast_in_dim": _identity,
    "reshape": _identity,
    "transpose": _identity,
    "squeeze": _identity,
    "slice": _identity,
    "rev": _identity,
    "copy": _identity,
    "stop_gradient": _identity,
    "dynamic_slice": lambda interp, eqn, ins: [ins[0]],
    "dynamic_update_slice": _r_dynamic_update_slice,
    "concatenate": _join_all,
    "gather": _r_gather,
    "scatter": _r_scatter,
    "scatter-add": _r_scatter_add,
    "pad": _join_all,  # pad value is the last operand; join covers it
}


# ---------------------------------------------------------------------------
# Entry-tier audit: every registered jit entry on canonical shapes
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class EntryInvariantReport:
    entry: str
    bool_outputs: int
    float_outputs: int
    outputs: List[str]
    findings: List[InvariantFinding]
    commit_carry: List[CommitCarryReport] = dataclasses.field(
        default_factory=list
    )

    @property
    def ok(self) -> bool:
        return not self.findings

    def carry_verdict_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for p in self.commit_carry:
            out[p.verdict] = out.get(p.verdict, 0) + 1
        return out

    def to_dict(self) -> dict:
        return {
            "entry": self.entry,
            "ok": self.ok,
            "bool_outputs": self.bool_outputs,
            "float_outputs": self.float_outputs,
            "outputs": self.outputs,
            "commit_carry": [p.to_dict() for p in sorted(self.commit_carry)],
            "findings": [f.to_dict() for f in self.findings],
        }


def check_traceable(entry: str, fn, args, kwargs=None) -> EntryInvariantReport:
    """Abstractly interpret one traceable callable on concrete args.

    Uses `.trace()` when `fn` is a jit Function (exact invar<->arg mapping
    via the Traced's flat args) and `jax.make_jaxpr` otherwise.
    """
    import jax

    kwargs = kwargs or {}
    if hasattr(fn, "trace"):
        traced = fn.trace(*args, **kwargs)
        closed = traced.jaxpr
        flat = traced._args_flat
    else:
        closed = jax.make_jaxpr(fn)(*args, **kwargs)
        flat = jax.tree_util.tree_leaves((args, kwargs))
    in_avals = [from_concrete(x) for x in flat]
    interp = Interpreter(entry)
    outs = interp.run_closed(closed, in_avals)

    bool_outputs = 0
    float_outputs = 0
    rendered = []
    for i, (out, var) in enumerate(zip(outs, closed.jaxpr.outvars)):
        rendered.append(out.describe())
        if out.kind == "b":
            bool_outputs += 1
            continue
        if out.kind == "f":
            float_outputs += 1
            if out.nan:
                interp.finding(
                    "nan-output", "output", f"out{i}",
                    f"float output {i} may be NaN ({out.describe()})",
                )
    return EntryInvariantReport(
        entry, bool_outputs, float_outputs, rendered, interp.findings,
        list(interp.carry_proofs),
    )


# ---------------------------------------------------------------------------
# Plugin-tier audit: each score kernel proves [0, 100]
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class PluginInvariantReport:
    plugin: str
    lo: float
    hi: float
    flags: List[str]
    findings: List[InvariantFinding]

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_dict(self) -> dict:
        return {
            "plugin": self.plugin,
            "ok": self.ok,
            "range": [self.lo, self.hi],
            "flags": self.flags,
            "findings": [f.to_dict() for f in self.findings],
        }


def check_score_plugin(name: str, fn, args) -> PluginInvariantReport:
    """Prove a score kernel's output is NaN-free, inf-free and in [0,100]."""
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    in_avals = [from_concrete(x) for x in jax.tree_util.tree_leaves(args)]
    interp = Interpreter(f"plugin:{name}")
    out = interp.run_closed(closed, in_avals)[0]

    findings = list(interp.findings)
    problems = []
    if out.nan:
        problems.append("may be NaN")
    if inf_any(out):
        problems.append("may be infinite")
    if out.lo < 0.0 or out.hi > 100.0:
        problems.append(f"range [{out.lo:g}, {out.hi:g}] escapes [0, 100]")
    if problems:
        findings.append(
            InvariantFinding(
                f"plugin:{name}", "score-range", "output", "out0",
                f"score {'; '.join(problems)} ({out.describe()})",
            )
        )
    return PluginInvariantReport(
        name, out.lo, out.hi, out.flags(), sorted(set(findings))
    )


def _plugin_specs():
    from ..ops import kernels as k

    return {
        "balanced_allocation": lambda ns, carry, pod: k.score_balanced(ns, carry, pod),
        "least_allocated": lambda ns, carry, pod: k.score_least_allocated(ns, carry, pod),
        "node_affinity": lambda ns, carry, pod: k.score_node_affinity(ns, pod),
        "taint_toleration": lambda ns, carry, pod: k.score_taint_toleration(ns, pod),
        "topology_spread": lambda ns, carry, pod: k.score_topology_spread(ns, carry, pod),
        "inter_pod_affinity": lambda ns, carry, pod: k.score_inter_pod_affinity(ns, carry, pod),
        "prefer_avoid_pods": lambda ns, carry, pod: k.score_prefer_avoid(ns, pod),
        "simon": lambda ns, carry, pod: k.score_simon(ns, carry, pod),
        "gpu_share": lambda ns, carry, pod: k.score_gpu_share(ns, carry, pod),
        "open_local": lambda ns, carry, pod: k.score_open_local(ns, carry, pod),
    }


# ---------------------------------------------------------------------------
# Top-level driver
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class InvariantAudit:
    entries: List[EntryInvariantReport]
    plugins: List[PluginInvariantReport]

    @property
    def ok(self) -> bool:
        return all(e.ok for e in self.entries) and all(
            p.ok for p in self.plugins
        )

    @property
    def findings(self) -> List[InvariantFinding]:
        out: List[InvariantFinding] = []
        for e in self.entries:
            out.extend(e.findings)
        for p in self.plugins:
            out.extend(p.findings)
        return sorted(out)

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "entries": [e.to_dict() for e in sorted(
                self.entries, key=lambda e: e.entry
            )],
            "plugins": [p.to_dict() for p in sorted(
                self.plugins, key=lambda p: p.plugin
            )],
        }

    def render_text(self) -> str:
        lines = [
            f"invariants: {'ok' if self.ok else 'FAILED'} — "
            f"{len(self.entries)} jit entries, {len(self.plugins)} score "
            f"plugins, {len(self.findings)} finding(s)"
        ]
        for e in sorted(self.entries, key=lambda e: e.entry):
            mark = "ok " if e.ok else "FAIL"
            lines.append(
                f"  [{mark}] {e.entry}: {e.bool_outputs} mask output(s) "
                f"proved {{0,1}}, {e.float_outputs} float output(s) NaN-free"
                if e.ok
                else f"  [{mark}] {e.entry}"
            )
            if e.commit_carry:
                counts = e.carry_verdict_counts()
                summary = ", ".join(
                    f"{counts[v]} {v}" for v in (
                        CARRY_PROVED, CARRY_GUARDED, CARRY_NON_DECREASING,
                        CARRY_UNCHANGED, CARRY_VIRTUAL, CARRY_UNRECOGNIZED,
                        CARRY_UNGUARDED,
                    ) if v in counts
                )
                lines.append(
                    f"        commit-carry: {len(e.commit_carry)} float "
                    f"slot(s) — {summary}"
                )
            for f in e.findings:
                lines.append(f"        {f.kind} @ {f.path}: {f.message}")
        for p in sorted(self.plugins, key=lambda p: p.plugin):
            mark = "ok " if p.ok else "FAIL"
            lines.append(
                f"  [{mark}] plugin {p.plugin}: score in "
                f"[{p.lo:g}, {p.hi:g}]"
            )
            for f in p.findings:
                lines.append(f"        {f.kind} @ {f.path}: {f.message}")
        return "\n".join(lines)


def run_invariants() -> InvariantAudit:
    """Retrace every canonical jit entry (jaxpr_audit.AUDIT_TARGETS) + the
    10 score plugins and abstractly interpret every jaxpr. Deterministic
    given the canonical state (the same one the jaxpr auditor uses)."""
    from . import jaxpr_audit as ja

    captured = ja._capture_calls()
    # one representative call per entry: the capture order is deterministic,
    # keep the first occurrence
    seen: Dict[str, object] = {}
    for cap in captured:
        seen.setdefault(cap.name, cap)

    entries = [
        check_traceable(name, cap.fn, cap.args, cap.kwargs)
        for name, cap in sorted(seen.items())
    ]

    probe = seen.get("ops.kernels:probe_step")
    plugins: List[PluginInvariantReport] = []
    if probe is not None:
        ns, carry, pod = probe.args[0], probe.args[1], probe.args[2]
        for pname, pfn in sorted(_plugin_specs().items()):
            plugins.append(check_score_plugin(pname, pfn, (ns, carry, pod)))
    return InvariantAudit(entries, plugins)
