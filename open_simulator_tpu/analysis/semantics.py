"""`simon prove`: exhaustive small-scope semantics checking on device.

Small-scope verification: enumerate EVERY scheduling universe in a bounded
family (<= 4 nodes x <= 5 pods drawn from a quantized catalog), run the real
device engine over all of them, and diff every placement, reason code, GPU
assignment and final carry against the independent pure-numpy oracle
(analysis/oracle.py). The family is small enough to enumerate completely and
rich enough to exercise the semantics the oracle models: feasibility edges,
score ties across equal nodes, selector mismatches, unschedulable nodes,
shared-GPU packing, priority-driven commit order and carry mutation chains.

TPU-native: universes are packed onto the scenario axis by STAMPED GATHER —
the catalog (4 node configs, 3 pod configs) is encoded exactly once, and
every stacked [S, ...] input tensor is assembled by numpy fancy-indexing of
catalog rows, so the whole 150k-universe corpus runs through
`ops.fast:schedule_universes` in a handful of identically-shaped vmapped
device calls (one compile total).

The run also banks the canonical commit-order contract
(budgets/commit_contract.json): a digest over the canonicalized placements
of the pinned corpus plus a machine-readable statement of the ordering
rules. ROADMAP item 1 (conflict-parallel wave commit) must reproduce this
digest under its documented reordering — the contract artifact is the
wave-commit gate.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import itertools
import json
import os
from types import SimpleNamespace
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from . import oracle as oracle_mod

#: default contract artifact location (relative to the repo root)
CONTRACT_PATH = os.path.join("budgets", "commit_contract.json")

#: universes per device call (multiple of 8; one compiled program for the
#: whole corpus since every chunk pads to this exact shape)
DEFAULT_CHUNK = 25608

#: recognized commit-rule mutations (seeded fault injection: `simon prove
#: --mutate <mode>` must exit nonzero with a minimized counterexample)
MUTATIONS = ("tiebreak", "nocommit")

_GI = 1 << 30


# ---------------------------------------------------------------------------
# The quantized catalog
# ---------------------------------------------------------------------------

def _node_dict(name, cpu, mem, labels=None, anno=None, unschedulable=False,
               capacity_extra=None):
    res = {"cpu": cpu, "memory": mem, "pods": "110"}
    if capacity_extra:
        res.update(capacity_extra)
    d = {
        "metadata": {
            "name": name,
            "labels": {"kubernetes.io/hostname": name, **(labels or {})},
            "annotations": dict(anno or {}),
        },
        "status": {"allocatable": dict(res), "capacity": dict(res)},
    }
    if unschedulable:
        d["spec"] = {"unschedulable": True}
    return d


def _pod_dict(name, cpu, mem, priority=0, node_selector=None, anno=None,
              owner_kind=None):
    meta = {
        "name": name,
        "namespace": "prove",
        "annotations": dict(anno or {}),
    }
    if owner_kind:
        meta["ownerReferences"] = [{"kind": owner_kind, "name": "rs-" + name}]
    spec = {
        "containers": [
            {"name": "c", "image": "img",
             "resources": {"requests": {"cpu": cpu, "memory": mem}}}
        ],
        "priority": priority,
    }
    if node_selector:
        spec["nodeSelector"] = dict(node_selector)
    return {"metadata": meta, "spec": spec}


class SmallScope:
    """The bounded universe family: catalog + encoded tables + packers.

    Node options (4 slots each drawing from):
      A: 4 cpu / 8Gi, tier=a                    — the roomy default
      B: 2 cpu / 4Gi, tier=b                    — the tight node
      C: 4 cpu / 8Gi, tier=a, 2x8Gi GPUs,
         preferAvoidPods annotation             — GPU + avoid-pods scoring
      D: 2 cpu / 8Gi, tier=b, unschedulable     — cordoned
      -: absent (pad row; clusters of 0..4 nodes)

    Pod options (5 slots each drawing from):
      p: 1 cpu / 2Gi, ReplicaSet-owned, prio 0  — prefer-avoid sensitive
      q: 2 cpu / 2Gi, nodeSelector tier=a, prio 10
      r: 500m / 1Gi + 1 GPU share of 4Gi, prio 5

    Every (node, pod) slot assignment is one universe: 5^4 * 3^5 = 151,875
    distinct universes, all sharing one (N=8, P=8) padded shape bucket.
    """

    NODE_OPTIONS = ("A", "B", "C", "D", "-")
    POD_OPTIONS = ("p", "q", "r")
    NODE_SLOTS = 4
    POD_SLOTS = 5
    N_PAD = 8
    P_PAD = 8

    def __init__(self) -> None:
        from ..core.objects import (
            ANNO_GPU_COUNT_POD,
            ANNO_GPU_MEM_POD,
            Node,
            Pod,
        )
        from ..ops import encode

        avoid = {
            "scheduler.alpha.kubernetes.io/preferAvoidPods": json.dumps(
                {"preferAvoidPods": [{"podSignature": {}}]}
            )
        }
        gpu_cap = {
            "alibabacloud.com/gpu-count": "2",
            ANNO_GPU_MEM_POD: str(16 * _GI),
        }
        self.node_dicts = {
            "A": _node_dict("prove-a", "4", "8Gi", labels={"tier": "a"}),
            "B": _node_dict("prove-b", "2", "4Gi", labels={"tier": "b"}),
            "C": _node_dict("prove-c", "4", "8Gi", labels={"tier": "a"},
                            anno=avoid, capacity_extra=gpu_cap),
            "D": _node_dict("prove-d", "2", "8Gi", labels={"tier": "b"},
                            unschedulable=True),
        }
        self.pod_dicts = {
            "p": _pod_dict("prove-p", "1", "2Gi", priority=0,
                           owner_kind="ReplicaSet"),
            "q": _pod_dict("prove-q", "2", "2Gi", priority=10,
                           node_selector={"tier": "a"}),
            "r": _pod_dict("prove-r", "500m", "1Gi", priority=5,
                           anno={ANNO_GPU_MEM_POD: str(4 * _GI),
                                 ANNO_GPU_COUNT_POD: "1"}),
        }
        self.pod_priority = {
            k: int(d["spec"]["priority"]) for k, d in self.pod_dicts.items()
        }

        self.enc = encode.Encoder()
        node_objs = [Node.from_dict(self.node_dicts[k]) for k in "ABCD"]
        pod_objs = [Pod.from_dict(self.pod_dicts[k]) for k in "pqr"]
        self.table = encode.encode_nodes(
            self.enc, node_objs, n_pad=self.N_PAD
        )
        self.batch = encode.encode_pods(self.enc, pod_objs, p_pad=self.P_PAD)
        #: catalog row index per node option ('-' maps to a pad row)
        self.node_row = {"A": 0, "B": 1, "C": 2, "D": 3, "-": 4}
        #: catalog row index per pod option
        self.pod_row = {"p": 0, "q": 1, "r": 2}
        self._np_cache: Optional[Tuple] = None

    # -- universe enumeration ----------------------------------------------

    def universes(self) -> List["Universe"]:
        """The full corpus, in canonical enumeration order."""
        return [
            Universe(nodes="".join(nc), pods="".join(pc))
            for nc in itertools.product(self.NODE_OPTIONS,
                                        repeat=self.NODE_SLOTS)
            for pc in itertools.product(self.POD_OPTIONS,
                                        repeat=self.POD_SLOTS)
        ]

    def corpus_size(self) -> int:
        return (len(self.NODE_OPTIONS) ** self.NODE_SLOTS
                * len(self.POD_OPTIONS) ** self.POD_SLOTS)

    # -- index rows ---------------------------------------------------------

    def node_rows(self, u: "Universe") -> List[int]:
        """Catalog row per packed node lane (pad lanes fill with distinct
        pad rows so every universe table is a plain row gather)."""
        rows = [self.node_row[c] for c in u.nodes]
        rows += list(range(len(rows), self.N_PAD))
        # '-' slots share pad row 4 with the first filler; harmless (both
        # are all-zero invalid rows) but keep indices in range
        return rows

    def pod_rows(self, u: "Universe") -> List[int]:
        """Catalog row per packed pod lane, in COMMIT ORDER: descending
        priority, ties broken by slot index (stable) — the harness side of
        the commit-order contract's pod-presentation clause."""
        ordered = sorted(
            range(len(u.pods)),
            key=lambda i: (-self.pod_priority[u.pods[i]], i),
        )
        rows = [self.pod_row[u.pods[i]] for i in ordered]
        n_pad_rows = self.P_PAD - len(self.POD_OPTIONS)
        rows += [len(self.POD_OPTIONS) + (i % n_pad_rows)
                 for i in range(self.P_PAD - len(rows))]
        return rows

    # -- oracle-side views --------------------------------------------------

    def oracle_table(self, u: "Universe") -> SimpleNamespace:
        idx = np.asarray(self.node_rows(u))
        t = self.table
        return SimpleNamespace(
            alloc=t.alloc[idx], free=t.free[idx],
            label_pair=t.label_pair[idx], label_key=t.label_key[idx],
            label_num=t.label_num[idx],
            taint_key=t.taint_key[idx], taint_val=t.taint_val[idx],
            taint_effect=t.taint_effect[idx],
            name_id=t.name_id[idx], unsched=t.unsched[idx],
            avoid_pods=t.avoid_pods[idx], valid=t.valid[idx],
            gpu_total=t.gpu_total[idx], gpu_free=t.gpu_free[idx],
            vg_free=t.vg_free[idx], dev_free=t.dev_free[idx],
            unsched_key_id=self.enc.unsched_key_id,
            empty_val_id=self.enc.empty_val_id,
        )

    def oracle_batch(self, u: "Universe") -> SimpleNamespace:
        from ..ops.kernels import PodRow

        idx = np.asarray(self.pod_rows(u))
        b = self.batch
        return SimpleNamespace(
            **{f: np.asarray(getattr(b, f))[idx] for f in PodRow._fields}
        )

    # -- device-side catalog leaves ----------------------------------------

    def _np_leaves(self):
        """(ns leaves dict, carry leaves dict, pod leaves dict, weights) —
        the encoded catalog as host numpy, gathered per chunk."""
        if self._np_cache is not None:
            return self._np_cache
        from ..ops import kernels, state as state_mod

        ns = state_mod.node_static_from_table(self.enc, self.table)
        carry = state_mod.carry_from_table(self.table)
        rows = state_mod.pod_rows_from_batch_host(self.batch)
        ns_np = {f: np.asarray(v) for f, v in zip(ns._fields, ns)}
        carry_np = {f: np.asarray(v) for f, v in zip(carry._fields, carry)}
        pod_np = {f: np.asarray(v) for f, v in zip(rows._fields, rows)}
        weights = np.asarray(kernels.weights_array(), np.float32)
        self._np_cache = (ns_np, carry_np, pod_np, weights)
        return self._np_cache


@dataclasses.dataclass(frozen=True, order=True)
class Universe:
    """One point of the small-scope family: a node-slot string over
    SmallScope.NODE_OPTIONS and a pod-slot string over POD_OPTIONS."""
    nodes: str
    pods: str

    @property
    def key(self) -> str:
        return f"{self.nodes}/{self.pods}"


# ---------------------------------------------------------------------------
# Stamped-gather packing (host numpy -> stacked [S, ...] device inputs)
# ---------------------------------------------------------------------------

#: NodeStatic leaf -> node-axis position (None = no node axis: broadcast;
#: "scalar" = 0-d leaf widened to [S]). Explicit by name: axis detection by
#: dim == N would mis-stamp square leaves like sel_counts.
_NS_AXIS = {
    "alloc": 0, "label_pair": 0, "label_key": 0, "label_num": 0,
    "taint_key": 0, "taint_val": 0, "taint_effect": 0, "name_id": 0,
    "unsched": 0, "avoid_pods": 0, "topo": 0, "valid": 0, "gpu_total": 0,
    "vg_cap": 0, "vg_name": 0, "dev_cap": 0, "dev_ssd": 0,
    "has_storage": 0,
    "domain_key": None, "topo_onehot": 2,
    "unsched_key_id": "scalar", "empty_val_id": "scalar",
    "anti_topo": None,
}

#: Carry leaf -> node-axis position
_CARRY_AXIS = {
    "free": 0, "sel_counts": 1, "gpu_free": 0, "vg_free": 0, "dev_free": 0,
    "port_any": 1, "port_wild": 1, "port_ipc": 1, "anti_counts": 1,
}


def _gather(leaves: Dict[str, np.ndarray], axes: Dict[str, object],
            idx: np.ndarray) -> Dict[str, np.ndarray]:
    """Stamp a chunk: idx i32[S, lanes] catalog-row matrix -> stacked leaves
    {name: [S, ...]} with the indexed axis replaced by the lane axis."""
    s = idx.shape[0]
    out: Dict[str, np.ndarray] = {}
    for name, a in leaves.items():
        ax = axes[name]
        if ax == "scalar":
            out[name] = np.broadcast_to(np.asarray(a), (s,))
        elif ax is None:
            out[name] = np.broadcast_to(a[None], (s,) + a.shape)
        elif ax == 0:
            out[name] = a[idx]
        else:
            taken = np.take(a, idx, axis=ax)      # [..., S, lanes, ...]
            out[name] = np.moveaxis(taken, ax, 0)  # [S, ..., lanes, ...]
    return out


def _pack_chunk(scope: SmallScope, chunk: Sequence[Universe], s_pad: int):
    """Stacked (NodeStatic, Carry, PodRow, weights) device inputs for one
    chunk; pad lanes replay universe 0 (results discarded)."""
    import jax.numpy as jnp

    from ..ops.kernels import Carry, NodeStatic, PodRow

    ns_np, carry_np, pod_np, weights = scope._np_leaves()
    ni = np.asarray(
        [scope.node_rows(u) for u in chunk]
        + [scope.node_rows(chunk[0])] * (s_pad - len(chunk))
    )
    pi = np.asarray(
        [scope.pod_rows(u) for u in chunk]
        + [scope.pod_rows(chunk[0])] * (s_pad - len(chunk))
    )
    ns_s = NodeStatic(**{
        k: jnp.asarray(v) for k, v in _gather(ns_np, _NS_AXIS, ni).items()
    })
    carry_s = Carry(**{
        k: jnp.asarray(v) for k, v in _gather(carry_np, _CARRY_AXIS, ni).items()
    })
    pods_s = PodRow(**{
        k: jnp.asarray(v)
        for k, v in _gather(pod_np, {f: 0 for f in pod_np}, pi).items()
    })
    weights_s = jnp.asarray(np.broadcast_to(weights[None], (s_pad,) + weights.shape))
    return ns_s, carry_s, pods_s, weights_s


# ---------------------------------------------------------------------------
# Seeded commit-rule mutations (fault injection for the checker itself)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _mutated_engine(mode: str):
    """A deliberately-wrong variant of schedule_universes: `tiebreak`
    breaks score ties to the HIGHEST node index, `nocommit` never threads
    the commit into the carry. Used by tests and `--mutate` to prove the
    checker actually detects commit-rule drift."""
    import jax
    import jax.numpy as jnp

    from ..ops import kernels

    if mode not in MUTATIONS:
        raise ValueError(f"unknown mutation {mode!r}; known: {MUTATIONS}")

    @jax.jit
    def run(ns_s, carry_s, pods_s, weights_s):
        def one(ns, carry, pods, weights):
            def step(c, pod):
                if mode == "nocommit":
                    _, outs = kernels.schedule_step(ns, weights, c, pod)
                    return c, outs
                mask, first_fail = kernels.run_filters(ns, c, pod)
                score = kernels.run_scores(ns, c, pod, weights)
                score = jnp.where(mask, score, -jnp.inf)
                n = score.shape[0]
                node = (n - 1) - jnp.argmax(score[::-1])  # highest index
                ok = jnp.any(mask) & pod.valid
                node_out = jnp.where(ok, node, -1)
                onehot = (jnp.arange(n) == node) & ok
                new_c, gpu_take, vg_take, dev_take = kernels.commit_onehot(
                    ns, c, pod, onehot
                )
                reasons = jnp.zeros(kernels.NUM_FILTERS, jnp.int32).at[
                    jnp.clip(first_fail, 0, kernels.NUM_FILTERS - 1)
                ].add(
                    jnp.where(
                        (first_fail < kernels.NUM_FILTERS) & ns.valid, 1, 0
                    )
                )
                reasons = jnp.where(ok, jnp.zeros_like(reasons), reasons)
                return new_c, (
                    node_out.astype(jnp.int32), reasons,
                    gpu_take.astype(jnp.int32), vg_take, dev_take,
                )

            final, (nodes, reasons, gt, vt, dt) = jax.lax.scan(
                step, carry, pods
            )
            return final, nodes, reasons, gt, vt, dt

        return jax.vmap(one)(ns_s, carry_s, pods_s, weights_s)

    return run


def _dispatch(scope: SmallScope, chunk: Sequence[Universe], s_pad: int,
              mutate: Optional[str], engine: str = "serial"):
    import jax

    from ..ops import fast

    ns_s, carry_s, pods_s, weights_s = _pack_chunk(scope, chunk, s_pad)
    if mutate is not None:
        # mutation screening always targets the serial oracle engine: the
        # mutations are authored against schedule_step's expression tree,
        # and the point is to prove the CHECKER catches them, not to
        # exercise the wave driver's fallback.
        fn = _mutated_engine(mutate)
    elif engine == "wave":
        fn = fast.schedule_universes_wave_host
    else:
        fn = fast.schedule_universes
    carry_out, nodes, reasons, gpu_take, _vt, _dt = fn(
        ns_s, carry_s, pods_s, weights_s
    )
    carry_host = {
        f: np.asarray(v) for f, v in zip(carry_out._fields, carry_out)
    }
    return (
        carry_host,
        np.asarray(jax.device_get(nodes)),
        np.asarray(jax.device_get(reasons)),
        np.asarray(jax.device_get(gpu_take)),
    )


# ---------------------------------------------------------------------------
# The checker
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Divergence:
    universe: str   # Universe.key
    field: str      # nodes | reasons | gpu_take | carry.<plane>
    engine: str
    oracle: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class ProveReport:
    universes_checked: int = 0
    device_calls: int = 0
    divergences: List[Divergence] = dataclasses.field(default_factory=list)
    divergence_total: int = 0
    digest: str = ""
    mutate: Optional[str] = None
    engine: str = "serial"
    contract_path: Optional[str] = None
    contract_ok: Optional[bool] = None   # None = not verified (smoke/write)
    contract_messages: List[str] = dataclasses.field(default_factory=list)
    minimized: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.divergence_total == 0 and self.contract_ok is not False

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "universes_checked": self.universes_checked,
            "device_calls": self.device_calls,
            "divergences": self.divergence_total,
            "divergence_samples": [d.to_dict() for d in self.divergences],
            "digest": self.digest,
            "mutate": self.mutate,
            "engine": self.engine,
            "contract": {
                "path": self.contract_path,
                "ok": self.contract_ok,
                "messages": self.contract_messages,
            },
            "minimized_counterexample": self.minimized,
        }

    def render_text(self) -> str:
        lines = [
            f"universes checked : {self.universes_checked}",
            f"engine            : {self.engine}",
            f"device calls      : {self.device_calls}",
            f"divergences       : {self.divergence_total}",
            f"placement digest  : {self.digest}",
        ]
        if self.mutate:
            lines.append(f"mutation injected : {self.mutate}")
        if self.contract_ok is not None:
            state = "VERIFIED" if self.contract_ok else "VIOLATED"
            lines.append(f"contract          : {state} ({self.contract_path})")
        for m in self.contract_messages:
            lines.append(f"  - {m}")
        if self.minimized:
            lines.append(f"minimized counterexample: {self.minimized}")
        for d in self.divergences:
            lines.append(
                f"  DIVERGED {d.universe} [{d.field}]\n"
                f"    engine: {d.engine}\n    oracle: {d.oracle}"
            )
        verdict = "PROVED" if self.ok else "FAILED"
        lines.append(f"verdict           : {verdict}")
        return "\n".join(lines)


def _diff_universe(u: Universe, engine: Tuple, oracle_res,
                   out: List[Divergence], limit: int) -> int:
    """Compare one universe's engine lane vs its oracle run; append up to
    `limit` sample divergences; return the number of diverging fields."""
    e_nodes, e_reasons, e_take, e_carry = engine
    count = 0

    def record(field, ev, ov):
        nonlocal count
        count += 1
        if len(out) < limit:
            out.append(Divergence(
                universe=u.key, field=field,
                engine=np.array2string(np.asarray(ev), threshold=64),
                oracle=np.array2string(np.asarray(ov), threshold=64),
            ))

    if not np.array_equal(e_nodes, oracle_res.nodes):
        record("nodes", e_nodes, oracle_res.nodes)
    if not np.array_equal(e_reasons, oracle_res.reasons):
        record("reasons", e_reasons, oracle_res.reasons)
    if not np.array_equal(e_take, oracle_res.gpu_take):
        record("gpu_take", e_take, oracle_res.gpu_take)
    for plane, want in oracle_res.carry.planes().items():
        got = e_carry[plane]
        if got.tobytes() != np.ascontiguousarray(want).tobytes():
            record(f"carry.{plane}", got, want)
    return count


def check_universes(
    scope: SmallScope,
    universes: Sequence[Universe],
    chunk: int = DEFAULT_CHUNK,
    mutate: Optional[str] = None,
    max_samples: int = 8,
    progress=None,
    engine: str = "serial",
) -> ProveReport:
    """Run the engine over `universes` (a handful of identically-shaped
    device calls), diff every lane against the oracle, and fold the
    canonical placement digest. `engine`: "serial" dispatches
    ops.fast:schedule_universes, "wave" drives the conflict-parallel
    wave engine (ops/wave.py) to its fixpoint — the digest must come out
    identical either way (the reordered engine's admission proof)."""
    report = ProveReport(mutate=mutate, engine=engine)
    h = hashlib.sha256()
    s_pad = max(8, min(chunk, ((len(universes) + 7) // 8) * 8))
    # Oracle runs depend only on (node slots, presented pod rows); the
    # priority sort collapses the 3^5 pod strings to C(7,2)=21 count
    # multisets, so memoizing drops oracle work ~11x on the full corpus.
    oracle_cache: Dict[Tuple[str, Tuple[int, ...]], object] = {}
    for lo in range(0, len(universes), s_pad):
        batch = universes[lo:lo + s_pad]
        carry_host, nodes, reasons, takes = _dispatch(
            scope, batch, s_pad, mutate, engine
        )
        for j, u in enumerate(batch):
            lane_carry = {f: a[j] for f, a in carry_host.items()}
            cache_key = (u.nodes, tuple(scope.pod_rows(u)))
            oracle_res = oracle_cache.get(cache_key)
            if oracle_res is None:
                oracle_res = oracle_mod.schedule(
                    scope.oracle_table(u), scope.oracle_batch(u)
                )
                oracle_cache[cache_key] = oracle_res
            report.divergence_total += _diff_universe(
                u, (nodes[j], reasons[j], takes[j], lane_carry),
                oracle_res, report.divergences, max_samples,
            )
            h.update(u.key.encode())
            h.update(nodes[j].astype("<i4").tobytes())
            h.update(reasons[j].astype("<i4").tobytes())
            h.update(takes[j].astype("<i4").tobytes())
            h.update(lane_carry["free"].astype("<f4").tobytes())
            h.update(lane_carry["gpu_free"].astype("<f4").tobytes())
        report.universes_checked += len(batch)
        report.device_calls += 1
        if progress is not None:
            progress(report.universes_checked, len(universes))
    report.digest = "sha256:" + h.hexdigest()
    return report


# ---------------------------------------------------------------------------
# Counterexample minimization
# ---------------------------------------------------------------------------

def _diverges(scope: SmallScope, u: Universe,
              mutate: Optional[str], engine: str = "serial") -> bool:
    rep = check_universes(scope, [u], chunk=8, mutate=mutate, max_samples=0,
                          engine=engine)
    return rep.divergence_total > 0


def minimize(scope: SmallScope, u: Universe,
             mutate: Optional[str] = None,
             engine: str = "serial") -> Universe:
    """Greedily shrink a diverging universe: drop pod slots, then blank node
    slots, keeping divergence at every step (ddmin-style one-at-a-time)."""
    changed = True
    while changed:
        changed = False
        for i in range(len(u.pods)):
            if len(u.pods) <= 1:
                break
            cand = Universe(u.nodes, u.pods[:i] + u.pods[i + 1:])
            if _diverges(scope, cand, mutate, engine):
                u, changed = cand, True
                break
        for i in range(len(u.nodes)):
            if u.nodes[i] == "-":
                continue
            cand = Universe(u.nodes[:i] + "-" + u.nodes[i + 1:], u.pods)
            if _diverges(scope, cand, mutate, engine):
                u, changed = cand, True
                break
    return u


# ---------------------------------------------------------------------------
# The canonical commit-order contract
# ---------------------------------------------------------------------------

def order_contract_statement() -> dict:
    """The machine-readable commit-order contract. ROADMAP item 1's wave
    commit must either reproduce these rules bit-for-bit or ship a new
    contract version with its documented reordering."""
    return {
        "commit_order": (
            "sequential: pods commit one at a time in presented order "
            "(lax.scan); every pod observes all prior commits through the "
            "carry"
        ),
        "pod_presentation": (
            "descending priority, ties broken by original slot index "
            "(stable sort)"
        ),
        "node_tie_break": (
            "first-max argmax: equal total scores place on the lowest "
            "node index"
        ),
        "score_fold": list(oracle_mod.WEIGHT_ORDER),
        "resource_slack": float(oracle_mod.EPS),
        "dtype": "float32",
    }


def contract_payload(scope: SmallScope, report: ProveReport) -> dict:
    return {
        "version": 1,
        "entry": "ops.fast:schedule_universes",
        "corpus": {
            "node_options": "".join(scope.NODE_OPTIONS),
            "node_slots": scope.NODE_SLOTS,
            "pod_options": "".join(scope.POD_OPTIONS),
            "pod_slots": scope.POD_SLOTS,
        },
        "universes": report.universes_checked,
        "digest": report.digest,
        "order_contract": order_contract_statement(),
    }


def write_contract(path: str, scope: SmallScope,
                   report: ProveReport) -> dict:
    payload = contract_payload(scope, report)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    return payload


def verify_contract(path: str, scope: SmallScope,
                    report: ProveReport) -> Tuple[bool, List[str]]:
    """Check a full-corpus run against the banked contract artifact."""
    if not os.path.exists(path):
        return False, [
            f"contract artifact missing: {path} "
            "(run `simon prove --write-contract` to bank it)"
        ]
    with open(path) as f:
        banked = json.load(f)
    fresh = contract_payload(scope, report)
    msgs: List[str] = []
    for field in ("corpus", "universes", "order_contract", "entry"):
        if banked.get(field) != fresh[field]:
            msgs.append(
                f"{field} drifted: banked {banked.get(field)!r} "
                f"vs current {fresh[field]!r}"
            )
    if banked.get("digest") != fresh["digest"]:
        msgs.append(
            f"placement digest mismatch: banked {banked.get('digest')} vs "
            f"current {fresh['digest']} — the canonical commit order "
            "changed"
        )
    return not msgs, msgs


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------

def run_prove(
    contract_path: str = CONTRACT_PATH,
    write: bool = False,
    smoke: Optional[int] = None,
    chunk: int = DEFAULT_CHUNK,
    mutate: Optional[str] = None,
    progress=None,
    engine: str = "serial",
) -> ProveReport:
    """The `simon prove` entry point.

    Full runs (smoke=None) verify — or with write=True, bank — the
    commit-order contract. Smoke runs (smoke=N: every k-th universe so the
    sample spans the corpus) only diff engine vs oracle; the digest is
    sample-dependent, so no contract check. Any divergence triggers the
    counterexample minimizer.

    engine="wave" runs the whole corpus through the conflict-parallel
    wave engine instead of the serial scan; the contract digest is
    engine-independent by design, so a full wave run must verify against
    the SAME banked artifact — that passing run is the wave engine's
    admission proof under the commit-order contract.
    """
    scope = SmallScope()
    corpus = scope.universes()
    if smoke is not None and smoke < len(corpus):
        stride = max(1, len(corpus) // max(smoke, 1))
        corpus = corpus[::stride][:smoke]
    report = check_universes(
        scope, corpus, chunk=chunk, mutate=mutate, progress=progress,
        engine=engine,
    )
    report.contract_path = contract_path
    if smoke is None and not mutate:
        if write:
            if report.divergence_total == 0:
                write_contract(contract_path, scope, report)
                report.contract_ok = True
                report.contract_messages = [
                    f"contract banked: {contract_path}"
                ]
            else:
                report.contract_ok = False
                report.contract_messages = [
                    "refusing to bank a contract over a diverging corpus"
                ]
        else:
            report.contract_ok, report.contract_messages = verify_contract(
                contract_path, scope, report
            )
    if report.divergence_total > 0 and report.divergences:
        first = report.divergences[0].universe
        nodes, pods = first.split("/")
        report.minimized = minimize(
            scope, Universe(nodes, pods), mutate, engine
        ).key
    return report
