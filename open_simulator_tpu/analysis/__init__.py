"""Static-analysis subsystem: AST lint pass + jaxpr auditor.

The engine's TPU-native advantage rests on contracts the runtime cannot
check for free:

* jitted kernels stay pure — no host syncs, tracer coercions, or
  environment reads inside traced code (``analysis.rules.purity``);
* every dynamic size flows through the ``round_up``/``_bucket`` shape
  family so the jit cache hits across capacity iterations
  (``analysis.rules.shapes``);
* arithmetic stays in the f32/i32 regime that keeps pod counts exact
  below 2**24 (``analysis.rules.dtype``).

``analysis.lint`` enforces these with a pure-AST pass (no jax import —
fast enough for a pre-commit hook); ``analysis.jaxpr_audit`` traces the
registered fast-path kernels and inspects the actual jaxprs, catching
what syntax alone cannot.
"""

from .lint import Finding, LintReport, iter_rules, run_lint

__all__ = ["Finding", "LintReport", "iter_rules", "run_lint"]
