"""SARIF 2.1.0 emission for the `simon check` umbrella verb.

Every static pass in the repo already emits a deterministic JSON report
with its own shape (`simon lint`, `simon audit`, `simon preflight`,
`simon interleave`). CI annotation UIs, though, speak one language:
SARIF. This module converts each pass's report into a SARIF *run* (one
``tool.driver`` per producer, so annotations are attributed to the pass
that found them) and `sarif_document` stitches the runs into a single
2.1.0 document.

Shape conventions:

* one SARIF ``run`` per producer (``simon-lint``, ``simon-audit``,
  ``simon-preflight``, ``simon-interleave``), even when a producer has
  zero results — the empty run is the machine-readable "this pass ran
  and was clean" statement;
* findings with a source position (lint, races) carry a
  ``physicalLocation``; report-level findings (budget violations,
  interleaving violations) anchor to the subsystem file they indict so
  annotation UIs still have somewhere to pin them;
* all output is plain dicts ordered for ``json.dumps(sort_keys=True)``
  byte-stability — no wall-clock, no randomness.

The converters take the pass report *objects* (duck-typed: only
``to_dict``-adjacent attributes are touched) so `simon check` can run
the passes in-process and hand the results straight over.
"""

from __future__ import annotations

from typing import List, Optional

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"
_INFO_URI = "https://github.com/open-simulator/open-simulator"

#: where a location-less interleave violation is pinned: the module whose
#: protocol the scenario exercises (see analysis/interleave.py SCENARIOS).
SCENARIO_SUBJECTS = {
    "admission": "open_simulator_tpu/server/admission.py",
    "fence": "open_simulator_tpu/server/loop.py",
    "session": "open_simulator_tpu/server/server.py",
    "journal": "open_simulator_tpu/durable/journal.py",
    "breaker": "open_simulator_tpu/resilience/policy.py",
}


def _location(path: str, line: int = 0, col: int = 0) -> dict:
    region: dict = {}
    if line:
        region["startLine"] = int(line)
    if col:
        # SARIF columns are 1-based; the AST passes report 0-based cols.
        region["startColumn"] = int(col) + 1
    loc: dict = {
        "physicalLocation": {
            "artifactLocation": {"uri": path, "uriBaseId": "SRCROOT"}
        }
    }
    if region:
        loc["physicalLocation"]["region"] = region
    return loc


def _result(
    rule_id: str,
    message: str,
    *,
    level: str = "error",
    path: str = "",
    line: int = 0,
    col: int = 0,
    properties: Optional[dict] = None,
) -> dict:
    res: dict = {
        "ruleId": rule_id,
        "level": level,
        "message": {"text": message},
    }
    if path:
        res["locations"] = [_location(path, line, col)]
    if properties:
        res["properties"] = properties
    return res


def _run(
    name: str,
    rule_ids: List[str],
    results: List[dict],
    properties: Optional[dict] = None,
) -> dict:
    run: dict = {
        "tool": {
            "driver": {
                "name": name,
                "informationUri": _INFO_URI,
                "rules": [{"id": r} for r in sorted(set(rule_ids))],
            }
        },
        "columnKind": "utf16CodeUnits",
        "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
        "results": results,
    }
    if properties:
        run["properties"] = properties
    return run


# ---------------------------------------------------------------------------
# per-pass converters
# ---------------------------------------------------------------------------

def lint_run(report) -> dict:
    """`simon lint` LintReport -> SARIF run. Suppressed findings are
    omitted (they are the accepted-and-annotated set, not annotations)."""
    results = [
        _result(
            f.rule,
            f.message + (f" [via {f.jit_root}]" if f.jit_root else ""),
            path=f.path,
            line=f.line,
            col=f.col,
        )
        for f in report.active
    ]
    return _run("simon-lint", list(report.rules), results)


def audit_run(report) -> dict:
    """`simon audit` SemanticAuditReport (races + invariants) -> SARIF
    run. Unused suppressions are findings too: a stale ``audit-ok``
    hides future regressions."""
    results: List[dict] = []
    rule_ids: List[str] = []
    races = getattr(report, "races", None)
    if races is not None:
        for f in races.active:
            rule_ids.append(f.rule)
            results.append(
                _result(
                    f.rule,
                    f"{f.message} [via {f.thread_root}]",
                    path=f.path,
                    line=f.line,
                    col=f.col,
                    properties={"state": f.state, "function": f.function},
                )
            )
        for u in races.unused_suppressions:
            rule_ids.append("unused-suppression")
            results.append(
                _result(
                    "unused-suppression",
                    f"unused audit suppression audit-ok[{u.rule}]",
                    level="warning",
                    path=u.path,
                    line=u.line,
                )
            )
    inv = getattr(report, "invariants", None)
    if inv is not None and not inv.ok:
        for f in inv.findings:
            rule_ids.append(f.kind)
            results.append(
                _result(
                    f.kind,
                    f"{f.entry} at {f.path}: {f.message}",
                    properties={"primitive": f.primitive},
                )
            )
    return _run("simon-audit", rule_ids, results)


def preflight_run(report) -> dict:
    """`simon preflight` PreflightReport -> SARIF run. Everything is
    report-level (budgets live in budgets/preflight.json), so results
    anchor to the budget book."""
    results: List[dict] = []
    rule_ids: List[str] = []
    anchor = report.budgets_path or "budgets/preflight.json"
    for v in report.violations:
        d = v.to_dict() if hasattr(v, "to_dict") else dict(v)
        rule = str(d.get("kind", "budget"))
        rule_ids.append(rule)
        results.append(
            _result(
                rule,
                f"{d.get('key', '?')}: {d.get('message', '')}",
                path=anchor,
                properties={k: d[k] for k in sorted(d)},
            )
        )
    for p in report.programs:
        if p.error:
            rule_ids.append("lowering-error")
            results.append(
                _result("lowering-error", f"{p.key}: {p.error}", path=anchor)
            )
        elif not p.estimate_ok:
            rule_ids.append("estimator-mismatch")
            results.append(
                _result(
                    "estimator-mismatch",
                    f"{p.key}: analytic estimator disagrees with compiled "
                    f"argument/output sizes",
                    path=anchor,
                )
            )
    for t in report.transfers:
        if not t.ok:
            rule_ids.append("steady-state-transfer")
            results.append(
                _result(
                    "steady-state-transfer",
                    f"{t.entry}: host transfer in steady state"
                    + (f" ({t.error})" if t.error else ""),
                    path=anchor,
                )
            )
    verdict = report.verdict
    if verdict is not None and not verdict.get("ok", False):
        rule_ids.append("plan-verdict")
        results.append(
            _result(
                "plan-verdict",
                f"plan verdict {verdict.get('config', '?')} failed: "
                f"{verdict.get('error') or 'does not fit'}",
                path=anchor,
            )
        )
    # the audited inventory rides in the run's property bag: a clean run
    # then still NAMES every covered program (the wave-commit entries
    # included), so a regression that drops an entry from the budget book
    # is visible as an inventory diff, not just an absent annotation
    covered = sorted({p.key for p in report.programs})
    return _run(
        "simon-preflight", rule_ids, results,
        properties={"programs": covered},
    )


def interleave_run(report) -> dict:
    """`simon interleave` InterleaveReport -> SARIF run. Violations anchor
    to the module whose protocol the scenario drives; the minimized
    schedule rides in the result's property bag so the annotation is
    replayable (`simon interleave --replay`)."""
    results: List[dict] = []
    rule_ids: List[str] = []
    for sc in sorted(report.scenarios, key=lambda s: s.name):
        for v in sc.violations:
            rule_ids.append(v.invariant)
            results.append(
                _result(
                    v.invariant,
                    f"scenario '{v.scenario}': {v.message}",
                    path=SCENARIO_SUBJECTS.get(v.scenario, ""),
                    line=1,
                    properties={
                        "scenario": v.scenario,
                        "interventions": [list(i) for i in v.interventions],
                        "seed": report.seed,
                        "mutate": report.mutate or "",
                    },
                )
            )
        if not sc.completed and not sc.violations:
            rule_ids.append("exploration-incomplete")
            results.append(
                _result(
                    "exploration-incomplete",
                    f"scenario '{sc.name}': exploration hit the run budget "
                    f"before exhausting the interleaving space "
                    f"({sc.runs} runs, {sc.states} states)",
                    level="warning",
                    path=SCENARIO_SUBJECTS.get(sc.name, ""),
                    line=1,
                )
            )
    return _run("simon-interleave", rule_ids, results)


def sarif_document(runs: List[dict]) -> dict:
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": runs,
    }
