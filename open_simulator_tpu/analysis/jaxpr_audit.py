"""jaxpr auditor: trace the fast-path kernels and inspect what XLA sees.

The AST lint (``analysis.lint``) catches what syntax shows; this layer
catches what only tracing shows. It builds one canonical encoded state —
a small synthetic cluster at the same bucket family production uses
(``node_bucket(n_nodes)`` ladder node axis, ``_bucket``-padded pod
groups) —
runs the real host dispatchers over it while *capturing* every jit-entry
call, then retraces each captured call with ``Function.trace`` and walks
the jaxpr:

* **forbidden primitives** — host callbacks and explicit transfers
  (``pure_callback``, ``io_callback``, ``debug_callback``, infeed /
  outfeed, ``device_put``...) mean a host round trip inside the kernel;
* **wide avals** — any f64/i64/u64/c128 intermediate means the f32/i32
  exactness regime leaked (x64 off: silent downcast hid the intent;
  x64 on: doubled HBM traffic).

Capturing at the dispatcher boundary (instead of hand-building each
kernel's arguments) keeps the audit signature-proof: when a kernel gains
a parameter the capture follows automatically, and the audit inspects
exactly the (shapes, dtypes, static values) production uses.

The recompile guard (:func:`run_recompile_guard`) is the dynamic half of
the shape-discipline story: it runs a small capacity-planning sweep —
the workload whose add-node search motivates bucketing in the first
place — and asserts the number of XLA backend compiles stays within the
declared shape-family budget, cross-checking its own count against the
``osim_compile_cache_total{event="backend_compile"}`` counter fed by
``utils.platform.install_compile_listener``.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Iterator, List, Optional, Tuple

#: Primitives that imply a host round trip or an explicit transfer inside
#: traced code. Non-empty by contract (the audit refuses to run otherwise —
#: an empty set would vacuously pass).
FORBIDDEN_PRIMITIVES = frozenset(
    {
        "pure_callback",
        "io_callback",
        "debug_callback",
        "callback",
        "outside_call",
        "host_callback_call",
        "infeed",
        "outfeed",
        "device_put",
        "copy_to_host",
    }
)

WIDE_DTYPES = frozenset({"float64", "int64", "uint64", "complex128"})

#: jit entry points per module; captured while the canonical dispatch runs.
AUDIT_TARGETS: Dict[str, Tuple[str, ...]] = {
    "open_simulator_tpu.ops.fast": (
        "build_trajectory",
        "sort_select",
        "cur_at",
        "light_scan",
        "domain_select",
        "light_reasons",
        "gather_takes",
        "exit_carry",
        "schedule_scenarios",
        "schedule_scenarios_chunked",
        "schedule_universes",
        "schedule_wave",
        "schedule_universes_wave",
        "commit_choices",
    ),
    "open_simulator_tpu.ops.grouped": ("_group_jit",),
    "open_simulator_tpu.ops.kernels": (
        "schedule_batch", "probe_step", "commit_step", "probe_many",
        "commit_wave",
    ),
    "open_simulator_tpu.ops.delta": ("apply_rows", "apply_flags", "digest_fold"),
}

#: entries the canonical state MUST exercise — a refactor that silently
#: stops routing through one of these should fail the audit, not shrink it.
REQUIRED_COVERAGE = frozenset(
    {
        "ops.fast:build_trajectory",
        "ops.fast:sort_select",
        "ops.fast:light_scan",
        "ops.fast:domain_select",
        "ops.fast:light_reasons",
        "ops.fast:cur_at",
        "ops.fast:gather_takes",
        "ops.fast:exit_carry",
        "ops.fast:schedule_scenarios",
        "ops.fast:schedule_scenarios_chunked",
        "ops.fast:schedule_universes",
        "ops.fast:schedule_wave",
        "ops.fast:schedule_universes_wave",
        "ops.fast:commit_choices",
        "ops.grouped:_group_jit",
        "ops.kernels:schedule_batch",
        "ops.kernels:probe_step",
        "ops.kernels:commit_step",
        "ops.kernels:probe_many",
        "ops.kernels:commit_wave",
        "ops.delta:apply_rows",
        "ops.delta:apply_flags",
        "ops.delta:digest_fold",
    }
)

#: XLA backend-compile budget for the capacity sweep: every probe of the
#: search shares one node-bucket per phase, so the whole sweep should stay
#: within a handful of shape families (kernels x {bracket bucket, pinned
#: bisection bucket}), not one compile per probe.
RECOMPILE_BUDGET = 48


@dataclasses.dataclass
class TargetReport:
    name: str
    traced: bool
    n_eqns: int = 0
    primitives: List[str] = dataclasses.field(default_factory=list)
    forbidden: List[str] = dataclasses.field(default_factory=list)
    wide_avals: List[str] = dataclasses.field(default_factory=list)
    #: positional args the entry donates (__osim_donate_argnums__)
    donated: List[int] = dataclasses.field(default_factory=list)
    #: donated-invar aliasing findings: a donated arg shared an array object
    #: with another arg of the same captured call (XLA would scatter into a
    #: buffer the other argument still reads)
    donation_aliased: List[str] = dataclasses.field(default_factory=list)
    error: str = ""

    @property
    def ok(self) -> bool:
        return (
            self.traced
            and not self.forbidden
            and not self.wide_avals
            and not self.donation_aliased
            and not self.error
        )

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "traced": self.traced,
            "ok": self.ok,
            "n_eqns": self.n_eqns,
            "forbidden": self.forbidden,
            "wide_avals": self.wide_avals,
            "donated": self.donated,
            "donation_aliased": self.donation_aliased,
            "error": self.error,
        }


@dataclasses.dataclass
class AuditReport:
    targets: List[TargetReport]
    uncovered: List[str]
    required_missing: List[str]

    @property
    def ok(self) -> bool:
        return not self.required_missing and all(t.ok for t in self.targets)

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "targets": [t.to_dict() for t in self.targets],
            "uncovered": self.uncovered,
            "required_missing": self.required_missing,
            "forbidden_primitives": sorted(FORBIDDEN_PRIMITIVES),
        }

    def render_text(self) -> str:
        out = []
        for t in sorted(self.targets, key=lambda t: t.name):
            status = "ok" if t.ok else "FAIL"
            detail = f"{t.n_eqns} eqns"
            if t.donated:
                detail += f"; donates arg(s) {t.donated}"
            if t.forbidden:
                detail += f"; forbidden: {', '.join(t.forbidden)}"
            if t.wide_avals:
                detail += f"; wide avals: {', '.join(t.wide_avals[:4])}"
            if t.donation_aliased:
                detail += f"; DONATION ALIASED: {', '.join(t.donation_aliased)}"
            if t.error:
                detail += f"; error: {t.error}"
            out.append(f"  {status:4s} {t.name} ({detail})")
        if self.uncovered:
            out.append(f"  not exercised by canonical state: {', '.join(self.uncovered)}")
        if self.required_missing:
            out.append(f"  REQUIRED but missing: {', '.join(self.required_missing)}")
        out.append(f"jaxpr audit: {'ok' if self.ok else 'FAILED'}")
        return "\n".join(out)


# --------------------------------------------------------------------------
# canonical state


def canonical_state():
    """A small synthetic cluster encoded at the production bucket family.

    24 nodes -> the 64-node `round_up` bucket `encode_nodes` always uses;
    four pod templates tiled into runs that deterministically exercise the
    dispatcher's strategies: a large plain group (trajectory + sort path),
    a zonal topology-spread group (domain path), a hostname-spread group
    (general light_scan body), and an infeasible group (light_reasons
    attribution).
    """
    from ..core.objects import Node, Pod
    from ..ops.encode import (
        Encoder,
        encode_nodes,
        encode_pods,
        initial_anti_counts,
        initial_port_counts,
        initial_selector_counts,
    )
    from ..ops.state import carry_from_table, node_static_from_table
    from ..ops.tile import tile_pod_batch

    nodes = [
        Node.from_dict(
            {
                "metadata": {
                    "name": f"audit-{i}",
                    "labels": {
                        "kubernetes.io/hostname": f"audit-{i}",
                        "topology.kubernetes.io/zone": f"az-{i % 3}",
                    },
                },
                "spec": {},
                "status": {
                    "allocatable": {"cpu": "16", "memory": "32Gi", "pods": "110"}
                },
            }
        )
        for i in range(24)
    ]

    def pod(name, cpu, labels=None, spec_extra=None):
        spec = {
            "containers": [
                {
                    "name": "c",
                    "resources": {"requests": {"cpu": cpu, "memory": "256Mi"}},
                }
            ]
        }
        spec.update(spec_extra or {})
        return Pod.from_dict(
            {
                "metadata": {"name": name, "namespace": "audit", "labels": labels or {}},
                "spec": spec,
            }
        )

    plain = pod("plain", "100m")
    spread = pod(
        "spread",
        "100m",
        labels={"app": "spread"},
        spec_extra={
            "topologySpreadConstraints": [
                {
                    "maxSkew": 1,
                    "topologyKey": "topology.kubernetes.io/zone",
                    "whenUnsatisfiable": "DoNotSchedule",
                    "labelSelector": {"matchLabels": {"app": "spread"}},
                }
            ]
        },
    )
    # hostname-keyed spread counts per node (not per domain), which voids
    # both the sort path and the domain merge -> the general light_scan body
    host_spread = pod(
        "hspread",
        "100m",
        labels={"app": "hspread"},
        spec_extra={
            "topologySpreadConstraints": [
                {
                    "maxSkew": 1,
                    "topologyKey": "kubernetes.io/hostname",
                    "whenUnsatisfiable": "DoNotSchedule",
                    "labelSelector": {"matchLabels": {"app": "hspread"}},
                }
            ]
        },
    )
    infeasible = pod("huge", "64")  # > any node's 16 cpu -> unschedulable

    templates = [plain, spread, host_spread, infeasible]
    counts = [220, 60, 50, 30]

    enc = Encoder()
    enc.register_pods(templates)
    table = encode_nodes(enc, nodes)
    batch = tile_pod_batch(encode_pods(enc, templates), counts)
    ns = node_static_from_table(enc, table)
    carry = carry_from_table(
        table,
        initial_selector_counts(enc, table, []),
        port_counts=initial_port_counts(enc, table, []),
        anti_counts=initial_anti_counts(enc, table, []),
    )
    return ns, carry, batch


# --------------------------------------------------------------------------
# capture + trace


@dataclasses.dataclass
class _Captured:
    name: str
    fn: Any  # the original jitted Function
    args: tuple
    kwargs: dict


def _is_concrete(x: Any) -> bool:
    import jax

    return not any(
        isinstance(leaf, jax.core.Tracer) for leaf in jax.tree.leaves(x)
    )


def _short(module: str, attr: str) -> str:
    return f"{module.split('.', 1)[1]}:{attr}"


def _capture_calls() -> List[_Captured]:
    """Run the host dispatchers over the canonical state with every jit
    entry wrapped by a recorder; return first-call args per entry."""
    import importlib

    import jax
    import jax.numpy as jnp
    import numpy as np

    captured: Dict[str, _Captured] = {}
    patches: List[Tuple[Any, str, Any]] = []

    def _snapshot_donated(fn, args: tuple) -> tuple:
        """Donating entries delete their donated inputs when the recorded
        call executes; keep copies so the retrace/invariant passes still
        see live concrete values."""
        donated = set(getattr(fn, "__osim_donate_argnums__", ()) or ())
        if not donated:
            return args
        return tuple(
            jax.tree.map(
                lambda a: a.copy() if hasattr(a, "dtype") else a, arg
            )
            if i in donated
            else arg
            for i, arg in enumerate(args)
        )

    try:
        for module_name, attrs in AUDIT_TARGETS.items():
            module = importlib.import_module(module_name)
            for attr in attrs:
                original = getattr(module, attr)
                name = _short(module_name, attr)

                def recorder(*args, _original=original, _name=name, **kwargs):
                    if _name not in captured and _is_concrete((args, kwargs)):
                        captured[_name] = _Captured(
                            _name,
                            _original,
                            _snapshot_donated(_original, args),
                            kwargs,
                        )
                    return _original(*args, **kwargs)

                setattr(module, attr, recorder)
                patches.append((module, attr, original))

        fast = importlib.import_module("open_simulator_tpu.ops.fast")
        grouped = importlib.import_module("open_simulator_tpu.ops.grouped")
        kernels = importlib.import_module("open_simulator_tpu.ops.kernels")
        state_mod = importlib.import_module("open_simulator_tpu.ops.state")

        ns, carry, batch = canonical_state()
        weights = kernels.weights_array()

        # the trajectory dispatcher: plain group -> build_trajectory +
        # light_scan (+ cur_at/gather_takes/exit_carry), spread group ->
        # domain path, infeasible group -> light_reasons
        fast.schedule_batch_fast(ns, carry, batch, weights, force_fast=True)
        # the per-pod grouped scan (`_group_jit`)
        grouped.schedule_batch_grouped(ns, carry, batch, weights)
        # the sequential oracle + the extender-path single-pod entries
        rows = state_mod.pod_rows_from_batch(batch)
        kernels.schedule_batch(ns, carry, rows, weights)
        row0 = _tree_first(rows)
        kernels.probe_step(ns, carry, row0, weights)
        kernels.commit_step(ns, carry, row0, jnp.int32(0))
        # the extender wave entries (engine/extender_wave.py): one bucketed
        # wave of pad-copied lanes, the exact shape discipline the wave
        # engine ships (lane 0 commits, the rest only recheck)
        w_pad = fast.scenario_bucket(2)
        rows_w = jax.tree.map(
            lambda a: jnp.broadcast_to(a[:1], (w_pad,) + a.shape[1:]), rows
        )
        mask_w, score_w, ff_w = kernels.probe_many(ns, carry, rows_w, weights)
        want_w = jnp.zeros(w_pad, bool).at[0].set(True)
        kernels.commit_wave(
            ns, carry, rows_w, weights, mask_w, ff_w, mask_w,
            jnp.zeros_like(score_w), want_w,
        )
        # the batched scenario engine (`schedule_scenarios`): a 2-lane
        # what-if sweep padded to the scenario bucket, the exact shapes
        # Simulator.run_scenarios ships (lane 1 masks off half the nodes;
        # pad lanes are copies of lane 0, as in production)
        s_pad = fast.scenario_bucket(2)
        valid_s = jnp.stack([ns.valid] * s_pad)
        valid_s = valid_s.at[1, 12:].set(False)
        weights_s = jnp.stack([weights] * s_pad)
        fast.schedule_scenarios_host(
            ns, state_mod.stack_carry(carry, s_pad), batch,
            weights_s, valid_s, 2,
        )
        # the chunked commit driver (`schedule_scenarios_chunked`,
        # OSIM_COMMIT_CHUNK > 0): one count-gated chunk at the same lane
        # shapes — a partial chunk (count < C) so the gate path is traced
        rows_c = jax.tree.map(lambda a: a[:4], rows)
        fast.schedule_scenarios_chunked(
            ns, state_mod.stack_carry(carry, s_pad), rows_c,
            weights_s, valid_s, jnp.int32(3),
        )
        # the exhaustive-checking universe engine (`schedule_universes`,
        # `simon prove`): every NodeStatic/Carry/PodRow leaf stacked to the
        # scenario bucket (scalars widened to [S]), the exact packing
        # analysis/semantics.py ships via stamped gather
        stack_leaf = lambda a: jnp.broadcast_to(  # noqa: E731
            a[None], (s_pad,) + a.shape
        )
        fast.schedule_universes(
            jax.tree.map(stack_leaf, ns),
            state_mod.stack_carry(carry, s_pad),
            jax.tree.map(stack_leaf, rows),
            weights_s,
        )
        # the conflict-parallel wave engine (ops/wave.py): one Jacobi
        # round at the chunked-driver shapes (cold -1 choices, partial
        # count so the live gate is traced), the replay-only commit
        # phase, and the universes-axis round `simon prove --engine
        # wave` drives — none of these donate their carry
        choices_w = jnp.full((s_pad, 4), -1, jnp.int32)
        fast.schedule_wave(
            ns, state_mod.stack_carry(carry, s_pad), rows_c,
            weights_s, valid_s, choices_w, jnp.int32(3),
        )
        fast.commit_choices(
            ns, state_mod.stack_carry(carry, s_pad), rows_c,
            valid_s, choices_w, jnp.int32(3),
        )
        fast.schedule_universes_wave(
            jax.tree.map(stack_leaf, ns),
            state_mod.stack_carry(carry, s_pad),
            jax.tree.map(stack_leaf, rows),
            weights_s,
            jnp.full(
                (s_pad, int(jax.tree.leaves(rows)[0].shape[0])),
                -1, jnp.int32,
            ),
        )
        # the resident-state delta kernels (engine/resident.py): scatter two
        # rows into the canonical free plane at production shapes (bucketed
        # index vector, pad slots dropped), flag-set on the valid vector,
        # and one drift-detector digest per representative dtype. The digest
        # runs first and the scatters get fresh copies: apply_rows /
        # apply_flags DONATE their plane argument, and the canonical
        # carry/ns must stay alive for the retrace of every other entry.
        delta = importlib.import_module("open_simulator_tpu.ops.delta")
        n = int(carry.free.shape[0])
        idx = jnp.asarray(delta.pad_indices([0, 1], n))
        rows = jnp.zeros((int(idx.shape[0]),) + carry.free.shape[1:],
                         carry.free.dtype)
        delta.digest_fold(carry.free)
        delta.apply_rows(carry.free.copy(), idx, rows)
        delta.apply_flags(ns.valid.copy(), idx,
                          jnp.zeros(int(idx.shape[0]), bool))
        del np
    finally:
        for module, attr, original in patches:
            setattr(module, attr, original)
    return list(captured.values())


def _tree_first(rows):
    import jax

    return jax.tree.map(lambda a: a[0], rows)


def _iter_eqns(jaxpr) -> Iterator[Any]:
    """All equations of a (possibly nested) jaxpr: pjit bodies, scan/cond/
    while branches — anything carrying a sub-jaxpr in its params."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from _iter_eqns(sub)


def _sub_jaxprs(v: Any) -> Iterator[Any]:
    if hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):  # ClosedJaxpr
        yield v.jaxpr
    elif hasattr(v, "eqns"):  # raw Jaxpr
        yield v
    elif isinstance(v, (list, tuple)):
        for item in v:
            yield from _sub_jaxprs(item)


def _donation_aliasing(cap: _Captured) -> Tuple[List[int], List[str]]:
    """Donated-invar alias check: no array object of a donated positional
    arg may appear in any OTHER argument of the same captured call — XLA
    aliases donated input buffers to outputs, so a second argument reading
    the same array would observe the in-place write. Object identity is the
    right granularity here (donated buffers may already be deleted by the
    capture run, so pointer comparison is unavailable; the engine only ever
    aliases by passing the same Array object twice)."""
    import jax

    donated = sorted(getattr(cap.fn, "__osim_donate_argnums__", ()) or ())
    findings: List[str] = []
    if not donated:
        return donated, findings
    leaves_by_arg = [
        (i, [l for l in jax.tree.leaves(a) if hasattr(l, "dtype")])
        for i, a in enumerate(cap.args)
    ]
    kw_leaves = [
        (k, l)
        for k, v in sorted(cap.kwargs.items())
        for l in jax.tree.leaves(v)
        if hasattr(l, "dtype")
    ]
    for d in donated:
        if d >= len(cap.args):
            findings.append(f"arg {d} not supplied positionally")
            continue
        donated_ids = {id(l) for l in dict(leaves_by_arg)[d]}
        for i, ls in leaves_by_arg:
            if i == d:
                continue
            if any(id(l) in donated_ids for l in ls):
                findings.append(f"arg {d} aliased by arg {i}")
        for k, l in kw_leaves:
            if id(l) in donated_ids:
                findings.append(f"arg {d} aliased by kwarg {k!r}")
    return donated, findings


def _audit_one(cap: _Captured) -> TargetReport:
    report = TargetReport(name=cap.name, traced=False)
    report.donated, report.donation_aliased = _donation_aliasing(cap)
    try:
        closed = cap.fn.trace(*cap.args, **cap.kwargs).jaxpr
    except Exception as exc:  # pragma: no cover - trace failure is a finding
        report.error = f"trace failed: {exc!r}"
        return report
    report.traced = True
    prims = set()
    wide = set()
    forbidden = set()
    for eqn in _iter_eqns(closed.jaxpr):
        pname = eqn.primitive.name
        prims.add(pname)
        if pname in FORBIDDEN_PRIMITIVES:
            forbidden.add(pname)
        report.n_eqns += 1
        for var in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(var, "aval", None)
            dtype = getattr(aval, "dtype", None)
            if dtype is not None and dtype.name in WIDE_DTYPES:
                wide.add(f"{pname}:{dtype.name}")
    report.primitives = sorted(prims)
    report.forbidden = sorted(forbidden)
    report.wide_avals = sorted(wide)
    return report


def run_audit() -> AuditReport:
    """Capture + retrace every registered kernel; see module docstring."""
    if not FORBIDDEN_PRIMITIVES:
        raise RuntimeError("forbidden-primitive set must be non-empty")
    caps = _capture_calls()
    by_name = {c.name: c for c in caps}
    targets = [_audit_one(c) for c in caps]
    all_names = {
        _short(m, a) for m, attrs in AUDIT_TARGETS.items() for a in attrs
    }
    uncovered = sorted(all_names - set(by_name))
    required_missing = sorted(REQUIRED_COVERAGE - set(by_name))
    return AuditReport(
        targets=targets, uncovered=uncovered, required_missing=required_missing
    )


# --------------------------------------------------------------------------
# recompile guard


#: max distinct scenario-axis paddings per (node bucket, pod count) program
#: key: the batched capacity search shapes its lanes to the scenario bucket,
#: so a bucket should see at most {ladder pad, refine pad} — more means the
#: lane shaping regressed and every sweep call recompiles.
SCENARIO_PROGRAMS_PER_BUCKET = 2


@dataclasses.dataclass
class GuardResult:
    compiles: int
    budget: int
    metric_compiles: int
    nodes_added: int
    attempts: int
    batched_calls: int = 0
    batched_nodes_added: int = -1
    scenario_programs: Dict[str, List[int]] = dataclasses.field(
        default_factory=dict
    )
    #: distinct node-axis paddings the sweep's batched programs compiled for
    ladder_rungs: List[int] = dataclasses.field(default_factory=list)

    @property
    def scenario_ok(self) -> bool:
        return self.batched_nodes_added == self.nodes_added and all(
            len(pads) <= SCENARIO_PROGRAMS_PER_BUCKET
            for pads in self.scenario_programs.values()
        )

    @property
    def ladder_ok(self) -> bool:
        """Every batched program's node axis sits exactly on a ladder rung
        (ops.encode.node_bucket is idempotent on it): the sweep never
        compiled an off-ladder node shape, so a growing search compiles at
        most SCENARIO_PROGRAMS_PER_BUCKET programs per rung it touches."""
        from ..ops.encode import node_bucket

        return all(node_bucket(n) == n for n in self.ladder_rungs)

    @property
    def ok(self) -> bool:
        return (
            0 < self.compiles <= self.budget
            and self.compiles == self.metric_compiles
            and self.scenario_ok
            and self.ladder_ok
        )

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "compiles": self.compiles,
            "budget": self.budget,
            "metric_compiles": self.metric_compiles,
            "nodes_added": self.nodes_added,
            "attempts": self.attempts,
            "batched_calls": self.batched_calls,
            "batched_nodes_added": self.batched_nodes_added,
            "scenario_programs": self.scenario_programs,
            "scenario_ok": self.scenario_ok,
            "ladder_rungs": self.ladder_rungs,
            "ladder_ok": self.ladder_ok,
        }

    def render_text(self) -> str:
        worst = max(
            (len(p) for p in self.scenario_programs.values()), default=0
        )
        return (
            f"recompile guard: {'ok' if self.ok else 'FAILED'} — "
            f"{self.compiles} backend compiles (budget {self.budget}, "
            f"metric cross-check {self.metric_compiles}) over a capacity "
            f"sweep adding {self.nodes_added} node(s) in {self.attempts} "
            f"probes; batched sweep: {self.batched_calls} call(s), "
            f"{worst} scenario program(s)/bucket "
            f"(max {SCENARIO_PROGRAMS_PER_BUCKET}), node rungs "
            f"{self.ladder_rungs} "
            f"{'on-ladder' if self.ladder_ok else 'OFF-LADDER'}, answer "
            f"{'agrees' if self.batched_nodes_added == self.nodes_added else 'DISAGREES'}"
        )


def _sweep_fixture():
    """An overloaded 3-node cluster + one Deployment that cannot fit, plus
    the clone template — the smallest sweep that makes plan_capacity walk
    its exponential + bisection phases."""
    from ..core.objects import Node
    from ..engine.simulator import AppResource, ClusterResource

    def node(name: str) -> Node:
        return Node.from_dict(
            {
                "metadata": {
                    "name": name,
                    "labels": {"kubernetes.io/hostname": name},
                },
                "spec": {},
                "status": {
                    "allocatable": {"cpu": "8", "memory": "16Gi", "pods": "110"}
                },
            }
        )

    cluster = ClusterResource(nodes=[node(f"guard-{i}") for i in range(3)])
    deployment = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": "load", "namespace": "guard"},
        "spec": {
            "replicas": 40,
            "selector": {"matchLabels": {"app": "load"}},
            "template": {
                "metadata": {"labels": {"app": "load"}},
                "spec": {
                    "containers": [
                        {
                            "name": "c",
                            "image": "load:v1",
                            "resources": {
                                "requests": {"cpu": "2", "memory": "1Gi"}
                            },
                        }
                    ]
                },
            },
        },
    }
    apps = [AppResource(name="guard", objects=[deployment])]
    return cluster, apps, node("guard-template")


def _backend_compiles() -> int:
    from ..utils import metrics

    return int(metrics.COMPILE_CACHE.value(event="backend_compile"))


def _run_sweeps():
    """The canonical capacity sweep, serial then batched — the shared
    workload of the recompile guard and the warm-start check. Returns
    (serial plan, batched plan); raises if either fails to converge."""
    from ..core.workloads import reset_name_rng
    from ..engine.capacity import plan_capacity

    reset_name_rng()
    cluster, apps, template = _sweep_fixture()
    plan = plan_capacity(
        cluster, apps, template, max_new_nodes=256, sweep_mode="serial"
    )
    # the batched half: same fixture through the vmapped scenario
    # engine, which must (a) reach the same answer and (b) keep every
    # (node bucket, pod count) program key within its scenario-padding
    # budget — one padding per sweep phase, not one per call
    reset_name_rng()
    cluster_b, apps_b, template_b = _sweep_fixture()
    plan_b = plan_capacity(
        cluster_b, apps_b, template_b, max_new_nodes=256,
        sweep_mode="batched",
    )
    if plan is None or plan_b is None:
        raise RuntimeError("recompile-guard sweep did not converge")
    return plan, plan_b


def run_recompile_guard(budget: int = RECOMPILE_BUDGET) -> GuardResult:
    """Run the canonical capacity sweep and bound its XLA compile count.

    Counts via the jax.monitoring backend-compile event (installed into the
    metrics registry by install_compile_listener) and cross-checks the
    local listener count against the registry's
    osim_compile_cache_total{event="backend_compile"} value.
    """
    from ..utils.platform import install_compile_listener

    if not install_compile_listener():
        raise RuntimeError("jax.monitoring unavailable; cannot count compiles")

    local = {"n": 0}

    def _local_listener(event: str, duration: float, **kwargs) -> None:
        if event.endswith("backend_compile_duration"):
            local["n"] += 1

    from jax import monitoring

    monitoring.register_event_duration_secs_listener(_local_listener)
    from ..ops.fast import reset_scenario_programs, scenario_programs

    metric_before = _backend_compiles()
    reset_scenario_programs()
    try:
        plan, plan_b = _run_sweeps()
    finally:
        try:
            monitoring._unregister_event_duration_listener_by_callback(
                _local_listener
            )
        except Exception:
            pass
    metric_delta = _backend_compiles() - metric_before
    return GuardResult(
        compiles=local["n"],
        budget=budget,
        metric_compiles=metric_delta,
        nodes_added=plan.nodes_added,
        attempts=plan.attempts,
        batched_calls=plan_b.batched_calls,
        batched_nodes_added=plan_b.nodes_added,
        scenario_programs={
            f"{n}x{p}": sorted(pads)
            for (n, p), pads in scenario_programs().items()
        },
        ladder_rungs=sorted({n for (n, _p) in scenario_programs()}),
    )


# --------------------------------------------------------------------------
# warm-start leg


@dataclasses.dataclass
class WarmStartResult:
    """Outcome of the warm-start check: the full canonical capacity sweep
    re-run after `simon warmup`, demanding that the persistent compilation
    cache absorbs every XLA compile request.

    ``cold_compiles`` counts requests the cache did NOT serve (backend
    compile events minus persistent-cache hits — in this jax version the
    duration event fires on hits too, so the raw event count alone would
    indict a perfectly warm cache). Zero cold compiles is the acceptance
    bar: the sweep may *request* compiles (a fresh process has empty
    in-process jit caches) but XLA must never actually compile."""

    backend_compiles: int
    persistent_hits: int
    nodes_added: int
    batched_nodes_added: int
    cache_dir: str = ""

    @property
    def cold_compiles(self) -> int:
        return max(0, self.backend_compiles - self.persistent_hits)

    @property
    def ok(self) -> bool:
        return (
            self.cold_compiles == 0
            and self.nodes_added == self.batched_nodes_added
        )

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "cold_compiles": self.cold_compiles,
            "backend_compiles": self.backend_compiles,
            "persistent_hits": self.persistent_hits,
            "nodes_added": self.nodes_added,
            "batched_nodes_added": self.batched_nodes_added,
            "cache_dir": self.cache_dir,
        }

    def render_text(self) -> str:
        return (
            f"warm-start check: {'ok' if self.ok else 'FAILED'} — "
            f"{self.cold_compiles} cold compile(s) "
            f"({self.backend_compiles} compile request(s), "
            f"{self.persistent_hits} persistent-cache hit(s)) over the "
            f"full capacity sweep; answers "
            f"{'agree' if self.nodes_added == self.batched_nodes_added else 'DISAGREE'}"
        )


def warm_start_check() -> WarmStartResult:
    """The warm-start leg of the recompile guard: run the full canonical
    capacity sweep (serial + batched) and demand ZERO cold compiles.

    Run this after `simon warmup` — in the same process (warmup's sweep
    rehearsal filled the in-process jit caches) or a later one sharing
    OSIM_COMPILE_CACHE (every compile request must then persistent-hit).
    Either way a nonzero cold count means some program the sweep needs was
    not banked, i.e. the production run would pay a compile inside its
    capture window."""
    from ..ops.fast import reset_scenario_programs
    from ..utils.platform import (
        CompileCounter,
        enable_compilation_cache,
        install_compile_listener,
    )

    cache_dir = enable_compilation_cache()
    install_compile_listener()
    reset_scenario_programs()
    with CompileCounter() as counter:
        plan, plan_b = _run_sweeps()
    return WarmStartResult(
        backend_compiles=counter.backend_compiles,
        persistent_hits=counter.persistent_hits,
        nodes_added=plan.nodes_added,
        batched_nodes_added=plan_b.nodes_added,
        cache_dir=cache_dir or "",
    )


def report_json(audit: Optional[AuditReport], guard: Optional[GuardResult]) -> str:
    return json.dumps(
        {
            "version": 1,
            "jaxpr_audit": audit.to_dict() if audit is not None else None,
            "recompile_guard": guard.to_dict() if guard is not None else None,
        },
        indent=2,
        sort_keys=True,
    )
