"""`simon interleave`: a deterministic concurrency model checker for the
serving and durability protocols.

`simon prove` closed the device-side gap by exhaustively checking every
small-scope universe against an oracle; this module does the same for the
HOST-side concurrency protocols that production serving depends on —
AdmissionQueue ticketing, SchedulerLoop packing, the warm-session LRU
checkout, generation fencing, the journal WAL, and the circuit breaker.
races.py reasons about these *syntactically* (lock discipline, lock-order
SCCs); interleave runs the *real code* and explores its schedules.

The architecture is stateless model checking in the CHESS tradition:

* **Cooperative serialization.** Each protocol scenario runs the real
  production objects with the module-level `threading` name rebound to a
  shim (`_ShimThreading`). Locks, RLocks, Conditions and Events created at
  *runtime* by the code under test therefore become cooperative
  primitives: every acquire/release/wait/notify/set posts a *pending op*
  and yields to the scheduler, which runs exactly one actor at a time.
  Code between two yields is one atomic block, so a run is fully
  determined by its sequence of scheduling choices.

* **Bounded exhaustive exploration.** A DFS over scheduling choices
  re-executes the scenario from scratch per branch (threads are real but
  only one ever runs). Three bounds keep the space finite and documented:
  bounded actors/ops (each scenario is small-scope by construction), a
  context-switch bound (a switch costs budget only when the previous
  actor was still runnable — voluntary yields are free, the CHESS
  insight), and run/step budgets.

* **Partial-order reduction.** Sleep sets over an object-level
  independence relation: two pending ops commute iff they target
  different shim objects. This is sound for code that races.py certifies
  data-race-free — any cross-actor access to plain shared state is
  protected by a common lock, so conflicting blocks are always ordered
  by ops on a *shared* shim object. Scenario-harness state that actors
  share outside the code under test goes through `_SharedCell`, which is
  itself a shim object, preserving the argument. `--no-dpor` disables
  the reduction for cross-checking.

* **Crash choices.** Scenarios that model durability (`journal`) add one
  pseudo-actor, CRASH: at any decision point the process may stop. A
  crash kills every actor and hands the on-disk state to the scenario's
  crash invariant (journal prefix-closure: every acknowledged record is
  on disk). The crash model is process-stop at sync boundaries; torn
  single-record writes are _scan/repair territory (tests/test_durable).

* **Minimized, replayable counterexamples.** A violating run is reduced
  to its *interventions* — the decisions where the schedule diverged
  from the deterministic default policy (continue the current actor,
  else lowest id) — and ddmin-style one-at-a-time removal (mirroring
  `semantics.minimize`) shrinks them while the violation reproduces.
  The surviving `[[step, actor], ...]` list is the schedule-replay
  format: `simon interleave --replay file.json` re-executes it exactly,
  which makes every future concurrency fix regression-testable.

Seeded known-bad protocol variants (`MUTATIONS`, the `simon prove`
"prove-the-prover" idiom) give the checker teeth: a drain loop that
drops concurrent submits, a lagging generation fence, an ack-before-
append checkpoint ordering, a check-then-act session checkout and a
racy breaker probe must each be caught and minimized
(tests/fixture_bad_protocols.py).

Determinism: reports carry no wall-clock — the scenario clock is the
decision counter — so the same seed produces byte-identical reports
(the digest field is the sha256 of the canonical JSON).
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import shutil
import tempfile
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

#: The pseudo-choice id for a process crash (journal scenario only).
CRASH = -1

#: Documented exploration bounds (the acceptance bar: every shipped
#: scenario must complete — empty DFS stack — within these).
DEFAULT_BOUNDS = {"preemptions": 2, "max_runs": 60000, "max_steps": 500}
#: CI / pre-commit quick mode: one preemption still catches every seeded
#: mutation (they are all two-actor races) at a fraction of the states.
QUICK_BOUNDS = {"preemptions": 1, "max_runs": 8000, "max_steps": 500}


class _Killed(BaseException):
    """Raised inside an actor to unwind it when a run is abandoned
    (crash chosen, violation found, or budget exhausted). BaseException
    so production `except Exception` handlers cannot swallow it."""


class _Prune(BaseException):
    """Raised by the DFS decide hook when every admissible choice is in
    the sleep set: the state's futures are all covered elsewhere."""


# ---------------------------------------------------------------------------
# Cooperative shim primitives
# ---------------------------------------------------------------------------


class _Op:
    """One pending sync operation: what an actor wants to do next. The
    scheduler only schedules an actor whose op is enabled; `apply` runs
    on the actor thread immediately after it is scheduled."""

    __slots__ = ("kind", "obj", "enabled")

    def __init__(self, kind: str, obj: "_ShimObject", enabled) -> None:
        self.kind = kind
        self.obj = obj
        self.enabled = enabled  # Callable[[_Actor], bool]


class _Actor:
    __slots__ = (
        "id", "name", "fn", "thread", "sem", "pending", "done",
        "exc", "killed", "dying",
    )

    def __init__(self, aid: int, name: str, fn: Callable[[], None]) -> None:
        self.id = aid
        self.name = name
        self.fn = fn
        self.thread: Optional[threading.Thread] = None
        self.sem = threading.Semaphore(0)
        self.pending: Optional[_Op] = None
        self.done = False
        self.exc: Optional[BaseException] = None
        self.killed = False
        self.dying = False


class _ShimObject:
    """Base for everything the independence relation can see. Labels are
    allocated in creation order, so they are deterministic per run and
    stable across same-seed explorations."""

    def __init__(self, shim: "Shim", kind: str) -> None:
        self._shim = shim
        self.label = shim._label(kind)


def _always(_actor: "_Actor") -> bool:
    return True


class CoopLock(_ShimObject):
    """threading.Lock stand-in: acquire blocks (op enabled once free),
    release always fires. Owner is an actor id or "ext" for ops issued
    from outside any actor (scenario setup/teardown)."""

    def __init__(self, shim: "Shim", kind: str = "lock") -> None:
        super().__init__(shim, kind)
        self.owner: Optional[object] = None

    def acquire(self, blocking: bool = True, timeout: float = -1):
        sh = self._shim
        if not blocking:
            def apply_try():
                if self.owner is None:
                    self.owner = sh._owner_token()
                    return True
                return False
            return sh.op("trylock", self, _always, apply_try)

        def enabled(_a):
            return self.owner is None

        def apply():
            self.owner = sh._owner_token()
            return True
        return sh.op("acquire", self, enabled, apply)

    def release(self) -> None:
        sh = self._shim

        def apply():
            self.owner = None
        sh.op("release", self, _always, apply)

    def locked(self) -> bool:
        return self.owner is not None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class CoopRLock(_ShimObject):
    """threading.RLock stand-in: re-entrant owner/count pair."""

    def __init__(self, shim: "Shim") -> None:
        super().__init__(shim, "rlock")
        self.owner: Optional[object] = None
        self.count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1):
        sh = self._shim
        me = sh._owner_token()

        def enabled(_a):
            return self.owner is None or self.owner == me

        def apply():
            self.owner = me
            self.count += 1
            return True
        return sh.op("acquire", self, enabled, apply)

    def release(self) -> None:
        sh = self._shim

        def apply():
            self.count -= 1
            if self.count <= 0:
                self.owner = None
                self.count = 0
        sh.op("release", self, _always, apply)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class CoopCondition(_ShimObject):
    """threading.Condition stand-in. Every op's independence object is
    the underlying lock, so condition traffic conflicts with plain users
    of the same lock (conservative, and exactly how the real primitive
    behaves). wait() is two ops — release-and-park, then
    notified-and-reacquire — so a waiter parks atomically and can only
    be rescheduled once notified (or, for timed waits, whenever the lock
    is free: a timeout may fire at any moment, which the scheduler
    models as a nondeterministic choice)."""

    def __init__(self, shim: "Shim", lock=None) -> None:
        super().__init__(shim, "cv")
        self._l = lock if lock is not None else CoopLock(shim, "cvlock")
        self._waiters: List[List[bool]] = []

    # the lock protocol delegates so `with cv:` works
    def acquire(self, *a, **kw):
        return self._l.acquire(*a, **kw)

    def release(self) -> None:
        self._l.release()

    def __enter__(self):
        self._l.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self._l.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        sh = self._shim
        token = [False]
        lock = self._l

        def park():
            self._waiters.append(token)
            lock.owner = None
        sh.op("cv-park", lock, _always, park)

        if timeout is None:
            def enabled(_a):
                return token[0] and lock.owner is None
        else:
            # a timed wait may time out whenever the lock is reacquirable
            def enabled(_a):
                return lock.owner is None

        def wake():
            if token in self._waiters:
                self._waiters.remove(token)
            lock.owner = sh._owner_token()
            return token[0]
        return bool(sh.op("cv-wake", lock, enabled, wake))

    def notify(self, n: int = 1) -> None:
        sh = self._shim

        def apply():
            woken = 0
            for t in self._waiters:
                if woken >= n:
                    break
                if not t[0]:
                    t[0] = True
                    woken += 1
        sh.op("notify", self._l, _always, apply)

    def notify_all(self) -> None:
        self.notify(n=len(self._waiters) + 1)


class CoopEvent(_ShimObject):
    """threading.Event stand-in. is_set() is a non-yielding read: it is
    a single atomic load whose placement inside its atomic block cannot
    be distinguished from a block-level reordering the scheduler already
    explores."""

    def __init__(self, shim: "Shim") -> None:
        super().__init__(shim, "event")
        self._flag = False

    def is_set(self) -> bool:
        return self._flag

    def set(self) -> None:
        sh = self._shim

        def apply():
            self._flag = True
        sh.op("event-set", self, _always, apply)

    def clear(self) -> None:
        sh = self._shim

        def apply():
            self._flag = False
        sh.op("event-clear", self, _always, apply)

    def wait(self, timeout: Optional[float] = None) -> bool:
        sh = self._shim
        if timeout is None:
            def enabled(_a):
                return self._flag
        else:
            enabled = _always
        return bool(sh.op("event-wait", self, enabled, lambda: self._flag))


class _SharedCell(_ShimObject):
    """Scenario-harness shared state as a first-class shim object. Any
    cross-actor mutable state a scenario introduces OUTSIDE the code
    under test must live in a cell (or be touched only in blocks already
    ordered by a common lock): cell ops conflict with each other, so the
    independence relation — and therefore sleep-set pruning — stays
    sound for the invariant-relevant state."""

    def __init__(self, shim: "Shim", name: str, value: Any) -> None:
        super().__init__(shim, f"cell:{name}")
        self.value = value

    def get(self) -> Any:
        return self._shim.op("cell-get", self, _always, lambda: self.value)

    def set(self, value: Any) -> None:
        def apply():
            self.value = value
        self._shim.op("cell-set", self, _always, apply)

    def incr(self, by: int = 1) -> int:
        def apply():
            self.value += by
            return self.value
        return self._shim.op("cell-incr", self, _always, apply)


class _ShimThreading:
    """Drop-in for a module's `threading` attribute: the four sync
    primitives become cooperative, everything else (current_thread,
    local, Thread, get_ident, ...) passes through to the real module."""

    def __init__(self, shim: "Shim") -> None:
        self._shim = shim

    def Lock(self) -> CoopLock:  # noqa: N802 - mirrors threading API
        return CoopLock(self._shim)

    def RLock(self) -> CoopRLock:  # noqa: N802
        return CoopRLock(self._shim)

    def Condition(self, lock=None) -> CoopCondition:  # noqa: N802
        return CoopCondition(self._shim, lock)

    def Event(self) -> CoopEvent:  # noqa: N802
        return CoopEvent(self._shim)

    def __getattr__(self, name: str):
        return getattr(threading, name)


class _FsyncFreeOs:
    """`os` proxy for the journal module during interleave runs: fsync
    becomes a no-op. The crash model is process-stop at sync boundaries,
    so the durability line is the flush that precedes the fsync — the
    real fsync only buys power-loss durability, at ~1000x the cost per
    explored state."""

    def __init__(self, real) -> None:
        self._real = real

    def fsync(self, fd: int) -> None:
        return None

    def __getattr__(self, name: str):
        return getattr(self._real, name)


# ---------------------------------------------------------------------------
# The cooperative scheduler
# ---------------------------------------------------------------------------


class Shim:
    """One scenario execution: real actor threads, exactly one runnable
    at a time, every context switch chosen by `decide`. The decision
    counter doubles as the scenario's logical clock (`clock()`), so no
    wall time ever reaches an invariant or a report."""

    def __init__(self) -> None:
        self._sched_sem = threading.Semaphore(0)
        self._actors: List[_Actor] = []
        self._by_thread: Dict[int, _Actor] = {}
        self._labels: Dict[str, int] = {}
        self._step = 0
        self.trace: List[Tuple[str, str, str]] = []
        self.status = "ok"

    # -- construction -------------------------------------------------------

    def _label(self, kind: str) -> str:
        n = self._labels.get(kind, 0)
        self._labels[kind] = n + 1
        return f"{kind}#{n}"

    def threading_shim(self) -> _ShimThreading:
        return _ShimThreading(self)

    def cell(self, name: str, value: Any) -> _SharedCell:
        return _SharedCell(self, name, value)

    def actor(self, name: str, fn: Callable[[], None]) -> None:
        a = _Actor(len(self._actors), name, fn)
        a.pending = _Op("start", _ShimObject(self, f"actor:{name}"), _always)
        self._actors.append(a)

    def clock(self) -> float:
        return float(self._step)

    # -- actor side ---------------------------------------------------------

    def _owner_token(self):
        a = self._by_thread.get(threading.get_ident())
        return a.id if a is not None else "ext"

    def op(self, kind: str, obj: _ShimObject, enabled, apply):
        """Announce a sync op and yield; execute it once scheduled. Ops
        from outside any actor (setup/teardown) or from a dying actor
        (unwinding after _Killed) execute immediately — the run is
        either not started or already abandoned, so their ordering is
        not part of the explored space."""
        a = self._by_thread.get(threading.get_ident())
        if a is None or a.dying:
            try:
                return apply()
            except Exception:
                return None
        a.pending = _Op(kind, obj, enabled)
        self._sched_sem.release()
        a.sem.acquire()
        if a.killed:
            a.killed = False
            a.dying = True
            raise _Killed()
        return apply()

    def _actor_main(self, a: _Actor) -> None:
        self._by_thread[threading.get_ident()] = a
        a.sem.acquire()
        if a.killed:
            a.dying = True
            a.done = True
            return
        try:
            a.fn()
        except _Killed:
            pass
        except BaseException as e:  # real code crashed: that IS a finding
            a.exc = e
        a.done = True
        if not a.dying:
            self._sched_sem.release()

    # -- scheduler side -----------------------------------------------------

    def drive(self, decide, *, max_steps: int, crashable: bool) -> str:
        """Run the scenario to completion under `decide`. Returns the
        run status: ok | deadlock | crashed | steps | pruned."""
        for a in self._actors:
            a.thread = threading.Thread(
                target=self._actor_main, args=(a,),
                name=f"osim-interleave-{a.name}", daemon=True,
            )
            a.thread.start()
        prev: Optional[int] = None
        status = "ok"
        while True:
            if self._step >= max_steps:
                status = "steps"
                break
            enabled = [
                a.id for a in self._actors
                if not a.done and a.pending is not None
                and a.pending.enabled(a)
            ]
            if not enabled:
                if all(a.done for a in self._actors):
                    status = "ok"
                else:
                    status = "deadlock"
                break
            try:
                c = decide(self._step, enabled, self._ops(), crashable, prev)
            except _Prune:
                status = "pruned"
                break
            if c == CRASH:
                crashable = False
                status = "crashed"
                break
            a = self._actors[c]
            op = a.pending
            assert op is not None
            self.trace.append((a.name, op.kind, op.obj.label))
            a.pending = None
            self._step += 1
            prev = c
            a.sem.release()
            self._sched_sem.acquire()
        self.status = status
        self._kill_all()
        return status

    def _ops(self) -> Dict[int, _Op]:
        return {
            a.id: a.pending for a in self._actors
            if not a.done and a.pending is not None
        }

    def _kill_all(self) -> None:
        for a in self._actors:
            if not a.done:
                a.killed = True
                a.sem.release()
        for a in self._actors:
            if a.thread is not None:
                a.thread.join(timeout=10.0)

    def blocked_summary(self) -> str:
        parts = []
        for a in self._actors:
            if not a.done and a.pending is not None:
                parts.append(f"{a.name} blocked on {a.pending.kind} "
                             f"of {a.pending.obj.label}")
        return "; ".join(parts) or "no pending actors"

    def actor_exceptions(self) -> List[Tuple[str, BaseException]]:
        return [(a.name, a.exc) for a in self._actors if a.exc is not None]


class _Patches:
    """Reversible setattr stack for per-run module/instance patching."""

    def __init__(self) -> None:
        self._saved: List[Tuple[Any, str, Any]] = []

    def set(self, obj: Any, name: str, value: Any) -> None:
        self._saved.append((obj, name, getattr(obj, name)))
        setattr(obj, name, value)

    def restore(self) -> None:
        while self._saved:
            obj, name, value = self._saved.pop()
            setattr(obj, name, value)


# ---------------------------------------------------------------------------
# Protocol scenarios: small-scope harnesses around the REAL production
# objects. Bounded actors, bounded ops; each declares the invariants it
# checks and (optionally) the seeded-bad mutation that proves the
# checker can catch its class of bug.
# ---------------------------------------------------------------------------

Violations = List[Tuple[str, str]]


class _State:
    """Per-run scenario state bag (actors registered on the shim, plus
    whatever the invariants need to read at quiescence)."""

    def __init__(self, **kw: Any) -> None:
        self.patches = _Patches()
        self.__dict__.update(kw)


class Scenario:
    name = ""
    title = ""
    crashable = False
    #: mutation name -> one-line description (None when the scenario has
    #: no seeded-bad variant).
    mutations: Dict[str, str] = {}

    def setup(self, shim: Shim, mutate: Optional[str]) -> _State:
        raise NotImplementedError

    def check(self, state: _State) -> Violations:
        return []

    def check_crash(self, state: _State) -> Violations:
        return []

    def teardown(self, state: _State) -> None:
        state.patches.restore()


def _bad_take_pack(loop):
    """Seeded lost-ticket bug: snapshot the queue under the lock but
    clear it in a SECOND acquisition — a submit landing between the two
    critical sections is wiped from the queue without ever being packed,
    so its ticket is never finalized."""
    q = loop.queue
    with q._cv:
        while not q._queue and not q._draining:
            q._cv.wait()
        if not q._queue:
            return None
        pack = list(q._queue)
    with q._cv:
        q._queue.clear()
    return pack or None


class AdmissionScenario(Scenario):
    """AdmissionQueue + SchedulerLoop ticket lifecycle: two submitters
    with distinct coalesce keys, the real continuous-batching loop, and
    a closer racing shutdown against them, over a depth-1 queue so the
    queue-full shed path is reachable. Invariants: every submitted
    ticket is finalized exactly once with a definite code (no lost
    ticket), a 200 ticket's body was executed exactly once and a shed
    ticket's never (no double dispatch)."""

    name = "admission"
    title = "AdmissionQueue/SchedulerLoop ticket lifecycle"
    mutations = {
        "lost-ticket": "take_pack snapshots and clears the queue in two "
                       "separate critical sections; a concurrent submit "
                       "is silently wiped",
    }

    def setup(self, shim: Shim, mutate: Optional[str]) -> _State:
        import types

        from ..server import admission as admission_mod

        st = _State(tickets=[], executed=[])
        st.patches.set(admission_mod, "threading", shim.threading_shim())

        def execute(bodies: List[dict]) -> List[Any]:
            st.executed.extend(b["k"] for b in bodies)
            return [{"ok": b["k"]} for b in bodies]

        q = admission_mod.AdmissionQueue(
            execute, depth=1, pack_window_ms=0.0, default_deadline_ms=0.0,
            clock=shim.clock, pack_lanes=2,
        )
        if mutate == "lost-ticket":
            q._loop.take_pack = types.MethodType(
                lambda loop: _bad_take_pack(loop), q._loop
            )
        st.queue = q

        def submitter(k: str):
            def fn() -> None:
                st.tickets.append(q.submit({"k": k}, key=k))
            return fn

        shim.actor("loop", q._loop.run_forever)
        shim.actor("submit-a", submitter("a"))
        shim.actor("submit-b", submitter("b"))
        shim.actor("closer", q.shutdown)
        return st

    def check(self, st: _State) -> Violations:
        v: Violations = []
        ok_keys = set()
        for t in st.tickets:
            if not t.done.is_set() or t.code == 0:
                v.append(("no-lost-ticket",
                          f"ticket {t.key!r} was never finalized "
                          f"(code={t.code})"))
            elif t.code == 200:
                ok_keys.add(t.key)
                n = st.executed.count(t.key)
                if n != 1:
                    kind = ("no-double-dispatch" if n > 1
                            else "no-lost-ticket")
                    v.append((kind,
                              f"ticket {t.key!r} answered 200 but its "
                              f"body was executed {n} time(s)"))
            elif t.code in (429, 503):
                if t.key in st.executed:
                    v.append(("no-double-dispatch",
                              f"shed ticket {t.key!r} ({t.code}) was "
                              "also executed"))
            else:
                v.append(("no-lost-ticket",
                          f"ticket {t.key!r} finalized with unexpected "
                          f"code {t.code}"))
        for k in st.executed:
            if k not in ok_keys:
                v.append(("no-double-dispatch",
                          f"executed body {k!r} belongs to no 200 ticket"))
        return v


def _lagged(fn: Callable[[], int]) -> Callable[[], int]:
    """Seeded fence-regression bug: a lag-1 memo over the generation
    fence — the loop re-keys tickets onto the epoch of the PREVIOUS
    pack, so a ticket can run against state newer than its stamp."""
    memo: List[Optional[int]] = [None]

    def g() -> int:
        cur = fn()
        prev = memo[0]
        memo[0] = cur
        return cur if prev is None else prev
    return g


class FenceScenario(Scenario):
    """Generation-fence epoch protocol: two fenced submitters race an
    epoch bumper while the real loop packs. The fence sample the loop
    takes once per pack must be monotone non-decreasing across packs,
    and every executed ticket's (possibly re-keyed) fence_epoch must
    equal its pack's sample — a ticket may never run against resident
    state newer than what its key encodes."""

    name = "fence"
    title = "generation-fence epoch monotonicity at dequeue"
    mutations = {
        "fence-regression": "the loop's fence read lags one pack behind "
                            "the true epoch, stamping tickets with a "
                            "stale generation",
    }

    def setup(self, shim: Shim, mutate: Optional[str]) -> _State:
        from ..server import admission as admission_mod

        st = _State(tickets=[], packs=[], samples=[])
        st.patches.set(admission_mod, "threading", shim.threading_shim())
        epoch = shim.cell("epoch", 0)
        st.epoch = epoch

        def fence() -> int:
            cur = epoch.get()
            st.samples.append(cur)  # loop actor only: single writer
            return cur

        by_key: Dict[str, Any] = {}

        def execute(bodies: List[dict]) -> List[Any]:
            pack_epoch = st.samples[-1]
            st.packs.append(
                (pack_epoch,
                 [(b["k"], by_key[b["k"]].fence_epoch) for b in bodies])
            )
            return [{"ok": b["k"]} for b in bodies]

        q = admission_mod.AdmissionQueue(
            execute, depth=4, pack_window_ms=0.0, default_deadline_ms=0.0,
            clock=shim.clock, pack_lanes=2,
            fence=_lagged(fence) if mutate == "fence-regression" else fence,
        )
        st.queue = q

        def submitter(k: str):
            def fn() -> None:
                t = q.submit({"k": k}, key=k, fence_epoch=epoch.get())
                by_key[k] = t
                st.tickets.append(t)
            return fn

        shim.actor("loop", q._loop.run_forever)
        shim.actor("submit-a", submitter("a"))
        shim.actor("bump", lambda: epoch.incr())
        shim.actor("submit-b", submitter("b"))
        shim.actor("closer", q.shutdown)
        return st

    def check(self, st: _State) -> Violations:
        v: Violations = []
        last = None
        for pack_epoch, entries in st.packs:
            if last is not None and pack_epoch < last:
                v.append(("fence-monotonic",
                          f"pack fence sample regressed {last} -> "
                          f"{pack_epoch}"))
            last = pack_epoch
            for key, stamped in entries:
                if stamped != pack_epoch:
                    v.append(("fence-stamp",
                              f"ticket {key!r} executed in a pack fenced "
                              f"at epoch {pack_epoch} but stamped "
                              f"epoch {stamped}"))
        return v


def _racy_checkout(server_mod, key):
    """Seeded double-checkout bug: the busy check and the busy set run
    in two separate critical sections (check-then-act), so two actors
    can both observe not-busy and both take the same session."""
    with server_mod._sessions_lock:
        ent = server_mod._sessions.get(key)
    if ent is None:
        return None, True
    if ent["busy"]:
        return None, False
    with server_mod._sessions_lock:
        ent["busy"] = True
        server_mod._sessions.move_to_end(key)
    return ent["session"], False


class SessionScenario(Scenario):
    """Warm-session LRU checkout (server._checkout_session /
    _checkin_session) under the real module-level lock, rebound to the
    shim: two workers race the same pre-populated key while a third
    exercises create + LRU eviction at cap 1. Invariants: a session is
    never held by two actors at once (no double checkout), and at
    quiescence nothing is marked busy and the cache respects the cap.

    The session objects are inert stand-ins — the scenario checks the
    checkout protocol, not ScenarioSession itself — so this import is
    the only place interleave touches the engine-heavy server module."""

    name = "session"
    title = "warm-session LRU checkout/checkin"
    mutations = {
        "double-checkout": "the busy check and busy set are split into "
                           "two critical sections; two actors can both "
                           "take the same session",
    }

    def setup(self, shim: Shim, mutate: Optional[str]) -> _State:
        from collections import OrderedDict

        from ..server import server as server_mod

        st = _State(live=[], holders={}, server_mod=server_mod)
        sess0 = object()
        st.patches.set(
            server_mod, "_sessions",
            OrderedDict([(("k",), {"session": sess0, "busy": False})]),
        )
        st.patches.set(
            server_mod, "_sessions_lock",
            CoopLock(shim, "sessions-lock"),
        )
        st.patches.set(server_mod, "_SESSION_CAP", 1)
        if mutate == "double-checkout":
            st.patches.set(
                server_mod, "_checkout_session",
                lambda key: _racy_checkout(server_mod, key),
            )

        def worker(key: tuple):
            def fn() -> None:
                sess, may_create = server_mod._checkout_session(key)
                if sess is None:
                    if not may_create:
                        return  # busy: the real caller falls back cold
                    sess = object()
                n = st.holders.get(id(sess), 0) + 1
                st.holders[id(sess)] = n
                if n > 1:
                    st.live.append(
                        ("no-double-checkout",
                         f"session for key {key!r} checked out by "
                         f"{n} actors at once")
                    )
                server_mod._checkin_session(key, sess, keep=True)
                st.holders[id(sess)] -= 1
            return fn

        shim.actor("warm-1", worker(("k",)))
        shim.actor("warm-2", worker(("k",)))
        shim.actor("warm-3", worker(("k2",)))
        return st

    def check(self, st: _State) -> Violations:
        v = list(st.live)
        sessions = st.server_mod._sessions
        for key, ent in sessions.items():
            if ent["busy"]:
                v.append(("no-double-checkout",
                          f"entry {key!r} still busy at quiescence"))
        cap = st.server_mod._SESSION_CAP
        if len(sessions) > cap:
            v.append(("session-cap",
                      f"{len(sessions)} cached sessions exceed cap {cap}"))
        return v


class JournalScenario(Scenario):
    """RunJournal WAL prefix-closure under crash: two appenders commit
    records through the real append path (write + flush; fsync is a
    no-op under the crash model — see _FsyncFreeOs) and acknowledge
    each record only after append returns. CRASH may fire at any
    decision point; afterwards every acknowledged seq must be on disk
    and the on-disk seqs must be gap-free from 0 (the commit-order
    contract of docs/durability.md, now schedule-checked)."""

    name = "journal"
    title = "journal WAL prefix-closure under crash"
    crashable = True
    mutations = {
        "torn-checkpoint": "records are acknowledged BEFORE the durable "
                           "append; a crash between the two loses an "
                           "acked record",
    }

    def setup(self, shim: Shim, mutate: Optional[str]) -> _State:
        from ..durable import journal as journal_mod

        st = _State(acked=[], journal_mod=journal_mod)
        st.patches.set(journal_mod, "threading", shim.threading_shim())
        st.patches.set(journal_mod, "os", _FsyncFreeOs(os))
        st.run_dir = tempfile.mkdtemp(prefix="osim-interleave-")
        j = journal_mod.RunJournal.open(st.run_dir)
        st.journal = j
        torn = mutate == "torn-checkpoint"

        def appender(name: str):
            def fn() -> None:
                for k in range(2):
                    if torn:
                        st.acked.append(j._seq)  # ack before durability
                        j.append("tick", actor=name, k=k)
                    else:
                        rec = j.append("tick", actor=name, k=k)
                        st.acked.append(rec["seq"])
            return fn

        shim.actor("append-a", appender("a"))
        shim.actor("append-b", appender("b"))
        return st

    def _disk(self, st: _State) -> List[int]:
        events, _ = st.journal_mod._scan(st.journal.path)
        return [e["seq"] for e in events]

    def _closure(self, st: _State) -> Violations:
        v: Violations = []
        disk = self._disk(st)
        if disk != sorted(set(disk)) or (disk and disk != list(
                range(disk[0], disk[0] + len(disk)))):
            v.append(("journal-seq-monotonic",
                      f"on-disk seqs not gap-free monotonic: {disk}"))
        missing = sorted(set(st.acked) - set(disk))
        if missing:
            v.append(("journal-prefix-closure",
                      f"acknowledged seq(s) {missing} not on disk "
                      f"(disk has {disk})"))
        return v

    def check(self, st: _State) -> Violations:
        return self._closure(st)

    def check_crash(self, st: _State) -> Violations:
        return self._closure(st)

    def teardown(self, st: _State) -> None:
        try:
            st.journal.close()
        finally:
            st.patches.restore()
            shutil.rmtree(st.run_dir, ignore_errors=True)


class BreakerScenario(Scenario):
    """CircuitBreaker state-machine legality: three clients race
    allow()/record_* against a breaker seeded open with an elapsed
    cooldown, under the shimmed instance lock. Invariants: every
    observed state *set* is a legal transition (in particular a
    half_open state can never be re-entered from half_open — the
    double-probe signature), and each open->half_open transition admits
    exactly one probe."""

    name = "breaker"
    title = "circuit-breaker probe admission and transitions"
    mutations = {
        "double-probe": "allow() checks the state outside the lock "
                        "(check-then-act); two clients can both be "
                        "admitted as the half-open probe",
    }

    _LEGAL = {
        ("closed", "closed"), ("closed", "open"),
        ("open", "open"), ("open", "half_open"), ("open", "closed"),
        ("half_open", "closed"), ("half_open", "open"),
    }

    def setup(self, shim: Shim, mutate: Optional[str]) -> _State:
        from ..resilience import policy as policy_mod

        st = _State(transitions=[], probes=[])
        st.patches.set(policy_mod, "threading", shim.threading_shim())
        b = policy_mod.CircuitBreaker(
            "interleave", failure_threshold=1, cooldown_s=0.0,
            clock=shim.clock,
        )
        b.force_open("seeded open")  # setup context: ops apply directly
        st.transitions.append(b.state)
        orig_export = b._export

        def export_wrap() -> None:
            # called inside the instance lock on every state set, so
            # appends are ordered by ops on a shared shim object
            st.transitions.append(b.state)
            orig_export()
        b._export = export_wrap
        if mutate == "double-probe":
            def racy_allow() -> bool:
                if b.state == b.CLOSED:
                    return True
                if (b.state == b.OPEN
                        and b.clock() - b._opened_at >= b.cooldown_s):
                    with b._lock:
                        b.state = b.HALF_OPEN
                        b._export()
                    return True
                return False
            b.allow = racy_allow
        st.breaker = b

        def client(name: str, succeed: bool):
            def fn() -> None:
                if b.allow():
                    st.probes.append((name, b.state))
                    if succeed:
                        b.record_success()
                    else:
                        b.record_failure("interleave probe failure")
            return fn

        shim.actor("probe-ok", client("probe-ok", True))
        shim.actor("probe-fail-1", client("probe-fail-1", False))
        shim.actor("probe-fail-2", client("probe-fail-2", False))
        return st

    def check(self, st: _State) -> Violations:
        v: Violations = []
        seq = st.transitions
        for prevs, nexts in zip(seq, seq[1:]):
            if (prevs, nexts) not in self._LEGAL:
                v.append(("breaker-legal-transitions",
                          f"illegal state set {prevs} -> {nexts} "
                          f"(full sequence: {seq})"))
        admissions = sum(
            1 for a, bn in zip(seq, seq[1:])
            if a == "open" and bn == "half_open"
        )
        half_open_probes = sum(
            1 for _, state in st.probes if state == "half_open"
        )
        if half_open_probes > admissions:
            v.append(("breaker-single-probe",
                      f"{half_open_probes} probe(s) admitted in "
                      f"half_open but only {admissions} open->half_open "
                      "transition(s)"))
        return v


SCENARIOS: Dict[str, Scenario] = {
    s.name: s for s in (
        AdmissionScenario(), FenceScenario(), SessionScenario(),
        JournalScenario(), BreakerScenario(),
    )
}

#: mutation name -> (scenario name, description); the seeded-bad
#: protocol variants that prove the checker's teeth (`--mutate`).
MUTATIONS: Dict[str, Tuple[str, str]] = {
    mname: (s.name, desc)
    for s in SCENARIOS.values()
    for mname, desc in s.mutations.items()
}


# ---------------------------------------------------------------------------
# The explorer: DFS over scheduling choices with a context-switch bound
# and sleep-set partial-order reduction.
# ---------------------------------------------------------------------------


@dataclass
class _Branch:
    """One unexplored DFS branch: replay `forced`, then free-run under
    the default policy. `sleep` is the sleep set in effect at decision
    index len(forced) (i.e. after taking the last forced choice)."""

    forced: List[int]
    sleep: frozenset = frozenset()


@dataclass
class _RunRecord:
    status: str = "ok"
    choices: List[int] = field(default_factory=list)
    defaults: List[int] = field(default_factory=list)
    violations: Violations = field(default_factory=list)
    trace: List[Tuple[str, str, str]] = field(default_factory=list)


def _independent(a: _Op, b: _Op) -> bool:
    """Object-level independence: ops on distinct shim objects commute.
    Sound for data-race-free code (races.py's certificate): any
    conflicting plain-state access is ordered by ops on a common lock,
    and scenario-harness state goes through _SharedCell."""
    return a.obj is not b.obj


def _sleepfree_default(prev: Optional[int], enabled: List[int]) -> int:
    """The deterministic baseline policy minimization replays against:
    continue the current actor while it is enabled, else lowest id.
    Never chooses CRASH."""
    if prev is not None and prev in enabled:
        return prev
    return min(enabled)


def _run_once(
    scenario: Scenario,
    mutate: Optional[str],
    decide,
    *,
    max_steps: int,
) -> Tuple[_RunRecord, Shim]:
    """Execute the scenario once under `decide`, check invariants, and
    tear the patches back down. Always leaves the process clean."""
    shim = Shim()
    rec = _RunRecord()
    state = scenario.setup(shim, mutate)
    try:
        status = shim.drive(
            decide, max_steps=max_steps, crashable=scenario.crashable
        )
        rec.status = status
        rec.trace = list(shim.trace)
        if status == "ok":
            rec.violations = list(scenario.check(state))
        elif status == "crashed":
            rec.violations = list(scenario.check_crash(state))
        elif status == "deadlock":
            rec.violations = [(
                "no-deadlock",
                f"semantic deadlock: {shim.blocked_summary()}",
            )]
        for name, exc in shim.actor_exceptions():
            rec.violations.append((
                "actor-exception",
                f"actor {name} raised {type(exc).__name__}: {exc}",
            ))
    finally:
        scenario.teardown(state)
    return rec, shim


def _explore(
    scenario: Scenario,
    mutate: Optional[str],
    *,
    seed: int,
    preemptions: int,
    max_runs: int,
    max_steps: int,
    use_dpor: bool,
) -> Dict[str, Any]:
    """Bounded-exhaustive DFS. Returns counters plus the first violating
    run (if any), un-minimized."""
    rng = random.Random(seed)
    stack: List[_Branch] = [_Branch(forced=[])]
    runs = states = pruned = crash_branches = 0
    deepest = 0
    first_violation: Optional[_RunRecord] = None

    while stack and runs < max_runs:
        branch = stack.pop()
        runs += 1
        sleep: Set[int] = set(branch.sleep)
        preempts = [0]
        record = _RunRecord()

        def decide(i, enabled, ops, crash_ok, prev, _b=branch,
                   _sleep=sleep, _pre=preempts, _rec=record):
            forced = _b.forced
            if i < len(forced):
                c = forced[i]
                if c != CRASH and c not in enabled:
                    raise RuntimeError(
                        f"scenario {scenario.name!r} replayed "
                        f"non-deterministically at step {i}"
                    )
            else:
                def is_preempt(x: int) -> bool:
                    return (prev is not None and prev in enabled
                            and x != prev)

                admissible = [
                    x for x in enabled
                    if _pre[0] + (1 if is_preempt(x) else 0) <= preemptions
                ]
                live = [x for x in admissible if x not in _sleep]
                if not live:
                    raise _Prune()
                c = prev if prev in live else min(live)
                siblings = [x for x in live if x != c]
                rng.shuffle(siblings)
                # push in reverse so LIFO explores c's subtree, then
                # siblings in order — each sibling sleeping on the
                # choices explored before it (Godefroid's sleep sets)
                pushes: List[_Branch] = []
                before: List[int] = [c]
                for s in siblings:
                    sl = frozenset(
                        x for x in (_sleep | set(before))
                        if x in ops and _independent(ops[x], ops[s])
                    ) if use_dpor else frozenset()
                    pushes.append(_Branch(_rec.choices[:i] + [s], sl))
                    before.append(s)
                for b in reversed(pushes):
                    stack.append(b)
                if crash_ok:
                    stack.append(_Branch(_rec.choices[:i] + [CRASH]))
                if use_dpor and c != CRASH:
                    kept = {
                        x for x in _sleep
                        if x in ops and _independent(ops[x], ops[c])
                    }
                    _sleep.clear()
                    _sleep.update(kept)
            if c != CRASH and prev is not None and prev in enabled \
                    and c != prev:
                _pre[0] += 1
            _rec.choices.append(c)
            _rec.defaults.append(_sleepfree_default(prev, enabled))
            return c

        rec, _shim = _run_once(
            scenario, mutate, decide, max_steps=max_steps
        )
        record.status = rec.status
        record.violations = rec.violations
        record.trace = rec.trace
        states += len(record.choices)
        deepest = max(deepest, len(record.choices))
        if rec.status == "pruned":
            pruned += 1
            continue
        if record.choices and record.choices[-1] == CRASH:
            crash_branches += 1
        if record.violations:
            first_violation = record
            break

    return {
        "runs": runs,
        "states": states,
        "pruned": pruned,
        "crash_branches": crash_branches,
        "deepest": deepest,
        "completed": not stack and runs <= max_runs,
        "violating_run": first_violation,
    }


def _replay_run(
    scenario: Scenario,
    mutate: Optional[str],
    interventions: List[Tuple[int, int]],
    *,
    max_steps: int,
) -> _RunRecord:
    """Execute exactly one run: follow the default policy except at the
    intervened decisions. An intervention whose actor is not enabled at
    its step falls back to the default (ddmin relies on this: removing
    an earlier intervention may shift what later steps see)."""
    forced = dict(interventions)
    rec = _RunRecord()

    def decide(i, enabled, ops, crash_ok, prev):
        want = forced.get(i)
        if want is not None and (
            want in enabled or (want == CRASH and crash_ok)
        ):
            c = want
        else:
            c = _sleepfree_default(prev, enabled)
        rec.choices.append(c)
        rec.defaults.append(_sleepfree_default(prev, enabled))
        return c

    out, _shim = _run_once(scenario, mutate, decide, max_steps=max_steps)
    out.choices = rec.choices
    out.defaults = rec.defaults
    return out


def _interventions_of(rec: _RunRecord) -> List[Tuple[int, int]]:
    return [
        (i, c) for i, (c, d) in enumerate(zip(rec.choices, rec.defaults))
        if c != d
    ]


def minimize(
    scenario: Scenario,
    mutate: Optional[str],
    rec: _RunRecord,
    *,
    max_steps: int,
) -> Tuple[List[Tuple[int, int]], _RunRecord]:
    """ddmin-style one-at-a-time reduction over the run's interventions
    (the `semantics.minimize` counterexample flow): drop each divergence
    from the default policy while the violation still reproduces. The
    result is the minimal replayable schedule."""
    interventions = _interventions_of(rec)
    best = _replay_run(scenario, mutate, interventions, max_steps=max_steps)
    if not best.violations:
        # the violating run is not reproducible from interventions alone
        # (should not happen for deterministic scenarios); keep the
        # original evidence rather than minimizing a non-repro.
        return interventions, rec
    changed = True
    while changed and interventions:
        changed = False
        for k in range(len(interventions)):
            candidate = interventions[:k] + interventions[k + 1:]
            attempt = _replay_run(
                scenario, mutate, candidate, max_steps=max_steps
            )
            if attempt.violations:
                interventions = candidate
                best = attempt
                changed = True
                break
    return interventions, best


# ---------------------------------------------------------------------------
# Report + driver
# ---------------------------------------------------------------------------


@dataclass
class InterleaveViolation:
    scenario: str
    invariant: str
    message: str
    #: the minimal replayable schedule: divergences from the default
    #: policy as [step, actor_id] pairs (actor -1 = CRASH).
    interventions: List[Tuple[int, int]]
    #: the minimized run, one (actor, op, object) row per decision.
    trace: List[Tuple[str, str, str]]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "scenario": self.scenario,
            "invariant": self.invariant,
            "message": self.message,
            "interventions": [list(p) for p in self.interventions],
            "trace": [list(t) for t in self.trace],
        }


@dataclass
class ScenarioResult:
    name: str
    title: str
    runs: int = 0
    states: int = 0
    pruned: int = 0
    crash_branches: int = 0
    deepest: int = 0
    completed: bool = False
    violations: List[InterleaveViolation] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "title": self.title,
            "runs": self.runs,
            "states": self.states,
            "pruned": self.pruned,
            "crash_branches": self.crash_branches,
            "deepest": self.deepest,
            "completed": self.completed,
            "violations": [v.to_dict() for v in self.violations],
        }


@dataclass
class InterleaveReport:
    """Deterministic (wall-clock-free) result of one interleave pass:
    same seed and bounds => byte-identical to_dict()/render_text()."""

    ok: bool
    seed: int
    mutate: Optional[str]
    bounds: Dict[str, int]
    dpor: bool
    scenarios: List[ScenarioResult]
    replayed: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "ok": self.ok,
            "seed": self.seed,
            "mutate": self.mutate,
            "bounds": dict(self.bounds),
            "dpor": self.dpor,
            "scenarios": [s.to_dict() for s in self.scenarios],
        }
        if self.replayed is not None:
            d["replayed"] = self.replayed
        d["digest"] = hashlib.sha256(
            json.dumps(d, sort_keys=True, separators=(",", ":")).encode()
        ).hexdigest()
        return d

    def render_text(self) -> str:
        lines: List[str] = []
        head = "interleave: OK" if self.ok else "interleave: FAIL"
        lines.append(
            f"{head}  (seed={self.seed}, preemptions<="
            f"{self.bounds['preemptions']}, dpor={'on' if self.dpor else 'off'}"
            + (f", mutate={self.mutate}" if self.mutate else "") + ")"
        )
        for s in self.scenarios:
            status = "complete" if s.completed else "BUDGET EXHAUSTED"
            lines.append(
                f"  {s.name:<10} {s.runs} runs, {s.states} states "
                f"(deepest {s.deepest}, {s.pruned} pruned, "
                f"{s.crash_branches} crash branches) [{status}]"
            )
            for v in s.violations:
                lines.append(f"    VIOLATION [{v.invariant}] {v.message}")
                lines.append(
                    "    schedule: "
                    + json.dumps({
                        "scenario": v.scenario,
                        "interventions": [list(p) for p in v.interventions],
                    })
                )
                for i, (actor, kind, obj) in enumerate(v.trace):
                    lines.append(f"      step {i:>3}  {actor:<14} "
                                 f"{kind:<10} {obj}")
        if self.ok:
            total_runs = sum(s.runs for s in self.scenarios)
            total_states = sum(s.states for s in self.scenarios)
            lines.append(
                f"  explored {total_runs} interleavings / {total_states} "
                "states; every invariant held in every schedule"
            )
        return "\n".join(lines)


def _schedule_dict(v: InterleaveViolation, seed: int,
                   mutate: Optional[str]) -> Dict[str, Any]:
    """The on-disk schedule-replay format (docs/static-analysis.md)."""
    return {
        "scenario": v.scenario,
        "seed": seed,
        "mutate": mutate,
        "interventions": [list(p) for p in v.interventions],
    }


def run_scenario(
    scenario: Scenario,
    *,
    mutate: Optional[str] = None,
    seed: int = 0,
    preemptions: int = 2,
    max_runs: int = 60000,
    max_steps: int = 500,
    use_dpor: bool = True,
) -> ScenarioResult:
    """Explore one scenario exhaustively within bounds; on the first
    violation, stop and ddmin-minimize it to a replayable schedule."""
    out = _explore(
        scenario, mutate, seed=seed, preemptions=preemptions,
        max_runs=max_runs, max_steps=max_steps, use_dpor=use_dpor,
    )
    result = ScenarioResult(
        name=scenario.name, title=scenario.title,
        runs=out["runs"], states=out["states"], pruned=out["pruned"],
        crash_branches=out["crash_branches"], deepest=out["deepest"],
        completed=out["completed"],
    )
    bad = out["violating_run"]
    if bad is not None:
        result.completed = False
        interventions, minimized = minimize(
            scenario, mutate, bad, max_steps=max_steps
        )
        for invariant, message in minimized.violations or bad.violations:
            result.violations.append(InterleaveViolation(
                scenario=scenario.name,
                invariant=invariant,
                message=message,
                interventions=interventions,
                trace=minimized.trace or bad.trace,
            ))
    return result


def run_interleave(
    scenarios: Optional[List[str]] = None,
    *,
    seed: int = 0,
    quick: bool = False,
    mutate: Optional[str] = None,
    preemptions: Optional[int] = None,
    max_runs: Optional[int] = None,
    max_steps: Optional[int] = None,
    use_dpor: bool = True,
    replay: Optional[Dict[str, Any]] = None,
) -> InterleaveReport:
    """The `simon interleave` entry point.

    Default mode explores every requested scenario within the documented
    bounds. `mutate` narrows to the mutation's scenario and runs it with
    the seeded bug applied (the checker must find and minimize it).
    `replay` executes exactly one schedule previously emitted by a
    violation (the regression vehicle for concurrency fixes)."""
    bounds = dict(QUICK_BOUNDS if quick else DEFAULT_BOUNDS)
    if preemptions is not None:
        bounds["preemptions"] = int(preemptions)
    if max_runs is not None:
        bounds["max_runs"] = int(max_runs)
    if max_steps is not None:
        bounds["max_steps"] = int(max_steps)

    if replay is not None:
        name = replay.get("scenario", "")
        if name not in SCENARIOS:
            raise ValueError(f"replay schedule names unknown scenario "
                             f"{name!r} (have: {sorted(SCENARIOS)})")
        scn = SCENARIOS[name]
        r_mutate = replay.get("mutate") or mutate
        interventions = [
            (int(i), int(c)) for i, c in replay.get("interventions", [])
        ]
        rec = _replay_run(
            scn, r_mutate, interventions, max_steps=bounds["max_steps"]
        )
        result = ScenarioResult(
            name=scn.name, title=scn.title, runs=1,
            states=len(rec.choices), completed=True,
        )
        for invariant, message in rec.violations:
            result.violations.append(InterleaveViolation(
                scenario=scn.name, invariant=invariant, message=message,
                interventions=interventions, trace=rec.trace,
            ))
        return InterleaveReport(
            ok=not result.violations, seed=seed, mutate=r_mutate,
            bounds=bounds, dpor=use_dpor, scenarios=[result],
            replayed={"scenario": name,
                      "interventions": [list(p) for p in interventions]},
        )

    if mutate is not None:
        if mutate not in MUTATIONS:
            raise ValueError(f"unknown mutation {mutate!r} "
                             f"(have: {sorted(MUTATIONS)})")
        names = [MUTATIONS[mutate][0]]
    elif scenarios:
        unknown = [n for n in scenarios if n not in SCENARIOS]
        if unknown:
            raise ValueError(f"unknown scenario(s) {unknown} "
                             f"(have: {sorted(SCENARIOS)})")
        names = list(scenarios)
    else:
        names = sorted(SCENARIOS)

    results = [
        run_scenario(
            SCENARIOS[n], mutate=mutate, seed=seed,
            preemptions=bounds["preemptions"],
            max_runs=bounds["max_runs"], max_steps=bounds["max_steps"],
            use_dpor=use_dpor,
        )
        for n in names
    ]
    ok = all(r.completed and not r.violations for r in results)
    return InterleaveReport(
        ok=ok, seed=seed, mutate=mutate, bounds=bounds, dpor=use_dpor,
        scenarios=results,
    )
