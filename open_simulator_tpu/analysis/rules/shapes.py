"""Shape-discipline rule: jit-entry shape args must come from the bucket
family.

The compile cache keys on concrete shapes. `ops/encode.py::round_up` and
the `_bucket*` helpers quantise every dynamic size to a small family of
shapes so the add-node capacity search compiles once per bucket instead
of once per probe. A call site that feeds a raw `len(...)` or request
count straight into a jit entry's shape-determining static argument
reintroduces a recompile per distinct value — the exact failure mode
the paper's order-of-magnitude win depends on avoiding.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, Optional, Set, Tuple

from ..lint import Finding, FunctionInfo, LintContext, ModuleInfo, rule

#: static argnames that determine array shapes
SHAPE_PARAM_RE = re.compile(r"(size|steps|cap|chunk|pad|bucket)", re.IGNORECASE)
#: the blessed quantisation helpers
BUCKET_HELPERS = {"round_up", "_bucket", "_bucket_j", "_bucket_light", "_bucket_chunk"}


def _params(node: ast.AST) -> Tuple[str, ...]:
    args = node.args  # type: ignore[attr-defined]
    return tuple(a.arg for a in args.posonlyargs + args.args + args.kwonlyargs)


def _positional_params(node: ast.AST) -> Tuple[str, ...]:
    args = node.args  # type: ignore[attr-defined]
    return tuple(a.arg for a in args.posonlyargs + args.args)


def _shape_entries(ctx: LintContext) -> Dict[Tuple[str, str], Set[str]]:
    """(module, qualname) -> shape-determining param names to check.

    Seeds: jit roots whose static_argnames look shape-like. Then a fixpoint
    adds thin wrappers: if ``wrapper(.., n, ..)`` forwards its own parameter
    verbatim into an entry's shape param, the wrapper's parameter becomes
    checked at *its* call sites (e.g. ``_group_call`` forwarding
    ``group_size`` into ``_group_jit``)."""
    entries: Dict[Tuple[str, str], Set[str]] = {}
    for mod in ctx.modules.values():
        for info in mod.functions.values():
            if info.is_jit_root and info.static_argnames:
                shaped = {n for n in info.static_argnames if SHAPE_PARAM_RE.search(n)}
                if shaped:
                    entries.setdefault((mod.name, info.qualname), set()).update(shaped)
    changed = True
    while changed:
        changed = False
        for mod in ctx.modules.values():
            for info in mod.functions.values():
                own = set(_params(info.node))
                for call, _scope in _calls_in(info.node):
                    target = _resolve_entry(ctx, mod, call, entries)
                    if target is None:
                        continue
                    tkey, tinfo, shaped = target
                    for pname, expr in _bind_args(tinfo, call):
                        if (
                            pname in shaped
                            and isinstance(expr, ast.Name)
                            and expr.id in own
                        ):
                            key = (mod.name, info.qualname)
                            cur = entries.setdefault(key, set())
                            if expr.id not in cur:
                                cur.add(expr.id)
                                changed = True
    return entries


def _calls_in(scope: ast.AST) -> Iterator[Tuple[ast.Call, ast.AST]]:
    for node in ast.walk(scope):
        if isinstance(node, ast.Call):
            yield node, scope


def _resolve_entry(
    ctx: LintContext,
    mod: ModuleInfo,
    call: ast.Call,
    entries: Dict[Tuple[str, str], Set[str]],
) -> Optional[Tuple[Tuple[str, str], FunctionInfo, Set[str]]]:
    resolved = ctx.resolve_call(mod, call.func)
    if resolved is None or resolved not in entries:
        return None
    tmod, tqual = resolved
    info = None
    for cand in ctx.modules[tmod].functions.values():
        if cand.qualname == tqual:
            info = cand
            break
    if info is None:
        return None
    return resolved, info, entries[resolved]


def _bind_args(info: FunctionInfo, call: ast.Call) -> Iterator[Tuple[str, ast.expr]]:
    """Map call-site expressions onto the callee's parameter names; gives up
    on *args/**kwargs splats (can't map statically)."""
    if any(isinstance(a, ast.Starred) for a in call.args) or any(
        kw.arg is None for kw in call.keywords
    ):
        return
    pos = _positional_params(info.node)
    for i, a in enumerate(call.args):
        if i < len(pos):
            yield pos[i], a
    for kw in call.keywords:
        if kw.arg is not None:
            yield kw.arg, kw.value


def _is_bucket_call(mod: ModuleInfo, func: ast.expr) -> bool:
    if isinstance(func, ast.Name):
        if func.id in BUCKET_HELPERS:
            return True
        imp = mod.imports.get(func.id)
        return imp is not None and imp[1] in BUCKET_HELPERS
    if isinstance(func, ast.Attribute):
        return func.attr in BUCKET_HELPERS
    return False


def _is_bucketed(
    expr: ast.expr, scope: ast.AST, mod: ModuleInfo, checked_params: Set[str]
) -> bool:
    """Conservative provenance check: True only when the expression's value
    provably comes from the bucket family (constant, bucket-helper call,
    shape access, or compositions thereof)."""
    if isinstance(expr, ast.Constant):
        return isinstance(expr.value, (int, bool)) or expr.value is None
    if isinstance(expr, ast.Name):
        if expr.id.isupper():  # module constants like J_CAP
            return True
        if expr.id in checked_params:
            # a parameter this rule already checks at the enclosing
            # function's own call sites (wrapper propagation)
            return True
        return _assignments_bucketed(expr.id, scope, mod, checked_params)
    if isinstance(expr, ast.Call):
        if _is_bucket_call(mod, expr.func):
            return True
        if isinstance(expr.func, ast.Name) and expr.func.id in ("min", "max"):
            return all(
                _is_bucketed(a, scope, mod, checked_params) for a in expr.args
            )
        return False
    if isinstance(expr, ast.Attribute):
        return expr.attr == "shape"
    if isinstance(expr, ast.Subscript):
        return _is_bucketed(expr.value, scope, mod, checked_params)
    if isinstance(expr, ast.BinOp):
        return _is_bucketed(expr.left, scope, mod, checked_params) and _is_bucketed(
            expr.right, scope, mod, checked_params
        )
    if isinstance(expr, ast.UnaryOp):
        return _is_bucketed(expr.operand, scope, mod, checked_params)
    if isinstance(expr, ast.IfExp):
        return _is_bucketed(expr.body, scope, mod, checked_params) and _is_bucketed(
            expr.orelse, scope, mod, checked_params
        )
    return False


def _assignments_bucketed(
    name: str, scope: ast.AST, mod: ModuleInfo, checked_params: Set[str]
) -> bool:
    """True when every assignment to ``name`` in the enclosing scope is
    bucketed. No assignment found -> unknown -> False (conservative)."""
    found = False
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    found = True
                    if not _is_bucketed(node.value, scope, mod, checked_params):
                        return False
        elif isinstance(node, ast.AnnAssign):
            if (
                isinstance(node.target, ast.Name)
                and node.target.id == name
                and node.value is not None
            ):
                found = True
                if not _is_bucketed(node.value, scope, mod, checked_params):
                    return False
        elif isinstance(node, ast.AugAssign):
            if isinstance(node.target, ast.Name) and node.target.id == name:
                return False
    return found


def _scopes(mod: ModuleInfo) -> Iterator[Tuple[ast.AST, Set[str]]]:
    """Every function scope in the module (module level excluded — jit
    entries aren't called at import time) with its parameter-name set."""
    seen: Set[int] = set()
    for info in mod.functions.values():
        if id(info.node) in seen:
            continue
        seen.add(id(info.node))
        yield info.node, set(_params(info.node))
        for node in ast.walk(info.node):
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node is not info.node
                and id(node) not in seen
            ):
                seen.add(id(node))
                yield node, set(_params(node))


@rule(
    "unbucketed-jit-shape",
    "a jit entry's shape-determining static argument bypasses the "
    "round_up/_bucket helpers, causing one recompile per distinct value",
)
def unbucketed_jit_shape(ctx: LintContext) -> Iterator[Finding]:
    entries = _shape_entries(ctx)
    if not entries:
        return
    for mod in ctx.modules.values():
        for scope, own_params in _scopes(mod):
            scope_key = None
            for info in mod.functions.values():
                if info.node is scope:
                    scope_key = (mod.name, info.qualname)
                    break
            checked = entries.get(scope_key, set()) if scope_key else set()
            for node in ast.walk(scope):
                if not isinstance(node, ast.Call):
                    continue
                target = _resolve_entry(ctx, mod, node, entries)
                if target is None:
                    continue
                tkey, tinfo, shaped = target
                if scope_key == tkey:
                    continue  # recursion / self-forwarding already covered
                for pname, expr in _bind_args(tinfo, node):
                    if pname not in shaped:
                        continue
                    if not _is_bucketed(expr, scope, mod, checked):
                        yield Finding(
                            "unbucketed-jit-shape", mod.path,
                            expr.lineno, expr.col_offset,
                            f"shape arg {pname!r} of {tinfo.qualname} does "
                            "not come from round_up/_bucket*; raw sizes "
                            "recompile per distinct value",
                        )
