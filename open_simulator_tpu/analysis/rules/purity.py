"""Purity rules: traced code must not sync to host or read host state.

A ``float(x)``/``int(x)``/``bool(x)``/``x.item()`` on a traced value
raises ``ConcretizationTypeError`` at best and, under ``jnp.where``-style
tracing, silently forces a device→host transfer at worst. ``np.asarray``
on a tracer materialises it. Host-state reads (``time.*``,
``os.environ``, ``random.*``) are baked in at trace time — the jitted
kernel replays the first call's value forever, which is exactly the bug
class the compile cache makes invisible.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from ..lint import Finding, LintContext, ModuleInfo, rule

_COERCERS = {"float", "int", "bool", "complex"}
#: numpy calls that materialise their argument (host transfer on tracers).
_NP_MATERIALIZERS = {"asarray", "array", "copy", "frombuffer", "ascontiguousarray"}
#: attribute names whose access yields static (Python-level) values even on
#: traced arrays — coercing these is fine.
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "nbytes", "itemsize"}


def _is_static_expr(node: ast.expr) -> bool:
    """True when the expression is host-level for sure (safe to coerce)."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Name):
        # module-level ALL_CAPS constants (J_CAP, DM_CAP, ...) are ints
        return node.id.isupper() or node.id == "__debug__"
    if isinstance(node, ast.Attribute):
        return node.attr in _STATIC_ATTRS or _is_static_expr(node.value)
    if isinstance(node, ast.Subscript):
        return _is_static_expr(node.value)
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Name) and node.func.id in ("len", "min", "max"):
            return all(_is_static_expr(a) for a in node.args)
        return False
    if isinstance(node, ast.BinOp):
        return _is_static_expr(node.left) and _is_static_expr(node.right)
    if isinstance(node, ast.UnaryOp):
        return _is_static_expr(node.operand)
    if isinstance(node, ast.IfExp):
        return _is_static_expr(node.body) and _is_static_expr(node.orelse)
    return False


def _attr_root(node: ast.expr) -> ast.expr:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node


def _np_aliases(mod: ModuleInfo) -> Set[str]:
    return mod.alias_for("numpy")


@rule(
    "tracer-coercion",
    "float()/int()/bool()/.item()/np.asarray on values inside jit-traced code "
    "forces a host sync or ConcretizationTypeError",
)
def tracer_coercion(ctx: LintContext) -> Iterator[Finding]:
    for mod, body, root in ctx.jit_regions():
        np_alias = _np_aliases(mod)
        for node in ast.walk(body):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            # float(x) / int(x) / bool(x) / complex(x)
            if (
                isinstance(fn, ast.Name)
                and fn.id in _COERCERS
                and fn.id not in mod.functions
                and node.args
                and not all(_is_static_expr(a) for a in node.args)
            ):
                yield Finding(
                    "tracer-coercion", mod.path, node.lineno, node.col_offset,
                    f"{fn.id}() on a potentially traced value concretizes the "
                    "tracer (host sync); use jnp casts or hoist to host code",
                    jit_root=root,
                )
            # x.item()
            elif isinstance(fn, ast.Attribute) and fn.attr == "item" and not node.args:
                yield Finding(
                    "tracer-coercion", mod.path, node.lineno, node.col_offset,
                    ".item() inside jit-traced code is a device->host "
                    "transfer; keep the value on device",
                    jit_root=root,
                )
            # np.asarray(x) and friends
            elif (
                isinstance(fn, ast.Attribute)
                and fn.attr in _NP_MATERIALIZERS
                and isinstance(fn.value, ast.Name)
                and fn.value.id in np_alias
                and node.args
                and not all(_is_static_expr(a) for a in node.args)
            ):
                yield Finding(
                    "tracer-coercion", mod.path, node.lineno, node.col_offset,
                    f"np.{fn.attr}() inside jit-traced code materializes the "
                    "tracer on host; use jnp equivalents",
                    jit_root=root,
                )


@rule(
    "impure-read",
    "time.*/os.environ/random.* reads inside jit-traced code are frozen at "
    "trace time and silently replayed from the compile cache",
)
def impure_read(ctx: LintContext) -> Iterator[Finding]:
    for mod, body, root in ctx.jit_regions():
        time_alias = mod.alias_for("time")
        os_alias = mod.alias_for("os")
        random_alias = mod.alias_for("random")
        np_alias = _np_aliases(mod)
        for node in ast.walk(body):
            if isinstance(node, ast.Attribute):
                base = node.value
                if (
                    node.attr == "environ"
                    and isinstance(base, ast.Name)
                    and base.id in os_alias
                ):
                    yield Finding(
                        "impure-read", mod.path, node.lineno, node.col_offset,
                        "os.environ read inside jit-traced code is evaluated "
                        "once at trace time; read it on host and pass a value",
                        jit_root=root,
                    )
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
                base_id = fn.value.id
                if base_id in time_alias:
                    yield Finding(
                        "impure-read", mod.path, node.lineno, node.col_offset,
                        f"time.{fn.attr}() inside jit-traced code is frozen "
                        "at trace time",
                        jit_root=root,
                    )
                elif base_id in random_alias:
                    yield Finding(
                        "impure-read", mod.path, node.lineno, node.col_offset,
                        f"random.{fn.attr}() inside jit-traced code is frozen "
                        "at trace time; use jax.random with explicit keys",
                        jit_root=root,
                    )
                elif base_id in os_alias and fn.attr == "getenv":
                    yield Finding(
                        "impure-read", mod.path, node.lineno, node.col_offset,
                        "os.getenv() inside jit-traced code is evaluated once "
                        "at trace time",
                        jit_root=root,
                    )
            # np.random.*() — stateful host RNG
            if (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Attribute)
                and fn.value.attr == "random"
                and isinstance(fn.value.value, ast.Name)
                and fn.value.value.id in np_alias
            ):
                yield Finding(
                    "impure-read", mod.path, node.lineno, node.col_offset,
                    f"np.random.{fn.attr}() inside jit-traced code is frozen "
                    "at trace time; use jax.random with explicit keys",
                    jit_root=root,
                )
