"""Dtype regime rule: ``ops/`` stays f32/i32.

The kernels keep every count and score exact in float32 below 2**24
(``ops/fast.py``'s fold-order contract) and JAX_ENABLE_X64 is off, so a
stray ``float64``/``int64`` dtype either silently downcasts (x64
disabled: wrong intent survives review) or doubles HBM traffic and
defeats TPU-native layouts (x64 enabled). Bare Python ``float``/``int``
as a dtype means float64/int64 by numpy convention — same trap spelled
differently.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..lint import Finding, LintContext, rule

_WIDE = {"float64", "int64", "uint64", "complex128", "double", "longdouble"}


@rule(
    "f64-literal",
    "float64/int64 dtypes (or bare float/int as dtype=) in ops/ break the "
    "f32/i32 exactness regime",
)
def f64_literal(ctx: LintContext) -> Iterator[Finding]:
    for mod in ctx.modules.values():
        if ".ops." not in f".{mod.name}.":
            continue
        np_like = mod.alias_for("numpy") | mod.alias_for("jax.numpy")
        for node in ast.walk(mod.tree):
            # np.float64 / jnp.int64 / np.double attribute access
            if (
                isinstance(node, ast.Attribute)
                and node.attr in _WIDE
                and isinstance(node.value, ast.Name)
                and node.value.id in np_like
            ):
                yield Finding(
                    "f64-literal", mod.path, node.lineno, node.col_offset,
                    f"{node.value.id}.{node.attr} in ops/ leaves the f32/i32 "
                    "regime; use float32/int32",
                )
            # dtype=float / dtype=int keywords, and astype(float)
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if (
                        kw.arg == "dtype"
                        and isinstance(kw.value, ast.Name)
                        and kw.value.id in ("float", "int")
                    ):
                        yield Finding(
                            "f64-literal", mod.path, kw.value.lineno, kw.value.col_offset,
                            f"dtype={kw.value.id} means "
                            f"{kw.value.id}64 by numpy convention; spell the "
                            "32-bit dtype explicitly",
                        )
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype"
                    and node.args
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id in ("float", "int")
                ):
                    yield Finding(
                        "f64-literal", mod.path, node.lineno, node.col_offset,
                        f"astype({node.args[0].id}) widens to 64-bit; spell "
                        "the 32-bit dtype explicitly",
                    )
