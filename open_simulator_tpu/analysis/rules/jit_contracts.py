"""jit API contracts: hashable static defaults, no import-time tracing.

``static_argnames`` values key the jit cache by ``__hash__``; a mutable
default (list/dict/set) raises ``Unhashable`` the first time the default
is actually used — often only on an uncommon code path. Module-level
``jnp.`` calls run a trace + device transfer at import time, which both
slows cold start and pins arrays to whatever backend happens to be
default during import (breaking later ``JAX_PLATFORMS`` overrides).
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..lint import Finding, LintContext, rule

_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp)


@rule(
    "unhashable-static-default",
    "a static_argnames parameter with a list/dict/set default raises "
    "TypeError: unhashable when the default is used as a jit cache key",
)
def unhashable_static_default(ctx: LintContext) -> Iterator[Finding]:
    for mod in ctx.modules.values():
        seen: set = set()
        for info in mod.functions.values():
            if not info.is_jit_root or not info.static_argnames or id(info.node) in seen:
                continue
            seen.add(id(info.node))
            args = info.node.args  # type: ignore[attr-defined]
            pos = args.posonlyargs + args.args
            defaults = args.defaults
            # defaults align with the tail of the positional parameters
            for a, d in zip(pos[len(pos) - len(defaults):], defaults):
                if a.arg in info.static_argnames and isinstance(d, _UNHASHABLE):
                    yield Finding(
                        "unhashable-static-default", mod.path, d.lineno, d.col_offset,
                        f"static arg {a.arg!r} of {info.qualname} has an "
                        "unhashable default; use a tuple/frozen value",
                    )
            for a, d in zip(args.kwonlyargs, args.kw_defaults):
                if d is not None and a.arg in info.static_argnames and isinstance(d, _UNHASHABLE):
                    yield Finding(
                        "unhashable-static-default", mod.path, d.lineno, d.col_offset,
                        f"static arg {a.arg!r} of {info.qualname} has an "
                        "unhashable default; use a tuple/frozen value",
                    )


@rule(
    "import-time-jnp",
    "module-level jnp. computation traces and transfers at import time, "
    "pinning arrays to the import-time backend",
)
def import_time_jnp(ctx: LintContext) -> Iterator[Finding]:
    for mod in ctx.modules.values():
        jnp_alias = mod.alias_for("jax.numpy")
        if not jnp_alias:
            continue

        def walk_module_level(node: ast.AST) -> Iterator[ast.AST]:
            """Statements executed at import: module body, class bodies, and
            if/try/with blocks at those levels — but not function bodies."""
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                yield child
                yield from walk_module_level(child)

        for node in walk_module_level(mod.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in jnp_alias
            ):
                yield Finding(
                    "import-time-jnp", mod.path, node.lineno, node.col_offset,
                    f"jnp.{node.func.attr}() at module import time traces on "
                    "the import-time backend; build constants inside the "
                    "kernel or behind a cached function",
                )
