"""Hot-path concurrency rule: lock identity must outlive the race.

A ``threading.Lock()`` constructed *inside* a function that runs on a
request-handler or scheduler thread is almost always a bug: each call
builds a fresh lock object, so two threads "synchronizing" through it
each lock their own private lock and exclude nobody (the interleave
checker can only catch this when a scenario happens to cover the call
site; this rule catches it at the AST). Correct lock identity is
module-lifetime (``_lock = threading.Lock()`` at module scope) or
instance-lifetime (``self._lock = threading.Lock()`` — the construction
races nothing because the instance is not yet published).

The hot set is the strict thread-reachability closure the race detector
computes (handler methods, ``Thread``/``Timer`` targets, executor tasks,
signal handlers, watchdog-guarded callables, subprocess wrappers, and
everything they call) — ``module_hosts=False``, so main-thread code that
merely shares a module with a root is not in scope.

Escape: the standard ``osim: lint-ok[lock-in-hot-path]`` comment on the
flagged line, for deliberately-scoped locks (e.g. a closure-lifetime
lock built once at decoration time).
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from ..lint import Finding, LintContext, ModuleInfo, rule

_LOCK_CTORS = {"Lock", "RLock"}


def _is_lock_call(n: ast.AST, mod: ModuleInfo,
                  threading_alias: Set[str]) -> bool:
    if not isinstance(n, ast.Call):
        return False
    f = n.func
    if (
        isinstance(f, ast.Attribute)
        and f.attr in _LOCK_CTORS
        and isinstance(f.value, ast.Name)
        and f.value.id in threading_alias
    ):
        return True
    if isinstance(f, ast.Name):
        imp = mod.imports.get(f.id)
        return (
            imp is not None
            and imp[0] == "threading"
            and imp[1] in _LOCK_CTORS
        )
    return False


@rule(
    "lock-in-hot-path",
    "threading.Lock()/RLock() constructed inside handler- or scheduler-"
    "reachable code builds a fresh lock per call and synchronizes nothing; "
    "lock identity must be module- or instance-lifetime",
)
def lock_in_hot_path(ctx: LintContext) -> Iterator[Finding]:
    from .. import races

    roots = races.thread_roots(ctx)
    hot = races.audited_functions(ctx, roots, module_hosts=False)
    for (mod_name, qual), reason in sorted(hot.items()):
        mod = ctx.modules.get(mod_name)
        if mod is None:
            continue
        info = next(
            (i for i in mod.functions.values() if i.qualname == qual), None
        )
        if info is None:
            continue
        alias = mod.alias_for("threading")
        # instance-lifetime publishes are fine: every Lock() whose Assign
        # binds only attribute targets (self._lock = Lock(), including
        # Condition(Lock()) wrappers) constructs before the instance is
        # shared
        exempt: Set[int] = set()
        for n in races._own_body(info):
            if isinstance(n, ast.Assign) and all(
                isinstance(t, ast.Attribute) for t in n.targets
            ):
                exempt.update(
                    id(c)
                    for c in ast.walk(n.value)
                    if _is_lock_call(c, mod, alias)
                )
        for n in races._own_body(info):
            if not _is_lock_call(n, mod, alias) or id(n) in exempt:
                continue
            yield Finding(
                rule="lock-in-hot-path",
                path=mod.path,
                line=n.lineno,
                col=n.col_offset,
                message=(
                    f"lock constructed inside {qual} (audited via "
                    f"{reason}); a per-call lock excludes nobody — hoist "
                    f"it to module scope or publish it on the instance"
                ),
            )
