"""Lint rules. Importing this package registers every rule with the
engine's registry (``analysis.lint.rule``). Each module groups rules by
the contract they guard:

* ``purity``       — no tracer coercions or host-state reads in jit code
* ``jit_contracts`` — static_argnames hashability, import-time jnp work
* ``dtype``        — f32/i32 regime in ``ops/``
* ``shapes``       — jit-entry shape args flow through bucketing helpers
* ``device_sync``  — host loops feeding jit entries stay sync-free
* ``hotpath``      — no per-call lock construction on handler/scheduler
  threads (lock identity must be module- or instance-lifetime)
"""

from . import (  # noqa: F401
    device_sync,
    dtype,
    hotpath,
    jit_contracts,
    purity,
    shapes,
)
