"""device-sync-in-loop: host loops that feed jit entries must stay async.

The dispatch side of the simulator is pipelined: host loops (the extender
wave chains, the resident-delta folds, the scheduler pack loop) enqueue
jitted work and let XLA run ahead. One synchronous read inside such a
loop — ``.block_until_ready()``, ``np.asarray`` on a device array,
``float(arr)`` — stalls the pipeline every iteration: the host blocks on
step N before it can even *trace* step N+1, turning async dispatch into
lock-step round trips.

This rule flags those syncs when they sit inside a host ``for``/``while``
loop whose body also calls a jit entry point. ``np.asarray``/``float``/
``int`` only fire on values traced back (by local assignment) to a jit
entry's result — coercing genuine numpy state in the same loop is host
arithmetic, not a sync. A consolidated ``jax.device_get`` of many results
at once is the blessed idiom this rule pushes toward and is deliberately
NOT flagged. Syncs outside such loops (epilogues, one-shot reads after a
batch) are fine; traced code is the purity rules' business and is
excluded here. A deliberate per-iteration sync (e.g. a small mask the
host algorithm genuinely needs before the next dispatch) takes the
standard ``osim: lint-ok[device-sync-in-loop]`` comment escape with a
one-line justification.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..lint import Finding, LintContext, ModuleInfo, _find_function, rule
from .purity import _is_static_expr

RULE = "device-sync-in-loop"

#: jax-Array-only blocking calls — unambiguous syncs wherever they appear
_SYNC_ATTRS = {"block_until_ready", "item"}
#: numpy calls that pull a device array to host
_NP_PULLS = {"asarray", "array"}
_COERCERS = {"float", "int"}


def _is_jitish(
    ctx: LintContext, mod: ModuleInfo, func: ast.expr,
    cache: Dict[Tuple[str, str], bool],
) -> bool:
    """True when ``func`` resolves to a jit entry, or to a thin wrapper
    whose body calls one directly (``ops.grouped._group_call``-style
    dispatchers return device arrays just like the entry itself). A
    wrapper that itself calls ``jax.device_get`` is host-returning
    (``schedule_scenarios_host``-style drivers do the one consolidated
    fetch internally) and is NOT jit-ish."""
    resolved = ctx.resolve_call(mod, func)
    if resolved is None:
        return False
    if resolved in cache:
        return cache[resolved]
    cache[resolved] = False  # cut recursion; one hop only below anyway
    tmod = ctx.modules.get(resolved[0])
    info = _find_function(tmod, resolved[1]) if tmod is not None else None
    result = False
    if info is not None:
        if info.is_jit_root:
            result = True
        else:
            calls_jit = fetches = False
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr == "device_get":
                    fetches = True
                    break
                inner = ctx.resolve_call(tmod, f)
                if inner is not None:
                    iinfo = _find_function(ctx.modules[inner[0]], inner[1])
                    if iinfo is not None and iinfo.is_jit_root:
                        calls_jit = True
            result = calls_jit and not fetches
    cache[resolved] = result
    return result


def _device_names(
    ctx: LintContext, mod: ModuleInfo, fn_node: ast.AST,
    jitish_cache: Dict[Tuple[str, str], bool],
) -> Set[str]:
    """Names assigned (anywhere in the function) from a jit entry's
    result — the values whose coercion inside a loop is a device sync."""
    out: Set[str] = set()
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Assign):
            continue
        produces_device = any(
            isinstance(sub, ast.Call)
            and _is_jitish(ctx, mod, sub.func, jitish_cache)
            for sub in ast.walk(node.value)
        )
        if not produces_device:
            continue
        for target in node.targets:
            elts = target.elts if isinstance(target, ast.Tuple) else [target]
            for elt in elts:
                inner = (
                    elt.elts
                    if isinstance(elt, (ast.Tuple, ast.List))
                    else [elt]
                )
                for e in inner:
                    if isinstance(e, ast.Starred):
                        e = e.value
                    if isinstance(e, ast.Name):
                        out.add(e.id)
    return out


def _root_name(node: ast.expr) -> Optional[str]:
    while isinstance(node, (ast.Subscript, ast.Attribute, ast.Call)):
        node = node.func if isinstance(node, ast.Call) else node.value
    return node.id if isinstance(node, ast.Name) else None


def _walk_skipping(root: ast.AST, skip: Set[int]) -> Iterator[ast.AST]:
    """ast.walk, but do not descend into function defs whose id is in
    ``skip`` (jit-reachable nested defs are traced code, not host code)."""
    stack: List[ast.AST] = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if (
                isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef))
                and id(child) in skip
            ):
                continue
            stack.append(child)


def _feeds_jit(ctx: LintContext, mod: ModuleInfo, loop: ast.AST,
               skip: Set[int],
               jitish_cache: Dict[Tuple[str, str], bool]) -> str:
    """The jit entry a loop body calls, or '' when the loop is jit-free."""
    for node in _walk_skipping(loop, skip):
        if not isinstance(node, ast.Call):
            continue
        resolved = ctx.resolve_call(mod, node.func)
        if resolved is None:
            continue
        if _is_jitish(ctx, mod, node.func, jitish_cache):
            return f"{resolved[0]}:{resolved[1]}"
    return ""


def _device_arg(args: List[ast.expr], device_names: Set[str]) -> bool:
    for a in args:
        if _is_static_expr(a):
            continue
        root = _root_name(a)
        if root is not None and root in device_names:
            return True
    return False


def _sync_findings(
    mod: ModuleInfo, loop: ast.AST, skip: Set[int], jit_entry: str,
    device_names: Set[str],
) -> Iterator[Tuple[int, int, str]]:
    np_alias = mod.alias_for("numpy")
    for node in _walk_skipping(loop, skip):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in _SYNC_ATTRS:
            if fn.attr == "item" and node.args:
                continue
            yield (
                node.lineno, node.col_offset,
                f".{fn.attr}() inside a host loop feeding jit entry "
                f"{jit_entry} blocks the dispatch pipeline every iteration;"
                " hoist the sync out of the loop or batch the reads into"
                " one jax.device_get",
            )
        elif (
            isinstance(fn, ast.Attribute)
            and fn.attr in _NP_PULLS
            and isinstance(fn.value, ast.Name)
            and fn.value.id in np_alias
            and _device_arg(node.args, device_names)
        ):
            yield (
                node.lineno, node.col_offset,
                f"np.{fn.attr}() on a jit result inside a host loop feeding "
                f"{jit_entry} is a per-iteration device->host sync; batch "
                "the reads into one jax.device_get",
            )
        elif (
            isinstance(fn, ast.Name)
            and fn.id in _COERCERS
            and fn.id not in mod.functions
            and _device_arg(node.args, device_names)
        ):
            yield (
                node.lineno, node.col_offset,
                f"{fn.id}() on a jit result inside a host loop feeding "
                f"{jit_entry} is a per-iteration device->host sync; keep "
                "the loop async and read once at the end",
            )


@rule(
    RULE,
    ".block_until_ready()/np.asarray/float() on jit results inside host "
    "for/while loops that call jit entries stall the dispatch pipeline "
    "every iteration",
)
def device_sync_in_loop(ctx: LintContext) -> Iterator[Finding]:
    jitish_cache: Dict[Tuple[str, str], bool] = {}
    for mod in ctx.modules.values():
        # traced defs in this module: their bodies are compiler business
        reachable_ids = {
            id(i.node)
            for i in mod.functions.values()
            if (mod.name, i.qualname) in ctx.reachable
        }
        flagged: Set[Tuple[int, int]] = set()
        for info in {id(i.node): i for i in mod.functions.values()}.values():
            if (mod.name, info.qualname) in ctx.reachable:
                continue
            device_names = _device_names(ctx, mod, info.node, jitish_cache)
            for node in ast.walk(info.node):
                if not isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                    continue
                jit_entry = _feeds_jit(ctx, mod, node, reachable_ids,
                                       jitish_cache)
                if not jit_entry:
                    continue
                for line, col, msg in _sync_findings(
                    mod, node, reachable_ids, jit_entry, device_names
                ):
                    if (line, col) in flagged:
                        continue  # nested loops / nested defs double-walk
                    flagged.add((line, col))
                    yield Finding(RULE, mod.path, line, col, msg)
