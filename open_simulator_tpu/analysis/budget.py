"""Static HBM budgets: shape-arithmetic byte estimator + checked-in books.

Two halves, both free of program execution:

* **estimator** — per-device byte arithmetic over pytrees of anything
  that carries ``.shape``/``.dtype`` (``jax.ShapeDtypeStruct`` avals,
  real ``jax.Array``\\ s, numpy arrays). A leaf with a ``NamedSharding``
  contributes ``shard_shape`` bytes to each device in its mesh; a leaf
  without one is treated as replicated. This is the pre-materialization
  twin of ``parallel.mesh.hbm_bytes_per_device`` (which sums *real*
  shard buffers): the preflight auditor cross-checks the estimate
  against ``compiled.memory_analysis()`` so the arithmetic can be
  trusted before any buffer exists, and ``hbm_bytes_per_device`` falls
  back to it for unmaterialized leaves.

* **budget book** — the checked-in per-(entry, rung, mesh) record of
  what each lowered program is allowed to cost: argument/output/temp/
  peak bytes from ``memory_analysis()`` plus the collective census
  (kind -> count, operand bytes). ``diff()`` compares a fresh
  measurement against the book and reports violations; CI fails on any.
  The only way to raise a budget is the explicit
  ``simon preflight --write-budgets`` flow, which rewrites the book
  from the measured matrix — a memory or collective regression can
  never land silently.

Keep this module import-light: stdlib + lazy jax, so budget diffs and
book round-trips run without touching XLA.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Iterable, List, Optional

BOOK_VERSION = 1

#: Relative headroom a measurement may exceed its budget by before it is
#: a violation. Absorbs jax-version alignment drift, not regressions.
DEFAULT_TOLERANCE = 0.05
#: Absolute slack added on top of the relative tolerance (bytes). Small
#: programs live entirely inside alignment padding; 1 MiB keeps them
#: from flapping while staying far below any real node-table leak.
DEFAULT_SLACK_BYTES = 1 << 20


# ---------------------------------------------------------------------------
# shape-arithmetic estimator
# ---------------------------------------------------------------------------

def dtype_nbytes(dtype: Any) -> int:
    import numpy as np

    return int(np.dtype(dtype).itemsize)


def leaf_nbytes(shape: Iterable[int], dtype: Any) -> int:
    n = dtype_nbytes(dtype)
    for d in shape:
        n *= int(d)
    return n


def leaf_bytes_by_device(
    leaf: Any, default_device: Optional[Any] = None
) -> Dict[str, int]:
    """Per-device bytes one array-like leaf will occupy once materialized.

    With a sharding (``NamedSharding`` on an aval or array), each device
    in the sharding's device set gets ``shard_shape`` bytes. Without one
    the leaf is attributed whole to ``default_device`` (or dropped when
    that is None — an unplaced aval has no device to charge).
    """
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return {}
    sharding = getattr(leaf, "sharding", None)
    if sharding is not None and hasattr(sharding, "shard_shape"):
        per = leaf_nbytes(sharding.shard_shape(tuple(shape)), dtype)
        return {str(d): per for d in sharding.device_set}
    if default_device is None:
        return {}
    return {str(default_device): leaf_nbytes(shape, dtype)}


def estimate_bytes_by_device(
    *trees: Any, default_device: Optional[Any] = None
) -> Dict[str, int]:
    """Sum :func:`leaf_bytes_by_device` over whole pytrees.

    ``default_device`` defaults to ``jax.devices()[0]`` so unsharded
    leaves land where jax would commit them; pass an explicit device (or
    a plain string) to avoid importing jax.
    """
    import jax

    if default_device is None:
        default_device = jax.devices()[0]
    out: Dict[str, int] = {}
    for tree in trees:
        for leaf in jax.tree.leaves(tree):
            for dev, n in leaf_bytes_by_device(leaf, default_device).items():
                out[dev] = out.get(dev, 0) + n
    return out


def estimate_max_bytes_per_device(
    *trees: Any, default_device: Optional[Any] = None
) -> int:
    """The headline scalar: the worst per-device byte load of the trees."""
    per = estimate_bytes_by_device(*trees, default_device=default_device)
    return max(per.values(), default=0)


# ---------------------------------------------------------------------------
# budget book
# ---------------------------------------------------------------------------

def program_key(entry: str, rung: int, mesh: str) -> str:
    """Canonical budget key, e.g. ``ops.fast:schedule_scenarios@r128@m2x2``."""
    return f"{entry}@r{int(rung)}@m{mesh}"


@dataclasses.dataclass
class ProgramBudget:
    """Per-device byte + collective envelope of one lowered program."""

    peak_bytes: int
    argument_bytes: int
    output_bytes: int
    temp_bytes: int
    alias_bytes: int = 0
    collectives: Dict[str, int] = dataclasses.field(default_factory=dict)
    collective_bytes: int = 0

    def to_dict(self) -> dict:
        return {
            "peak_bytes": int(self.peak_bytes),
            "argument_bytes": int(self.argument_bytes),
            "output_bytes": int(self.output_bytes),
            "temp_bytes": int(self.temp_bytes),
            "alias_bytes": int(self.alias_bytes),
            "collectives": {k: int(v) for k, v in sorted(self.collectives.items())},
            "collective_bytes": int(self.collective_bytes),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ProgramBudget":
        return cls(
            peak_bytes=int(d["peak_bytes"]),
            argument_bytes=int(d["argument_bytes"]),
            output_bytes=int(d["output_bytes"]),
            temp_bytes=int(d["temp_bytes"]),
            alias_bytes=int(d.get("alias_bytes", 0)),
            collectives=dict(d.get("collectives", {})),
            collective_bytes=int(d.get("collective_bytes", 0)),
        )


@dataclasses.dataclass
class BudgetViolation:
    key: str
    kind: str      # unbudgeted | memory | new-collective | collective-bytes
    field: str     # which quantity tripped
    measured: int
    budget: int
    message: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return f"{self.key}: {self.kind}: {self.message}"


@dataclasses.dataclass
class BudgetBook:
    """The checked-in budget file (``budgets/preflight.json``)."""

    programs: Dict[str, ProgramBudget] = dataclasses.field(default_factory=dict)
    #: machine-checked verdicts (e.g. plan_1m_100k fits-in-HBM) written by
    #: --write-budgets so bench/CI can surface them without recompiling
    verdicts: Dict[str, dict] = dataclasses.field(default_factory=dict)
    tolerance: float = DEFAULT_TOLERANCE
    slack_bytes: int = DEFAULT_SLACK_BYTES
    version: int = BOOK_VERSION

    # -- persistence --------------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "version": self.version,
            "tolerance": self.tolerance,
            "slack_bytes": self.slack_bytes,
            "programs": {
                k: self.programs[k].to_dict() for k in sorted(self.programs)
            },
            "verdicts": {k: self.verdicts[k] for k in sorted(self.verdicts)},
        }

    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(self.to_dict(), fh, indent=2, sort_keys=True)
            fh.write("\n")

    @classmethod
    def load(cls, path: str) -> "BudgetBook":
        with open(path, "r", encoding="utf-8") as fh:
            d = json.load(fh)
        return cls(
            programs={
                k: ProgramBudget.from_dict(v)
                for k, v in d.get("programs", {}).items()
            },
            verdicts=dict(d.get("verdicts", {})),
            tolerance=float(d.get("tolerance", DEFAULT_TOLERANCE)),
            slack_bytes=int(d.get("slack_bytes", DEFAULT_SLACK_BYTES)),
            version=int(d.get("version", BOOK_VERSION)),
        )

    # -- diff ---------------------------------------------------------------

    def _cap(self, budget: int) -> int:
        return int(budget * (1.0 + self.tolerance)) + self.slack_bytes

    def diff(self, measured: Dict[str, ProgramBudget]) -> List[BudgetViolation]:
        """Violations of ``measured`` against this book.

        * a measured program with no budget is ``unbudgeted`` (a new entry
          / rung / mesh must be admitted via --write-budgets, consciously);
        * any byte field above ``budget * (1 + tolerance) + slack`` is a
          ``memory`` violation — shrinking is always fine;
        * a collective kind with more instances than budgeted (absent kind
          = 0) is ``new-collective``: a program that was collective-free
          must stay collective-free;
        * collective operand bytes above the byte cap is
          ``collective-bytes`` (same count, fatter gathers).

        Book entries absent from ``measured`` are NOT violations — partial
        matrices (test subsets, --entries filters) diff only what they ran.
        """
        out: List[BudgetViolation] = []
        for key in sorted(measured):
            m = measured[key]
            b = self.programs.get(key)
            if b is None:
                out.append(BudgetViolation(
                    key=key, kind="unbudgeted", field="", measured=0, budget=0,
                    message="no checked-in budget for this (entry, rung, mesh)"
                            " — run `simon preflight --write-budgets` to"
                            " admit it",
                ))
                continue
            for field in ("peak_bytes", "argument_bytes", "output_bytes",
                          "temp_bytes"):
                mv = int(getattr(m, field))
                bv = int(getattr(b, field))
                if mv > self._cap(bv):
                    out.append(BudgetViolation(
                        key=key, kind="memory", field=field,
                        measured=mv, budget=bv,
                        message=f"{field} {mv} exceeds budget {bv} "
                                f"(cap {self._cap(bv)})",
                    ))
            for kind in sorted(set(m.collectives) | set(b.collectives)):
                mc = int(m.collectives.get(kind, 0))
                bc = int(b.collectives.get(kind, 0))
                if mc > bc:
                    out.append(BudgetViolation(
                        key=key, kind="new-collective", field=kind,
                        measured=mc, budget=bc,
                        message=f"{mc} {kind} op(s) vs {bc} budgeted — new "
                                f"cross-device communication in this program",
                    ))
            if int(m.collective_bytes) > self._cap(int(b.collective_bytes)):
                out.append(BudgetViolation(
                    key=key, kind="collective-bytes", field="collective_bytes",
                    measured=int(m.collective_bytes),
                    budget=int(b.collective_bytes),
                    message=f"collective operand bytes "
                            f"{int(m.collective_bytes)} exceed budget "
                            f"{int(b.collective_bytes)}",
                ))
        return out
