"""Independent reference oracle of the Filter/Score/commit semantics.

`simon prove` (analysis/semantics.py) diffs the real device engine against
this module over an exhaustively enumerated universe corpus, so this file is
deliberately NOT allowed to share code with ops/kernels.py: it is written
straight from the kube-scheduler contract (PAPER.md; vendored plugin sources
cited per function in ops/kernels.py) in plain numpy — no jax import, no
reuse of the device kernels' helpers. Constants that both sides must agree
on (filter indices, weight fold order, the f32 comparison slack) are
REDECLARED here; tests/test_oracle.py cross-checks them against
ops/kernels.py so a drift on either side trips the suite, not the prover.

Scope: the small-scope universe family (docs/static-analysis.md). Features
whose carry machinery the enumerator never exercises — active topology
spread constraints, active inter-pod (anti)affinity terms, local-storage
volumes, out-of-tree extra plugins — raise OracleUnsupported instead of
guessing: an oracle that silently approximates is worse than none.

Float discipline: every arithmetic step mirrors the device kernel's exact
f32 expression structure (same guards, same fold order, same floor/clip
placement), because the contract being proven is bit-level placement
equality, and f32 addition is not associative.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

# --- the shared contract constants, redeclared (see module docstring) ------

F_UNSCHEDULABLE = 0
F_NODE_NAME = 1
F_TAINT = 2
F_NODE_AFFINITY = 3
F_NODE_PORTS = 4
F_RESOURCES = 5
F_SPREAD = 6
F_POD_AFFINITY = 7
F_STORAGE = 8
F_GPU = 9
F_EXTRA = 10
NUM_FILTERS = 11

#: resource axis position of the whole-GPU extended resource
GPU_COUNT_IDX = 3

#: label-selector operator encoding (ops/encode.py vocabulary)
OP_PAD = 0
OP_IN = 1
OP_NOT_IN = 2
OP_EXISTS = 3
OP_NOT_EXISTS = 4
OP_GT = 5
OP_LT = 6

#: absolute f32 comparison slack (milli-cpu / MiB units)
EPS = np.float32(1e-3)

DEFAULT_WEIGHTS = {
    "balanced_allocation": 1.0,
    "least_allocated": 1.0,
    "node_affinity": 1.0,
    "taint_toleration": 1.0,
    "topology_spread": 2.0,
    "inter_pod_affinity": 1.0,
    "prefer_avoid_pods": 10000.0,
    "simon": 1.0,
    "gpu_share": 1.0,
    "open_local": 1.0,
}

#: the canonical score fold order: alphabetical over the node-local plugins,
#: then the two carry-coupled plugins last (the commit-order contract's
#: fold-order clause; ops/kernels.py WEIGHT_ORDER)
WEIGHT_ORDER = tuple(
    sorted(k for k in DEFAULT_WEIGHTS
           if k not in ("inter_pod_affinity", "topology_spread"))
) + ("inter_pod_affinity", "topology_spread")


class OracleUnsupported(ValueError):
    """The universe exercises semantics outside the oracle's small-scope
    family (spread/inter-pod-affinity/local-storage/extra plugins)."""


f32 = np.float32


def _asf32(a) -> np.ndarray:
    return np.asarray(a, np.float32)


# ---------------------------------------------------------------------------
# Carry
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class OracleCarry:
    """Mutable cluster state, mirroring ops/kernels.Carry plane by plane."""
    free: np.ndarray         # f32[N,R]
    sel_counts: np.ndarray   # f32[S,N]
    gpu_free: np.ndarray     # f32[N,G]
    vg_free: np.ndarray      # f32[N,V]
    dev_free: np.ndarray     # f32[N,DV]
    port_any: np.ndarray     # f32[PID,N]
    port_wild: np.ndarray    # f32[PID,N]
    port_ipc: np.ndarray     # f32[PIP,N]
    anti_counts: np.ndarray  # f32[AT,N]

    def copy(self) -> "OracleCarry":
        return OracleCarry(**{
            f.name: getattr(self, f.name).copy()
            for f in dataclasses.fields(self)
        })

    def planes(self) -> Dict[str, np.ndarray]:
        return {
            f.name: getattr(self, f.name) for f in dataclasses.fields(self)
        }


def carry_from_table(
    table,
    num_selectors: int = 1,
    port_rows: int = 2,
    port_ip_rows: int = 2,
    anti_rows: int = 2,
) -> OracleCarry:
    """Fresh carry for an encoded NodeTable (ops/state.carry_from_table
    defaults: zero selector/port/anti planes, free planes from the table)."""
    n = table.free.shape[0]
    sel_rows = max(int(num_selectors), 1)
    sel_rows += (-sel_rows) % 8  # selector_table_size bucketing
    return OracleCarry(
        free=_asf32(table.free).copy(),
        sel_counts=np.zeros((sel_rows, n), np.float32),
        gpu_free=_asf32(table.gpu_free).copy(),
        vg_free=_asf32(table.vg_free).copy(),
        dev_free=_asf32(table.dev_free).copy(),
        port_any=np.zeros((port_rows, n), np.float32),
        port_wild=np.zeros((port_rows, n), np.float32),
        port_ipc=np.zeros((port_ip_rows, n), np.float32),
        anti_counts=np.zeros((anti_rows, n), np.float32),
    )


# ---------------------------------------------------------------------------
# Per-pod view + family guard
# ---------------------------------------------------------------------------

class _Pod:
    """Row p of a PodBatch-shaped SoA (duck-typed: any object with the
    PodBatch field names works)."""

    def __init__(self, batch, p: int) -> None:
        self._b = batch
        self._p = p

    def __getattr__(self, name):
        return np.asarray(getattr(self._b, name))[self._p]


def _check_supported(batch) -> None:
    b = batch
    if np.any(np.asarray(b.spread_topo) >= 0):
        raise OracleUnsupported("active topology spread constraints")
    if np.any(np.asarray(b.aff_topo) >= 0):
        raise OracleUnsupported("active inter-pod (anti)affinity terms")
    if np.any(np.asarray(b.has_local)):
        raise OracleUnsupported("local-storage volumes")
    if np.any(np.asarray(b.match_anti)) or np.any(np.asarray(b.own_anti)):
        raise OracleUnsupported("required-anti-affinity symmetry terms")


# ---------------------------------------------------------------------------
# Filters (kube filter plugin order; each mirrors its device kernel)
# ---------------------------------------------------------------------------

def _expr_matches(table, op, key, val, num) -> np.ndarray:
    label_key = np.asarray(table.label_key)
    label_pair = np.asarray(table.label_pair)
    label_num = _asf32(table.label_num)
    has_key = np.any((label_key == key) & (key != 0), axis=1)
    pair_hit = np.any(
        (label_pair[:, :, None] == val[None, None, :])
        & (val != 0)[None, None, :],
        axis=(1, 2),
    )
    key_rows = label_key == key
    with np.errstate(invalid="ignore"):
        gt = np.any(key_rows & (label_num > num), axis=1)
        lt = np.any(key_rows & (label_num < num), axis=1)
    ones = np.ones_like(has_key)
    branches = {
        OP_IN: pair_hit, OP_NOT_IN: ~pair_hit, OP_EXISTS: has_key,
        OP_NOT_EXISTS: ~has_key, OP_GT: gt, OP_LT: lt,
    }
    return branches.get(int(op), ones)


def _term_matches(table, ops, keys, vals, nums) -> np.ndarray:
    n = np.asarray(table.valid).shape[0]
    non_empty = bool(np.any(np.asarray(ops) != OP_PAD))
    if not non_empty:  # pad term: matches nothing (and skips the expr work)
        return np.zeros(n, bool)
    per_expr = np.stack(
        [
            _expr_matches(table, ops[e], keys[e], vals[e], nums[e])
            for e in range(len(ops))
        ],
        axis=1,
    ) if len(ops) else np.ones((n, 0), bool)
    return np.all(per_expr, axis=1)


def node_affinity_mask(table, pod: _Pod) -> np.ndarray:
    wanted = np.asarray(pod.ns_pair)
    label_pair = np.asarray(table.label_pair)
    present = np.any(
        label_pair[:, :, None] == wanted[None, None, :], axis=1
    )
    ns_ok = np.all(present | (wanted == 0)[None, :], axis=1)
    sel_op = np.asarray(pod.sel_op)
    term_hits = np.stack(
        [
            _term_matches(
                table, sel_op[t], np.asarray(pod.sel_key)[t],
                np.asarray(pod.sel_val)[t], _asf32(pod.sel_num)[t],
            )
            for t in range(sel_op.shape[0])
        ],
        axis=1,
    ) if sel_op.shape[0] else np.zeros((ns_ok.shape[0], 0), bool)
    terms_ok = np.any(term_hits, axis=1) | (not bool(pod.has_terms))
    return ns_ok & terms_ok


def _tolerated(table, pod: _Pod) -> np.ndarray:
    """tolerated[n, t]: taint t of node n is tolerated by the pod."""
    tk = np.asarray(table.taint_key)
    tv = np.asarray(table.taint_val)
    te = np.asarray(table.taint_effect)
    tol_key = np.asarray(pod.tol_key)[None, None, :]
    tol_val = np.asarray(pod.tol_val)[None, None, :]
    tol_exists = np.asarray(pod.tol_exists)[None, None, :]
    tol_effect = np.asarray(pod.tol_effect)[None, None, :]
    tol_valid = np.asarray(pod.tol_valid)[None, None, :]
    eff_ok = (tol_effect == 0) | (tol_effect == te[:, :, None])
    key_ok = (tol_key == 0) | (tol_key == tk[:, :, None])
    val_ok = tol_exists | (tol_val == tv[:, :, None])
    return np.any(tol_valid & eff_ok & key_ok & val_ok, axis=2)


def taint_mask(table, pod: _Pod) -> np.ndarray:
    te = np.asarray(table.taint_effect)
    hard = (te == 1) | (te == 3)  # NoSchedule / NoExecute
    return np.all(_tolerated(table, pod) | ~hard, axis=1)


def ports_mask(carry: OracleCarry, pod: _Pod) -> np.ndarray:
    hp_pid = np.asarray(pod.hp_pid)
    hp_wild = np.asarray(pod.hp_wild)
    hp_ipid = np.asarray(pod.hp_ipid)
    any_tbl = carry.port_any[hp_pid]
    wild_tbl = carry.port_wild[hp_pid]
    ip_tbl = carry.port_ipc[hp_ipid]
    conf_wild = any_tbl > 0.0
    conf_spec = (wild_tbl > 0.0) | (ip_tbl > 0.0)
    conf = np.where(hp_wild[:, None], conf_wild, conf_spec)
    conf = conf & (hp_pid > 0)[:, None]
    return ~np.any(conf, axis=0)


def allocatable_gpus(table, carry: OracleCarry) -> np.ndarray:
    usable = (carry.gpu_free > EPS) & (_asf32(table.gpu_total) > 0)
    return np.sum(usable.astype(np.float32), axis=1)


def resource_fail(table, carry: OracleCarry, pod: _Pod) -> np.ndarray:
    req = _asf32(pod.req)
    alloc = _asf32(table.alloc)
    static_fail = np.any(req[None, :] > carry.free + EPS, axis=1)
    whole_req = req[GPU_COUNT_IDX]
    whole_used = alloc[:, GPU_COUNT_IDX] - carry.free[:, GPU_COUNT_IDX]
    whole_fail = whole_req > allocatable_gpus(table, carry) - whole_used + EPS
    return static_fail | whole_fail


def gpu_mask(table, carry: OracleCarry, pod: _Pod) -> np.ndarray:
    mem = f32(pod.gpu_mem)
    num = f32(pod.gpu_num)
    is_gpu = mem > 0
    caps = np.where(
        _asf32(table.gpu_total) > 0,
        np.floor((carry.gpu_free + EPS) / max(mem, f32(1e-9))),
        f32(0.0),
    )
    feasible = (num >= 1) & (np.sum(caps, axis=1) >= num)
    return feasible if is_gpu else np.ones_like(feasible)


def run_filters(table, carry: OracleCarry, pod: _Pod):
    """-> (mask bool[N], first_fail i32[N]); first_fail = NUM_FILTERS when
    feasible, else the index of the first failing filter (kube stops the
    node's filter chain at the first failure)."""
    tol_key = np.asarray(pod.tol_key)
    tol_val = np.asarray(pod.tol_val)
    tol_exists = np.asarray(pod.tol_exists)
    tol_effect = np.asarray(pod.tol_effect)
    tol_valid = np.asarray(pod.tol_valid)
    unsched_key = int(table.unsched_key_id)
    empty_val = int(table.empty_val_id)
    unsched_tolerated = bool(np.any(
        tol_valid
        & ((tol_key == 0) | (tol_key == unsched_key))
        & (tol_exists | (tol_val == empty_val))
        & ((tol_effect == 0) | (tol_effect == 1))
    ))
    na_ok = node_affinity_mask(table, pod)
    valid = np.asarray(table.valid)
    n = valid.shape[0]
    name_id = np.asarray(table.name_id)
    pod_name_id = int(pod.node_name_id)
    fails = np.stack(
        [
            np.asarray(table.unsched).astype(bool) & (not unsched_tolerated),
            (pod_name_id != 0) & (name_id != pod_name_id),
            ~taint_mask(table, pod),
            ~na_ok,
            ~ports_mask(carry, pod),
            resource_fail(table, carry, pod),
            np.zeros(n, bool),  # F_SPREAD: family has no constraints
            np.zeros(n, bool),  # F_POD_AFFINITY: family has no terms
            np.zeros(n, bool),  # F_STORAGE: family has no volumes
            ~gpu_mask(table, carry, pod),
            np.zeros(n, bool),  # F_EXTRA: no out-of-tree plugins
        ],
        axis=1,
    )
    mask = ~np.any(fails, axis=1) & valid
    first_fail = np.where(
        np.any(fails, axis=1), np.argmax(fails, axis=1), NUM_FILTERS
    ).astype(np.int32)
    return mask, first_fail


# ---------------------------------------------------------------------------
# Score plugins
# ---------------------------------------------------------------------------

def _minmax_normalize(score: np.ndarray, valid: np.ndarray) -> np.ndarray:
    lo = np.min(np.where(valid, score, np.float32(np.inf)))
    hi = np.max(np.where(valid, score, np.float32(-np.inf)))
    rng = f32(hi - lo)
    out = np.where(
        rng > 0,
        (score - lo) * f32(100.0) / np.maximum(rng, f32(1e-9)),
        f32(0.0),
    )
    return np.clip(out, f32(0.0), f32(100.0))


def score_least_allocated(table, carry, pod: _Pod) -> np.ndarray:
    alloc = _asf32(table.alloc)[:, :2]
    free_after = carry.free[:, :2] - _asf32(pod.req)[None, :2]
    frac = np.where(
        alloc > 0, free_after / np.maximum(alloc, f32(1e-9)), f32(0.0)
    )
    return np.clip(np.mean(frac, axis=1, dtype=np.float32),
                   f32(0.0), f32(1.0)) * f32(100.0)


def score_balanced(table, carry, pod: _Pod) -> np.ndarray:
    alloc = _asf32(table.alloc)[:, :2]
    used_after = alloc - carry.free[:, :2] + _asf32(pod.req)[None, :2]
    frac = np.where(
        alloc > 0, used_after / np.maximum(alloc, f32(1e-9)), f32(0.0)
    )
    frac = np.clip(frac, f32(0.0), f32(1.0))
    return (f32(1.0) - np.abs(frac[:, 0] - frac[:, 1])) * f32(100.0)


def _worst_fit_share(alloc: np.ndarray, req: np.ndarray) -> np.ndarray:
    """share(req, alloc-req) saturated to 1 on negative headroom -> f32[N]."""
    avail = alloc - req[None, :]
    denom = np.where(avail == 0, f32(1.0), avail)
    share = np.where(
        req[None, :] == 0,
        f32(0.0),
        np.where(avail == 0, f32(1.0), req[None, :] / denom),
    )
    share = np.where(avail < 0, f32(1.0), share)
    return np.max(share, axis=1)


def score_simon(table, carry, pod: _Pod) -> np.ndarray:
    raw = np.floor(
        _worst_fit_share(_asf32(table.alloc), _asf32(pod.req)) * f32(100.0)
    )
    raw = np.where(bool(pod.has_req), raw, f32(100.0))
    return _minmax_normalize(raw, np.asarray(table.valid))


def score_gpu_share(table, carry: OracleCarry, pod: _Pod) -> np.ndarray:
    alloc = _asf32(table.alloc).copy()
    alloc[:, GPU_COUNT_IDX] = allocatable_gpus(table, carry)
    raw = _worst_fit_share(alloc, _asf32(pod.req)) * f32(100.0)
    raw = np.where(bool(pod.has_req), raw, f32(100.0))
    return _minmax_normalize(raw, np.asarray(table.valid))


def score_taint_toleration(table, pod: _Pod) -> np.ndarray:
    te = np.asarray(table.taint_effect)
    valid = np.asarray(table.valid)
    intolerable = (te == 2) & ~_tolerated(table, pod)  # PreferNoSchedule
    cnt = np.sum(intolerable.astype(np.float32), axis=1)
    max_cnt = np.max(np.where(valid, cnt, f32(0.0)))
    return np.clip(
        np.where(
            max_cnt > 0,
            (max_cnt - cnt) * f32(100.0) / np.maximum(max_cnt, f32(1e-9)),
            f32(100.0),
        ),
        f32(0.0), f32(100.0),
    )


def score_node_affinity(table, pod: _Pod) -> np.ndarray:
    valid = np.asarray(table.valid)
    pref_op = np.asarray(pod.pref_op)
    hits = np.stack(
        [
            _term_matches(
                table, pref_op[t], np.asarray(pod.pref_key)[t],
                np.asarray(pod.pref_val)[t], _asf32(pod.pref_num)[t],
            )
            for t in range(pref_op.shape[0])
        ],
        axis=1,
    ) if pref_op.shape[0] else np.zeros((valid.shape[0], 0), bool)
    raw = np.sum(
        hits * _asf32(pod.pref_weight)[None, :], axis=1, dtype=np.float32
    )
    mx = np.max(np.where(valid, raw, f32(0.0)))
    return np.clip(
        np.where(
            mx > 0, raw * f32(100.0) / np.maximum(mx, f32(1e-9)), f32(0.0)
        ),
        f32(0.0), f32(100.0),
    )


def score_prefer_avoid(table, pod: _Pod) -> np.ndarray:
    avoided = np.asarray(table.avoid_pods) & bool(pod.owned_by_rs)
    return np.where(avoided, f32(0.0), f32(100.0))


def run_scores(table, carry: OracleCarry, pod: _Pod,
               weights: Dict[str, float]) -> np.ndarray:
    n = np.asarray(table.valid).shape[0]
    by_name = {
        "balanced_allocation": score_balanced(table, carry, pod),
        "least_allocated": score_least_allocated(table, carry, pod),
        "node_affinity": score_node_affinity(table, pod),
        "taint_toleration": score_taint_toleration(table, pod),
        # family-inactive plugins, at their inactive-path values:
        # spread reverse-normalizes an all-zero count sum to 100,
        # inter-pod affinity gates its normalize on any active term (0),
        # open-local scores storageless pods 0 everywhere
        "topology_spread": np.full(n, f32(100.0)),
        "inter_pod_affinity": np.zeros(n, np.float32),
        "prefer_avoid_pods": score_prefer_avoid(table, pod),
        "simon": score_simon(table, carry, pod),
        "gpu_share": score_gpu_share(table, carry, pod),
        "open_local": np.zeros(n, np.float32),
    }
    total = None
    for name in WEIGHT_ORDER:  # the explicit left fold of the contract
        term = f32(weights.get(name, 0.0)) * by_name[name]
        total = term if total is None else total + term
    return total


# ---------------------------------------------------------------------------
# Commit
# ---------------------------------------------------------------------------

def gpu_allocate(table, carry: OracleCarry, pod: _Pod,
                 node: int) -> np.ndarray:
    """Device shares taken on `node` -> f32[G] (tightest-fit for a single
    share, lowest-id-first two-pointer greedy for multi-share)."""
    mem = f32(pod.gpu_mem)
    num = f32(pod.gpu_num)
    free_d = carry.gpu_free[node]
    total_d = _asf32(table.gpu_total)[node]
    g = free_d.shape[0]

    elig = (total_d > 0) & (free_d >= mem - EPS)
    tight = int(np.argmin(np.where(elig, free_d, np.float32(np.inf))))
    take_single = (
        (np.arange(g) == tight) & np.any(elig)
    ).astype(np.float32)

    caps = np.where(
        total_d > 0,
        np.floor((free_d + EPS) / np.maximum(mem, f32(1e-9))),
        f32(0.0),
    )
    prefix = np.cumsum(caps, dtype=np.float32) - caps
    take_multi = np.clip(num - prefix, f32(0.0), caps)
    if not np.sum(caps) >= num:
        take_multi = np.zeros_like(take_multi)

    take = take_single if num == 1 else take_multi
    if not (mem > 0 and num >= 1):
        take = np.zeros_like(take)
    return take


def commit(table, carry: OracleCarry, pod: _Pod, node: int) -> np.ndarray:
    """Mutate `carry` for a placement of `pod` on `node` -> gpu take f32[G]."""
    carry.free[node] -= _asf32(pod.req)
    carry.sel_counts[:, node] += np.asarray(pod.match_sel).astype(np.float32)
    take = gpu_allocate(table, carry, pod, node)
    carry.gpu_free[node] -= take * f32(pod.gpu_mem)
    hp_pid = np.asarray(pod.hp_pid)
    hp_wild = np.asarray(pod.hp_wild)
    hp_ipid = np.asarray(pod.hp_ipid)
    for s in range(hp_pid.shape[0]):
        pid = int(hp_pid[s])
        if pid <= 0:
            continue
        carry.port_any[pid, node] += f32(1.0)
        if bool(hp_wild[s]):
            carry.port_wild[pid, node] += f32(1.0)
        elif int(hp_ipid[s]) > 0:
            carry.port_ipc[int(hp_ipid[s]), node] += f32(1.0)
    return take


# ---------------------------------------------------------------------------
# The sequential schedule loop
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class OracleResult:
    nodes: np.ndarray     # i32[P] chosen node index or -1
    reasons: np.ndarray   # i32[P,NUM_FILTERS] unschedulable reason counts
    gpu_take: np.ndarray  # i32[P,G]
    carry: OracleCarry    # final carry
    scores: np.ndarray    # f32[P,N] post-mask scores (debugging aid)


def schedule(table, batch, weights: Optional[Dict[str, float]] = None
             ) -> OracleResult:
    """Sequentially filter/score/commit every row of `batch` against `table`
    — the reference semantics `simon prove` holds the device engine to."""
    _check_supported(batch)
    weights = DEFAULT_WEIGHTS if weights is None else weights
    carry = carry_from_table(
        table,
        num_selectors=np.asarray(batch.match_sel).shape[1],
        port_rows=2, port_ip_rows=2,
        anti_rows=np.asarray(batch.own_anti).shape[1],
    )
    p = np.asarray(batch.valid).shape[0]
    n = np.asarray(table.valid).shape[0]
    g = carry.gpu_free.shape[1]
    valid_nodes = np.asarray(table.valid)

    nodes = np.full(p, -1, np.int32)
    reasons = np.zeros((p, NUM_FILTERS), np.int32)
    takes = np.zeros((p, g), np.int32)
    scores = np.full((p, n), -np.inf, np.float32)

    for i in range(p):
        pod = _Pod(batch, i)
        mask, first_fail = run_filters(table, carry, pod)
        score = run_scores(table, carry, pod, weights)
        score = np.where(mask, score, np.float32(-np.inf))
        node = int(np.argmax(score))  # first max: lowest index wins ties
        ok = bool(np.any(mask)) and bool(pod.valid)
        scores[i] = score
        if ok:
            nodes[i] = node
            takes[i] = commit(table, carry, pod, node).astype(np.int32)
        else:
            failed = (first_fail < NUM_FILTERS) & valid_nodes
            np.add.at(
                reasons[i], np.clip(first_fail, 0, NUM_FILTERS - 1),
                failed.astype(np.int32),
            )
    return OracleResult(
        nodes=nodes, reasons=reasons, gpu_take=takes,
        carry=carry, scores=scores,
    )
