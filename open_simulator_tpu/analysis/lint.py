"""AST lint engine: package walker, jit-reachability, rule driver.

Pure stdlib (``ast`` only — importing jax just to lint would pay XLA
startup on every pre-commit run). The engine builds a package-wide
model once, computes which functions are reachable from a ``jax.jit``
entry point, then hands a :class:`LintContext` to every registered rule.

Jit entry points are recognised in all three spellings the codebase
uses::

    @jax.jit                                   # bare decorator
    @functools.partial(jax.jit, static_argnames=("n",))
    _group_jit = jax.jit(schedule_group, static_argnames=(...))

Reachability is a worklist over the call graph: any function called by
name from a jit-reachable body (including function-valued arguments to
``jax.lax.scan``/``cond``/``while_loop``/``switch``/``fori_loop`` and
``jax.vmap``) is itself jit-reachable. ``from .sibling import helper``
imports are resolved within the package, so a helper in ``ops/encode.py``
called from a jitted body in ``ops/fast.py`` is covered.

Suppressions: append an ``osim: lint-ok[rule-id]`` comment to the flagged
line. Every suppression should carry a one-line justification on the same
or the preceding line; suppressions that no longer match a finding are
reported as ``unused-suppression`` so they cannot rot into cover for a
future real finding.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Set, Tuple

SUPPRESS_RE = re.compile(r"#\s*osim:\s*lint-ok\[([a-z0-9-]+(?:\s*,\s*[a-z0-9-]+)*)\]")

#: jax higher-order functions whose function-valued arguments are traced.
_TRACED_HOFS = {
    "scan",
    "cond",
    "while_loop",
    "switch",
    "fori_loop",
    "vmap",
    "checkpoint",
    "remat",
    "custom_vjp",
    "custom_jvp",
}


@dataclasses.dataclass
class Finding:
    """One lint violation. ``jit_root`` names the jit entry point that makes
    the enclosing function traced (empty for rules that apply anywhere)."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    jit_root: str = ""
    suppressed: bool = False

    def to_dict(self) -> dict:
        d = {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }
        if self.jit_root:
            d["jit_root"] = self.jit_root
        if self.suppressed:
            d["suppressed"] = True
        return d

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        ctx = f" [via {self.jit_root}]" if self.jit_root else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}{ctx}{tag}"


@dataclasses.dataclass
class FunctionInfo:
    """A function def somewhere in a module (module-level or nested)."""

    module: "ModuleInfo"
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    qualname: str
    is_jit_root: bool = False
    static_argnames: Tuple[str, ...] = ()
    jit_alias: str = ""  # name bound by `alias = jax.jit(func, ...)`

    @property
    def name(self) -> str:
        return self.node.name  # type: ignore[attr-defined]


@dataclasses.dataclass
class ModuleInfo:
    """Parsed module plus the name-resolution tables rules need."""

    name: str  # dotted module name, e.g. open_simulator_tpu.ops.fast
    path: str  # path as reported in findings (relative to repo root)
    tree: ast.Module
    lines: List[str]
    # module-level defs by local name (includes jit-alias assignments)
    functions: Dict[str, FunctionInfo] = dataclasses.field(default_factory=dict)
    # local alias -> (dotted module, attr-or-None) for import/from-import
    imports: Dict[str, Tuple[str, Optional[str]]] = dataclasses.field(default_factory=dict)
    suppressions: Dict[int, Set[str]] = dataclasses.field(default_factory=dict)

    def alias_for(self, dotted: str) -> Set[str]:
        """Local names that refer to module ``dotted`` (e.g. {'jnp'} for
        jax.numpy)."""
        return {
            local
            for local, (mod, attr) in self.imports.items()
            if attr is None and mod == dotted
        }


@dataclasses.dataclass
class LintContext:
    """Everything a rule gets: the package model + reachability results."""

    modules: Dict[str, ModuleInfo]
    # (module name, function qualname) -> representative jit root qualname
    reachable: Dict[Tuple[str, str], str]
    package: str

    def jit_regions(self) -> Iterator[Tuple[ModuleInfo, ast.AST, str]]:
        """Yield (module, function node, jit root qualname) for every
        jit-reachable function body, nested defs excluded (they are part of
        their parent's subtree and would double-report)."""
        seen: Set[int] = set()
        for (mod_name, qual), root in sorted(self.reachable.items()):
            mod = self.modules[mod_name]
            info = _find_function(mod, qual)
            if info is None or id(info.node) in seen:
                continue
            # skip nested defs whose ancestor is also reachable
            if any(
                (mod_name, anc) in self.reachable
                for anc in _ancestor_quals(qual)
            ):
                continue
            seen.add(id(info.node))
            yield mod, info.node, root

    def resolve_call(
        self, mod: ModuleInfo, func: ast.expr
    ) -> Optional[Tuple[str, str]]:
        """Resolve a Call.func expression to (module name, function qualname)
        within the package, or None."""
        if isinstance(func, ast.Name):
            target = mod.functions.get(func.id)
            if target is not None:
                return mod.name, target.qualname
            imp = mod.imports.get(func.id)
            if imp is not None:
                tmod, attr = imp
                if attr is not None and tmod in self.modules:
                    t = self.modules[tmod].functions.get(attr)
                    if t is not None:
                        return tmod, t.qualname
        elif isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
            imp = mod.imports.get(func.value.id)
            if imp is not None and imp[1] is None and imp[0] in self.modules:
                t = self.modules[imp[0]].functions.get(func.attr)
                if t is not None:
                    return imp[0], t.qualname
        return None


@dataclasses.dataclass
class LintReport:
    findings: List[Finding]
    files_scanned: int
    rules: List[str]

    @property
    def active(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    def to_json(self) -> str:
        return json.dumps(
            {
                "version": 1,
                "files_scanned": self.files_scanned,
                "rules": self.rules,
                "findings": [f.to_dict() for f in self.findings if not f.suppressed],
                "suppressed": [f.to_dict() for f in self.findings if f.suppressed],
            },
            indent=2,
            sort_keys=True,
        )

    def render_text(self) -> str:
        out = [f.render() for f in self.active]
        n_sup = sum(1 for f in self.findings if f.suppressed)
        out.append(
            f"simon lint: {len(self.active)} finding(s), {n_sup} suppressed, "
            f"{self.files_scanned} file(s) scanned"
        )
        return "\n".join(out)


# --------------------------------------------------------------------------
# rule registry

RuleFunc = Callable[[LintContext], Iterable[Finding]]
_RULES: Dict[str, Tuple[str, RuleFunc]] = {}


def rule(rule_id: str, doc: str) -> Callable[[RuleFunc], RuleFunc]:
    """Register a rule. ``doc`` is the one-line catalogue entry."""

    def deco(fn: RuleFunc) -> RuleFunc:
        _RULES[rule_id] = (doc, fn)
        return fn

    return deco


def iter_rules() -> List[Tuple[str, str]]:
    """(rule-id, doc) pairs, sorted — the rule catalogue."""
    _load_rules()
    return sorted((rid, doc) for rid, (doc, _) in _RULES.items())


_rules_loaded = False


def _load_rules() -> None:
    global _rules_loaded
    if not _rules_loaded:
        from . import rules as _rules_pkg  # noqa: F401  (registers via decorator)

        _rules_loaded = True


# --------------------------------------------------------------------------
# package model construction


def _module_name(pkg_root: str, py_path: str) -> str:
    rel = os.path.relpath(py_path, os.path.dirname(pkg_root))
    parts = rel[:-3].split(os.sep)  # strip .py
    if parts[-1] == "__init__":
        parts.pop()
    return ".".join(parts)


def _parse_suppressions(lines: List[str]) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(lines, start=1):
        m = SUPPRESS_RE.search(line)
        if m:
            out[i] = {p.strip() for p in m.group(1).split(",")}
    return out


def _is_jax_jit(expr: ast.expr, mod: ModuleInfo) -> bool:
    """True for expressions referring to jax.jit (via `import jax` or
    `from jax import jit`)."""
    if isinstance(expr, ast.Attribute) and expr.attr == "jit":
        if isinstance(expr.value, ast.Name):
            imp = mod.imports.get(expr.value.id)
            return imp is not None and imp[0] == "jax" and imp[1] is None
    if isinstance(expr, ast.Name):
        imp = mod.imports.get(expr.id)
        return imp == ("jax", "jit")
    return False


def _static_argnames_from_call(call: ast.Call) -> Tuple[str, ...]:
    for kw in call.keywords:
        if kw.arg in ("static_argnames", "static_argnums"):
            v = kw.value
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(
                    e.value
                    for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                )
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return (v.value,)
    return ()


def _scan_imports(tree: ast.Module, mod_name: str) -> Dict[str, Tuple[str, Optional[str]]]:
    out: Dict[str, Tuple[str, Optional[str]]] = {}
    pkg_parts = mod_name.split(".")
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                local = a.asname or a.name.split(".")[0]
                # `import jax.numpy as jnp` binds jnp -> jax.numpy; plain
                # `import jax.numpy` binds jax (the root) only.
                out[local] = (a.name if a.asname else a.name.split(".")[0], None)
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                # relative import: resolve against this module's package
                base = pkg_parts[: len(pkg_parts) - node.level]
                target = ".".join(base + ([node.module] if node.module else []))
            else:
                target = node.module or ""
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = (target, a.name)
    return out


def _collect_functions(mod: ModuleInfo) -> None:
    """Fill mod.functions (module-level defs + jit-alias assignments) and
    mark jit roots anywhere in the module (nested defs included)."""

    def visit(node: ast.AST, prefix: str, module_level: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                info = FunctionInfo(module=mod, node=child, qualname=qual)
                for dec in child.decorator_list:
                    if _is_jax_jit(dec, mod):
                        info.is_jit_root = True
                    elif isinstance(dec, ast.Call):
                        # @jax.jit(...) or @functools.partial(jax.jit, ...)
                        if _is_jax_jit(dec.func, mod):
                            info.is_jit_root = True
                            info.static_argnames = _static_argnames_from_call(dec)
                        elif (
                            isinstance(dec.func, ast.Attribute)
                            and dec.func.attr == "partial"
                            or isinstance(dec.func, ast.Name)
                            and dec.func.id == "partial"
                        ) and dec.args and _is_jax_jit(dec.args[0], mod):
                            info.is_jit_root = True
                            info.static_argnames = _static_argnames_from_call(dec)
                if module_level:
                    mod.functions[child.name] = info
                else:
                    mod.functions.setdefault(qual, info)
                visit(child, f"{qual}.", False)
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.", False)
            elif module_level and isinstance(child, ast.Assign):
                # alias = jax.jit(func, static_argnames=...)
                v = child.value
                if (
                    isinstance(v, ast.Call)
                    and _is_jax_jit(v.func, mod)
                    and v.args
                    and isinstance(v.args[0], ast.Name)
                ):
                    target_name = v.args[0].id
                    target = mod.functions.get(target_name)
                    if target is not None:
                        target.is_jit_root = True
                        target.static_argnames = _static_argnames_from_call(v)
                        for t in child.targets:
                            if isinstance(t, ast.Name):
                                target.jit_alias = t.id
                                mod.functions.setdefault(t.id, target)
            elif not isinstance(child, (ast.Lambda, ast.expr)):
                # descend through if/for/while/try/with blocks so defs nested
                # under control flow (e.g. jit closures built behind a cache
                # check) are still discovered; module_level is preserved for
                # module-level `if` guards around jit-alias assignments
                visit(child, prefix, module_level)

    visit(mod.tree, "", True)


def _find_function(mod: ModuleInfo, qualname: str) -> Optional[FunctionInfo]:
    for info in mod.functions.values():
        if info.qualname == qualname:
            return info
    return None


def _ancestor_quals(qual: str) -> Iterator[str]:
    parts = qual.split(".")
    for i in range(1, len(parts)):
        yield ".".join(parts[:i])


def _called_functions(
    ctx: LintContext, mod: ModuleInfo, body: ast.AST
) -> Iterator[Tuple[str, str]]:
    """(module, qualname) pairs for package functions called from ``body``,
    including function-valued args to traced higher-order functions."""
    for node in ast.walk(body):
        if not isinstance(node, ast.Call):
            continue
        resolved = ctx.resolve_call(mod, node.func)
        if resolved is not None:
            yield resolved
        # jax.lax.scan(step, ...), jax.vmap(fn), lax.cond(p, t, f, ...)
        fn = node.func
        hof = isinstance(fn, ast.Attribute) and fn.attr in _TRACED_HOFS
        if hof:
            for arg in node.args:
                if isinstance(arg, (ast.Name, ast.Attribute)):
                    r = ctx.resolve_call(mod, arg)
                    if r is not None:
                        yield r


def _compute_reachability(ctx: LintContext) -> None:
    work: List[Tuple[str, str, str]] = []  # (module, qualname, root)
    for mod in ctx.modules.values():
        seen_ids: Set[int] = set()
        for info in mod.functions.values():
            if info.is_jit_root and id(info.node) not in seen_ids:
                seen_ids.add(id(info.node))
                root = f"{mod.name}:{info.qualname}"
                work.append((mod.name, info.qualname, root))
    while work:
        mod_name, qual, root = work.pop()
        key = (mod_name, qual)
        if key in ctx.reachable:
            continue
        ctx.reachable[key] = root
        mod = ctx.modules[mod_name]
        info = _find_function(mod, qual)
        if info is None:
            continue
        for tmod, tqual in _called_functions(ctx, mod, info.node):
            if (tmod, tqual) not in ctx.reachable:
                work.append((tmod, tqual, root))


# --------------------------------------------------------------------------
# driver


def build_context(
    package_root: Optional[str] = None, report_root: Optional[str] = None
) -> LintContext:
    """Parse the package and compute jit reachability.

    ``package_root`` is the directory of the top-level package (defaults to
    the installed ``open_simulator_tpu``); ``report_root`` is what finding
    paths are made relative to (defaults to the package's parent).
    """
    if package_root is None:
        package_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    package_root = os.path.abspath(package_root)
    if report_root is None:
        report_root = os.path.dirname(package_root)
    pkg_name = os.path.basename(package_root)

    modules: Dict[str, ModuleInfo] = {}
    for dirpath, dirnames, filenames in os.walk(package_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            full = os.path.join(dirpath, fname)
            with open(full, "r", encoding="utf-8") as fh:
                src = fh.read()
            tree = ast.parse(src, filename=full)
            name = _module_name(package_root, full)
            mod = ModuleInfo(
                name=name,
                path=os.path.relpath(full, report_root),
                tree=tree,
                lines=src.splitlines(),
            )
            mod.imports = _scan_imports(tree, name)
            mod.suppressions = _parse_suppressions(mod.lines)
            modules[name] = mod
    for mod in modules.values():
        _collect_functions(mod)
    ctx = LintContext(modules=modules, reachable={}, package=pkg_name)
    _compute_reachability(ctx)
    return ctx


def run_lint(
    package_root: Optional[str] = None,
    report_root: Optional[str] = None,
    only_rules: Optional[Iterable[str]] = None,
) -> LintReport:
    """Run every registered rule; suppression comments are honoured here so
    rules stay oblivious to them."""
    _load_rules()
    ctx = build_context(package_root, report_root)
    wanted = set(only_rules) if only_rules else None
    findings: List[Finding] = []
    for rid, (_doc, fn) in sorted(_RULES.items()):
        if wanted is not None and rid not in wanted:
            continue
        for f in fn(ctx):
            mod = _module_by_path(ctx, f.path)
            if mod is not None:
                sup = mod.suppressions.get(f.line, set())
                if f.rule in sup:
                    f.suppressed = True
            findings.append(f)
    if wanted is None:
        # Every rule ran, so a suppression comment that matched nothing is
        # stale — report it before it rots into cover for a future real
        # finding. (Skipped under --rules: a filtered run can't tell.)
        used = {
            (f.path, f.line, f.rule) for f in findings if f.suppressed
        }
        for mod in ctx.modules.values():
            for line, rules in sorted(mod.suppressions.items()):
                for rid in sorted(rules):
                    if (mod.path, line, rid) not in used:
                        findings.append(
                            Finding(
                                rule="unused-suppression",
                                path=mod.path,
                                line=line,
                                col=0,
                                message=(
                                    f"suppression lint-ok[{rid}] matches no "
                                    f"finding on this line"
                                    + (
                                        ""
                                        if rid in _RULES
                                        else f" (unknown rule id {rid!r})"
                                    )
                                ),
                            )
                        )
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return LintReport(
        findings=findings,
        files_scanned=len(ctx.modules),
        rules=[rid for rid in sorted(_RULES) if wanted is None or rid in wanted],
    )


def _module_by_path(ctx: LintContext, path: str) -> Optional[ModuleInfo]:
    for mod in ctx.modules.values():
        if mod.path == path:
            return mod
    return None
