"""Pre-flight program auditor: static HBM + collective audit of lowered HLO.

One layer below the jaxpr audit: every entry in the warmup registry
(`engine.warmup.warmup_registry()` — the audited jit list by
construction) is re-lowered **abstractly** at each node-ladder rung ×
mesh shape and compiled without ever executing, then three things are
extracted from the compiled artifact:

* **memory** — per-device argument/output/temp/alias bytes from
  ``compiled.memory_analysis()`` (peak derived as arg+out+temp−alias;
  jax 0.4.37 reports no peak field), cross-checked against the
  shape-arithmetic estimator in ``analysis.budget`` so the estimator —
  which also backs ``parallel.mesh.hbm_bytes_per_device`` for
  unmaterialized trees — is continuously proven against XLA's own
  accounting (outputs byte-tight; arguments as a sound upper bound,
  since XLA dedupes repeated jit parameters the caller would still
  materialize);
* **collective census** — all-gather / all-reduce / reduce-scatter /
  collective-permute / all-to-all counts and operand bytes parsed from
  the HLO text. An ``all-gather`` whose output carries a full-rung node
  dimension is **node-table replication** (the exact failure the 1M×100k
  headline must not have: GSPMD silently gathering the sharded node
  table back to every device) and fails the audit outright. Entries in
  ``LANE_PARALLEL`` must compile to *zero* collectives on scenario-only
  meshes — lanes are independent by design, any cross-device op there is
  a sharding bug. ``SCENARIO_ONLY`` entries (global-id node indexing)
  are audited at node_devices == 1 only; node-sharded combos are skipped
  visibly, never silently passed. ``FIXED_SHAPE`` entries (the
  small-scope prover engine, whose captured shapes are themselves the
  contract) are lowered once, unsharded, at the canonical point — the
  ladder does not apply to them.
* **budget diff** — measurements compared against the checked-in
  per-(entry, rung, mesh) book (``budgets/preflight.json``); regressions
  fail CI without running a single program. ``--write-budgets`` is the
  only update flow.

The **transfer audit** is the one pass that does execute: each entry is
warm-called once (compile-time constant transfers land, deliberately
outside the guard) and then re-called under
``jax.transfer_guard("disallow")`` — any steady-state per-call
host↔device transfer in the hot path raises and is reported. Donation
is handled by feeding fresh device copies per call; results are only
``block_until_ready``-ed, never indexed, inside the guard (indexing
transfers the index scalar host→device).

Abstract shapes: NodeStatic / Carry node-axis positions are derived from
``parallel.mesh`` sharding specs (single source of truth — a new field
with a node axis is picked up automatically); stacked sweep carries are
recognised by rank (base+1) and get the scenario axis at position 0;
PodRow rescales its leading pod axis; plain arrays rescale any dim equal
to the canonical node bucket (64). Python scalars/None stay concrete, so
static args survive unchanged.
"""

from __future__ import annotations

import dataclasses
import json
import re
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import budget as budget_mod
from .budget import BudgetBook, BudgetViolation, ProgramBudget, program_key

#: Canonical node bucket every capture runs at (ops.encode.NODE_BUCKET_FLOOR).
N_CANON = 64

#: Entries whose scenario lanes are independent by construction: on a
#: scenario-only mesh (node axis = 1 device) their programs must contain
#: ZERO collectives — any cross-device op is an accidental dependency.
LANE_PARALLEL = frozenset({
    "ops.fast:schedule_scenarios",
    "ops.fast:schedule_wave",
    "ops.fast:commit_choices",
})

#: Entries that index nodes by *global id* (dynamic_slice over the node
#: axis inside their scan loop): node-sharding them forces GSPMD to
#: all-gather the node tables every iteration, so they are deployed on
#: scenario lanes / single devices only (the node-sharded path is
#: schedule_batch). The preflight audits them at node_devices == 1 and
#: skips node-sharded meshes *visibly* (``programs_skipped`` in the
#: report) — a capability boundary, not a suppression.
SCENARIO_ONLY = frozenset({"ops.fast:light_scan"})

#: Entries whose captured shapes ARE the contract. The small-scope prover
#: (`simon prove`, analysis/semantics.py) packs fixed bounded-scope
#: universes onto the scenario axis, so EVERY leaf of
#: ``schedule_universes`` — NodeStatic fields included — carries a leading
#: stacked axis the per-field node-axis tables know nothing about;
#: rescaling "the node dim" there rewrites the scenario axis on some
#: leaves and misses others, producing a vmap axis-size mismatch. These
#: entries are therefore lowered exactly once, at the captured shapes on
#: a single device with no resharding, and every other (rung, mesh) combo
#: is skipped *visibly* (``programs_skipped``) — a shape contract, not a
#: suppression.
FIXED_SHAPE = frozenset({
    "ops.fast:schedule_universes",
    "ops.fast:schedule_universes_wave",
})

DEFAULT_RUNGS: Tuple[int, ...] = (64, 128)
DEFAULT_MESHES: Tuple[str, ...] = ("1", "2x1", "2x2")
DEFAULT_HBM_GIB = 32.0  # one v4/v5p-class chip's HBM

#: Cross-check tolerance: the estimator must agree with memory_analysis()
#: within this envelope (XLA adds tuple/alignment padding the shape
#: arithmetic cannot see; a real replication bug is megabytes, not this).
ESTIMATE_REL_TOL = 0.02
ESTIMATE_ABS_SLACK = 64 * 1024

_COLLECTIVE_RE = re.compile(
    r"%\S+\s*=\s*(\([^)]*\)|\S+?)\s+"
    r"(all-gather|all-reduce|reduce-scatter|collective-permute|all-to-all)"
    r"(?:-start)?\("
)
_TYPED_ARRAY_RE = re.compile(r"([a-z][a-z0-9]*)\[([\d,]*)\]")
_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}


# ---------------------------------------------------------------------------
# collective census
# ---------------------------------------------------------------------------

def _shape_str_bytes(shape_str: str) -> int:
    """Bytes of one HLO result shape string, e.g. ``f32[8,64]{1,0}`` or a
    tuple ``(f32[4,2]{1,0}, s32[4,2]{1,0})``."""
    total = 0
    for dtype, dims in _TYPED_ARRAY_RE.findall(shape_str):
        itemsize = _HLO_DTYPE_BYTES.get(dtype, 4)
        n = itemsize
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n
    return total


def _shape_str_dims(shape_str: str) -> List[int]:
    dims: List[int] = []
    for _dtype, ds in _TYPED_ARRAY_RE.findall(shape_str):
        if ds:
            dims.extend(int(d) for d in ds.split(","))
    return dims


def collective_census(hlo_text: str) -> Tuple[Dict[str, int], int, List[Tuple[str, str]]]:
    """(kind -> count, total result bytes, [(kind, shape_str), ...]) for
    every collective op in the HLO text. ``-start`` async halves count as
    the op; ``-done`` halves carry no shape work and never match."""
    kinds: Dict[str, int] = {}
    total = 0
    ops: List[Tuple[str, str]] = []
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if m is None:
            continue
        shape_str, kind = m.group(1), m.group(2)
        kinds[kind] = kinds.get(kind, 0) + 1
        total += _shape_str_bytes(shape_str)
        ops.append((kind, shape_str))
    return kinds, total, ops


def node_table_gathers(
    ops: Sequence[Tuple[str, str]], rung: int
) -> List[str]:
    """The replication detector: ``all-gather`` results carrying a
    full-rung node dimension mean GSPMD gathered a node-axis-sharded
    table back whole. Legitimate gathers (lane scalars like ``f32[4,2]``,
    flattened sort keys like ``f32[16384]``) never show the rung as a
    distinct dimension, which the probes on every audited entry confirm."""
    flagged = []
    for kind, shape_str in ops:
        if kind != "all-gather":
            continue
        if rung in _shape_str_dims(shape_str):
            flagged.append(shape_str)
    return flagged


# ---------------------------------------------------------------------------
# mesh / abstract-shape machinery
# ---------------------------------------------------------------------------

def parse_mesh(tag: str) -> Tuple[int, int]:
    """``"1"`` -> (1, 1); ``"2x1"`` -> (scenario_devices, node_devices)."""
    t = tag.strip().lower()
    if t in ("1", "1x1"):
        return (1, 1)
    m = re.fullmatch(r"(\d+)x(\d+)", t)
    if m is None:
        raise ValueError(f"mesh tag {tag!r} is not SxN (e.g. 2x1, 2x2)")
    return (int(m.group(1)), int(m.group(2)))


def _build_mesh(tag: str):
    """The jax Mesh for a tag, or None for 1×1 (unsharded compile)."""
    from ..parallel import mesh as pmesh

    s, n = parse_mesh(tag)
    if s * n <= 1:
        return None
    return pmesh.product_mesh_2d(s, n)


def _axis_tables() -> Tuple[Dict[str, Optional[int]], Dict[str, Optional[int]]]:
    """(NodeStatic field -> node-axis dim index, Carry field -> same),
    derived from parallel.mesh's sharding specs on a throwaway 1×2 mesh
    so the preflight can never drift from the real sharding layout."""
    from ..parallel import mesh as pmesh

    probe = pmesh.product_mesh_2d(1, 2)

    def table(spec_tree) -> Dict[str, Optional[int]]:
        out: Dict[str, Optional[int]] = {}
        for field, sh in spec_tree._asdict().items():
            out[field] = next(
                (i for i, p in enumerate(sh.spec) if p == pmesh.NODE_AXIS),
                None,
            )
        return out

    return table(pmesh.node_sharding(probe)), table(pmesh.carry_sharding(probe))


def abstract_args(
    cap: Any,
    rung: int,
    mesh: Any,
    tables: Optional[Tuple[Dict[str, Optional[int]], Dict[str, Optional[int]]]] = None,
    pod_bucket: Optional[int] = None,
    resize: bool = True,
) -> Tuple[tuple, dict]:
    """Captured concrete args -> ShapeDtypeStruct avals at ``rung``.

    Array leaves become avals (node dims rescaled, NamedSharding attached
    when ``mesh`` is a 2-D product mesh); non-array leaves (None, Python
    scalars — i.e. static args) pass through concrete. ``pod_bucket``
    additionally rescales PodRow's leading axis (the 1M-pod verdict).
    ``resize=False`` (FIXED_SHAPE entries) keeps every leaf at its
    captured shape, unsharded — the ladder does not apply to them."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..ops.kernels import Carry, NodeStatic, PodRow
    from ..parallel import mesh as pmesh

    if not resize:
        def fixed(leaf):
            if not hasattr(leaf, "dtype") or not hasattr(leaf, "shape"):
                return leaf
            return jax.ShapeDtypeStruct(tuple(leaf.shape), leaf.dtype)

        args = tuple(jax.tree.map(fixed, a) for a in cap.args)
        kwargs = {k: jax.tree.map(fixed, v) for k, v in cap.kwargs.items()}
        return args, kwargs

    if tables is None:
        tables = _axis_tables()
    ns_axis, carry_axis = tables

    def spec_for(ndim: int, node_pos: Optional[int],
                 scen_pos: Optional[int] = None):
        if mesh is None:
            return None
        parts: List[Optional[str]] = [None] * ndim
        if node_pos is not None:
            parts[node_pos] = pmesh.NODE_AXIS
        if scen_pos is not None:
            parts[scen_pos] = pmesh.SCENARIO_AXIS
        return NamedSharding(mesh, P(*parts))

    def aval(leaf, shape, node_pos, scen_pos=None):
        return jax.ShapeDtypeStruct(
            tuple(shape), leaf.dtype,
            sharding=spec_for(len(shape), node_pos, scen_pos),
        )

    def conv(arg):
        if isinstance(arg, NodeStatic):
            d = {}
            for f, leaf in arg._asdict().items():
                pos = ns_axis[f]
                shp = list(leaf.shape)
                if pos is not None:
                    shp[pos] = rung
                d[f] = aval(leaf, shp, pos)
            return NodeStatic(**d)
        if isinstance(arg, Carry):
            d = {}
            for f, leaf in arg._asdict().items():
                base = carry_axis[f]
                # stacked sweep carries carry a leading scenario axis on
                # top of the 2-D base layout (ops.state.stack_carry)
                off = 1 if leaf.ndim == 3 else 0
                pos = base + off if base is not None else None
                shp = list(leaf.shape)
                if pos is not None:
                    shp[pos] = rung
                d[f] = aval(leaf, shp, pos, 0 if off else None)
            return Carry(**d)
        if isinstance(arg, PodRow):
            def pod_leaf(leaf):
                shp = list(leaf.shape)
                if pod_bucket is not None and shp:
                    shp[0] = pod_bucket
                return aval(leaf, shp, None)
            return jax.tree.map(pod_leaf, arg)

        def one(leaf):
            if not hasattr(leaf, "dtype") or not hasattr(leaf, "shape"):
                return leaf
            pos = next(
                (i for i, d in enumerate(leaf.shape) if d == N_CANON), None
            )
            shp = list(leaf.shape)
            if pos is not None:
                shp[pos] = rung
            return aval(leaf, shp, pos)

        return jax.tree.map(one, arg)

    args = tuple(conv(a) for a in cap.args)
    kwargs = {k: conv(v) for k, v in cap.kwargs.items()}
    return args, kwargs


# ---------------------------------------------------------------------------
# per-program audit
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ProgramAudit:
    """One (entry, rung, mesh) lowered-and-compiled program's evidence."""

    entry: str
    rung: int
    mesh: str
    argument_bytes: int = 0
    output_bytes: int = 0
    temp_bytes: int = 0
    alias_bytes: int = 0
    peak_bytes: int = 0
    est_argument_bytes: int = 0
    est_output_bytes: int = 0
    estimate_ok: bool = True
    collectives: Dict[str, int] = dataclasses.field(default_factory=dict)
    collective_bytes: int = 0
    node_gathers: List[str] = dataclasses.field(default_factory=list)
    lane_parallel_violation: bool = False
    seconds: float = 0.0
    error: str = ""

    @property
    def key(self) -> str:
        return program_key(self.entry, self.rung, self.mesh)

    @property
    def ok(self) -> bool:
        return (
            not self.error
            and self.estimate_ok
            and not self.node_gathers
            and not self.lane_parallel_violation
        )

    def to_budget(self) -> ProgramBudget:
        return ProgramBudget(
            peak_bytes=self.peak_bytes,
            argument_bytes=self.argument_bytes,
            output_bytes=self.output_bytes,
            temp_bytes=self.temp_bytes,
            alias_bytes=self.alias_bytes,
            collectives=dict(self.collectives),
            collective_bytes=self.collective_bytes,
        )

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["key"] = self.key
        d["ok"] = self.ok
        d["seconds"] = round(self.seconds, 3)
        return d


def _estimate_close(est: int, real: int) -> bool:
    return abs(est - real) <= max(
        int(ESTIMATE_REL_TOL * max(est, real)), ESTIMATE_ABS_SLACK
    )


def _estimate_covers(est: int, real: int) -> bool:
    """Sound-upper-bound check: the shape-arithmetic estimate must cover
    the measured residency (small envelope for XLA tuple/alignment
    padding the arithmetic cannot see). The estimate is allowed to sit
    ABOVE the measurement: XLA dedupes repeated jit parameters into one
    executable parameter (sort_select's broadcast weight tables collapse
    76 -> 11 params), while the estimator prices the argument tree a
    caller would actually materialize — exactly what
    ``hbm_bytes_per_device`` answers for an unplaced tree."""
    return real <= est + max(
        int(ESTIMATE_REL_TOL * max(est, real)), ESTIMATE_ABS_SLACK
    )


def audit_program(
    cap: Any,
    rung: int,
    mesh_tag: str,
    tables: Optional[tuple] = None,
    pod_bucket: Optional[int] = None,
    resize: bool = True,
) -> ProgramAudit:
    """Lower-and-compile one entry at (rung, mesh) abstractly and extract
    memory stats + collective census. Never executes the program."""
    import jax

    pa = ProgramAudit(entry=cap.name, rung=int(rung), mesh=mesh_tag)
    t0 = time.perf_counter()
    try:
        mesh = _build_mesh(mesh_tag) if resize else None
        args, kwargs = abstract_args(
            cap, rung, mesh, tables=tables, pod_bucket=pod_bucket,
            resize=resize,
        )
        traced = cap.fn.trace(*args, **kwargs)
        compiled = traced.lower().compile()
        ma = compiled.memory_analysis()
        pa.argument_bytes = int(ma.argument_size_in_bytes)
        pa.output_bytes = int(ma.output_size_in_bytes)
        pa.temp_bytes = int(ma.temp_size_in_bytes)
        pa.alias_bytes = int(ma.alias_size_in_bytes)
        # jax 0.4.37's CompiledMemoryStats has no peak field on CPU; the
        # simultaneously-live upper bound is args + outputs + temps minus
        # donated aliases (donated inputs are reused as outputs).
        pa.peak_bytes = max(
            0,
            pa.argument_bytes + pa.output_bytes + pa.temp_bytes
            - pa.alias_bytes,
        )

        # estimator cross-check: the budget arithmetic must reproduce
        # XLA's per-device accounting from shapes alone (memory_analysis
        # numbers equal the compiled module's post-SPMD entry interface,
        # byte for byte). Arguments are priced from the *intended* abstract
        # tree — the same tree hbm_bytes_per_device would price before
        # materialization — and checked as a sound upper bound, because
        # XLA dedupes repeated jit parameters into one executable
        # parameter (compiled.input_shardings follows the deduped
        # executable params, NOT in_avals — the two do not zip). Outputs
        # cannot dedupe, so they get the tight two-sided check:
        # traced.out_info and compiled.output_shardings mirror the same
        # output pytree and pair leaf-for-leaf.
        dev0 = str(jax.devices()[0])
        pa.est_argument_bytes = budget_mod.estimate_max_bytes_per_device(
            (args, kwargs), default_device=dev0
        )
        est_out = 0
        for leaf, sh in zip(
            jax.tree.leaves(traced.out_info),
            jax.tree.leaves(compiled.output_shardings),
        ):
            per = budget_mod.leaf_bytes_by_device(
                jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=sh),
                default_device=dev0,
            )
            est_out += max(per.values(), default=0)
        pa.est_output_bytes = est_out
        pa.estimate_ok = _estimate_covers(
            pa.est_argument_bytes, pa.argument_bytes
        ) and _estimate_close(pa.est_output_bytes, pa.output_bytes)

        kinds, coll_bytes, ops = collective_census(compiled.as_text())
        pa.collectives = kinds
        pa.collective_bytes = coll_bytes
        # at the canonical rung every fixed 64-wide dim (J_CAP-sized caps,
        # lane tables) is indistinguishable from the node dim, so the
        # replication detector only has signal at rescaled rungs
        pa.node_gathers = (
            node_table_gathers(ops, rung) if rung != N_CANON else []
        )
        s_dev, n_dev = parse_mesh(mesh_tag)
        if cap.name in LANE_PARALLEL and n_dev == 1 and s_dev > 1 and kinds:
            pa.lane_parallel_violation = True
    except Exception as e:  # pragma: no cover - exercised via error report
        pa.error = f"{type(e).__name__}: {e}"
    pa.seconds = time.perf_counter() - t0
    return pa


# ---------------------------------------------------------------------------
# transfer audit
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class TransferCheck:
    entry: str
    ok: bool
    error: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _fresh_device_args(tree: Any) -> Any:
    """Fresh device copies of every array leaf (donation-safe: a donated
    call must never consume the capture's snapshot, and a second call
    needs buffers the first call didn't eat)."""
    import jax
    import jax.numpy as jnp

    def one(leaf):
        if hasattr(leaf, "dtype") and hasattr(leaf, "shape"):
            return jnp.array(leaf)
        return leaf

    return jax.tree.map(one, tree)


def guarded_steady_state_check(fn: Any, args: tuple, kwargs: dict) -> TransferCheck:
    """Warm-call once (compile-time constants transfer here, outside the
    guard — a one-time cost is fine), then call again under
    ``jax.transfer_guard("disallow")``: any transfer the second call makes
    is a *per-call* host↔device sync in the hot path. Results are only
    block_until_ready'd inside the guard — indexing them would transfer
    the index scalar and false-positive the check."""
    import jax

    name = getattr(fn, "__name__", str(fn))
    try:
        warm = _fresh_device_args(args)
        jax.block_until_ready(fn(*warm, **kwargs))
        again = _fresh_device_args(args)
        with jax.transfer_guard("disallow"):
            jax.block_until_ready(fn(*again, **kwargs))
        return TransferCheck(entry=name, ok=True)
    except Exception as e:
        return TransferCheck(entry=name, ok=False, error=f"{type(e).__name__}: {e}")


def transfer_audit(caps: Sequence[Any]) -> List[TransferCheck]:
    """Steady-state transfer check of every captured entry at its
    canonical shapes. The only preflight pass that executes programs —
    `--no-transfers` skips it; the memory/collective matrix never runs."""
    out = []
    for cap in caps:
        chk = guarded_steady_state_check(cap.fn, cap.args, cap.kwargs)
        chk.entry = cap.name
        out.append(chk)
    return out


# ---------------------------------------------------------------------------
# plan_1m_100k verdict
# ---------------------------------------------------------------------------

def plan_verdict(
    caps: Sequence[Any],
    hbm_gib: float = DEFAULT_HBM_GIB,
    tables: Optional[tuple] = None,
) -> dict:
    """The machine-checked headline: does `plan_1m_100k`'s scenario
    program (1M pods -> pod bucket, 100k nodes -> rung 102400) fit
    per-device HBM on a 1×4 node-sharded mesh with the node table proven
    sharded (zero full-rung gathers)? Purely from the lowered program."""
    from ..ops.encode import node_bucket, round_up

    rung = node_bucket(100_000)
    pods = round_up(1_000_000)
    cap = next((c for c in caps if c.name in LANE_PARALLEL), None)
    verdict: Dict[str, Any] = {
        "config": "plan_1m_100k",
        "entry": cap.name if cap else "",
        "rung": rung,
        "pod_bucket": pods,
        "mesh": "1x4",
        "hbm_gib": float(hbm_gib),
    }
    if cap is None:
        verdict["error"] = "schedule_scenarios not in capture registry"
        verdict["ok"] = False
        return verdict
    import jax

    if len(jax.devices()) < 4:
        verdict["error"] = (
            f"needs 4 devices for the 1x4 mesh, have {len(jax.devices())} "
            f"(run under --xla_force_host_platform_device_count)"
        )
        verdict["ok"] = False
        return verdict
    pa = audit_program(cap, rung, "1x4", tables=tables, pod_bucket=pods)
    gib = 1024 ** 3
    verdict.update(
        peak_bytes=pa.peak_bytes,
        peak_gib=round(pa.peak_bytes / gib, 3),
        argument_bytes=pa.argument_bytes,
        output_bytes=pa.output_bytes,
        temp_bytes=pa.temp_bytes,
        alias_bytes=pa.alias_bytes,
        collectives=pa.collectives,
        node_gathers=pa.node_gathers,
        node_table_sharded=not pa.node_gathers,
        fits=pa.peak_bytes <= int(hbm_gib * gib),
        compile_seconds=round(pa.seconds, 2),
        error=pa.error,
    )
    verdict["ok"] = bool(
        not pa.error and verdict["fits"] and verdict["node_table_sharded"]
    )
    return verdict


# ---------------------------------------------------------------------------
# the preflight driver
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class PreflightReport:
    programs: List[ProgramAudit]
    transfers: List[TransferCheck]
    verdict: Optional[dict]
    violations: List[BudgetViolation]
    meshes_skipped: List[str]
    budgets_path: str = ""
    seconds: float = 0.0
    #: (entry, rung, mesh) combos not compiled because the entry is
    #: SCENARIO_ONLY and the mesh shards the node axis, or the entry is
    #: FIXED_SHAPE and the combo is off the canonical point
    programs_skipped: List[str] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return (
            all(p.ok for p in self.programs)
            and all(t.ok for t in self.transfers)
            and (self.verdict is None or self.verdict.get("ok", False))
            and not self.violations
        )

    def measured(self) -> Dict[str, ProgramBudget]:
        return {p.key: p.to_budget() for p in self.programs if not p.error}

    def to_book(self, base: Optional[BudgetBook] = None) -> BudgetBook:
        """A fresh budget book from this run's measurements (the
        --write-budgets flow). Keeps ``base``'s tolerance knobs and any
        budgets for programs this run didn't measure (partial matrices
        must not silently drop the rest of the book)."""
        book = BudgetBook()
        if base is not None:
            book.tolerance = base.tolerance
            book.slack_bytes = base.slack_bytes
            book.programs = dict(base.programs)
            book.verdicts = dict(base.verdicts)
        book.programs.update(self.measured())
        if self.verdict is not None:
            book.verdicts[str(self.verdict.get("config", "plan"))] = dict(
                self.verdict
            )
        return book

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "programs": [p.to_dict() for p in self.programs],
            "transfers": [t.to_dict() for t in self.transfers],
            "verdict": self.verdict,
            "violations": [v.to_dict() for v in self.violations],
            "meshes_skipped": list(self.meshes_skipped),
            "programs_skipped": list(self.programs_skipped),
            "budgets_path": self.budgets_path,
            "seconds": round(self.seconds, 2),
        }

    def render_text(self) -> str:
        lines = [
            f"preflight: {'ok' if self.ok else 'FAILED'} — "
            f"{len(self.programs)} program(s) audited in {self.seconds:.1f}s"
        ]
        mib = 1024 ** 2
        for p in sorted(self.programs, key=lambda p: p.key):
            colls = (
                ",".join(f"{k}:{v}" for k, v in sorted(p.collectives.items()))
                or "-"
            )
            status = "ok" if p.ok else "FAIL"
            lines.append(
                f"  {status:4s} {p.key:52s} peak {p.peak_bytes / mib:9.2f} MiB"
                f"  colls {colls}"
            )
            if p.error:
                lines.append(f"       error: {p.error}")
            if not p.estimate_ok:
                lines.append(
                    f"       estimator mismatch: est arg "
                    f"{p.est_argument_bytes} vs {p.argument_bytes}, est out "
                    f"{p.est_output_bytes} vs {p.output_bytes}"
                )
            if p.node_gathers:
                lines.append(
                    f"       NODE TABLE REPLICATED: all-gather {p.node_gathers}"
                )
            if p.lane_parallel_violation:
                lines.append(
                    "       lane-parallel entry emits collectives on a "
                    "scenario-only mesh"
                )
        for t in self.transfers:
            if not t.ok:
                lines.append(f"  transfer {t.entry}: {t.error}")
        if self.transfers and all(t.ok for t in self.transfers):
            lines.append(
                f"  transfers: {len(self.transfers)} entries steady-state "
                f"clean under transfer_guard"
            )
        if self.programs_skipped:
            lines.append(
                f"  skipped {len(self.programs_skipped)} combo(s) outside "
                f"entry capability (scenario-only on node-sharded meshes; "
                f"fixed-shape off the canonical point): "
                f"{', '.join(self.programs_skipped)}"
            )
        for v in self.violations:
            lines.append(f"  budget: {v.render()}")
        if self.verdict is not None:
            v = self.verdict
            if v.get("error"):
                lines.append(f"  verdict {v['config']}: ERROR {v['error']}")
            else:
                lines.append(
                    f"  verdict {v['config']}: "
                    f"{'fits' if v['fits'] else 'DOES NOT FIT'} — peak "
                    f"{v['peak_gib']} GiB/device vs {v['hbm_gib']} GiB HBM "
                    f"at mesh {v['mesh']} (rung {v['rung']}, "
                    f"{v['pod_bucket']} pods; node table "
                    f"{'sharded' if v['node_table_sharded'] else 'REPLICATED'})"
                )
        return "\n".join(lines)


def _filter_meshes(tags: Sequence[str]) -> Tuple[List[str], List[str]]:
    import jax

    have = len(jax.devices())
    use, skipped = [], []
    for t in tags:
        s, n = parse_mesh(t)
        (use if s * n <= have else skipped).append(t)
    return use, skipped


def run_preflight(
    rungs: Optional[Sequence[int]] = None,
    meshes: Optional[Sequence[str]] = None,
    entries: Optional[Sequence[str]] = None,
    caps: Optional[Sequence[Any]] = None,
    book: Optional[BudgetBook] = None,
    transfers: bool = True,
    verdict: bool = True,
    hbm_gib: float = DEFAULT_HBM_GIB,
) -> PreflightReport:
    """The full preflight: capture registry -> (entry × rung × mesh)
    abstract compile matrix -> budget diff -> transfer audit -> plan
    verdict. ``caps`` short-circuits the capture pass (tests, audit
    --memory); ``entries`` filters by audit name."""
    from ..engine.warmup import registry_captures

    t0 = time.perf_counter()
    if caps is None:
        caps = registry_captures(entries)
    elif entries is not None:
        wanted = set(entries)
        caps = [c for c in caps if c.name in wanted]
    rungs = tuple(rungs) if rungs else DEFAULT_RUNGS
    mesh_tags, skipped = _filter_meshes(tuple(meshes) if meshes else DEFAULT_MESHES)

    tables = _axis_tables()
    programs: List[ProgramAudit] = []
    programs_skipped: List[str] = []
    for cap in caps:
        if cap.name in FIXED_SHAPE:
            # the captured shapes are the contract: one compile, at the
            # canonical point, unsharded; the rest of the matrix is
            # skipped visibly (see FIXED_SHAPE)
            programs.append(
                audit_program(cap, N_CANON, "1", tables=tables,
                              resize=False)
            )
            programs_skipped.extend(
                program_key(cap.name, rung, tag)
                for rung in rungs for tag in mesh_tags
                if (rung, tag) != (N_CANON, "1")
            )
            continue
        for rung in rungs:
            for tag in mesh_tags:
                _s, n_dev = parse_mesh(tag)
                if cap.name in SCENARIO_ONLY and n_dev > 1:
                    programs_skipped.append(
                        program_key(cap.name, rung, tag)
                    )
                    continue
                programs.append(
                    audit_program(cap, rung, tag, tables=tables)
                )

    violations: List[BudgetViolation] = []
    if book is not None:
        measured = {p.key: p.to_budget() for p in programs if not p.error}
        violations = book.diff(measured)

    checks = transfer_audit(caps) if transfers else []
    vd = plan_verdict(caps, hbm_gib=hbm_gib, tables=tables) if verdict else None

    return PreflightReport(
        programs=programs,
        transfers=checks,
        verdict=vd,
        violations=violations,
        meshes_skipped=skipped,
        programs_skipped=programs_skipped,
        seconds=time.perf_counter() - t0,
    )


def report_json(report: PreflightReport) -> str:
    return json.dumps(report.to_dict(), indent=2, sort_keys=True)
