"""`simon audit` driver: the semantic verification passes.

Where `simon lint` checks *syntactic* contracts (purity, shape bucketing,
dtype discipline) and the jaxpr auditor checks *structural* ones (what
actually got traced), `simon audit` proves two semantic properties:

* **races** (`analysis.races`) — every mutation of module-level shared
  state reachable from server handler threads, thread targets, or signal
  handlers is dominated by a ``with <lock>:`` block or an explicit
  ``@guarded_by`` annotation;
* **invariants** (`analysis.invariants`) — an abstract interpretation of
  the captured jaxprs of all registered jit entry points, proving mask
  outputs stay in {0, 1}, score plugins stay in [0, 100], and no NaN
  (e.g. the ``-inf * 0.0`` sentinel pattern) can reach a selection
  primitive;
* **memory** (`analysis.hlo_audit`, opt-in via ``--memory``) — a compact
  slice of the preflight matrix: every entry lowered at the canonical
  rung on the meshes the host has devices for, collective census +
  estimator cross-check included. The full rung × mesh × budget-diff
  matrix (plus transfer guard and the plan_1m_100k verdict) lives under
  ``simon preflight``.

Both passes emit deterministic findings (stable sort keys, no wall-clock
or randomness), so the JSON report is byte-identical across runs and
diffable in CI. The runtime companion is ``OSIM_SANITIZE=1``
(`ops.sanitize`), which checks the same entries dynamically via
``checkify``.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional

from .races import RaceAuditReport, run_races


@dataclasses.dataclass
class SemanticAuditReport:
    races: Optional[RaceAuditReport]
    invariants: Optional[object]  # invariants.InvariantAudit (jax-importing)
    memory: Optional[object] = None  # hlo_audit.PreflightReport

    @property
    def ok(self) -> bool:
        return (
            (self.races is None or self.races.ok)
            and (self.invariants is None or self.invariants.ok)
            and (self.memory is None or self.memory.ok)
        )

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "races": self.races.to_dict() if self.races is not None else None,
            "invariants": (
                self.invariants.to_dict()
                if self.invariants is not None
                else None
            ),
            "memory": (
                self.memory.to_dict() if self.memory is not None else None
            ),
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    def render_text(self) -> str:
        parts = []
        if self.races is not None:
            parts.append(self.races.render_text())
        if self.invariants is not None:
            parts.append(self.invariants.render_text())
        if self.memory is not None:
            parts.append(self.memory.render_text())
        parts.append(f"audit: {'ok' if self.ok else 'FAILED'}")
        return "\n".join(parts)


def run_semantic_audit(
    races: bool = True,
    invariants: bool = True,
    memory: bool = False,
    package_root: Optional[str] = None,
    report_root: Optional[str] = None,
) -> SemanticAuditReport:
    """Run the requested passes. The race pass is pure-AST; the invariant
    and memory passes import jax and trace/lower the registered entries —
    callers that need a deterministic platform should run
    ``ensure_platform()`` first (the CLI does)."""
    race_report = (
        run_races(package_root=package_root, report_root=report_root)
        if races
        else None
    )
    inv_report = None
    if invariants:
        from .invariants import run_invariants

        inv_report = run_invariants()
    mem_report = None
    if memory:
        from .hlo_audit import N_CANON, run_preflight

        # compact slice: canonical rung, whatever meshes fit the host's
        # devices; no transfer execution, no verdict, no budget diff —
        # those are `simon preflight` business
        mem_report = run_preflight(
            rungs=(N_CANON,), transfers=False, verdict=False,
        )
    return SemanticAuditReport(
        races=race_report, invariants=inv_report, memory=mem_report
    )
