"""Lock-discipline race detector over the lint engine's package model.

The threaded surface of the simulator is small but real: the metrics
server handles every request on its own thread (ThreadingHTTPServer), the
chaos/apply paths run in the main thread, and SIGTERM lands in a signal
handler. This pass reconstructs, purely from the AST model lint.py already
builds:

  1. **shared mutable state** — module-level dict/list/set bindings plus
     module-level scalars that some function rebinds through ``global``;
  2. **locks** — module-level ``threading.Lock()``/``RLock()``/
     ``Semaphore()``-style bindings;
  3. **thread roots** — methods of ``BaseHTTPRequestHandler`` subclasses,
     ``threading.Thread(target=...)`` targets (including
     ``target=self._method`` inside classes and nested-function targets),
     ``executor.submit(fn, ...)`` work items (the extender wave engine's
     HTTP fan-out) and ``signal.signal`` handlers, then everything
     reachable from them through the call graph — ``self.method`` inside a
     class, plus ``self.<attr>.<method>()`` hops across classes when the
     method name is unique package-wide (the admission worker thread's
     ``self._loop.run_forever()`` pulls ``SchedulerLoop`` into the audit).

Any read-modify-write of a shared scalar (AugAssign, ``x = f(x)``, or a
read + rebind pair in one function) and any container mutation
(``.append``/``.pop``/``x[k] = v``/``del x[k]`` …) performed in an
audited function without a dominating ``with <lock>:`` block is reported.
A *plain single rebind* of a scalar with no read in the same function is
an atomic publish under the GIL and is deliberately not flagged.

Escapes:

  * ``@guarded_by("lockname")`` (utils/concurrency.py) asserts every
    caller already holds the named module-level lock; the body is then
    treated as dominated by it. The annotation is trusted — it exists for
    guards the detector cannot see (e.g. a ``Semaphore.acquire`` in the
    caller).
  * an ``osim: audit-ok[race]`` comment on the flagged line suppresses
    it; unused suppressions are themselves reported so they cannot rot.

Functions living in a module that *defines* a thread root are audited
even when not reachable from one: once handler threads exist in the
process, main-thread writes to the same state race against them.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .lint import FunctionInfo, LintContext, ModuleInfo, build_context

AUDIT_SUPPRESS_RE = re.compile(
    r"#\s*osim:\s*audit-ok\[([a-z0-9-]+(?:\s*,\s*[a-z0-9-]+)*)\]"
)

RULE_RACE = "race"
RULE_DEADLOCK = "deadlock"

#: every rule this auditor can emit; an `audit-ok[...]` naming anything
#: else is reported stale immediately (it can never match a finding)
KNOWN_RULES = frozenset({RULE_RACE, RULE_DEADLOCK})

_LOCK_FACTORIES = {
    "Lock", "RLock", "Semaphore", "BoundedSemaphore", "Condition", "Event",
}
_CONTAINER_FACTORIES = {
    "dict", "list", "set", "deque", "defaultdict", "OrderedDict", "Counter",
}
_MUTATING_METHODS = {
    "append", "add", "update", "pop", "popitem", "clear", "remove",
    "discard", "extend", "insert", "setdefault", "appendleft", "popleft",
    "sort", "reverse",
}
_HANDLER_BASES = ("BaseHTTPRequestHandler", "SimpleHTTPRequestHandler")


# ---------------------------------------------------------------------------
# findings / report
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class RaceFinding:
    path: str
    line: int
    col: int
    state: str        # dotted shared object, e.g. server.server._snapshot
    function: str     # module:qualname performing the access
    access: str       # rmw | mutate | check-then-act
    thread_root: str  # why this function is audited
    message: str
    suppressed: bool = False
    rule: str = RULE_RACE

    def sort_key(self):
        return (self.path, self.line, self.col, self.state)

    def to_dict(self) -> dict:
        d = {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "state": self.state,
            "function": self.function,
            "access": self.access,
            "thread_root": self.thread_root,
            "message": self.message,
        }
        if self.suppressed:
            d["suppressed"] = True
        return d

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule}: "
            f"{self.message} [via {self.thread_root}]{tag}"
        )


@dataclasses.dataclass
class UnusedSuppression:
    path: str
    line: int
    rule: str

    def to_dict(self) -> dict:
        return {"path": self.path, "line": self.line, "rule": self.rule}


@dataclasses.dataclass
class RaceAuditReport:
    findings: List[RaceFinding]
    unused_suppressions: List[UnusedSuppression]
    shared_objects: List[str]
    locks: List[str]
    thread_roots: List[str]
    audited_functions: int
    #: rendered acquisition-order edges "outer -> inner" (deadlock pass)
    lock_edges: List[str] = dataclasses.field(default_factory=list)

    @property
    def active(self) -> List[RaceFinding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def ok(self) -> bool:
        return not self.active and not self.unused_suppressions

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "findings": [f.to_dict() for f in self.active],
            "suppressed": [f.to_dict() for f in self.findings if f.suppressed],
            "unused_suppressions": [
                u.to_dict() for u in self.unused_suppressions
            ],
            "shared_objects": self.shared_objects,
            "locks": self.locks,
            "lock_edges": self.lock_edges,
            "thread_roots": self.thread_roots,
            "audited_functions": self.audited_functions,
        }

    def render_text(self) -> str:
        out = [f.render() for f in self.active]
        for u in self.unused_suppressions:
            out.append(
                f"{u.path}:{u.line}: unused audit suppression "
                f"[audit-ok[{u.rule}]] — no finding on this line"
            )
        n_sup = sum(1 for f in self.findings if f.suppressed)
        out.append(
            f"races: {len(self.active)} finding(s), {n_sup} suppressed, "
            f"{len(self.unused_suppressions)} stale suppression(s) — "
            f"{len(self.shared_objects)} shared object(s), "
            f"{len(self.locks)} lock(s), {len(self.lock_edges)} lock-order "
            f"edge(s), {len(self.thread_roots)} thread root(s), "
            f"{self.audited_functions} audited function(s)"
        )
        return "\n".join(out)


# ---------------------------------------------------------------------------
# shared-state / lock collection
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ModuleShared:
    containers: Set[str] = dataclasses.field(default_factory=set)
    scalars: Set[str] = dataclasses.field(default_factory=set)
    locks: Set[str] = dataclasses.field(default_factory=set)
    #: lock/instance-lock name -> factory ("Lock", "RLock", ...); instance
    #: locks (`self.x = threading.Lock()` in a method) key as "Class.x"
    lock_kinds: Dict[str, str] = dataclasses.field(default_factory=dict)


def _module_level_assigns(tree: ast.Module) -> Iterator[ast.Assign]:
    """Module-level Assign statements, descending through top-level
    if/try blocks (e.g. `if TYPE_CHECKING` or platform guards)."""
    stack: List[ast.AST] = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Assign):
            yield node
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            # `_breakers: Dict[str, Breaker] = {}` — same binding, typed
            if isinstance(node.target, ast.Name):
                synth = ast.Assign(targets=[node.target], value=node.value)
                yield synth
        elif isinstance(node, (ast.If, ast.Try)):
            for fld in ("body", "orelse", "finalbody", "handlers"):
                for child in getattr(node, fld, []):
                    if isinstance(child, ast.ExceptHandler):
                        stack.extend(child.body)
                    else:
                        stack.append(child)


def _callee_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def collect_shared(mod: ModuleInfo) -> ModuleShared:
    out = ModuleShared()
    candidates_scalar: Set[str] = set()
    for node in _module_level_assigns(mod.tree):
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        # tuple targets: `_breakers, _lock = {}, Lock()` style
        for t in node.targets:
            if isinstance(t, ast.Tuple):
                names.extend(
                    e.id for e in t.elts if isinstance(e, ast.Name)
                )
        if not names:
            continue
        v = node.value
        if isinstance(v, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                          ast.DictComp, ast.SetComp)):
            out.containers.update(names)
        elif isinstance(v, ast.Call):
            callee = _callee_name(v)
            if callee in _LOCK_FACTORIES:
                out.locks.update(names)
                for n in names:
                    out.lock_kinds[n] = callee
            elif callee in _CONTAINER_FACTORIES:
                out.containers.update(names)
        elif isinstance(v, ast.Constant):
            candidates_scalar.update(names)
        elif isinstance(v, ast.Tuple) and isinstance(node.targets[0], ast.Tuple):
            # `a, b = 1, {}` — classify element-wise
            tgt = node.targets[0]
            for te, ve in zip(tgt.elts, v.elts):
                if not isinstance(te, ast.Name):
                    continue
                if isinstance(ve, (ast.Dict, ast.List, ast.Set)):
                    out.containers.add(te.id)
                elif isinstance(ve, ast.Constant):
                    candidates_scalar.add(te.id)

    # a scalar is shared-mutable only if some function rebinds it via global
    globally_written: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            declared: Set[str] = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Global):
                    declared.update(sub.names)
            if declared:
                for sub in ast.walk(node):
                    if (
                        isinstance(sub, ast.Name)
                        and isinstance(sub.ctx, ast.Store)
                        and sub.id in declared
                    ):
                        globally_written.add(sub.id)
    out.scalars = candidates_scalar & globally_written

    # instance locks: `self.x = threading.Lock()` anywhere in a class body
    # registers "Class.x" — `with self.x:` in that class's methods resolves
    # to it (the AdmissionQueue/SchedulerLoop/session-pool pattern)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Assign) or not isinstance(
                sub.value, ast.Call
            ):
                continue
            callee = _callee_name(sub.value)
            if callee not in _LOCK_FACTORIES:
                continue
            for t in sub.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    out.lock_kinds[f"{node.name}.{t.attr}"] = callee
    return out


# ---------------------------------------------------------------------------
# thread roots + reachability
# ---------------------------------------------------------------------------

def _class_of(qual: str) -> str:
    return qual.rsplit(".", 1)[0] if "." in qual else ""

def _is_handler_class(cls: ast.ClassDef) -> bool:
    for base in cls.bases:
        name = base.attr if isinstance(base, ast.Attribute) else getattr(
            base, "id", ""
        )
        if any(h in name for h in _HANDLER_BASES):
            return True
    return False


def _thread_target_exprs(node: ast.Call) -> Tuple[List[ast.expr], str]:
    """The callable expressions a Call hands to another thread, if any."""
    callee = _callee_name(node)
    if callee == "Thread":
        return (
            [kw.value for kw in node.keywords if kw.arg == "target"],
            "thread target",
        )
    if callee == "submit" and node.args:
        # executor.submit(fn, ...) — ThreadPoolExecutor work items run on
        # pool threads (the extender wave engine's HTTP fan-out); audit
        # the submitted callable like a Thread target
        return [node.args[0]], "executor task"
    if callee == "signal" and len(node.args) >= 2:
        return [node.args[1]], "signal handler"
    if callee == "Timer" and len(node.args) >= 2:
        return [node.args[1]], "timer thread"
    if callee == "guarded_call" and len(node.args) >= 2:
        # guarded_call(stage, fn, deadline) runs fn on a daemon watchdog
        # worker thread (durable/watchdog.py) whenever a deadline is armed
        # — every checkpoint/resume driver's device call routes through it
        return [node.args[1]], "watchdog-guarded call"
    return [], ""


#: subprocess entry points that put a child process to work while the
#: parent keeps running (the `simon chaos --capacity` kill/resume driver):
#: the wrapper coordinates with the child through the run journal and
#: environment, so it is audited like a thread root.
_SUBPROCESS_LAUNCHES = {"run", "Popen", "call", "check_call", "check_output"}


def _is_subprocess_launch(mod: ModuleInfo, node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        if f.attr not in _SUBPROCESS_LAUNCHES:
            return False
        imp = mod.imports.get(f.value.id)
        return imp is not None and imp[0] == "subprocess" and imp[1] is None
    if isinstance(f, ast.Name):
        imp = mod.imports.get(f.id)
        return (
            imp is not None
            and imp[0] == "subprocess"
            and imp[1] in _SUBPROCESS_LAUNCHES
        )
    return False


def _own_body(info: FunctionInfo) -> Iterator[ast.AST]:
    """A function's own statements, nested defs excluded (those carry
    their own FunctionInfo and attribute their own calls)."""
    stack = list(ast.iter_child_nodes(info.node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _qualnames(mod: ModuleInfo) -> Dict[str, FunctionInfo]:
    return {i.qualname: i for i in mod.functions.values()}


def thread_roots(ctx: LintContext) -> Dict[Tuple[str, str], str]:
    """(module, qualname) -> human-readable root reason."""
    roots: Dict[Tuple[str, str], str] = {}
    for mod in ctx.modules.values():
        quals = _qualnames(mod)
        # 1. request-handler methods run on server threads
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef) and _is_handler_class(node):
                for info in quals.values():
                    if _class_of(info.qualname) == node.name:
                        roots[(mod.name, info.qualname)] = (
                            f"handler thread {mod.name}:{info.qualname}"
                        )
        # 2. module-scope resolution: plain names and module.attr targets
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            target_exprs, reason = _thread_target_exprs(node)
            for expr in target_exprs:
                resolved = ctx.resolve_call(mod, expr)
                if resolved is not None:
                    roots[resolved] = (
                        f"{reason} {resolved[0]}:{resolved[1]}"
                    )
        # 3. enclosing-scope resolution: `Thread(target=self._worker_main)`
        # inside a method roots the sibling method; `Thread(target=_worker)`
        # inside a function roots the nested def (stored under its
        # qualname, invisible to module-scope lookup)
        for info in quals.values():
            cls = _class_of(info.qualname)
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Call):
                    continue
                target_exprs, reason = _thread_target_exprs(node)
                for expr in target_exprs:
                    qual = None
                    if (
                        cls
                        and isinstance(expr, ast.Attribute)
                        and isinstance(expr.value, ast.Name)
                        and expr.value.id == "self"
                        and f"{cls}.{expr.attr}" in quals
                    ):
                        qual = f"{cls}.{expr.attr}"
                    elif (
                        isinstance(expr, ast.Name)
                        and f"{info.qualname}.{expr.id}" in quals
                    ):
                        qual = f"{info.qualname}.{expr.id}"
                    if qual is not None:
                        roots[(mod.name, qual)] = (
                            f"{reason} {mod.name}:{qual}"
                        )
        # 4. subprocess wrappers: a function that launches a child process
        # keeps running concurrently with it, coordinating through the
        # journal/run-dir/env (`simon chaos --capacity` SIGKILLs the child
        # mid-chunk and resumes from its on-disk state) — audit the
        # wrapper itself like a thread root
        for info in quals.values():
            if any(
                isinstance(n, ast.Call) and _is_subprocess_launch(mod, n)
                for n in _own_body(info)
            ):
                roots[(mod.name, info.qualname)] = (
                    f"subprocess wrapper {mod.name}:{info.qualname}"
                )
    return roots


def _method_index(ctx: LintContext) -> Dict[str, List[Tuple[str, str]]]:
    """method name -> every (module, Class.method) in the package. Used to
    chase ``self.<attr>.<method>()`` hops across classes (the scheduler
    worker thread calling ``self._loop.run_forever()``): with no type
    information, a hop is followed only when the method name is unique
    package-wide — ambiguity means no resolution, never a guess."""
    index: Dict[str, List[Tuple[str, str]]] = {}
    for mod in ctx.modules.values():
        for info in _qualnames(mod).values():
            qual = info.qualname
            if "." not in qual:
                continue
            index.setdefault(qual.rsplit(".", 1)[1], []).append(
                (mod.name, qual)
            )
    return index


def _call_targets(
    ctx: LintContext, mod: ModuleInfo, cls: str, node: ast.Call,
    method_index: Optional[Dict[str, List[Tuple[str, str]]]] = None,
) -> Iterator[Tuple[str, str]]:
    """Every (module, qualname) a single Call node may enter."""
    resolved = ctx.resolve_call(mod, node.func)
    if resolved is not None:
        yield resolved
    f = node.func
    if not isinstance(f, ast.Attribute):
        return
    if isinstance(f.value, ast.Name) and f.value.id == "self":
        if cls:
            sibling = f"{cls}.{f.attr}"
            if any(i.qualname == sibling for i in mod.functions.values()):
                yield (mod.name, sibling)
    elif (
        method_index is not None
        and isinstance(f.value, ast.Attribute)
        and isinstance(f.value.value, ast.Name)
        and f.value.value.id == "self"
    ):
        # self.<attr>.<method>() — cross-class hop, unique-name only
        candidates = method_index.get(f.attr, [])
        if len(candidates) == 1:
            yield candidates[0]


def _calls_from(
    ctx: LintContext, mod: ModuleInfo, info: FunctionInfo,
    method_index: Optional[Dict[str, List[Tuple[str, str]]]] = None,
) -> Iterator[Tuple[str, str]]:
    cls = _class_of(info.qualname)
    for node in ast.walk(info.node):
        if not isinstance(node, ast.Call):
            continue
        yield from _call_targets(ctx, mod, cls, node, method_index)


def audited_functions(
    ctx: LintContext, roots: Dict[Tuple[str, str], str],
    module_hosts: bool = True,
) -> Dict[Tuple[str, str], str]:
    """Thread-reachable closure of the roots, plus (when ``module_hosts``)
    every function in a module that defines a root (main-thread code
    racing the handlers). ``module_hosts=False`` gives the strict
    reachability closure — what the ``lock-in-hot-path`` lint rule wants:
    only code that actually runs on a hot thread."""
    audited: Dict[Tuple[str, str], str] = {}
    index = _method_index(ctx)
    work = [(key, reason) for key, reason in sorted(roots.items())]
    while work:
        key, reason = work.pop()
        if key in audited:
            continue
        audited[key] = reason
        mod = ctx.modules.get(key[0])
        if mod is None:
            continue
        info = next(
            (i for i in mod.functions.values() if i.qualname == key[1]), None
        )
        if info is None:
            continue
        for tgt in _calls_from(ctx, mod, info, index):
            if tgt not in audited:
                work.append((tgt, reason))

    if not module_hosts:
        return audited
    root_modules = {m for (m, _q) in roots}
    for mod_name in root_modules:
        mod = ctx.modules[mod_name]
        for info in mod.functions.values():
            key = (mod_name, info.qualname)
            if key not in audited:
                audited[key] = f"module hosts thread roots ({mod_name})"
    return audited


# ---------------------------------------------------------------------------
# per-function access scan
# ---------------------------------------------------------------------------

def _guarded_by_decorator(info: FunctionInfo) -> Optional[str]:
    node = info.node
    for dec in getattr(node, "decorator_list", []):
        if isinstance(dec, ast.Call):
            name = _callee_name(dec)
            if name == "guarded_by" and dec.args:
                a = dec.args[0]
                if isinstance(a, ast.Constant) and isinstance(a.value, str):
                    return a.value
    return None


def _with_locks(node: ast.With, locks: Set[str],
                mod: ModuleInfo, ctx: LintContext) -> Set[str]:
    held: Set[str] = set()
    for item in node.items:
        e = item.context_expr
        if isinstance(e, ast.Name) and e.id in locks:
            held.add(e.id)
        elif isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name):
            target = _imported_module(mod, e.value.id, ctx)
            if target is not None:
                # with othermod.lock: — trust the name, shape checked there
                held.add(f"{target}:{e.attr}")
    return held


def _imported_module(mod: ModuleInfo, local: str,
                     ctx: LintContext) -> Optional[str]:
    """Dotted module name a local name refers to: `import pkg.sub as m`
    gives (pkg.sub, None); `from pkg import sub` gives (pkg, sub) with
    pkg.sub itself a module."""
    imp = mod.imports.get(local)
    if imp is None:
        return None
    target = imp[0] if imp[1] is None else f"{imp[0]}.{imp[1]}"
    return target if target in ctx.modules else None


def _shared_ref(
    expr: ast.expr, mod: ModuleInfo, ctx: LintContext,
    shared: Dict[str, ModuleShared],
) -> Optional[Tuple[str, str]]:
    """Resolve an expression to (module, name) of a shared container."""
    if isinstance(expr, ast.Name):
        if expr.id in shared[mod.name].containers:
            return (mod.name, expr.id)
        imp = mod.imports.get(expr.id)
        if (
            imp is not None
            and imp[1] is not None
            and imp[0] in shared
            and imp[1] in shared[imp[0]].containers
        ):
            return (imp[0], imp[1])
    elif isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        target = _imported_module(mod, expr.value.id, ctx)
        if (
            target is not None
            and target in shared
            and expr.attr in shared[target].containers
        ):
            return (target, expr.attr)
    return None


def _scan_function(
    ctx: LintContext,
    mod: ModuleInfo,
    info: FunctionInfo,
    shared: Dict[str, ModuleShared],
    root_reason: str,
    findings: List[RaceFinding],
) -> None:
    my_shared = shared[mod.name]
    anno = _guarded_by_decorator(info)
    base_held: Set[str] = {anno} if anno else set()

    declared_global: Set[str] = set()
    for sub in ast.walk(info.node):
        if isinstance(sub, ast.Global):
            declared_global.update(sub.names)
    watched_scalars = declared_global & my_shared.scalars

    # (name -> [(node, kind 'r'/'w', held?)]) for scalar RMW analysis
    scalar_events: Dict[str, List[Tuple[ast.AST, str, bool]]] = {}

    def emit(node: ast.AST, state: Tuple[str, str], access: str, msg: str):
        findings.append(
            RaceFinding(
                path=mod.path,
                line=getattr(node, "lineno", 0),
                col=getattr(node, "col_offset", 0),
                state=f"{state[0]}.{state[1]}",
                function=f"{mod.name}:{info.qualname}",
                access=access,
                thread_root=root_reason,
                message=msg,
            )
        )

    def container_mutation(node: ast.AST, held: bool):
        # x.append(...) / x[k] = v / del x[k] / x[k] += v
        target: Optional[ast.expr] = None
        verb = ""
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _MUTATING_METHODS:
                target, verb = f.value, f".{f.attr}()"
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            tgts = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in tgts:
                if isinstance(t, ast.Subscript):
                    target, verb = t.value, "[...] ="
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    target, verb = t.value, "del [...]"
        if target is None:
            return
        ref = _shared_ref(target, mod, ctx, shared)
        if ref is not None and not held:
            emit(
                node, ref, "mutate",
                f"unguarded mutation `{ref[1]}{verb}` of shared "
                f"module state {ref[0]}.{ref[1]} — wrap in `with <lock>:` "
                f"or annotate the function @guarded_by(...)",
            )

    def visit(node: ast.AST, held: Set[str]):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
            node is not info.node
        ):
            return  # nested def runs later, on its own audit entry
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new = held | _with_locks(node, my_shared.locks, mod, ctx)
            for item in node.items:
                visit(item.context_expr, held)
            for child in node.body:
                visit(child, new)
            return
        guarded = bool(held)
        container_mutation(node, guarded)
        if isinstance(node, ast.Name) and node.id in watched_scalars:
            kind = "w" if isinstance(node.ctx, ast.Store) else "r"
            scalar_events.setdefault(node.id, []).append(
                (node, kind, guarded)
            )
        if isinstance(node, ast.AugAssign) and isinstance(
            node.target, ast.Name
        ) and node.target.id in watched_scalars:
            # AugAssign's target Name has Store ctx; record the read half too
            scalar_events.setdefault(node.target.id, []).append(
                (node, "r", guarded)
            )
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in info.node.body:  # type: ignore[attr-defined]
        visit(stmt, set(base_held))

    for name, events in sorted(scalar_events.items()):
        reads = [e for e in events if e[1] == "r"]
        writes = [e for e in events if e[1] == "w"]
        if not writes or not reads:
            continue  # pure publish or pure read: atomic under the GIL
        unguarded = [e for e in events if not e[2]]
        if not unguarded:
            continue
        node = writes[0][0]
        access = (
            "rmw"
            if any(isinstance(e[0], ast.AugAssign) for e in events)
            else "check-then-act"
        )
        emit(
            node, (mod.name, name), access,
            f"read-modify-write of shared scalar {mod.name}.{name} with "
            f"{len(unguarded)} unguarded access(es) — a concurrent thread "
            f"can interleave between the read and the write",
        )


# ---------------------------------------------------------------------------
# lock-order deadlock pass
# ---------------------------------------------------------------------------
#
# Two thread roots acquiring the same locks in opposite orders deadlock the
# process; so does blocking forever (join()/Queue.get() with no timeout)
# while holding a lock another thread needs. Both are order properties the
# race pass above cannot see. This pass:
#
#   1. resolves every `with <lock>:` in the audited (thread-reachable)
#      functions to a canonical lock identity — module-level locks
#      ("mod:name", including `with othermod.lock:`) and instance locks
#      ("mod:Class.attr" from `self.x = threading.Lock()`);
#   2. computes each function's may-acquire set (direct + transitive
#      callees, interprocedural fixpoint over the same call graph the race
#      pass walks);
#   3. builds the lock-acquisition graph: edge L1 -> L2 when L2 is acquired
#      (directly or via a callee) while L1 is held;
#   4. reports every cycle (Tarjan SCC; self-edges only for non-reentrant
#      plain Locks) and every no-timeout blocking call made under a lock.
#
# An ``osim: audit-ok[deadlock]`` comment on the flagged line suppresses a
# finding, with the same staleness cross-check as the race rule.

@dataclasses.dataclass
class _LockUse:
    """One audited function's lock behavior."""
    acquires: Dict[str, ast.AST] = dataclasses.field(default_factory=dict)
    orders: List[Tuple[str, str, ast.AST]] = dataclasses.field(
        default_factory=list
    )
    calls_holding: List[Tuple[Tuple[str, ...], Tuple[str, str], ast.AST]] = (
        dataclasses.field(default_factory=list)
    )
    blocking: List[Tuple[Tuple[str, ...], str, ast.AST]] = dataclasses.field(
        default_factory=list
    )
    calls: Set[Tuple[str, str]] = dataclasses.field(default_factory=set)


def _resolve_lock(
    expr: ast.expr, mod: ModuleInfo, cls: str, ctx: LintContext,
    shared: Dict[str, ModuleShared],
) -> Optional[str]:
    """Canonical lock id for a `with` context expression, if it is one."""
    my = shared[mod.name]
    if isinstance(expr, ast.Name) and expr.id in my.locks:
        return f"{mod.name}:{expr.id}"
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        if expr.value.id == "self" and cls:
            if f"{cls}.{expr.attr}" in my.lock_kinds:
                return f"{mod.name}:{cls}.{expr.attr}"
            return None
        target = _imported_module(mod, expr.value.id, ctx)
        if (
            target is not None
            and target in shared
            and expr.attr in shared[target].locks
        ):
            return f"{target}:{expr.attr}"
    return None


def _blocking_verb(node: ast.Call) -> Optional[str]:
    """'.join()' / '.get()' when the call can block forever.

    Zero-positional-arg is the discriminator: `thread.join()` and
    `queue.get()` block indefinitely, while `",".join(parts)` and
    `d.get(key)` always carry a positional argument. A timeout= (or
    block=False) keyword makes either bounded.
    """
    f = node.func
    if not isinstance(f, ast.Attribute) or f.attr not in ("join", "get"):
        return None
    if node.args:
        return None
    kwargs = {kw.arg for kw in node.keywords}
    if "timeout" in kwargs or "block" in kwargs:
        return None
    return f".{f.attr}()"


def _lock_use(
    ctx: LintContext, mod: ModuleInfo, info: FunctionInfo,
    shared: Dict[str, ModuleShared],
    method_index: Dict[str, List[Tuple[str, str]]],
) -> _LockUse:
    use = _LockUse()
    cls = _class_of(info.qualname)
    anno = _guarded_by_decorator(info)
    base_held: Tuple[str, ...] = (
        (f"{mod.name}:{anno}",) if anno else ()
    )

    def visit(node: ast.AST, held: Tuple[str, ...]):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
            node is not info.node
        ):
            return  # nested defs are separate audit entries
        if isinstance(node, (ast.With, ast.AsyncWith)):
            taken: List[str] = []
            for item in node.items:
                visit(item.context_expr, held)
                lock = _resolve_lock(
                    item.context_expr, mod, cls, ctx, shared
                )
                if lock is not None:
                    use.acquires.setdefault(lock, node)
                    for h in held + tuple(taken):
                        use.orders.append((h, lock, node))
                    taken.append(lock)
            new = held + tuple(t for t in taken if t not in held)
            for child in node.body:
                visit(child, new)
            return
        if isinstance(node, ast.Call):
            if held:
                verb = _blocking_verb(node)
                if verb is not None:
                    use.blocking.append((held, verb, node))
            for tgt in _call_targets(ctx, mod, cls, node, method_index):
                use.calls.add(tgt)
                if held:
                    use.calls_holding.append((held, tgt, node))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in info.node.body:  # type: ignore[attr-defined]
        visit(stmt, base_held)
    return use


def _lock_kind(lock: str, shared: Dict[str, ModuleShared]) -> str:
    mod_name, _, name = lock.partition(":")
    s = shared.get(mod_name)
    return s.lock_kinds.get(name, "") if s else ""


def deadlock_pass(
    ctx: LintContext,
    shared: Dict[str, ModuleShared],
    audited: Dict[Tuple[str, str], str],
) -> Tuple[List[RaceFinding], List[str]]:
    """-> (findings, rendered lock-graph edges)."""
    method_index = _method_index(ctx)
    uses: Dict[Tuple[str, str], _LockUse] = {}
    infos: Dict[Tuple[str, str], Tuple[ModuleInfo, FunctionInfo]] = {}
    for key in audited:
        mod = ctx.modules.get(key[0])
        if mod is None:
            continue
        info = next(
            (i for i in mod.functions.values() if i.qualname == key[1]), None
        )
        if info is None:
            continue
        infos[key] = (mod, info)
        uses[key] = _lock_use(ctx, mod, info, shared, method_index)

    # interprocedural may-acquire fixpoint (call graph is small; iterate)
    may_acquire: Dict[Tuple[str, str], Set[str]] = {
        k: set(u.acquires) for k, u in uses.items()
    }
    changed = True
    while changed:
        changed = False
        for k, u in uses.items():
            for tgt in u.calls:
                extra = may_acquire.get(tgt, set()) - may_acquire[k]
                if extra:
                    may_acquire[k].update(extra)
                    changed = True

    # acquisition edges: (outer, inner) -> (mod, site node, function key)
    edges: Dict[Tuple[str, str], Tuple[ModuleInfo, ast.AST, Tuple[str, str]]]
    edges = {}
    for k, u in uses.items():
        mod = infos[k][0]
        for outer, inner, site in u.orders:
            edges.setdefault((outer, inner), (mod, site, k))
        for held, tgt, site in u.calls_holding:
            for inner in may_acquire.get(tgt, ()):
                for outer in held:
                    edges.setdefault((outer, inner), (mod, site, k))

    findings: List[RaceFinding] = []

    def emit(key: Tuple[str, str], site: ast.AST, mod: ModuleInfo,
             state: str, access: str, msg: str):
        findings.append(
            RaceFinding(
                path=mod.path,
                line=getattr(site, "lineno", 0),
                col=getattr(site, "col_offset", 0),
                state=state,
                function=f"{key[0]}:{key[1]}",
                access=access,
                thread_root=audited.get(key, "?"),
                message=msg,
                rule=RULE_DEADLOCK,
            )
        )

    # self-edges: re-acquiring a non-reentrant Lock you already hold
    # deadlocks immediately; RLock/Semaphore/Condition re-entry does not
    for (outer, inner), (mod, site, key) in sorted(
        edges.items(), key=lambda e: (e[0], e[1][0].path)
    ):
        if outer == inner and _lock_kind(outer, shared) == "Lock":
            emit(
                key, site, mod, outer, "lock-order",
                f"non-reentrant lock {outer} re-acquired while already "
                f"held — self-deadlock",
            )

    # cycles across distinct locks: Tarjan SCC over the acquisition graph
    adj: Dict[str, List[str]] = {}
    for outer, inner in edges:
        if outer != inner:
            adj.setdefault(outer, []).append(inner)
            adj.setdefault(inner, [])
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Dict[str, bool] = {}
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str):
        # iterative Tarjan (the lock graph is tiny, but no recursion limits)
        work = [(v, iter(adj.get(v, ())))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack[v] = True
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack[w] = True
                    work.append((w, iter(adj.get(w, ()))))
                    advanced = True
                    break
                if on_stack.get(w):
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)

    for comp in sccs:
        if len(comp) < 2:
            continue
        comp_set = set(comp)
        cycle_edges = sorted(
            (o, i) for (o, i) in edges
            if o in comp_set and i in comp_set and o != i
        )
        mod, site, key = edges[cycle_edges[0]]
        order = " -> ".join(sorted(comp_set) + [sorted(comp_set)[0]])
        emit(
            key, site, mod, ",".join(sorted(comp_set)), "lock-order",
            f"lock-order cycle {order}: threads acquiring these locks in "
            f"different orders can deadlock; establish one global order "
            f"(edges: "
            + "; ".join(f"{o} then {i}" for o, i in cycle_edges)
            + ")",
        )

    # blocking calls under a lock
    for k, u in uses.items():
        mod = infos[k][0]
        for held, verb, site in u.blocking:
            emit(
                k, site, mod, ",".join(sorted(held)), "blocking",
                f"unbounded blocking call `{verb}` while holding "
                f"{', '.join(sorted(held))} — any thread needing the lock "
                f"waits forever if the peer never finishes; pass a timeout "
                f"or move the wait outside the lock",
            )

    rendered = sorted(f"{o} -> {i}" for (o, i) in edges if o != i)
    return findings, rendered


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _audit_suppressions(mod: ModuleInfo) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(mod.lines, start=1):
        m = AUDIT_SUPPRESS_RE.search(line)
        if m:
            out[i] = {p.strip() for p in m.group(1).split(",")}
    return out


def run_races(
    package_root: Optional[str] = None,
    report_root: Optional[str] = None,
    ctx: Optional[LintContext] = None,
) -> RaceAuditReport:
    if ctx is None:
        ctx = build_context(package_root, report_root)

    shared = {m.name: collect_shared(m) for m in ctx.modules.values()}
    roots = thread_roots(ctx)
    audited = audited_functions(ctx, roots)

    findings: List[RaceFinding] = []
    for (mod_name, qual), reason in sorted(audited.items()):
        mod = ctx.modules[mod_name]
        info = next(
            (i for i in mod.functions.values() if i.qualname == qual), None
        )
        if info is not None:
            _scan_function(ctx, mod, info, shared, reason, findings)

    deadlock_findings, lock_edges = deadlock_pass(ctx, shared, audited)
    findings.extend(deadlock_findings)

    # dedupe (a function reachable from several roots scans once per (line,
    # state) anyway; reachability map already collapses roots)
    uniq: Dict[Tuple, RaceFinding] = {}
    for f in findings:
        uniq.setdefault((f.path, f.line, f.col, f.state, f.access), f)
    findings = sorted(uniq.values(), key=RaceFinding.sort_key)

    # apply + cross-check audit-ok suppressions (per rule: an audit-ok for
    # one rule never silences the other)
    used: Set[Tuple[str, int, str]] = set()
    sup_by_mod = {m.name: _audit_suppressions(m) for m in ctx.modules.values()}
    path_to_mod = {m.path: m.name for m in ctx.modules.values()}
    for f in findings:
        mod_name = path_to_mod.get(f.path)
        if mod_name is None:
            continue
        sup = sup_by_mod[mod_name].get(f.line, set())
        if f.rule in sup:
            f.suppressed = True
            used.add((f.path, f.line, f.rule))

    unused: List[UnusedSuppression] = []
    for mod in ctx.modules.values():
        for line, rules in sorted(sup_by_mod[mod.name].items()):
            for r in sorted(rules):
                if r not in KNOWN_RULES or (mod.path, line, r) not in used:
                    unused.append(UnusedSuppression(mod.path, line, r))

    shared_objects = sorted(
        f"{name}.{obj}"
        for name, s in shared.items()
        for obj in (s.containers | s.scalars)
    )
    locks = sorted(
        f"{name}.{lk}" for name, s in shared.items() for lk in s.locks
    )
    return RaceAuditReport(
        findings=findings,
        unused_suppressions=unused,
        shared_objects=shared_objects,
        locks=locks,
        thread_roots=sorted(set(roots.values())),
        audited_functions=len(audited),
        lock_edges=lock_edges,
    )


def report_json(report: RaceAuditReport) -> str:
    return json.dumps(report.to_dict(), indent=2, sort_keys=True)
