"""Retry policies and circuit breakers for external I/O.

Production schedulers treat extender/apiserver flakiness as the common case:
a transport blip must cost one bounded retry, not a failed pod, and a dead
backend must fail fast instead of eating a full timeout per pod. Two
composable pieces implement that discipline:

  * `RetryPolicy` — bounded attempts with decorrelated-jitter exponential
    backoff (the AWS architecture-blog variant: each delay is drawn uniformly
    from [base, 3 × previous] and capped), a per-attempt timeout, and an
    overall deadline budget. The RNG, clock, and sleep function are all
    injectable so tests are deterministic and sleep-free.
  * `CircuitBreaker` — per-endpoint closed → open after N consecutive
    failures, half-open probe after a cooldown, success closes. State is
    exported through `osim_circuit_state{endpoint=}` and every retry through
    `osim_retry_attempts_total{target=}` (utils/metrics.py).

Both are dependency-free and thread-safe; the extender transport, the kube
client, and the capacity planner share them (see docs/resilience.md).
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple, Type

from ..utils import metrics


class RetryExhaustedError(Exception):
    """All attempts of a RetryPolicy.execute() call failed. `last_exc` is the
    final attempt's exception; `attempts` the number of attempts made."""

    def __init__(self, last_exc: BaseException, attempts: int) -> None:
        super().__init__(f"{last_exc} (after {attempts} attempt(s))")
        self.last_exc = last_exc
        self.attempts = attempts


class CircuitOpenError(Exception):
    """A call was refused because the endpoint's circuit breaker is open."""


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


@dataclass
class RetryPolicy:
    """Bounded retry with decorrelated jitter.

    `execute(fn)` calls `fn(timeout)` up to `max_attempts` times; `timeout`
    is the per-attempt budget (min of `per_attempt_timeout_s` and the
    remaining `deadline_s`, or None when neither is set). Exceptions listed
    in `retryable` are retried after a backoff; anything else propagates
    immediately. When attempts or the deadline run out the last exception is
    wrapped in RetryExhaustedError so callers can render an aggregate
    message ("... after 3 attempts").
    """

    max_attempts: int = 3
    base_s: float = 0.05
    cap_s: float = 2.0
    per_attempt_timeout_s: Optional[float] = None
    deadline_s: Optional[float] = None
    rng: random.Random = field(default_factory=lambda: random.Random(0))
    clock: Callable[[], float] = time.monotonic
    sleep: Callable[[float], None] = time.sleep
    # random.Random.uniform is a read-modify-write of hidden generator state;
    # the wave engine shares one policy across its HTTP worker threads, so
    # jitter draws must serialize (execute() itself keeps all other state in
    # locals). Excluded from comparison: a Lock carries no policy identity.
    _rng_lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    @staticmethod
    def from_env(
        max_attempts: int = 3,
        deadline_s: Optional[float] = None,
    ) -> "RetryPolicy":
        """Policy from the OSIM_RETRY_* env knobs (docs/resilience.md).
        Arguments are the caller's defaults; a set env knob overrides them
        (OSIM_RETRY_DEADLINE_S <= 0 means no deadline)."""
        env_deadline = _env_float("OSIM_RETRY_DEADLINE_S", -1.0)
        if env_deadline >= 0:
            deadline_s = env_deadline if env_deadline > 0 else None
        return RetryPolicy(
            max_attempts=max(1, _env_int("OSIM_RETRY_MAX_ATTEMPTS", max_attempts)),
            base_s=max(0.0, _env_float("OSIM_RETRY_BASE_S", 0.05)),
            cap_s=max(0.0, _env_float("OSIM_RETRY_CAP_S", 2.0)),
            deadline_s=deadline_s,
            rng=random.Random(_env_int("OSIM_RETRY_JITTER_SEED", 0)),
        )

    def next_delay(self, prev_delay: float) -> float:
        """One decorrelated-jitter step: uniform(base, 3 × prev), capped.
        Thread-safe: the shared RNG draw serializes under `_rng_lock`."""
        lo = self.base_s
        hi = max(lo, prev_delay * 3.0)
        with self._rng_lock:
            return min(self.cap_s, self.rng.uniform(lo, hi))

    def _attempt_timeout(self, start: float) -> Optional[float]:
        """Per-attempt budget: min(per_attempt_timeout_s, remaining
        deadline). The deadline clamp floors at 0 — once the budget is
        blown, `remaining` is negative, and handing a negative/zero timeout
        to a transport (which commonly treats <=0 as *unbounded*) would let
        one attempt overshoot the whole deadline. execute() refuses to
        launch an attempt whose clamped budget is 0."""
        timeout = self.per_attempt_timeout_s
        if self.deadline_s is not None:
            remaining = max(0.0, self.deadline_s - (self.clock() - start))
            timeout = remaining if timeout is None else min(timeout, remaining)
        return timeout

    def execute(
        self,
        fn: Callable[[Optional[float]], object],
        retryable: Tuple[Type[BaseException], ...] = (Exception,),
        target: str = "",
        on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
    ):
        start = self.clock()
        delay = self.base_s
        attempt = 0
        last_exc: BaseException = TimeoutError(
            f"retry deadline of {self.deadline_s}s exhausted before an "
            "attempt could start"
        )
        while True:
            attempt += 1
            timeout = self._attempt_timeout(start)
            if timeout is not None and timeout <= 0:
                # deadline exhausted before this attempt could launch
                raise RetryExhaustedError(last_exc, attempt - 1)
            try:
                return fn(timeout)
            except retryable as e:
                last_exc = e
                if attempt >= self.max_attempts:
                    raise RetryExhaustedError(e, attempt)
                delay = self.next_delay(delay)
                if (
                    self.deadline_s is not None
                    and (self.clock() - start) + delay > self.deadline_s
                ):
                    # the backoff would blow the overall budget: give up now
                    raise RetryExhaustedError(e, attempt)
                metrics.RETRY_ATTEMPTS.inc(target=target)
                if on_retry is not None:
                    on_retry(attempt, e, delay)
                if delay > 0:
                    self.sleep(delay)


class CircuitBreaker:
    """Per-endpoint circuit breaker.

    closed: calls flow; N consecutive failures trip it open.
    open:   calls are refused (allow() is False) until `cooldown_s` elapses,
            then ONE probe is admitted (half-open).
    half-open: the probe's success closes the breaker; its failure reopens
            it (and restarts the cooldown). Further calls while the probe is
            in flight are refused.

    State is mirrored to osim_circuit_state{endpoint=} as 0/1/2 for
    closed/open/half-open.
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"
    _STATE_VALUE = {CLOSED: 0.0, OPEN: 1.0, HALF_OPEN: 2.0}

    def __init__(
        self,
        endpoint: str,
        failure_threshold: int = 5,
        cooldown_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.endpoint = endpoint
        self.failure_threshold = max(1, failure_threshold)
        self.cooldown_s = cooldown_s
        self.clock = clock
        self._lock = threading.Lock()
        self.state = self.CLOSED
        self.consecutive_failures = 0
        self.last_error = ""
        self._opened_at = 0.0
        self._export()

    def _export(self) -> None:
        metrics.CIRCUIT_STATE.set(
            self._STATE_VALUE[self.state], endpoint=self.endpoint
        )

    def allow(self) -> bool:
        """True when a call may proceed; transitions open→half-open once the
        cooldown has elapsed (admitting exactly one probe)."""
        with self._lock:
            if self.state == self.CLOSED:
                return True
            if self.state == self.OPEN:
                if self.clock() - self._opened_at >= self.cooldown_s:
                    self.state = self.HALF_OPEN
                    self._export()
                    return True
                return False
            # half-open: one probe already in flight
            return False

    def record_success(self) -> None:
        with self._lock:
            self.state = self.CLOSED
            self.consecutive_failures = 0
            self.last_error = ""
            self._export()

    def record_failure(self, error: str = "") -> None:
        with self._lock:
            self.consecutive_failures += 1
            if error:
                self.last_error = error
            if (
                self.state == self.HALF_OPEN
                or self.consecutive_failures >= self.failure_threshold
            ):
                self.state = self.OPEN
                self._opened_at = self.clock()
            self._export()

    def force_open(self, error: str = "forced open") -> None:
        """Trip the breaker immediately (test/chaos helper)."""
        with self._lock:
            self.consecutive_failures = max(
                self.consecutive_failures, self.failure_threshold
            )
            self.last_error = error
            self.state = self.OPEN
            self._opened_at = self.clock()
            self._export()

    def describe(self) -> str:
        return (
            f"circuit {self.state} ({self.consecutive_failures} consecutive "
            f"failure(s)"
            + (f"; last error: {self.last_error}" if self.last_error else "")
            + ")"
        )


# ---------------------------------------------------------------------------
# Endpoint-keyed breaker registry. HTTPExtender instances are rebuilt per
# simulate() call, so breaker state must live OUTSIDE them to persist across
# pods, probes, and capacity-search iterations; keyed by endpoint base URL.
# ---------------------------------------------------------------------------

_breakers: Dict[str, CircuitBreaker] = {}
_breakers_lock = threading.Lock()


def breaker_for(endpoint: str) -> CircuitBreaker:
    """Get-or-create the shared breaker for an endpoint. Threshold/cooldown
    come from OSIM_BREAKER_THRESHOLD / OSIM_BREAKER_COOLDOWN_S at creation."""
    with _breakers_lock:
        b = _breakers.get(endpoint)
        if b is None:
            b = _breakers[endpoint] = CircuitBreaker(
                endpoint,
                failure_threshold=max(1, _env_int("OSIM_BREAKER_THRESHOLD", 5)),
                cooldown_s=_env_float("OSIM_BREAKER_COOLDOWN_S", 30.0),
            )
        return b


def reset_breakers() -> None:
    """Drop all breaker state (test isolation; `simon chaos` startup)."""
    with _breakers_lock:
        _breakers.clear()


def breaker_states() -> Dict[str, str]:
    """endpoint -> state for every registered breaker, sorted by endpoint
    (the `simon chaos` report and /metrics-adjacent debugging)."""
    with _breakers_lock:
        return {ep: _breakers[ep].state for ep in sorted(_breakers)}
