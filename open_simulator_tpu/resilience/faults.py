"""Deterministic fault injection for the external-I/O surfaces.

A `FaultPlan` (YAML file, or the OSIM_FAULT_PLAN env var holding a path or
inline YAML) names rules that inject latency, connection errors, HTTP 5xx,
malformed-JSON responses, or generic errors into the extender transport
(`engine/extenders.py`), the apiserver client (`utils/kubeclient.py`), and
chart rendering (`utils/chart.py`). The schedule is seeded — rule order,
per-rule call counters, and one `random.Random(seed)` for probabilistic
rules — so a plan replays byte-identically: the same calls fail in the same
order on every run, which is what makes degraded-mode behavior testable
(`simon chaos`, tests/test_resilience.py).

Plan schema:

    seed: 7
    rules:
      - target: extender          # extender | kubeclient | chart
                                  # | backend | journal | admission
                                  # | resident | device
        op: filter                # optional substring match on the call's
                                  # operation (extender verb, api path,
                                  # chart release/path, backend stage,
                                  # journal event, admission phase
                                  # "submit"/"drain", resident phase
                                  # "apply"/"verify"/"fence", device chunk
                                  # "commit-chunk:<i>"); empty = any
        kind: connection_error    # latency | connection_error | http_error
                                  # | malformed_json | error | kill
                                  # | queue_full | slow_drain
                                  # | deadline_storm  (admission only)
                                  # | torn_delta | stale_generation
                                  # | digest_mismatch  (resident only)
                                  # | device_lost | chunk_kill (device only)
        times: 2                  # inject on the first 2 matching calls
                                  # (omit = every matching call)
        after: 0                  # skip this many matching calls first
        probability: 1.0          # seeded coin per matching call
        latency_s: 0.05           # kind=latency: injected delay
        status: 503               # kind=http_error: response status
        body: ""                  # http_error/malformed_json response body

Call sites consult `maybe_inject(target, op)`; with no plan installed this
is a single None-check, so the production hot path pays nothing.
"""

from __future__ import annotations

import io
import os
import random
import threading
import urllib.error
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import yaml

from ..utils import metrics

TARGETS = (
    "extender", "kubeclient", "chart", "backend", "journal", "admission",
    "resident", "device",
)
KINDS = (
    "latency", "connection_error", "http_error", "malformed_json", "error",
    "kill", "queue_full", "slow_drain", "deadline_storm",
    "torn_delta", "stale_generation", "digest_mismatch",
    "device_lost", "chunk_kill",
)


class FaultInjectionError(Exception):
    """A fault plan could not be loaded or is invalid."""


class DeviceLostError(Exception):
    """The accelerator holding the resident carry disappeared mid-plan
    (preemption, ICI partition, tunnel death). Raised by the `device_lost`
    fault kind; the chunked commit driver handles it by restoring the last
    checkpointed carry and replaying, or re-raises once out of budget."""


@dataclass
class FaultRule:
    target: str
    kind: str
    op: str = ""
    times: Optional[int] = None
    after: int = 0
    probability: float = 1.0
    latency_s: float = 0.0
    status: int = 503
    body: str = ""
    # runtime counters (mutated under the injector lock)
    seen: int = 0
    injected: int = 0
    # keyed-mode counters (see FaultInjector.intercept): per-key occurrence
    # and injection counts, so `after`/`times`/`probability` gate per key
    # instead of per global call order
    seen_by_key: Dict[str, int] = field(default_factory=dict)
    injected_by_key: Dict[str, int] = field(default_factory=dict)

    def __post_init__(self) -> None:
        if self.target not in TARGETS:
            raise FaultInjectionError(
                f"fault rule: unknown target {self.target!r} "
                f"(expected one of {', '.join(TARGETS)})"
            )
        if self.kind not in KINDS:
            raise FaultInjectionError(
                f"fault rule: unknown kind {self.kind!r} "
                f"(expected one of {', '.join(KINDS)})"
            )
        if not 0.0 <= self.probability <= 1.0:
            raise FaultInjectionError(
                f"fault rule: probability {self.probability} not in [0, 1]"
            )

    @staticmethod
    def from_dict(doc: Dict[str, Any]) -> "FaultRule":
        known = {
            "target", "kind", "op", "times", "after", "probability",
            "latency_s", "status", "body",
        }
        unknown = set(doc) - known
        if unknown:
            raise FaultInjectionError(
                f"fault rule: unknown key(s) {sorted(unknown)}"
            )
        return FaultRule(
            target=str(doc.get("target", "")),
            kind=str(doc.get("kind", "")),
            op=str(doc.get("op", "") or ""),
            times=(None if doc.get("times") is None else int(doc["times"])),
            after=int(doc.get("after", 0) or 0),
            probability=float(doc.get("probability", 1.0)),
            latency_s=float(doc.get("latency_s", 0.0) or 0.0),
            status=int(doc.get("status", 503) or 503),
            body=str(doc.get("body", "") or ""),
        )


@dataclass
class FaultPlan:
    seed: int = 0
    rules: List[FaultRule] = field(default_factory=list)

    @staticmethod
    def from_dict(doc: Dict[str, Any]) -> "FaultPlan":
        if not isinstance(doc, dict):
            raise FaultInjectionError("fault plan: top level must be a mapping")
        rules = doc.get("rules")
        if not isinstance(rules, list) or not rules:
            raise FaultInjectionError("fault plan: 'rules' must be a non-empty list")
        return FaultPlan(
            seed=int(doc.get("seed", 0) or 0),
            rules=[FaultRule.from_dict(r or {}) for r in rules],
        )

    @staticmethod
    def load(path: str) -> "FaultPlan":
        try:
            with open(path) as fh:
                doc = yaml.safe_load(fh)
        except OSError as e:
            raise FaultInjectionError(f"cannot read fault plan {path}: {e}")
        except yaml.YAMLError as e:
            raise FaultInjectionError(f"invalid fault plan YAML {path}: {e}")
        return FaultPlan.from_dict(doc or {})

    @staticmethod
    def from_env() -> Optional["FaultPlan"]:
        """OSIM_FAULT_PLAN: a path to a plan file, or inline YAML."""
        raw = os.environ.get("OSIM_FAULT_PLAN", "").strip()
        if not raw:
            return None
        if os.path.exists(raw):
            return FaultPlan.load(raw)
        try:
            doc = yaml.safe_load(raw)
        except yaml.YAMLError as e:
            raise FaultInjectionError(f"OSIM_FAULT_PLAN: invalid YAML: {e}")
        if not isinstance(doc, dict):
            raise FaultInjectionError(
                f"OSIM_FAULT_PLAN: not a file and not inline plan YAML: {raw!r}"
            )
        return FaultPlan.from_dict(doc)


class FaultInjector:
    """Evaluates a FaultPlan against intercepted calls. Deterministic: rules
    fire in plan order, per-rule counters gate `after`/`times`, and the one
    seeded RNG drives `probability` coins in call order.

    Keyed mode: a call site that passes `key` (the extender transport passes
    the pod UID) is gated by per-(rule, key) counters and a hash-seeded coin
    instead of the shared call-order state — so a concurrent wave of calls
    injects the exact same faults into the exact same pods regardless of
    thread interleaving. A pod's own calls are temporally ordered (retries
    are sequential within one chain), so per-key occurrence numbering is
    deterministic even though cross-pod order is not.

    `snapshot_key`/`restore_key` give a caller that may re-issue a keyed
    call sequence (the wave engine, after a commit-conflict respill or a
    discarded speculative dispatch) replay semantics: snapshot the key's
    occurrence counters before the first dispatch, restore them before any
    re-issue, and the re-run draws the exact coin positions of its first
    run — outcomes stay byte-identical to the serial path, which runs the
    sequence exactly once from the same starting positions (aggregate
    `injected` counters do count the replay; the per-pod behavior is what
    determinism is about)."""

    def __init__(self, plan: FaultPlan) -> None:
        self.plan = plan
        self.rng = random.Random(plan.seed)
        self._lock = threading.Lock()

    def _match_ordered(self, rule: FaultRule) -> bool:
        """Legacy gating: global call-order counters + the shared RNG."""
        rule.seen += 1
        if rule.seen <= rule.after:
            return False
        if rule.times is not None and rule.injected >= rule.times:
            return False
        if rule.probability < 1.0 and self.rng.random() >= rule.probability:
            return False
        rule.injected += 1
        return True

    def _match_keyed(self, rule: FaultRule, idx: int, key: str) -> bool:
        """Keyed gating: `after`/`times` count this key's own calls, and the
        probability coin is a pure function of (seed, rule, key, occurrence)
        — byte-deterministic under any cross-key interleaving."""
        seen = rule.seen_by_key.get(key, 0) + 1
        rule.seen_by_key[key] = seen
        rule.seen += 1
        if seen <= rule.after:
            return False
        if rule.times is not None and (
            rule.injected_by_key.get(key, 0) >= rule.times
        ):
            return False
        if rule.probability < 1.0:
            coin = random.Random(
                f"{self.plan.seed}|{idx}|{key}|{seen}"
            ).random()
            if coin >= rule.probability:
                return False
        rule.injected_by_key[key] = rule.injected_by_key.get(key, 0) + 1
        rule.injected += 1
        return True

    def snapshot_key(self, key: str) -> List[Tuple[int, int]]:
        """Per-rule (seen, injected) counters for `key`, in plan order —
        taken before a keyed sequence's first dispatch (see class
        docstring)."""
        with self._lock:
            return [
                (
                    rule.seen_by_key.get(key, 0),
                    rule.injected_by_key.get(key, 0),
                )
                for rule in self.plan.rules
            ]

    def restore_key(self, key: str, snap: List[Tuple[int, int]]) -> None:
        """Rewind `key`'s counters to a snapshot so a re-issued sequence
        replays its first run's coin positions. The aggregate per-rule
        `seen`/`injected` counters are deliberately not rewound."""
        with self._lock:
            for rule, (seen, injected) in zip(self.plan.rules, snap):
                if seen:
                    rule.seen_by_key[key] = seen
                else:
                    rule.seen_by_key.pop(key, None)
                if injected:
                    rule.injected_by_key[key] = injected
                else:
                    rule.injected_by_key.pop(key, None)

    def intercept(
        self, target: str, op: str = "", key: str = ""
    ) -> Optional[FaultRule]:
        with self._lock:
            for idx, rule in enumerate(self.plan.rules):
                if rule.target != target:
                    continue
                if rule.op and rule.op not in op:
                    continue
                matched = (
                    self._match_keyed(rule, idx, key)
                    if key
                    else self._match_ordered(rule)
                )
                if not matched:
                    continue
                metrics.FAULTS_INJECTED.inc(target=target, kind=rule.kind)
                return rule
        return None

    def summary(self) -> List[Dict[str, Any]]:
        """Per-rule injection counts, in plan order (deterministic)."""
        with self._lock:
            return [
                {
                    "target": r.target,
                    "op": r.op,
                    "kind": r.kind,
                    "matched": r.seen,
                    "injected": r.injected,
                }
                for r in self.plan.rules
            ]


# ---------------------------------------------------------------------------
# Global installation point. None (the default) = production: maybe_inject
# is a single attribute read.
# ---------------------------------------------------------------------------

_active: Optional[FaultInjector] = None


def install_plan(plan: FaultPlan) -> FaultInjector:
    global _active
    _active = FaultInjector(plan)
    return _active


def uninstall_plan() -> None:
    global _active
    _active = None


def active_injector() -> Optional[FaultInjector]:
    return _active


def maybe_inject(
    target: str, op: str = "", key: str = ""
) -> Optional[FaultRule]:
    inj = _active
    if inj is None:
        return None
    return inj.intercept(target, op, key=key)


def has_rules(target: str) -> bool:
    """True when an installed plan names any rule for `target`. Call sites
    that must pay extra bookkeeping to make a fault recoverable (the chunked
    commit driver keeps a host copy of the carry only when a device fault
    can actually fire) use this to keep the production path free."""
    inj = _active
    return inj is not None and any(
        r.target == target for r in inj.plan.rules
    )


def snapshot_key(key: str) -> Optional[List[Tuple[int, int]]]:
    """Snapshot `key`'s fault counters (None with no active plan)."""
    inj = _active
    return None if inj is None else inj.snapshot_key(key)


def restore_key(key: str, snap: Optional[List[Tuple[int, int]]]) -> None:
    """Rewind `key`'s counters to `snap` before re-issuing its sequence
    (no-op with no active plan or a None snapshot)."""
    if snap is None:
        return
    inj = _active
    if inj is not None:
        inj.restore_key(key, snap)


class injected:
    """Context manager: install a plan for the duration of a block."""

    def __init__(self, plan: FaultPlan) -> None:
        self.plan = plan
        self.injector: Optional[FaultInjector] = None

    def __enter__(self) -> FaultInjector:
        self.injector = install_plan(self.plan)
        return self.injector

    def __exit__(self, *exc) -> None:
        uninstall_plan()


# ---------------------------------------------------------------------------
# Fault application helpers (shared by the HTTP transports and the chart
# renderer so every call site maps kinds to behavior the same way).
# ---------------------------------------------------------------------------

def apply_http_fault(rule: FaultRule, url: str) -> Optional[bytes]:
    """Raise the rule's fault as the exception the real transport would see,
    or return a replacement response body (malformed_json). latency sleeps
    and returns None so the real call proceeds afterwards."""
    import time as _time

    if rule.kind == "latency":
        if rule.latency_s > 0:
            _time.sleep(rule.latency_s)
        return None
    if rule.kind == "connection_error":
        raise urllib.error.URLError("injected by fault plan: connection refused")
    if rule.kind == "http_error":
        body = (rule.body or "injected by fault plan").encode()
        raise urllib.error.HTTPError(
            url, rule.status, "injected by fault plan", None,  # type: ignore[arg-type]
            io.BytesIO(body),
        )
    if rule.kind == "malformed_json":
        return (rule.body or '{"truncated": ').encode()
    # generic "error" behaves like a connection error on HTTP targets
    raise urllib.error.URLError("injected by fault plan: error")


def apply_chart_fault(rule: FaultRule, what: str) -> None:
    """Chart rendering has no transport: latency sleeps, every error kind
    degrades to a ChartError (the apply layer records a per-app failure)."""
    import time as _time

    if rule.kind == "latency":
        if rule.latency_s > 0:
            _time.sleep(rule.latency_s)
        return
    from ..utils.chart import ChartError

    raise ChartError(f"injected by fault plan ({rule.kind}) rendering {what}")


def apply_backend_fault(rule: FaultRule) -> None:
    """Backend acquisition faults reproduce the observed wedge modes:
    latency simulates the r03–r05 tunnel hang (the watchdog must fire),
    every other kind is an immediate init failure."""
    import time as _time

    if rule.kind == "latency":
        if rule.latency_s > 0:
            _time.sleep(rule.latency_s)
        return
    if rule.kind == "kill":
        os.kill(os.getpid(), 9)
    raise RuntimeError(f"injected by fault plan ({rule.kind}): backend init failed")


def apply_device_fault(rule: FaultRule) -> None:
    """Device faults model accelerator churn against the chunked commit
    driver (ops/fast.py). `chunk_kill` SIGKILLs the process *before* the
    chunk's `plan_chunk` record is journaled — the deterministic mid-plan
    preemption the crash-resume smoke kills with; `device_lost` raises
    DeviceLostError as if the backend dropped the resident carry, which the
    driver recovers from its last good host snapshot (degraded, not
    failed). Other kinds degrade to DeviceLostError too."""
    import time as _time

    if rule.kind == "latency":
        if rule.latency_s > 0:
            _time.sleep(rule.latency_s)
        return
    if rule.kind in ("chunk_kill", "kill"):
        os.kill(os.getpid(), 9)
    raise DeviceLostError(
        f"injected by fault plan ({rule.kind}): device lost mid-plan"
    )


def apply_journal_fault(rule: FaultRule) -> None:
    """Journal faults model a dying host. `kill` SIGKILLs the process
    *before* the record is written — the deterministic crash the
    crash-resume smoke uses (the k-th trial is then NOT committed, exactly
    like a preemption between probe and commit). Other error kinds surface
    as an OSError the journal wraps in JournalError."""
    import time as _time

    if rule.kind == "latency":
        if rule.latency_s > 0:
            _time.sleep(rule.latency_s)
        return
    if rule.kind == "kill":
        os.kill(os.getpid(), 9)
    raise OSError(f"injected by fault plan ({rule.kind}): journal write failed")
