"""Resilience layer: retry/backoff policies, circuit breakers, and
deterministic fault injection for every external-I/O path (scheduler
extenders, the apiserver client, chart rendering, the REST server).

See docs/resilience.md for the operator-facing knobs and the fault-plan
YAML schema; `simon chaos` runs an apply under a plan and reports what
degraded vs. what failed.
"""

from .faults import (
    DeviceLostError,
    FaultInjectionError,
    FaultInjector,
    FaultPlan,
    FaultRule,
    active_injector,
    has_rules,
    injected,
    install_plan,
    maybe_inject,
    uninstall_plan,
)
from .policy import (
    CircuitBreaker,
    CircuitOpenError,
    RetryExhaustedError,
    RetryPolicy,
    breaker_for,
    breaker_states,
    reset_breakers,
)

__all__ = [
    "CircuitBreaker",
    "CircuitOpenError",
    "DeviceLostError",
    "FaultInjectionError",
    "FaultInjector",
    "FaultPlan",
    "FaultRule",
    "RetryExhaustedError",
    "RetryPolicy",
    "active_injector",
    "breaker_for",
    "breaker_states",
    "has_rules",
    "injected",
    "install_plan",
    "maybe_inject",
    "reset_breakers",
    "uninstall_plan",
]
