"""Overload-safe serving core: admission, deadlines, coalescing, shedding.

The REST path is the one surface that faces arbitrary client concurrency,
and until this module existed it was single-flight: `server.py` guarded
POST with a non-blocking try-lock, so under N concurrent clients N−1 got
an instant 503 with no queueing, no Retry-After, and no shed accounting.
Production schedulers treat admission as a first-class scheduling
decision; this module is that front door:

* **Bounded admission queue** — POST bodies are enqueued up to
  `OSIM_SERVER_QUEUE_DEPTH` and drained by one dedicated scheduler-worker
  thread (simulate calls stay serialized exactly as under the old lock,
  so the engine sees no new concurrency). When the queue is full the
  request is *shed*: 429 plus a `Retry-After` computed from the observed
  service-time EWMA and the current backlog — an honest "come back in
  N seconds", not a blind 503.

* **Deadline propagation** — an `X-Osim-Deadline-Ms` request header (or
  the `OSIM_SERVER_DEFAULT_DEADLINE_MS` default) rides through the queue
  as an absolute deadline. A request whose deadline passes while it is
  still queued is shed *at dequeue* — cheap, before any simulate work —
  and the remaining budget of requests that do start is handed to the
  simulate call's watchdog (`durable/watchdog.guarded_call`), so a
  deadline can abort a wedged simulate mid-flight (504) instead of
  letting the client hang.

* **Continuous-batching pack** — the queue is drained by the persistent
  scheduler loop (`server/loop.py`): between consecutive device calls,
  whatever compatible tickets are queued are packed into the next
  scenario-batched call. The old fixed coalescing window survives only
  as the *pack window* — an upper bound on how long a partial pack may
  wait for stragglers, never a latency floor (a lone ticket dispatches
  immediately). `OSIM_SERVER_PACK_WINDOW_MS` names it; the legacy
  `OSIM_SERVER_COALESCE_MS` still works as a deprecated alias. Tickets
  with the same coalesce key (body digest + snapshot generation) run as
  ONE entry in the batch executor and the result is fanned back out to
  every waiter.

* **Shed accounting** — `osim_requests_shed_total{reason=queue_full|
  deadline|draining}`, `osim_admission_queue_depth`,
  `osim_coalesced_batch_size`, and a request-latency histogram make
  overload visible; `osim_requests_dropped_total` counts the one failure
  mode that is never acceptable (a waiter abandoned without a response —
  only possible if the worker dies) so `simon chaos` can classify
  shed-with-Retry-After as *degraded* and dropped as *failed*.

Every response is definite: 200 (simulated), 400 (bad request /
simulation error), 429 + Retry-After (shed: queue full or deadline),
503 + Retry-After (shed: draining on SIGTERM), 504 (deadline fired
mid-simulate), or 500 (dropped — worker death, counted and reported).

Tests drive the queue without the worker thread (`run_pending()`) under
an injectable clock, so queue-full/deadline/coalescing behavior is
provable sleep-free — the same idiom as `resilience/policy.py` and
`durable/watchdog.py`.

Fault injection (docs/resilience.md): target `admission`, kinds
`queue_full` / `deadline_storm` (consulted at submit, op "submit") and
`slow_drain` (consulted per drained batch, op "drain").
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

# Re-exported for the scheduler loop (server/loop.py), which resolves
# guarded_call / call_deadline_s / DeadlineExceeded through THIS module's
# namespace so tests monkeypatching `admission.guarded_call` keep
# intercepting the device call.
from ..durable.watchdog import (  # noqa: F401
    DeadlineExceeded,
    call_deadline_s,
    guarded_call,
)
from ..resilience import faults
from ..utils import metrics
from ..utils import tracing
from ..utils.tracing import log

# Serve-time defaults; the env knobs are resolved when the queue is
# constructed (serve()/make_server() time), never at import.
DEFAULT_QUEUE_DEPTH = 16
DEFAULT_COALESCE_MS = 0.0
DEFAULT_DEADLINE_MS = 0.0
#: Retry-After fallback for the zero-sample cold start: before the loop
#: has completed a single iteration there is no observed service time, so
#: the hint is this flat constant WITHOUT backlog scaling (the old code
#: multiplied a made-up 1 s by the backlog, telling the first burst's
#: clients to back off for the full queue depth before anything had run).
DEFAULT_SERVICE_TIME_S = 1.0

# One-time deprecation warning for OSIM_SERVER_COALESCE_MS (kept working
# as the pack-window upper bound; see SchedulerLoop). The flag is read and
# set under the lock because queues are constructed from handler-bearing
# modules.
_deprecation_lock = threading.Lock()
_coalesce_ms_warned = False


def _warn_coalesce_deprecated() -> None:
    global _coalesce_ms_warned
    with _deprecation_lock:
        if _coalesce_ms_warned:
            return
        _coalesce_ms_warned = True
    log.warning(
        "OSIM_SERVER_COALESCE_MS is deprecated: the coalesce window became "
        "the continuous-batching pack window (an upper bound, not a latency "
        "floor). Set OSIM_SERVER_PACK_WINDOW_MS instead; the old variable "
        "keeps working with identical units (docs/serving.md)."
    )

REASON_QUEUE_FULL = "queue_full"
REASON_DEADLINE = "deadline"
REASON_DRAINING = "draining"

#: Shed reason -> HTTP status. queue_full/deadline are client-retryable
#: (429); draining means THIS server is going away (503 + Retry-After).
_SHED_CODE = {
    REASON_QUEUE_FULL: 429,
    REASON_DEADLINE: 429,
    REASON_DRAINING: 503,
}


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        log.warning("%s=%r is not a number; using %g", name, raw, default)
        return default


def _env_int(name: str, default: int) -> int:
    return int(_env_float(name, float(default)))


def coalesce_key(
    path: str,
    body: dict,
    generation: Optional[int] = None,
    stale: bool = False,
) -> str:
    """Stable identity of a request's *work*: two requests with the same key
    would produce byte-identical results, so one simulate pass serves both.
    `generation` folds in the live-snapshot generation for kubeconfig-backed
    requests (the same body against a refreshed snapshot is different work).
    `stale` marks a snapshot served past a failed refresh: the failure does
    not advance the generation, so staleness needs its own key dimension —
    a request admitted while degraded must never share a response with one
    admitted against the same generation served fresh."""
    digest = hashlib.sha256(
        json.dumps(body, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()
    if generation is None:
        return f"{path}:{digest}"
    suffix = ":stale" if stale else ""
    return f"{path}:{digest}:gen{generation}{suffix}"


@dataclass
class Ticket:
    """One admitted (or shed) request. The handler thread blocks on `done`;
    the scheduler worker (or submit itself, for sheds) finalizes it."""

    body: dict
    key: str
    enqueued_at: float
    deadline_at: Optional[float] = None  # absolute, clock() domain
    # live-snapshot generation recorded at admission; None = not fenced.
    # the loop re-keys the ticket at pack time if the fence moved past it.
    fence_epoch: Optional[int] = None
    done: threading.Event = field(default_factory=threading.Event)
    # response (valid once done is set)
    code: int = 0
    payload: Optional[dict] = None
    headers: Dict[str, str] = field(default_factory=dict)
    shed_reason: str = ""
    # Trace context captured on the submitting (handler) thread so the
    # scheduler loop can parent/link its pack span to the request's trace
    # across the queue hop; pack_ctx is filled by the loop at execution
    # time so the handler can link its root span to the pack that served
    # the request (utils/tracing.py, docs/observability.md).
    trace_ctx: Optional[Any] = None
    pack_ctx: Optional[Any] = None

    def remaining_s(self, now: float) -> Optional[float]:
        if self.deadline_at is None:
            return None
        return self.deadline_at - now


class AdmissionQueue:
    """Bounded admission queue drained by the continuous-batching scheduler
    loop (server/loop.py).

    `execute` is the batch executor: it receives the pack's UNIQUE bodies
    (one per coalesce key, in arrival order) and returns one result per
    body — a payload dict, or an Exception instance for a per-body
    failure. All other parameters default from the environment at
    construction time (never import time):

        OSIM_SERVER_QUEUE_DEPTH         max queued requests (beyond the
                                        pack being executed)
        OSIM_SERVER_PACK_WINDOW_MS      upper bound on how long a PARTIAL
                                        pack waits for stragglers; 0
                                        disables (never a latency floor)
        OSIM_SERVER_COALESCE_MS         deprecated alias of the pack
                                        window (same units; warns once)
        OSIM_SERVER_DEFAULT_DEADLINE_MS deadline for requests that carry
                                        no X-Osim-Deadline-Ms; 0 = none

    `service_time_s` seeds the loop-iteration EWMA behind Retry-After;
    None (the default) starts with zero samples — sheds before the first
    completed iteration answer a flat DEFAULT_SERVICE_TIME_S hint instead
    of a backlog multiple of a constant nobody measured.

    `clock` and `watchdog_poll_s` are injectable so tests prove deadline
    and shed behavior without sleeping.
    """

    def __init__(
        self,
        execute: Callable[[List[dict]], List[Any]],
        *,
        depth: Optional[int] = None,
        coalesce_ms: Optional[float] = None,
        pack_window_ms: Optional[float] = None,
        pack_lanes: Optional[int] = None,
        default_deadline_ms: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        service_time_s: Optional[float] = None,
        watchdog_poll_s: float = 0.25,
        fence: Optional[Callable[[], int]] = None,
    ) -> None:
        self._execute = execute
        # Generation fence (engine/resident.py): called once per PACK at
        # pack-take time; fenced tickets whose recorded epoch differs are
        # re-keyed so they only coalesce with same-state work
        # (docs/serving.md).
        self._fence = fence
        self.depth = (
            depth
            if depth is not None
            else _env_int("OSIM_SERVER_QUEUE_DEPTH", DEFAULT_QUEUE_DEPTH)
        )
        if self.depth < 1:
            raise ValueError(f"queue depth must be >= 1, got {self.depth}")
        # Pack window resolution: explicit pack_window_ms wins, then the
        # legacy coalesce_ms parameter, then OSIM_SERVER_PACK_WINDOW_MS,
        # then the deprecated OSIM_SERVER_COALESCE_MS (with a one-time
        # warning). The attribute keeps its historical name — it is public
        # API for tests and the server.
        if pack_window_ms is not None:
            window_ms = float(pack_window_ms)
        elif coalesce_ms is not None:
            window_ms = float(coalesce_ms)
        elif os.environ.get("OSIM_SERVER_PACK_WINDOW_MS", "").strip():
            window_ms = _env_float(
                "OSIM_SERVER_PACK_WINDOW_MS", DEFAULT_COALESCE_MS
            )
        else:
            if os.environ.get("OSIM_SERVER_COALESCE_MS", "").strip():
                _warn_coalesce_deprecated()
            window_ms = _env_float(
                "OSIM_SERVER_COALESCE_MS", DEFAULT_COALESCE_MS
            )
        self.coalesce_s = window_ms / 1000.0
        self.default_deadline_ms = (
            default_deadline_ms
            if default_deadline_ms is not None
            else _env_float("OSIM_SERVER_DEFAULT_DEADLINE_MS", DEFAULT_DEADLINE_MS)
        )
        self._clock = clock
        self._poll_s = watchdog_poll_s
        self._cv = threading.Condition()
        self._queue: List[Ticket] = []
        self._draining = False
        # Loop-iteration EWMA (seconds per iteration); None = no samples.
        self._service_time_s: Optional[float] = (
            max(float(service_time_s), 0.001)
            if service_time_s is not None
            else None
        )
        self._worker: Optional[threading.Thread] = None
        from .loop import SchedulerLoop  # local: loop.py imports this module

        self._loop = SchedulerLoop(self, pack_lanes=pack_lanes)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "AdmissionQueue":
        self._worker = threading.Thread(
            target=self._worker_main, name="osim-scheduler-loop", daemon=True
        )
        self._worker.start()
        return self

    def shutdown(self) -> None:
        """Begin draining: shed everything still QUEUED (reason=draining,
        those clients should retry elsewhere) and let the batch already
        executing complete and respond. Idempotent."""
        with self._cv:
            self._draining = True
            for t in self._queue:
                self._shed_locked(t, REASON_DRAINING)
            self._queue.clear()
            metrics.ADMISSION_QUEUE_DEPTH.set(0)
            self._cv.notify_all()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._worker is not None:
            self._worker.join(timeout)

    @property
    def draining(self) -> bool:
        return self._draining

    # -- submit / wait (handler-thread side) --------------------------------

    def submit(
        self,
        body: dict,
        *,
        key: Optional[str] = None,
        deadline_ms: Optional[float] = None,
        op: str = "submit",
        fence_epoch: Optional[int] = None,
        trace_ctx: Optional[Any] = None,
    ) -> Ticket:
        """Admit, or immediately shed, one request. Never blocks.
        `fence_epoch` is the live-snapshot generation the caller keyed the
        request under (None = the request is not generation-dependent).
        `trace_ctx` pins the trace the ticket belongs to; defaults to the
        calling thread's current trace context, so the queue hop to the
        scheduler loop does not sever the request's trace."""
        now = self._clock()
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        if trace_ctx is None:
            trace_ctx = tracing.current_context()
        ticket = Ticket(
            body=body,
            key=key if key is not None else coalesce_key("", body),
            enqueued_at=now,
            deadline_at=(now + deadline_ms / 1000.0) if deadline_ms > 0 else None,
            fence_epoch=fence_epoch,
            trace_ctx=trace_ctx,
        )
        rule = faults.maybe_inject("admission", op)
        with self._cv:
            if self._draining:
                self._shed_locked(ticket, REASON_DRAINING)
                return ticket
            if rule is not None and rule.kind == "queue_full":
                self._shed_locked(ticket, REASON_QUEUE_FULL)
                return ticket
            if rule is not None and rule.kind == "deadline_storm":
                # every request arrives with its deadline already spent —
                # must be shed at dequeue without entering a simulate call
                ticket.deadline_at = now
            if len(self._queue) >= self.depth:
                self._shed_locked(ticket, REASON_QUEUE_FULL)
                return ticket
            self._queue.append(ticket)
            metrics.ADMISSION_QUEUE_DEPTH.set(len(self._queue))
            self._cv.notify_all()
        return ticket

    def wait(self, ticket: Ticket, poll_s: float = 1.0) -> Ticket:
        """Block the handler thread until the ticket is finalized. If the
        worker dies with the ticket unfinalized (the only way a request
        could be silently dropped), answer 500 and count it dropped."""
        while not ticket.done.wait(poll_s):
            worker = self._worker
            if worker is not None and not worker.is_alive():
                self._drop(ticket)
                break
        return ticket

    # -- the scheduler-loop thread ------------------------------------------

    def worker_alive(self) -> bool:
        """Whether the scheduler-loop thread is running. The HTTP layer
        consults this before submit to take the per-request degradation
        path (docs/serving.md) instead of queueing behind a dead loop."""
        w = self._worker
        return w is not None and w.is_alive()

    def _worker_main(self) -> None:
        """Thread body: the continuous-batching loop (server/loop.py) plus
        crash containment — a dying loop drains every queued ticket as
        dropped (counted; the one unacceptable outcome) instead of leaving
        waiters hanging."""
        try:
            self._loop.run_forever()
        except BaseException:  # pragma: no cover - loop must never die silently
            log.exception("scheduler loop crashed; draining queue as dropped")
            with self._cv:
                for t in self._queue:
                    self._drop(t)
                self._queue.clear()
                metrics.ADMISSION_QUEUE_DEPTH.set(0)
            raise

    def run_pending(self) -> int:
        """Test/embedding hook: synchronously process everything queued NOW
        (no window waiting, no loop thread). Returns packs processed."""
        n = 0
        while True:
            with self._cv:
                batch = list(self._queue)
                self._queue.clear()
                metrics.ADMISSION_QUEUE_DEPTH.set(0)
            if not batch:
                return n
            self._loop.run_pack(batch)
            n += 1

    def _note_iteration(self, elapsed: float) -> None:
        """Fold one observed loop-iteration duration into the Retry-After
        EWMA. Called by the loop for EVERY iteration (even all-shed ones):
        the hint must track what an iteration costs under current load."""
        metrics.LOOP_ITERATION.observe(elapsed)
        with self._cv:
            if self._service_time_s is None:
                self._service_time_s = max(elapsed, 0.001)
            else:
                self._service_time_s = max(
                    0.3 * elapsed + 0.7 * self._service_time_s, 0.001
                )

    # -- finalization -------------------------------------------------------

    def _retry_hint_locked(self) -> int:
        """Honest backoff hint (seconds, >= 1): observed loop-iteration
        EWMA x backlog — with continuous batching the backlog drains pack
        by pack, so iterations-to-drain scales with how many tickets sit
        ahead. Zero-sample cold start (no iteration observed yet) answers
        the flat DEFAULT_SERVICE_TIME_S instead of backlog x guess."""
        if self._service_time_s is None:
            return max(1, int(math.ceil(DEFAULT_SERVICE_TIME_S)))
        backlog = len(self._queue) + 1
        return max(1, int(math.ceil(self._service_time_s * backlog)))

    def retry_after_s(self) -> int:
        with self._cv:
            return self._retry_hint_locked()

    def _shed_locked(self, ticket: Ticket, reason: str) -> None:
        self._finalize(
            ticket,
            _SHED_CODE[reason],
            {
                "error": f"request shed: {reason.replace('_', ' ')}",
                "reason": reason,
            },
            headers={"Retry-After": str(self._retry_hint_locked())},
            shed_reason=reason,
        )

    def _shed(self, ticket: Ticket, reason: str) -> None:
        with self._cv:
            self._shed_locked(ticket, reason)

    def _drop(self, ticket: Ticket) -> None:
        if ticket.done.is_set():
            return
        metrics.REQUESTS_DROPPED.inc()
        self._finalize(
            ticket, 500, {"error": "request dropped: scheduler worker died"}
        )

    def _finalize(
        self,
        ticket: Ticket,
        code: int,
        payload: dict,
        headers: Optional[Dict[str, str]] = None,
        shed_reason: str = "",
    ) -> None:
        if ticket.done.is_set():
            return
        ticket.code = code
        ticket.payload = payload
        if headers:
            ticket.headers.update(headers)
        ticket.shed_reason = shed_reason
        if shed_reason:
            metrics.REQUESTS_SHED.inc(reason=shed_reason)
        metrics.REQUEST_LATENCY.observe(
            max(self._clock() - ticket.enqueued_at, 0.0)
        )
        ticket.done.set()
