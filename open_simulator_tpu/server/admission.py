"""Overload-safe serving core: admission, deadlines, coalescing, shedding.

The REST path is the one surface that faces arbitrary client concurrency,
and until this module existed it was single-flight: `server.py` guarded
POST with a non-blocking try-lock, so under N concurrent clients N−1 got
an instant 503 with no queueing, no Retry-After, and no shed accounting.
Production schedulers treat admission as a first-class scheduling
decision; this module is that front door:

* **Bounded admission queue** — POST bodies are enqueued up to
  `OSIM_SERVER_QUEUE_DEPTH` and drained by one dedicated scheduler-worker
  thread (simulate calls stay serialized exactly as under the old lock,
  so the engine sees no new concurrency). When the queue is full the
  request is *shed*: 429 plus a `Retry-After` computed from the observed
  service-time EWMA and the current backlog — an honest "come back in
  N seconds", not a blind 503.

* **Deadline propagation** — an `X-Osim-Deadline-Ms` request header (or
  the `OSIM_SERVER_DEFAULT_DEADLINE_MS` default) rides through the queue
  as an absolute deadline. A request whose deadline passes while it is
  still queued is shed *at dequeue* — cheap, before any simulate work —
  and the remaining budget of requests that do start is handed to the
  simulate call's watchdog (`durable/watchdog.guarded_call`), so a
  deadline can abort a wedged simulate mid-flight (504) instead of
  letting the client hang.

* **Coalescing window** — requests arriving within
  `OSIM_SERVER_COALESCE_MS` of the batch head are drained together;
  requests with the same coalesce key (body digest + snapshot
  generation) run as ONE entry in the batch executor and the result is
  fanned back out to every waiter. The batch executor
  (`execute(bodies) -> results`) is the seam the vmapped multi-scenario
  engine (ROADMAP item 1) will slot into; today it loops.

* **Shed accounting** — `osim_requests_shed_total{reason=queue_full|
  deadline|draining}`, `osim_admission_queue_depth`,
  `osim_coalesced_batch_size`, and a request-latency histogram make
  overload visible; `osim_requests_dropped_total` counts the one failure
  mode that is never acceptable (a waiter abandoned without a response —
  only possible if the worker dies) so `simon chaos` can classify
  shed-with-Retry-After as *degraded* and dropped as *failed*.

Every response is definite: 200 (simulated), 400 (bad request /
simulation error), 429 + Retry-After (shed: queue full or deadline),
503 + Retry-After (shed: draining on SIGTERM), 504 (deadline fired
mid-simulate), or 500 (dropped — worker death, counted and reported).

Tests drive the queue without the worker thread (`run_pending()`) under
an injectable clock, so queue-full/deadline/coalescing behavior is
provable sleep-free — the same idiom as `resilience/policy.py` and
`durable/watchdog.py`.

Fault injection (docs/resilience.md): target `admission`, kinds
`queue_full` / `deadline_storm` (consulted at submit, op "submit") and
`slow_drain` (consulted per drained batch, op "drain").
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..durable.watchdog import DeadlineExceeded, call_deadline_s, guarded_call
from ..resilience import faults
from ..utils import metrics
from ..utils.tracing import log

# Serve-time defaults; the env knobs are resolved when the queue is
# constructed (serve()/make_server() time), never at import.
DEFAULT_QUEUE_DEPTH = 16
DEFAULT_COALESCE_MS = 0.0
DEFAULT_DEADLINE_MS = 0.0
DEFAULT_SERVICE_TIME_S = 1.0

REASON_QUEUE_FULL = "queue_full"
REASON_DEADLINE = "deadline"
REASON_DRAINING = "draining"

#: Shed reason -> HTTP status. queue_full/deadline are client-retryable
#: (429); draining means THIS server is going away (503 + Retry-After).
_SHED_CODE = {
    REASON_QUEUE_FULL: 429,
    REASON_DEADLINE: 429,
    REASON_DRAINING: 503,
}


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        log.warning("%s=%r is not a number; using %g", name, raw, default)
        return default


def _env_int(name: str, default: int) -> int:
    return int(_env_float(name, float(default)))


def coalesce_key(
    path: str,
    body: dict,
    generation: Optional[int] = None,
    stale: bool = False,
) -> str:
    """Stable identity of a request's *work*: two requests with the same key
    would produce byte-identical results, so one simulate pass serves both.
    `generation` folds in the live-snapshot generation for kubeconfig-backed
    requests (the same body against a refreshed snapshot is different work).
    `stale` marks a snapshot served past a failed refresh: the failure does
    not advance the generation, so staleness needs its own key dimension —
    a request admitted while degraded must never share a response with one
    admitted against the same generation served fresh."""
    digest = hashlib.sha256(
        json.dumps(body, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()
    if generation is None:
        return f"{path}:{digest}"
    suffix = ":stale" if stale else ""
    return f"{path}:{digest}:gen{generation}{suffix}"


@dataclass
class Ticket:
    """One admitted (or shed) request. The handler thread blocks on `done`;
    the scheduler worker (or submit itself, for sheds) finalizes it."""

    body: dict
    key: str
    enqueued_at: float
    deadline_at: Optional[float] = None  # absolute, clock() domain
    # live-snapshot generation recorded at admission; None = not fenced.
    # _run_batch re-keys the ticket if the queue's fence moved past it.
    fence_epoch: Optional[int] = None
    done: threading.Event = field(default_factory=threading.Event)
    # response (valid once done is set)
    code: int = 0
    payload: Optional[dict] = None
    headers: Dict[str, str] = field(default_factory=dict)
    shed_reason: str = ""

    def remaining_s(self, now: float) -> Optional[float]:
        if self.deadline_at is None:
            return None
        return self.deadline_at - now


class AdmissionQueue:
    """Bounded admission queue drained by one scheduler worker thread.

    `execute` is the batch executor: it receives the drained batch's
    UNIQUE bodies (one per coalesce key, in arrival order) and returns one
    result per body — a payload dict, or an Exception instance for a
    per-body failure. All other parameters default from the environment at
    construction time (never import time):

        OSIM_SERVER_QUEUE_DEPTH         max queued requests (beyond the
                                        batch being executed)
        OSIM_SERVER_COALESCE_MS         micro-batching window; 0 disables
        OSIM_SERVER_DEFAULT_DEADLINE_MS deadline for requests that carry
                                        no X-Osim-Deadline-Ms; 0 = none

    `clock` and `watchdog_poll_s` are injectable so tests prove deadline
    and shed behavior without sleeping.
    """

    def __init__(
        self,
        execute: Callable[[List[dict]], List[Any]],
        *,
        depth: Optional[int] = None,
        coalesce_ms: Optional[float] = None,
        default_deadline_ms: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        service_time_s: float = DEFAULT_SERVICE_TIME_S,
        watchdog_poll_s: float = 0.25,
        fence: Optional[Callable[[], int]] = None,
    ) -> None:
        self._execute = execute
        # Generation fence (engine/resident.py): called once per batch at
        # dequeue; fenced tickets whose recorded epoch differs are re-keyed
        # so they can only coalesce with same-state work (docs/serving.md).
        self._fence = fence
        self.depth = (
            depth
            if depth is not None
            else _env_int("OSIM_SERVER_QUEUE_DEPTH", DEFAULT_QUEUE_DEPTH)
        )
        if self.depth < 1:
            raise ValueError(f"queue depth must be >= 1, got {self.depth}")
        self.coalesce_s = (
            coalesce_ms
            if coalesce_ms is not None
            else _env_float("OSIM_SERVER_COALESCE_MS", DEFAULT_COALESCE_MS)
        ) / 1000.0
        self.default_deadline_ms = (
            default_deadline_ms
            if default_deadline_ms is not None
            else _env_float("OSIM_SERVER_DEFAULT_DEADLINE_MS", DEFAULT_DEADLINE_MS)
        )
        self._clock = clock
        self._poll_s = watchdog_poll_s
        self._cv = threading.Condition()
        self._queue: List[Ticket] = []
        self._draining = False
        self._service_time_s = max(float(service_time_s), 0.001)
        self._worker: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "AdmissionQueue":
        self._worker = threading.Thread(
            target=self._worker_loop, name="osim-admission-worker", daemon=True
        )
        self._worker.start()
        return self

    def shutdown(self) -> None:
        """Begin draining: shed everything still QUEUED (reason=draining,
        those clients should retry elsewhere) and let the batch already
        executing complete and respond. Idempotent."""
        with self._cv:
            self._draining = True
            for t in self._queue:
                self._shed_locked(t, REASON_DRAINING)
            self._queue.clear()
            metrics.ADMISSION_QUEUE_DEPTH.set(0)
            self._cv.notify_all()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._worker is not None:
            self._worker.join(timeout)

    @property
    def draining(self) -> bool:
        return self._draining

    # -- submit / wait (handler-thread side) --------------------------------

    def submit(
        self,
        body: dict,
        *,
        key: Optional[str] = None,
        deadline_ms: Optional[float] = None,
        op: str = "submit",
        fence_epoch: Optional[int] = None,
    ) -> Ticket:
        """Admit, or immediately shed, one request. Never blocks.
        `fence_epoch` is the live-snapshot generation the caller keyed the
        request under (None = the request is not generation-dependent)."""
        now = self._clock()
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        ticket = Ticket(
            body=body,
            key=key if key is not None else coalesce_key("", body),
            enqueued_at=now,
            deadline_at=(now + deadline_ms / 1000.0) if deadline_ms > 0 else None,
            fence_epoch=fence_epoch,
        )
        rule = faults.maybe_inject("admission", op)
        with self._cv:
            if self._draining:
                self._shed_locked(ticket, REASON_DRAINING)
                return ticket
            if rule is not None and rule.kind == "queue_full":
                self._shed_locked(ticket, REASON_QUEUE_FULL)
                return ticket
            if rule is not None and rule.kind == "deadline_storm":
                # every request arrives with its deadline already spent —
                # must be shed at dequeue without entering a simulate call
                ticket.deadline_at = now
            if len(self._queue) >= self.depth:
                self._shed_locked(ticket, REASON_QUEUE_FULL)
                return ticket
            self._queue.append(ticket)
            metrics.ADMISSION_QUEUE_DEPTH.set(len(self._queue))
            self._cv.notify_all()
        return ticket

    def wait(self, ticket: Ticket, poll_s: float = 1.0) -> Ticket:
        """Block the handler thread until the ticket is finalized. If the
        worker dies with the ticket unfinalized (the only way a request
        could be silently dropped), answer 500 and count it dropped."""
        while not ticket.done.wait(poll_s):
            worker = self._worker
            if worker is not None and not worker.is_alive():
                self._drop(ticket)
                break
        return ticket

    # -- the scheduler worker -----------------------------------------------

    def _worker_loop(self) -> None:
        try:
            while True:
                batch = self._collect_batch()
                if batch is None:
                    return
                self._run_batch(batch)
        except BaseException:  # pragma: no cover - worker must never die silently
            log.exception("admission worker crashed; draining queue as dropped")
            with self._cv:
                for t in self._queue:
                    self._drop(t)
                self._queue.clear()
                metrics.ADMISSION_QUEUE_DEPTH.set(0)
            raise

    def _collect_batch(self) -> Optional[List[Ticket]]:
        """Wait for work, hold the coalescing window open, then take the
        whole backlog as one batch. Returns None when drained out."""
        with self._cv:
            while not self._queue and not self._draining:
                self._cv.wait()
            if not self._queue:  # draining and empty
                return None
            if self.coalesce_s > 0:
                head = self._queue[0]
                window_end = head.enqueued_at + self.coalesce_s
                while not self._draining:
                    remaining = window_end - self._clock()
                    if remaining <= 0 or len(self._queue) >= self.depth:
                        break
                    self._cv.wait(remaining)
            batch = list(self._queue)
            self._queue.clear()
            metrics.ADMISSION_QUEUE_DEPTH.set(0)
            return batch or None

    def run_pending(self) -> int:
        """Test/embedding hook: synchronously process everything queued NOW
        (no window waiting, no worker thread). Returns batches processed."""
        n = 0
        while True:
            with self._cv:
                batch = list(self._queue)
                self._queue.clear()
                metrics.ADMISSION_QUEUE_DEPTH.set(0)
            if not batch:
                return n
            self._run_batch(batch)
            n += 1

    def _run_batch(self, batch: List[Ticket]) -> None:
        now = self._clock()
        # 1. deadline sheds AT DEQUEUE: expired requests never reach execute
        live: List[Ticket] = []
        for t in batch:
            if t.deadline_at is not None and now >= t.deadline_at:
                self._shed(t, REASON_DEADLINE)
            else:
                live.append(t)
        if not live:
            return
        # 2. generation fence AT DEQUEUE: a fenced ticket admitted under
        #    epoch E whose snapshot moved to E' before this batch drained is
        #    re-keyed onto E' — it will be served against the E' state, and
        #    must only coalesce with other E' work. Without this, a ticket
        #    keyed "...:genE" could fan out one result to waiters that were
        #    admitted across a state change (the stale_generation chaos kind
        #    forces the mismatch by returning a sentinel epoch).
        if self._fence is not None and any(t.fence_epoch is not None for t in live):
            current = self._fence()
            for t in live:
                if t.fence_epoch is None:
                    continue
                if t.fence_epoch == current:
                    metrics.ADMISSION_FENCE.inc(outcome="current")
                else:
                    t.key += f"@fence{current}"
                    t.fence_epoch = current
                    metrics.ADMISSION_FENCE.inc(outcome="rekeyed")
        # 3. injected slow drain (models a wedged backend eating the window)
        rule = faults.maybe_inject("admission", "drain")
        if rule is not None and rule.kind == "slow_drain" and rule.latency_s > 0:
            time.sleep(rule.latency_s)
        # 4. coalesce: one executor entry per distinct key, arrival order
        groups: Dict[str, List[Ticket]] = {}
        order: List[str] = []
        for t in live:
            if t.key not in groups:
                groups[t.key] = []
                order.append(t.key)
            groups[t.key].append(t)
        bodies = [groups[k][0].body for k in order]
        # 5. watchdog budget: the most generous live deadline (a stricter
        #    per-request budget would abort shared work other waiters still
        #    have time for); deadline-less waiters fall back to the global
        #    OSIM_CALL_DEADLINE_S (0 = unguarded).
        budgets = [t.remaining_s(now) for t in live]
        budget = call_deadline_s() if any(b is None for b in budgets) else max(budgets)
        t0 = self._clock()
        try:
            results = guarded_call(
                "serve-simulate",
                lambda: self._execute(bodies),
                budget if budget and budget > 0 else 0.0,
                clock=self._clock,
                poll_s=self._poll_s,
            )
            if len(results) != len(bodies):
                raise RuntimeError(
                    f"batch executor returned {len(results)} results "
                    f"for {len(bodies)} bodies"
                )
        except DeadlineExceeded as e:
            for t in live:
                self._finalize(t, 504, {"error": str(e)})
            return
        except Exception as e:  # executor-level failure: every waiter gets a 400
            for t in live:
                self._finalize(t, 400, {"error": str(e)})
            return
        elapsed = max(self._clock() - t0, 0.0)
        # EWMA of per-entry service time feeds Retry-After on future sheds
        per_entry = elapsed / len(bodies)
        with self._cv:
            self._service_time_s = max(
                0.3 * per_entry + 0.7 * self._service_time_s, 0.001
            )
        # 6. fan each group's one result back out to all of its waiters
        for k, res in zip(order, results):
            waiters = groups[k]
            # mode="fanout": N identical requests served by ONE result.
            # (mode="scenarios" — distinct bodies merged into one batched
            # device call — is observed by the executor, which is the layer
            # that knows the scenario grouping; see server._execute_bodies.)
            metrics.COALESCED_BATCH.observe(len(waiters), mode="fanout")
            for t in waiters:
                if isinstance(res, BaseException):
                    self._finalize(t, 400, {"error": str(res)})
                else:
                    self._finalize(t, 200, res)

    # -- finalization -------------------------------------------------------

    def retry_after_s(self) -> int:
        """Honest backoff hint: the backlog's expected drain time under the
        observed per-request service time, floored at 1 s."""
        with self._cv:
            backlog = len(self._queue) + 1
            est = self._service_time_s * backlog
        return max(1, int(math.ceil(est)))

    def _shed_locked(self, ticket: Ticket, reason: str) -> None:
        backlog = len(self._queue) + 1
        est = self._service_time_s * backlog
        self._finalize(
            ticket,
            _SHED_CODE[reason],
            {
                "error": f"request shed: {reason.replace('_', ' ')}",
                "reason": reason,
            },
            headers={"Retry-After": str(max(1, int(math.ceil(est))))},
            shed_reason=reason,
        )

    def _shed(self, ticket: Ticket, reason: str) -> None:
        with self._cv:
            self._shed_locked(ticket, reason)

    def _drop(self, ticket: Ticket) -> None:
        if ticket.done.is_set():
            return
        metrics.REQUESTS_DROPPED.inc()
        self._finalize(
            ticket, 500, {"error": "request dropped: scheduler worker died"}
        )

    def _finalize(
        self,
        ticket: Ticket,
        code: int,
        payload: dict,
        headers: Optional[Dict[str, str]] = None,
        shed_reason: str = "",
    ) -> None:
        if ticket.done.is_set():
            return
        ticket.code = code
        ticket.payload = payload
        if headers:
            ticket.headers.update(headers)
        ticket.shed_reason = shed_reason
        if shed_reason:
            metrics.REQUESTS_SHED.inc(reason=shed_reason)
        metrics.REQUEST_LATENCY.observe(
            max(self._clock() - ticket.enqueued_at, 0.0)
        )
        ticket.done.set()
