"""REST simulation service.

Parity: `/root/reference/pkg/server/server.go` — gin routes
  POST /api/deploy-apps   simulate deploying workloads onto a cluster snapshot
  POST /api/scale-apps    remove a workload's pods, re-simulate at new counts
  GET  /healthz           liveness

The reference guards POST with a TryLock busy-rejection (503 while a
simulation runs); this port upgrades that front door to real admission
control (`server/admission.py`): a bounded queue drained by one
continuous-batching scheduler loop (`server/loop.py`) that packs whatever
compatible tickets are queued into the next batched device call — honest
429 + Retry-After shedding when the queue is full, `X-Osim-Deadline-Ms`
deadline propagation, identical concurrent requests coalesced into one
simulate pass, and weights-only-different requests merged as scenario
lanes served by one warm ScenarioSession (the encode pass and Simulator
construction are paid once per (cluster, apps) key, not per pack). Long
capacity plans run as async jobs (`POST /v1/jobs`) backed by the durable
journal, resumable via `simon runs`. Knobs and semantics: docs/serving.md.

The reference snapshots a live cluster through informers; here the snapshot
comes from the request body, a manifest directory on disk, or — when the
server was started with --kubeconfig — a fresh REST snapshot of the live
cluster per request (CreateClusterResourceFromClient parity). Request schema:

  {
    "cluster": {"objects": [...k8s objects...]} | {"path": "dir"},  # optional
                                     # with --kubeconfig
    "apps":    [{"name": "a", "objects": [...]}],
    "newNodes": [...Node objects...],            # optional
    "removeWorkloads": [{"kind": "Deployment", "name": "x", "namespace": "d"}]
  }
"""

from __future__ import annotations

import json
import os
import signal
import threading
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..core.objects import (
    ANNO_WORKLOAD_KIND,
    ANNO_WORKLOAD_NAME,
    ANNO_WORKLOAD_NAMESPACE,
    Node,
)
from ..engine.simulator import (
    AppResource,
    ClusterResource,
    Scenario,
    ScenarioSession,
    simulate,
    simulate_batch,
)
from ..utils import metrics
from ..utils.concurrency import guarded_by
from ..utils.yamlio import objects_from_directory
from . import admission as admission_mod
from .admission import AdmissionQueue

_kubeconfig: Optional[str] = None  # set by serve()/make_server()
_master: str = ""                  # apiserver URL override (--master)

# Live-cluster snapshot cache (parity: the reference serves every request
# from a SharedInformerFactory with a 30 s resync period, synced once at
# startup — server.go:98-136 — rather than re-listing the apiserver per
# request). The snapshot is re-fetched only when older than _resync_s;
# requests in between reuse it, so per-request latency against a large real
# cluster is simulation-bound, not list-bound. Handler threads read the
# generation while the scheduler worker refreshes, so all access is under
# _snapshot_lock (the old design piggybacked on the POST _busy try-lock,
# which admission control removed).
RESYNC_SECONDS = 30.0
_resync_s = RESYNC_SECONDS
_snapshot_lock = threading.Lock()
_snapshot: Optional[ClusterResource] = None
_snapshot_at = 0.0
_snapshot_fetches = 0  # observability + test hook (NOT the coalesce generation
#                        — that is the resident epoch / (fetches, stale) pair
#                        below, see _snapshot_generation)
_snapshot_stale = False  # last refresh attempt failed; serving cached data
# Device-resident encoded planes for the live snapshot (engine/resident.py):
# created on the first successful fetch when OSIM_RESIDENT is on, delta-synced
# on every refresh, handed to simulate() so live-snapshot requests skip the
# full re-encode. None when no live source or the knob is off.
_resident = None  # Optional[engine.resident.ResidentCluster]

# Warm ScenarioSession cache (engine/simulator.ScenarioSession): one entry per
# (body-minus-weights digest, snapshot generation, stale) key, so consecutive
# packs over the same cluster/apps reuse one encoded Simulator instead of
# re-paying construction + encode per device call — the lane-slot-reuse half
# of continuous batching. Entries are checked out exclusively (busy flag);
# a concurrent second user of the same key falls back to the cold path rather
# than blocking the scheduler loop. Capacity-capped LRU; any session error
# drops the entry (cold path is always correct). OSIM_SERVER_LOOP=0 disables
# the cache entirely (the bench's baseline mode).
_SESSION_CAP = 8
_sessions_lock = threading.Lock()
_sessions: "OrderedDict[tuple, dict]" = OrderedDict()

# Async jobs registry (POST /v1/jobs): job id -> {thread, run_dir, error}.
# The durable state is the run directory's journal (durable/journal.py) —
# this dict only tracks in-process liveness, so a restarted server still
# serves GET /v1/jobs/<id> for journaled runs it never started.
_jobs_lock = threading.Lock()
_jobs: dict = {}

# Per-connection socket read timeout: a slow-loris client trickling a request
# body would otherwise pin a handler thread forever. Body reads that exceed
# it return 408. The OSIM_SERVER_REQUEST_TIMEOUT_S env knob is applied by
# _resolve_env_config() at serve()/make_server() time — NOT at import, so
# setting it after this module is imported still takes effect.
REQUEST_TIMEOUT_S = 30.0

# serve()'s active server, so the SIGTERM/SIGINT handler (and tests) can
# trigger a graceful drain from outside the serve_forever loop.
_current_server: Optional[ThreadingHTTPServer] = None


def _resolve_env_config() -> None:
    """Apply env knobs at serve()/make_server() time (the import-time read
    these replaced silently ignored variables set after import). Only
    overrides when the variable is actually present, so tests that poke the
    module attributes directly keep their values."""
    global REQUEST_TIMEOUT_S, _resync_s
    for env, attr in (
        ("OSIM_SERVER_REQUEST_TIMEOUT_S", "REQUEST_TIMEOUT_S"),
        ("OSIM_SERVER_RESYNC_S", "_resync_s"),
    ):
        raw = os.environ.get(env, "").strip()
        if not raw:
            continue
        try:
            globals()[attr] = float(raw)
        except ValueError:
            from ..utils.tracing import log

            log.warning("%s=%r is not a number; keeping %g", env, raw,
                        globals()[attr])


def _scenario_compat_key(body: dict) -> str:
    """Digest of a request body MINUS its per-scenario `weights` field: two
    bodies with equal compat keys describe the same cluster/apps and differ
    only in score weights, so one batched (vmapped) device call can serve
    both as scenario lanes."""
    import hashlib

    stripped = {k: v for k, v in body.items() if k != "weights"}
    return hashlib.sha256(
        json.dumps(stripped, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()


def _loop_sessions_enabled() -> bool:
    """OSIM_SERVER_LOOP gates the warm-session cache (default on). Resolved
    at call time, not import time, so the bench can flip it per mode."""
    raw = os.environ.get("OSIM_SERVER_LOOP", "").strip().lower()
    return raw not in ("0", "false", "off", "no")


def _session_key_for(body: dict) -> Optional[tuple]:
    """Cache key for a warm ScenarioSession serving this body, or None when
    the body cannot be session-backed: a `path` cluster reads a directory
    whose contents may change between packs (no identity to key on). Live
    bodies fold in the snapshot (generation, stale) pair — a refresh moves
    the key, so a session never outlives the snapshot it encoded — and the
    key computation touches _live_snapshot() first so the resync clock still
    ticks even when every request is served warm."""
    spec = body.get("cluster") or {}
    if "path" in spec:
        return None
    digest = _scenario_compat_key(body)
    if spec.get("objects") or not (_kubeconfig or _master):
        # body fully describes the cluster: deterministic under any epoch
        return digest, None, None
    try:
        _live_snapshot()
    except Exception:
        return None  # cold path owns the error attribution
    gen, stale = _snapshot_generation()
    return digest, gen, stale


def _checkout_session(key: tuple):
    """(session, may_create): the cached session marked busy, or (None, True)
    when absent (caller may create one), or (None, False) when another
    thread holds it (caller falls back cold rather than waiting)."""
    with _sessions_lock:
        ent = _sessions.get(key)
        if ent is None:
            return None, True
        if ent["busy"]:
            return None, False
        ent["busy"] = True
        _sessions.move_to_end(key)
        return ent["session"], False


def _checkin_session(key: tuple, session, *, keep: bool) -> None:
    """Return a checked-out (or freshly created) session to the cache.
    keep=False drops it — any run error or batched-path refusal invalidates
    the warm state. A concurrent creator that lost the key race discards its
    session silently; the winner's entry stays."""
    with _sessions_lock:
        ent = _sessions.get(key)
        if ent is not None and ent.get("session") is not session:
            return
        if not keep:
            if ent is not None:
                del _sessions[key]
            return
        if ent is None:
            _sessions[key] = {"session": session, "busy": False}
        else:
            ent["busy"] = False
        _sessions.move_to_end(key)
        while len(_sessions) > _SESSION_CAP:
            victim = next(
                (k for k, e in _sessions.items() if not e["busy"]), None
            )
            if victim is None:
                break
            del _sessions[victim]


def _run_scenarios_warm(
    body: dict, cluster, apps, scenarios, resident
) -> Optional[list]:
    """Serve a scenario group through the warm-session cache; returns
    formatted per-body results, or None to fall back to the cold path
    (disabled, unkeyable body, session busy, run refused, or any error)."""
    if not _loop_sessions_enabled():
        return None
    key = _session_key_for(body)
    if key is None:
        return None
    sess, may_create = _checkout_session(key)
    if sess is None:
        if not may_create:
            return None
        try:
            sess = ScenarioSession(cluster, apps, resident=resident)
        except Exception:
            from ..utils.tracing import log

            log.warning(
                "warm session creation failed; serving cold", exc_info=True
            )
            return None
    try:
        results = sess.run(scenarios)
    except Exception:
        from ..utils.tracing import log

        log.warning(
            "warm session run failed; dropping session and serving cold",
            exc_info=True,
        )
        _checkin_session(key, sess, keep=False)
        return None
    if results is None:  # batch-ineligible workload: cold path handles it
        _checkin_session(key, sess, keep=False)
        return None
    _checkin_session(key, sess, keep=True)
    return [_format_result(r) for r in results]


def _execute_bodies(bodies: list) -> list:
    """Admission-queue batch executor. Bodies that differ only in their
    `weights` field (same cluster/apps — see _scenario_compat_key) are
    merged into ONE batched device call through the vmapped multi-scenario
    engine (simulate_batch), observed as
    osim_coalesced_batch_size{mode="scenarios"}; everything else runs one
    simulate pass per body. Per-body failures are returned as the Exception
    (the queue fans it out as a 400 to that key's waiters only) — a batched
    group that fails re-runs serially so errors stay attributed per body.
    Resolves _simulate_request/_simulate_scenario_group through module
    globals at call time so tests can monkeypatch them."""
    groups: dict = {}
    order: list = []
    for i, body in enumerate(bodies):
        key = _scenario_compat_key(body)
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(i)
    results: list = [None] * len(bodies)
    for key in order:
        idxs = groups[key]
        if len(idxs) >= 2:
            try:
                outs = _simulate_scenario_group([bodies[i] for i in idxs])
                for i, out in zip(idxs, outs):
                    results[i] = out
                continue
            except Exception:
                from ..utils.tracing import log

                log.warning(
                    "batched scenario group of %d failed; re-running "
                    "serially for per-body error attribution", len(idxs),
                    exc_info=True,
                )
        for i in idxs:
            try:
                results[i] = _simulate_request(bodies[i])
            except Exception as e:
                results[i] = e
    return results


class _DrainingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer whose server_close() actually drains.

    socketserver only tracks non-daemon handler threads for the
    block_on_close join, and ThreadingHTTPServer marks handlers daemonic —
    so a plain server_close() would drop in-flight requests on the floor.
    Non-daemon handlers make the close a real drain: every request already
    being computed completes and its response is sent before the process
    exits. The per-socket REQUEST_TIMEOUT_S bounds how long a wedged or idle
    keep-alive client can stall that drain.

    Owns the AdmissionQueue: close first sheds everything still queued
    (reason=draining, 503 + Retry-After), then joins handler threads — the
    in-flight batch finishes and its waiters get real responses."""

    daemon_threads = False
    # socketserver's default TCP accept backlog is 5: a concurrent burst
    # larger than that gets kernel-level connection resets BEFORE admission
    # control can answer with an honest 429. The queue's shed path is the
    # only overload response allowed to reject a client, so the backlog
    # must comfortably exceed any burst the admission queue is sized for.
    request_queue_size = 128

    def __init__(
        self,
        addr,
        handler,
        *,
        queue_depth: Optional[int] = None,
        coalesce_ms: Optional[float] = None,
        pack_window_ms: Optional[float] = None,
        default_deadline_ms: Optional[float] = None,
    ) -> None:
        super().__init__(addr, handler)
        self.admission = AdmissionQueue(
            _execute_bodies,
            depth=queue_depth,
            coalesce_ms=coalesce_ms,
            pack_window_ms=pack_window_ms,
            default_deadline_ms=default_deadline_ms,
            # generation fence: tickets stamped with a live-snapshot epoch at
            # submit are re-keyed at dequeue if the epoch moved (resident
            # delta / snapshot refresh) — a coalesced batch can never mix
            # requests that saw different cluster states
            fence=_fence_epoch,
        ).start()

    def server_close(self) -> None:
        self.admission.shutdown()   # queued work -> 503 draining + Retry-After
        super().server_close()      # joins in-flight handler threads
        self.admission.join(timeout=5.0)


def _snapshot_generation() -> tuple:
    """Identity of the cached live snapshot as (generation, stale), folded
    into coalesce keys so identical bodies against different snapshots are
    never merged. The generation is the resident epoch when a resident
    exists — globally monotonic, never reused across re-serves — with
    _snapshot_fetches as the fallback when OSIM_RESIDENT=0. `stale` marks a
    snapshot being served past a failed refresh (_refresh_snapshot_locked's
    degradation path): the refresh failure does NOT advance the generation,
    so without the flag a body admitted just before the apiserver flapped
    would coalesce with one admitted just after — same data, but the stale
    response carries degraded-mode semantics the fresh one must not inherit."""
    with _snapshot_lock:
        gen = _resident.fence_epoch() if _resident is not None else _snapshot_fetches
        return gen, _snapshot_stale


def _fence_epoch() -> int:
    """Dequeue-side fence value for the admission queue (see
    AdmissionQueue fence=): the current generation only, staleness has its
    own key dimension."""
    return _snapshot_generation()[0]


def _coalesce_key_for(path: str, body: dict) -> tuple:
    """(coalesce key, fence epoch) for one request. Only live-snapshot bodies
    are generation-keyed and fenced (fence_epoch=None for the rest: a body
    that carries its own cluster produces the same bytes under any epoch, so
    re-keying it at dequeue would only split a valid coalesce)."""
    spec = body.get("cluster") or {}
    uses_live = (
        "path" not in spec
        and not spec.get("objects")
        and bool(_kubeconfig or _master)
    )
    if not uses_live:
        return admission_mod.coalesce_key(path, body), None
    gen, stale = _snapshot_generation()
    return admission_mod.coalesce_key(path, body, generation=gen, stale=stale), gen


def _live_snapshot() -> ClusterResource:
    """Cached kubeconfig/master-backed cluster snapshot. Returns a fresh
    ClusterResource wrapper over shared immutable objects: request handling
    appends newNodes / filters pods on the wrapper's lists, and simulate()
    deep-copies every pod it mutates, so sharing Node/Pod objects across
    requests is safe."""
    with _snapshot_lock:
        return _refresh_snapshot_locked()


@guarded_by("_snapshot_lock")
def _refresh_snapshot_locked() -> ClusterResource:
    import time

    global _snapshot, _snapshot_at, _snapshot_fetches, _snapshot_stale
    global _resident
    now = time.monotonic()
    if _snapshot is None or now - _snapshot_at > _resync_s:
        from ..engine.resident import ResidentCluster, resident_enabled
        from ..utils.kubeclient import (
            KubeClientError,
            create_cluster_resource_from_kubeconfig,
        )

        try:
            _snapshot = create_cluster_resource_from_kubeconfig(
                _kubeconfig or "", master=_master
            )
            _snapshot_at = now
            _snapshot_fetches += 1
            _snapshot_stale = False
            # Keep the device-resident planes in lockstep with the cache:
            # most refreshes land as row deltas, structural changes or drift
            # degrade to a full re-encode inside sync() (engine/resident.py).
            # sync() with the knob off keeps the state machine honest about a
            # mid-run OSIM_RESIDENT=0 flip (counted as a "disabled" repair).
            if _resident is None and resident_enabled():
                _resident = ResidentCluster()
            if _resident is not None:
                _resident.sync(_snapshot.nodes, _snapshot.pods)
        except KubeClientError as e:
            if _snapshot is None:
                raise  # nothing cached to degrade to
            # Graceful degradation: a failed refresh serves the stale cached
            # snapshot instead of failing the request (the reference's
            # informer cache behaves the same way when the apiserver flaps).
            # _snapshot_at is left unchanged so the next request retries the
            # refresh immediately; _snapshot_stale stamps the staleness into
            # coalesce keys (_snapshot_generation) so degraded responses
            # never merge with fresh ones.
            from ..utils.tracing import log

            _snapshot_stale = True
            metrics.SNAPSHOT_STALE.inc()
            log.warning(
                "cluster snapshot refresh failed (%s); serving stale "
                "snapshot (age %.0fs)", e, now - _snapshot_at,
            )
    c = _snapshot
    return ClusterResource(
        nodes=list(c.nodes),
        pods=list(c.pods),
        daemonsets=list(c.daemonsets),
        others={k: list(v) for k, v in c.others.items()},
    )


def _request_cluster_apps(body: dict):
    """Decode one request body into (cluster, apps) — shared by the serial
    per-body path and the batched scenario-group path."""
    cluster_spec = body.get("cluster") or {}
    if "path" in cluster_spec:
        objs = objects_from_directory(cluster_spec["path"])
        cluster = ClusterResource.from_objects(objs)
    elif cluster_spec.get("objects"):
        cluster = ClusterResource.from_objects(list(cluster_spec["objects"]))
    elif _kubeconfig or _master:
        cluster = _live_snapshot()
    else:
        cluster = ClusterResource.from_objects([])
    for nd in body.get("newNodes") or []:
        cluster.nodes.append(Node.from_dict(nd))

    # scale-apps: drop pods owned by the named workloads before re-simulating
    # (parity: removePodsOfApp, server.go:404-444)
    removals = {
        (w.get("kind", ""), w.get("namespace", "default"), w.get("name", ""))
        for w in body.get("removeWorkloads") or []
    }
    if removals:
        # Deployment indirection (server.go:408-419): real-cluster pods of a
        # Deployment are owned by its ReplicaSets, which the snapshot lists —
        # an RS whose ownerReferences name a removed Deployment marks its own
        # pods removable. (Simulated pods match directly via annotations.)
        rs_of_removed = set()
        for rs in cluster.others.get("ReplicaSet", []):
            meta = rs.get("metadata") or {}
            ns = meta.get("namespace", "default")
            for ref in meta.get("ownerReferences") or []:
                if (ref.get("kind", ""), ns, ref.get("name", "")) in removals:
                    rs_of_removed.add((ns, meta.get("name", "")))

        def owned(pod) -> bool:
            ann = pod.meta.annotations
            key = (
                ann.get(ANNO_WORKLOAD_KIND, pod.meta.owner_kind),
                ann.get(ANNO_WORKLOAD_NAMESPACE, pod.meta.namespace),
                ann.get(ANNO_WORKLOAD_NAME, pod.meta.owner_name),
            )
            if key in removals:
                return True
            # OwnedByWorkload scans EVERY ownerReference (utils.go:840-853)
            # — a multi-owner pod's RS/STS ref need not be listed first
            refs = ((pod.raw or {}).get("metadata") or {}).get(
                "ownerReferences"
            ) or []
            for ref in refs:
                kind = ref.get("kind", "")
                name = ref.get("name", "")
                if (kind, pod.meta.namespace, name) in removals:
                    return True
                if (
                    kind == "ReplicaSet"
                    and (pod.meta.namespace, name) in rs_of_removed
                ):
                    return True
            return False

        cluster.pods = [p for p in cluster.pods if not owned(p)]

    apps = [
        AppResource(name=a.get("name", f"app-{i}"), objects=list(a.get("objects") or []))
        for i, a in enumerate(body.get("apps") or [])
    ]
    return cluster, apps


def _format_result(result) -> dict:
    placements = {}
    for st in result.node_status:
        for pod in st.pods:
            placements[pod.key] = st.node.name
    return {
        "placements": placements,
        "unscheduled": [
            {"pod": u.pod.key, "reason": u.reason} for u in result.unscheduled
        ],
    }


def _request_resident(body: dict):
    """The ResidentCluster to offer simulate(), or None. Only live-snapshot
    bodies can be covered, and a body that edits the cluster (newNodes /
    removeWorkloads) is simulated against a derived cluster the resident does
    not hold — skipping it here avoids a guaranteed not_covering fallback.
    simulate() still re-checks coverage (covers_reason), so offering the
    resident is always safe, never load-bearing."""
    spec = body.get("cluster") or {}
    uses_live = (
        "path" not in spec
        and not spec.get("objects")
        and bool(_kubeconfig or _master)
    )
    if not uses_live or body.get("newNodes") or body.get("removeWorkloads"):
        return None
    with _snapshot_lock:
        return _resident


def _simulate_request(body: dict) -> dict:
    # Warm-only fast path: an EXISTING session for this body's key serves a
    # lone request as a pack of one — byte-identical to simulate() (the
    # session rewinds the workload-name RNG per run) without re-paying the
    # encode. A lone request never CREATES a session: construction is only
    # amortized when scenario groups recur.
    if _loop_sessions_enabled():
        key = _session_key_for(body)
        if key is not None:
            sess, _may_create = _checkout_session(key)
            if sess is not None:
                try:
                    results = sess.run(
                        [Scenario(name="req-0", weights=body.get("weights"))]
                    )
                except Exception:
                    from ..utils.tracing import log

                    log.warning(
                        "warm session run failed; dropping session and "
                        "serving cold", exc_info=True,
                    )
                    _checkin_session(key, sess, keep=False)
                    results = None
                else:
                    _checkin_session(key, sess, keep=results is not None)
                if results:
                    return _format_result(results[0])
    cluster, apps = _request_cluster_apps(body)
    result = simulate(
        cluster, apps, weights=body.get("weights"),
        resident=_request_resident(body),
    )
    return _format_result(result)


def _simulate_scenario_group(bodies: list) -> list:
    """One batched device call for a group of scenario-compatible bodies
    (identical cluster/apps, per-body weights): one vmapped lane per body,
    results in body order. Served through the warm-session cache when
    possible (the encode pass and Simulator construction amortize across
    consecutive packs); otherwise a cold simulate_batch, which falls back
    to serial internally when the workload is batch-ineligible — either
    way this always returns real per-body results."""
    cluster, apps = _request_cluster_apps(bodies[0])
    scenarios = [
        Scenario(name=f"req-{i}", weights=b.get("weights"))
        for i, b in enumerate(bodies)
    ]
    resident = _request_resident(bodies[0])
    out = _run_scenarios_warm(bodies[0], cluster, apps, scenarios, resident)
    if out is None:
        results = simulate_batch(cluster, apps, scenarios, resident=resident)
        out = [_format_result(r) for r in results]
    metrics.COALESCED_BATCH.observe(len(bodies), mode="scenarios")
    return out


# ---------------------------------------------------------------------------
# Async jobs (POST /v1/jobs): long capacity plans run on a job thread, with
# the durable run journal (durable/journal.py) as the source of truth — the
# record sequence is exactly what `simon sweep --capacity --run-dir` writes,
# so `simon runs list/show/resume` work on job directories unchanged, and a
# job interrupted by a server restart resumes with {"resume": true}.
# ---------------------------------------------------------------------------


def _submit_job(body: dict):
    """Validate and launch one async job; returns (code, payload). 202 on
    launch, 409 while the same job id is still running (re-POST after
    completion is allowed: with resume=true it replays the journal and
    re-serves the committed result without new device calls)."""
    from ..durable import default_runs_root

    if body.get("kind", "capacity") != "capacity":
        metrics.JOBS.inc(outcome="rejected")
        return 400, {
            "error": (
                f"unsupported job kind {body.get('kind')!r}; "
                "only 'capacity' is implemented"
            )
        }
    if not isinstance(body.get("newNode"), dict):
        metrics.JOBS.inc(outcome="rejected")
        return 400, {"error": "capacity job needs a newNode candidate object"}
    job_id = str(body.get("job") or "") or f"job-{_scenario_compat_key(body)[:12]}"
    if "/" in job_id or job_id in (".", ".."):
        metrics.JOBS.inc(outcome="rejected")
        return 400, {"error": f"invalid job id {job_id!r}"}
    run_dir = os.path.join(default_runs_root(), job_id)
    with _jobs_lock:
        ent = _jobs.get(job_id)
        if ent is not None and ent["thread"].is_alive():
            metrics.JOBS.inc(outcome="rejected")
            return 409, {
                "error": "job is already running",
                "job": job_id,
                "status_url": f"/v1/jobs/{job_id}",
            }
        from ..utils import tracing

        t = threading.Thread(
            # the job thread outlives the POST that spawned it; the captured
            # trace context keeps its journal/sweep spans in the same trace
            target=_run_job,
            args=(job_id, run_dir, body, tracing.current_context()),
            name=f"osim-job-{job_id}", daemon=True,
        )
        _jobs[job_id] = {"thread": t, "run_dir": run_dir, "error": None}
        t.start()
    return 202, {
        "job": job_id,
        "run_dir": run_dir,
        "status_url": f"/v1/jobs/{job_id}",
    }


def _run_job(job_id: str, run_dir: str, body: dict, trace_ctx=None) -> None:
    """Job worker thread: a journaled capacity sweep. Every phase of the
    batched ladder lands as a `sweep` record (plan_capacity journals them),
    which is what GET /v1/jobs/<id> streams back as progress. The trace
    context captured at submit time keeps the job's spans in the same
    trace as the POST /v1/jobs request that launched it."""
    from ..utils import tracing
    from ..utils.tracing import log

    outcome = "failed"
    try:
        with tracing.activate(trace_ctx), tracing.span("job", job=job_id):
            _run_job_inner(job_id, run_dir, body)
        outcome = "completed"
    except Exception as e:
        log.warning("job %s failed", job_id, exc_info=True)
        with _jobs_lock:
            ent = _jobs.get(job_id)
            if ent is not None:
                ent["error"] = str(e)
    metrics.JOBS.inc(outcome=outcome)


def _run_job_inner(job_id: str, run_dir: str, body: dict) -> None:
    import json as _json

    from ..durable import RunJournal, atomic_write
    from ..engine.apply import placement_digest
    from ..engine.capacity import plan_capacity

    cluster, apps = _request_cluster_apps(body)
    new_node = Node.from_dict(body["newNode"])
    resume = bool(body.get("resume"))
    use_greed = bool(body.get("useGreed"))
    with RunJournal.open(run_dir) as journal:
        if resume:
            journal.append("run_resume")
        else:
            journal.append(
                "run_start", kind="sweep", job=job_id, use_greed=use_greed,
            )
        plan = plan_capacity(
            cluster, apps, new_node, use_greed=use_greed,
            journal=journal, resume=resume, sweep_mode="batched",
        )
        journal.append(
            "run_end",
            outcome="ok" if plan is not None else "does_not_fit",
            nodes_added=plan.nodes_added if plan else -1,
        )
        # timestamp-free snapshot, byte-identical across crash-resume
        # (mirrors `simon sweep --capacity --run-dir`, cli/main.py)
        atomic_write(
            os.path.join(run_dir, "outcome.json"),
            _json.dumps(
                {
                    "outcome": "ok" if plan else "does_not_fit",
                    "kind": "sweep",
                    "nodes_added": plan.nodes_added if plan else -1,
                    "attempts": plan.attempts if plan else 0,
                    "batched_calls": plan.batched_calls if plan else 0,
                    "retries": plan.retries if plan else 0,
                    "unscheduled": (
                        len(plan.result.unscheduled) if plan else -1
                    ),
                    "placement_digest": (
                        placement_digest(plan.result) if plan else ""
                    ),
                },
                indent=2,
                sort_keys=True,
            )
            + "\n",
        )


def _job_status(job_id: str, after: int):
    """GET /v1/jobs/<id>?after=N: job state plus the `sweep` progress
    records with seq > N (pass the returned next_after back to poll
    incrementally). Works for journaled runs this process never started —
    the registry only adds in-process liveness on top of the journal."""
    from ..durable import default_runs_root, replay, summarize_run
    from ..durable.journal import JOURNAL_NAME

    run_dir = os.path.join(default_runs_root(), job_id)
    with _jobs_lock:
        ent = _jobs.get(job_id)
        running = ent is not None and ent["thread"].is_alive()
        error = ent["error"] if ent is not None else None
    if not os.path.isfile(os.path.join(run_dir, JOURNAL_NAME)):
        if running:
            # submitted moments ago; the journal's first fsync hasn't landed
            return 200, {
                "job": job_id, "run_dir": run_dir, "status": "starting",
                "progress": [], "next_after": after,
            }
        return 404, {"error": f"unknown job {job_id!r}"}
    events = replay(run_dir)
    summary = summarize_run(run_dir)
    if running:
        status = "running"
    elif error is not None:
        status = "failed"
    elif summary["status"] == "completed":
        status = "completed"
    else:
        # journal exists, no run_end, no live thread: interrupted —
        # resumable with POST /v1/jobs {"job": ..., "resume": true}
        status = "interrupted"
    progress = [
        {
            "seq": e.get("seq"),
            "ts": e.get("ts"),
            "phase": e.get("phase"),
            "counts": e.get("counts"),
            "good": e.get("good"),
            "n_pad": e.get("n_pad"),
        }
        for e in events
        if e.get("event") == "sweep" and e.get("seq", -1) > after
    ]
    payload = {
        "job": job_id,
        "run_dir": run_dir,
        "status": status,
        "summary": summary,
        "progress": progress,
        "next_after": events[-1]["seq"] if events else after,
    }
    if error is not None:
        payload["error"] = error
    if status == "completed":
        try:
            with open(os.path.join(run_dir, "outcome.json")) as fh:
                payload["outcome"] = json.load(fh)
        except (OSError, ValueError):
            pass
    return 200, payload


def _jobs_index():
    """GET /v1/jobs: in-process jobs plus every journaled run under the
    runs root (jobs land there, so a restarted server still lists them)."""
    from ..durable import default_runs_root, list_runs

    with _jobs_lock:
        live = {
            job_id: ent["thread"].is_alive() for job_id, ent in _jobs.items()
        }
    return 200, {
        "runs_root": default_runs_root(),
        "jobs": [
            dict(r, running=live.get(r["name"], False))
            for r in list_runs(default_runs_root())
        ],
    }


def _cpu_profile(seconds: float) -> dict:
    """Sampling wall-clock profiler over every live thread (the pprof
    `/debug/pprof/profile?seconds=N` analog): poll sys._current_frames() at
    ~100 Hz, aggregate identical stacks, report the hottest ones. The
    sampling thread excludes itself and the serving thread's own frames are
    visible — exactly like Go's profile including the HTTP handler."""
    import sys
    import time
    import traceback
    from collections import Counter

    me = threading.get_ident()
    samples: Counter = Counter()
    n = 0
    deadline = time.time() + max(0.1, seconds)
    while time.time() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            stack = tuple(
                f"{fs.filename.rsplit('/', 1)[-1]}:{fs.lineno}:{fs.name}"
                for fs in traceback.extract_stack(frame)[-12:]
            )
            samples[stack] += 1
        n += 1
        time.sleep(0.01)
    top = [
        {"count": c, "stack": list(stack)}
        for stack, c in samples.most_common(25)
    ]
    return {"seconds": seconds, "polls": n, "stacks": top}


def _goroutine_dump() -> dict:
    """Instantaneous all-thread stack dump (the `/debug/pprof/goroutine`
    analog — the exact tool the reference's leak postmortem used,
    docs/design/内存泄漏.md). One pass over sys._current_frames(), no
    sampling window: safe to hit on a wedged process."""
    import sys
    import traceback

    names = {t.ident: t for t in threading.enumerate()}
    threads = []
    for tid, frame in sys._current_frames().items():
        t = names.get(tid)
        threads.append(
            {
                "id": tid,
                "name": t.name if t else "?",
                "daemon": bool(t.daemon) if t else None,
                "stack": [
                    f"{fs.filename}:{fs.lineno}:{fs.name}"
                    for fs in traceback.extract_stack(frame)
                ],
            }
        )
    threads.sort(key=lambda d: d["id"])
    return {"count": len(threads), "threads": threads}


_tracemalloc_on = False
# /debug/pprof/heap is served off concurrent _Handler threads, so
# two concurrent requests can both observe _tracemalloc_on False, both call
# tracemalloc.start() and both mislabel their snapshot "tracing just
# started" — serialize the check-then-act.
_tracemalloc_lock = threading.Lock()


def _heap_profile() -> dict:
    """Allocation snapshot (the `/debug/pprof/heap` analog): tracemalloc top
    allocation sites. Tracing starts on the first call — the first snapshot
    only covers allocations made after it (noted in the payload), matching
    how pprof heap profiles need the runtime flag enabled."""
    import tracemalloc

    global _tracemalloc_on
    with _tracemalloc_lock:
        first = not _tracemalloc_on
        if first:
            tracemalloc.start(10)
            _tracemalloc_on = True
    current, peak = tracemalloc.get_traced_memory()
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")[:25]
    return {
        "note": (
            "tracing just started; snapshot covers allocations from now on"
            if first
            else ""
        ),
        "traced_current_bytes": current,
        "traced_peak_bytes": peak,
        "top": [
            {
                "site": str(s.traceback[0]) if s.traceback else "?",
                "size_bytes": s.size,
                "count": s.count,
            }
            for s in stats
        ],
    }


class _Handler(BaseHTTPRequestHandler):
    def setup(self):
        # BaseRequestHandler applies self.timeout to the connection socket;
        # read dynamically so tests / serve() can tune it per server
        self.timeout = REQUEST_TIMEOUT_S
        super().setup()

    def _count(self, code: int) -> None:
        from urllib.parse import urlparse

        metrics.HTTP_REQUESTS.inc(
            path=urlparse(self.path).path, code=str(code)
        )

    def _send(
        self, code: int, payload: dict, headers: Optional[dict] = None
    ) -> None:
        data = json.dumps(payload).encode()
        self._count(code)
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        headers = dict(headers or {})
        # Every traced response echoes its trace id, so a client (or a
        # human with curl) can find the request's spans in the trace
        # export / flight recorder without guessing.
        if "X-Osim-Trace-Id" not in headers:
            from ..utils import tracing

            tid = tracing.current_trace_id()
            if tid is not None:
                headers["X-Osim-Trace-Id"] = tid
        for name, value in headers.items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(data)

    def _send_text(self, data: bytes, content_type: str) -> None:
        self._count(200)
        self.send_response(200)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802
        if self.path == "/healthz":
            self._send(200, {"status": "ok"})
        elif self.path == "/metrics":
            # Prometheus text exposition (the kube-scheduler serves its
            # metrics package at the same path) — see utils/metrics.py
            self._send_text(
                metrics.REGISTRY.render().encode(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        elif self.path == "/debug/timings":
            # span trees (server.go:152's pprof registration analog), see
            # utils/tracing.py
            from ..utils.tracing import recent_timings

            self._send(200, {"timings": recent_timings()})
        elif self.path.startswith("/debug/profile"):
            # Device-time profiling (utils/profiling.py): capture a
            # jax.profiler trace for ?ms=N (default 1000, capped) into the
            # runs root, Perfetto/TensorBoard-loadable. Distinct from
            # /debug/pprof/profile, which samples HOST thread stacks.
            from urllib.parse import parse_qs, urlparse

            from ..durable import default_runs_root
            from ..utils.profiling import capture_device_trace

            q = parse_qs(urlparse(self.path).query)
            try:
                ms = min(float(q.get("ms", ["1000"])[0]), 60_000.0)
            except ValueError:
                ms = 1000.0
            out_dir = os.path.join(default_runs_root(), "device-profile")
            report = capture_device_trace(out_dir, duration_ms=ms)
            self._send(200 if report.get("ok") else 500, report)
        elif self.path.startswith("/debug/pprof/profile"):
            # CPU profile: sample every thread's stack at ~100 Hz for
            # ?seconds=N (default 2; capped), return aggregated stacks —
            # the wall-clock sampling profile gin-contrib/pprof exposes at
            # the same path, in text form
            from urllib.parse import parse_qs, urlparse

            q = parse_qs(urlparse(self.path).query)
            try:
                seconds = min(float(q.get("seconds", ["2"])[0]), 30.0)
            except ValueError:
                seconds = 2.0
            self._send(200, _cpu_profile(seconds))
        elif self.path in ("/debug/pprof", "/debug/pprof/"):
            # the gin-contrib/pprof index (server.go:152): what's available
            self._send(
                200,
                {
                    "profiles": {
                        "goroutine": "/debug/pprof/goroutine",
                        "heap": "/debug/pprof/heap",
                        "profile": "/debug/pprof/profile?seconds=N",
                        "cmdline": "/debug/pprof/cmdline",
                        "timings": "/debug/timings",
                        "device": "/debug/profile?ms=N",
                        "metrics": "/metrics",
                    }
                },
            )
        elif self.path.startswith("/debug/pprof/cmdline"):
            import sys

            self._send(200, {"cmdline": sys.argv})
        elif self.path.startswith("/debug/pprof/goroutine"):
            self._send(200, _goroutine_dump())
        elif self.path.startswith("/debug/pprof/heap"):
            self._send(200, _heap_profile())
        elif self.path.startswith("/v1/jobs"):
            from urllib.parse import parse_qs, urlparse

            u = urlparse(self.path)
            parts = [p for p in u.path.split("/") if p]
            if parts == ["v1", "jobs"]:
                code, payload = _jobs_index()
            elif len(parts) == 3:
                try:
                    after = int(parse_qs(u.query).get("after", ["-1"])[0])
                except ValueError:
                    after = -1
                code, payload = _job_status(parts[2], after)
            else:
                code, payload = 404, {"error": "not found"}
            self._send(code, payload)
        elif self.path == "/test":
            # parity: GET /test returns the literal "test" (server.go:154-156)
            self._send_text(b"test", "text/plain")
        else:
            self._send(404, {"error": "not found"})

    def do_POST(self):  # noqa: N802
        # One request = one trace: the handler opens the request's root
        # span here, continuing the caller's trace when the request
        # carries a W3C `traceparent` header (utils/tracing.py). Tickets
        # capture this context at submit, the scheduler loop re-activates
        # it across the queue hop, and _send echoes the trace id back as
        # X-Osim-Trace-Id (docs/observability.md).
        from ..utils import tracing

        remote = tracing.TraceContext.from_traceparent(
            self.headers.get("traceparent")
        )
        with tracing.activate(remote):
            with tracing.span(
                "http-request", path=self.path, method="POST"
            ) as root:
                self._do_post_inner(root)

    def _do_post_inner(self, root) -> None:
        if self.path not in ("/api/deploy-apps", "/api/scale-apps", "/v1/jobs"):
            self._send(404, {"error": "not found"})
            return
        # Body I/O stays on the handler thread: the scheduler worker must
        # never block on a client socket, so a slow-loris client costs one
        # handler thread for at most REQUEST_TIMEOUT_S and never a queue
        # slot or the simulate pipeline.
        try:
            length = int(self.headers.get("Content-Length", 0))
            try:
                raw = self.rfile.read(length)
            except TimeoutError:
                self.close_connection = True
                self._send(408, {"error": "request body read timed out"})
                return
            body = json.loads(raw or b"{}")
        except Exception as e:
            self._send(400, {"error": str(e)})
            return
        if self.path == "/v1/jobs":
            # jobs bypass admission: submit is O(validate + thread spawn),
            # and the long work runs on the job thread against the journal
            code, payload = _submit_job(body)
            self._send(code, payload)
            return
        deadline_ms: Optional[float] = None
        hdr = self.headers.get("X-Osim-Deadline-Ms")
        if hdr is not None:
            try:
                deadline_ms = float(hdr)
            except ValueError:
                self._send(
                    400, {"error": f"invalid X-Osim-Deadline-Ms: {hdr!r}"}
                )
                return
        # Admission control (server/admission.py): enqueue or shed, then
        # block this handler thread until the scheduler worker finalizes the
        # ticket. Every outcome is a definite response — 200, 400, 408,
        # 429/503 + Retry-After (shed), 504 (deadline mid-simulate), or 500
        # (worker death, counted in osim_requests_dropped_total).
        queue = self.server.admission
        if not queue.worker_alive():
            # Degradation ladder, bottom rung (docs/serving.md): the
            # scheduler-loop thread died, so serve this request per-request
            # on the handler thread — correctness preserved, batching lost.
            # (Tickets already queued when the loop died still get their
            # honest 500 from wait()'s dead-worker check.)
            metrics.LOOP_FALLBACKS.inc()
            try:
                res = _execute_bodies([body])[0]
            except Exception as e:
                res = e
            if isinstance(res, BaseException):
                self._send(400, {"error": str(res)})
            else:
                self._send(200, res)
            return
        key, fence_epoch = _coalesce_key_for(self.path, body)
        ticket = queue.submit(
            body,
            key=key,
            deadline_ms=deadline_ms,
            fence_epoch=fence_epoch,
        )
        queue.wait(ticket)
        # Link (not parent) this root to the pack span that executed the
        # ticket: the pack ran on the loop thread, possibly serving many
        # lanes, so the relationship is a peer link in both directions.
        if ticket.pack_ctx is not None:
            root.add_link(ticket.pack_ctx)
        self._send(ticket.code, ticket.payload or {}, headers=ticket.headers)

    def log_message(self, fmt, *args):  # quiet gin-style access logs
        pass


def _graceful_shutdown(signum=None, frame=None) -> None:
    """SIGTERM/SIGINT handler: stop accepting connections and let serve()
    fall through to its drain. shutdown() must not run on the thread inside
    serve_forever (it deadlocks waiting for the loop to exit), so it is
    dispatched to a helper thread; signal handlers always run on the main
    thread, which IS the serve_forever thread."""
    httpd = _current_server
    if httpd is None:
        return
    name = signal.Signals(signum).name if signum is not None else "shutdown"
    print(f"simon server: received {name}, draining in-flight requests")
    try:
        # last-breath evidence: what the server was doing when it was told
        # to die (utils/flightrec.py) — written before the drain starts so
        # a kill -9 follow-up can't lose it
        from ..utils import flightrec

        flightrec.dump("sigterm")
    except Exception:
        pass
    threading.Thread(
        target=httpd.shutdown, name="osim-shutdown", daemon=True
    ).start()


def serve(
    port: int = 9998,
    ready: Optional[threading.Event] = None,
    kubeconfig: str = "",
    master: str = "",
    queue_depth: Optional[int] = None,
    coalesce_ms: Optional[float] = None,
    pack_window_ms: Optional[float] = None,
    default_deadline_ms: Optional[float] = None,
) -> int:
    global _kubeconfig, _master, _snapshot, _snapshot_at, _current_server
    global _resident, _snapshot_stale
    _resolve_env_config()
    # Crash flight recorder: an unhandled exception on any thread dumps the
    # recent-span/metric/journal ring before the process dies
    # (utils/flightrec.py; idempotent).
    from ..utils import flightrec

    flightrec.install_crash_hook()
    _kubeconfig = kubeconfig or None
    _master = master
    # A previous serve() in this process may have cached a snapshot (and
    # resident planes) of a DIFFERENT cluster — never serve them against the
    # new config. _snapshot_fetches deliberately SURVIVES the reset: it must
    # stay monotonic across re-serves, because a coalesce key minted as
    # "...:gen3" by the old serve would otherwise collide with "...:gen3" of
    # the new cluster once the counter restarted — same key, different work,
    # one (wrong) shared response. With a resident the generation is its
    # epoch, drawn from a module-global counter in engine/resident.py that is
    # never reused across instances, which subsumes this counter entirely;
    # the surviving _snapshot_fetches covers the OSIM_RESIDENT=0 path.
    _snapshot, _snapshot_at = None, 0.0
    _resident, _snapshot_stale = None, False
    # Warm sessions of a previous serve() are keyed so they could never be
    # confused with the new config's (inline bodies are self-describing,
    # live keys carry a never-reused generation), but there is no reason to
    # hold their device buffers across a re-serve.
    with _sessions_lock:
        _sessions.clear()
    httpd = _DrainingHTTPServer(
        ("127.0.0.1", port),
        _Handler,
        queue_depth=queue_depth,
        coalesce_ms=coalesce_ms,
        pack_window_ms=pack_window_ms,
        default_deadline_ms=default_deadline_ms,
    )
    _current_server = httpd
    # Graceful termination: SIGTERM (kubelet/systemd stop) and SIGINT drain
    # in-flight requests before exiting. signal.signal only works on the
    # main thread — embedded/test serve() threads skip installation and can
    # call _graceful_shutdown directly instead.
    prior = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            prior[sig] = signal.signal(sig, _graceful_shutdown)
        except ValueError:
            break
    if ready is not None:
        ready.set()
    print(f"simon server listening on 127.0.0.1:{port}")
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        # server_close() joins every in-flight handler thread
        # (_DrainingHTTPServer) — this IS the drain.
        httpd.server_close()
        _current_server = None
        for sig, handler in prior.items():
            signal.signal(sig, handler)
    return 0


def make_server(
    port: int = 0,
    *,
    queue_depth: Optional[int] = None,
    coalesce_ms: Optional[float] = None,
    pack_window_ms: Optional[float] = None,
    default_deadline_ms: Optional[float] = None,
):
    """Embeddable server for tests; returns the ThreadingHTTPServer (its
    `.admission` attribute is the live AdmissionQueue)."""
    _resolve_env_config()
    return _DrainingHTTPServer(
        ("127.0.0.1", port),
        _Handler,
        queue_depth=queue_depth,
        coalesce_ms=coalesce_ms,
        pack_window_ms=pack_window_ms,
        default_deadline_ms=default_deadline_ms,
    )
