"""Continuous-batching scheduler loop: one always-hot device loop.

PR 6 gave serving a bounded admission queue drained by a worker that held
a fixed `OSIM_SERVER_COALESCE_MS` window open, then dispatched one cold
batch end-to-end — every request paid the window as a latency floor, and
requests arriving while a batch executed queued behind the *next* window
too. This module replaces that drain policy with the architecture LLM
inference serving converged on (continuous batching of sequences):

* a **persistent scheduler loop** owns the device; between consecutive
  device calls it packs whatever compatible tickets are queued into the
  next scenario-batched call — lanes join and leave between calls;
* the coalesce window shrank to a **pack heuristic**: a lone ticket on
  an idle server dispatches immediately (no mandatory wait — the p50 of
  an idle server is one device call), a full pack (>= pack_lanes or
  queue depth) dispatches immediately, and a *partial* pack — or a lone
  ticket arriving right behind a multi-lane pack, i.e. the head of a
  re-posting herd — holds the window open, bounded by `pack_window_s`,
  hoping stragglers fill the SCENARIO_BUCKET before the next call;
* the generation fence (engine/resident.py) is consulted **once per
  pack** at pack-take time, so a ticket can only coalesce with work that
  will run against the same cluster epoch it will actually see.

The split of responsibilities: `AdmissionQueue` (admission.py) keeps the
ticket lifecycle — submit/shed/wait/finalize and the Retry-After
accounting — while this loop owns *when the device runs and with what
pack*. The loop deliberately reaches into the queue's internals
(`_cv`/`_queue`/`_shed`/`_finalize`); they are two halves of one
scheduler separated so each half stays testable sleep-free.

Observability: `osim_loop_iteration_seconds` (one full iteration:
deadline sheds + fence + device call + fan-out; its EWMA feeds
Retry-After), `osim_pack_latency_seconds` (per-ticket admission->pack
time — the queueing cost of continuous batching), and the engine-side
`osim_lane_occupancy_ratio` (how full the padded scenario shape ran).

Fault injection and the watchdog budget semantics are unchanged from the
window era (docs/serving.md, docs/resilience.md); `guarded_call` /
`call_deadline_s` / `DeadlineExceeded` are resolved through the
admission module namespace so tests that monkeypatch
`admission.guarded_call` keep intercepting the device call.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ..resilience import faults
from ..utils import metrics
from ..utils import tracing
from . import admission as admission_mod


def default_pack_lanes() -> int:
    """Target lanes per pack: one SCENARIO_BUCKET, so a full pack exactly
    fills the padded scenario shape the compiled program already has warm.
    Falls back to 8 (the bucket's value) if the ops layer is unavailable —
    the heuristic must not make admission import the device stack."""
    try:
        from ..ops.fast import SCENARIO_BUCKET

        return int(SCENARIO_BUCKET)
    except Exception:  # pragma: no cover - ops always importable in-tree
        return 8


def pack_ready(
    n_queued: int, *, depth: int, pack_lanes: int, saturated: bool = False
) -> bool:
    """Dispatch-now predicate of the pack heuristic. True when waiting any
    longer cannot improve the pack:

    * a lone ticket on an IDLE server — no latency floor; the p50 of a
      lone request is one device call, exactly like serial simulate();
    * a full pack — `pack_lanes` (one scenario bucket) or the queue depth
      reached, whichever is smaller: more waiting cannot add lanes worth
      padding for.

    Anything in between is a *partial* pack: the loop may hold the window
    open (bounded by pack_window_s) for stragglers to join.

    `saturated` is the loop's recent-load signal: the previous pack was
    multi-lane and just finished. Under saturation a lone ticket is
    almost always the FIRST straggler of a thundering herd — the waiters
    of the pack that just fanned out are re-posting — so dispatching it
    alone would burn a full device call on one lane while the rest of
    the herd queues behind it. Treat it as a partial pack instead and
    let the window (an upper bound, not a floor) collect the herd."""
    if n_queued <= 0:
        return False
    if n_queued == 1:
        return not saturated
    return n_queued >= min(pack_lanes, depth)


class SchedulerLoop:
    """The always-hot half of the serving scheduler: take_pack() decides
    *when* the device runs, run_pack() is one loop iteration (deadline
    sheds -> per-pack fence re-key -> coalesce -> guarded device call ->
    fan-out). Constructed by AdmissionQueue; `queue` is the ticket store."""

    def __init__(
        self,
        queue,
        *,
        pack_lanes: Optional[int] = None,
        pack_window_s: Optional[float] = None,
    ) -> None:
        self.queue = queue
        self.pack_lanes = (
            int(pack_lanes) if pack_lanes is not None else default_pack_lanes()
        )
        # The window is an UPPER BOUND on how long a partial pack may wait,
        # not a floor; defaults to the queue's configured window (the
        # OSIM_SERVER_COALESCE_MS deprecation shim resolves into it).
        self.pack_window_s = (
            float(pack_window_s)
            if pack_window_s is not None
            else queue.coalesce_s
        )
        # Saturation signal for pack_ready's lone-ticket case: lane count
        # and completion time of the previous pack. A lone arrival within
        # one pack window of a multi-lane pack finishing is the head of a
        # re-posting herd, not an idle-server request.
        self._last_pack_lanes = 0
        self._last_pack_end: Optional[float] = None
        # Bench-only switch (bench.py serving_saturation): when True the
        # window reverts to the PRE-loop semantics — a latency floor every
        # pack waits out, pack_ready ignored — so the replaced coalesce-
        # window-then-cold-dispatch architecture can be measured as the
        # baseline of the continuous-batching speedup claim. Never set in
        # production paths.
        self.legacy_floor = False

    # -- loop driver --------------------------------------------------------

    def run_forever(self) -> None:
        """Body of the scheduler-loop thread: pack, run, repeat, until the
        queue drains out (shutdown). Crash containment lives in the
        queue's thread wrapper (_worker_main), not here."""
        while True:
            pack = self.take_pack()
            if pack is None:
                return
            self.run_pack(pack)

    def take_pack(self) -> Optional[List]:
        """Block until work exists, apply the pack heuristic, then take the
        whole backlog as the next pack. Returns None when draining and
        empty (loop exit)."""
        q = self.queue
        with q._cv:
            while not q._queue and not q._draining:
                q._cv.wait()
            if not q._queue:  # draining and empty
                return None
            if self.pack_window_s > 0:
                head = q._queue[0]
                window_end = head.enqueued_at + self.pack_window_s
                while not q._draining:
                    saturated = (
                        self._last_pack_lanes > 1
                        and self._last_pack_end is not None
                        and q._clock() - self._last_pack_end
                        < self.pack_window_s
                    )
                    if not self.legacy_floor and pack_ready(
                        len(q._queue), depth=q.depth,
                        pack_lanes=self.pack_lanes, saturated=saturated,
                    ):
                        break
                    remaining = window_end - q._clock()
                    if remaining <= 0:
                        break
                    q._cv.wait(remaining)
            pack = list(q._queue)
            q._queue.clear()
            metrics.ADMISSION_QUEUE_DEPTH.set(0)
            return pack or None

    # -- one loop iteration -------------------------------------------------

    def run_pack(self, pack: List) -> None:
        """One iteration of the hot loop over one pack of tickets. Always
        observes osim_loop_iteration_seconds and feeds the iteration-time
        EWMA, even when every ticket sheds — Retry-After must track what
        an iteration actually costs under the current load."""
        q = self.queue
        t_iter = q._clock()
        now = t_iter
        for t in pack:
            metrics.PACK_LATENCY.observe(max(now - t.enqueued_at, 0.0))
        # Cross-thread trace stitching: the pack runs on the loop thread,
        # but every ticket carries the trace context of its submitting
        # request. The pack's execution span is parented (by ID) on the
        # FIRST ticket's trace and records span *links* to every other
        # lane's context — one span cannot have N parents, so extra lanes
        # become links, and each handler links back via ticket.pack_ctx.
        ctx0 = next(
            (t.trace_ctx for t in pack if t.trace_ctx is not None), None
        )
        try:
            with tracing.activate(ctx0):
                with tracing.span("loop-pack", lanes=len(pack)) as s:
                    for t in pack:
                        t.pack_ctx = s.context()
                        if (
                            t.trace_ctx is not None
                            and t.trace_ctx is not ctx0
                        ):
                            s.add_link(t.trace_ctx)
                    self._run_pack_inner(pack, now)
        finally:
            self._last_pack_lanes = len(pack)
            self._last_pack_end = q._clock()
            q._note_iteration(max(q._clock() - t_iter, 0.0))

    def _run_pack_inner(self, pack: List, now: float) -> None:
        q = self.queue
        # 1. deadline sheds AT PACK TIME: expired tickets never reach the
        #    device call (the deadline_storm chaos kind relies on this).
        live: List = []
        for t in pack:
            if t.deadline_at is not None and now >= t.deadline_at:
                q._shed(t, admission_mod.REASON_DEADLINE)
            else:
                live.append(t)
        if not live:
            return
        # 2. generation fence PER PACK: a fenced ticket admitted under epoch
        #    E whose snapshot moved to E' before this pack was taken is
        #    re-keyed onto E' — it runs against the E' state and must only
        #    coalesce with other E' work. One fence() call covers the whole
        #    pack: every lane of the coming device call sees the same
        #    resident state (the stale_generation chaos kind forces the
        #    mismatch with a sentinel epoch).
        if q._fence is not None and any(
            t.fence_epoch is not None for t in live
        ):
            current = q._fence()
            for t in live:
                if t.fence_epoch is None:
                    continue
                if t.fence_epoch == current:
                    metrics.ADMISSION_FENCE.inc(outcome="current")
                else:
                    t.key += f"@fence{current}"
                    t.fence_epoch = current
                    metrics.ADMISSION_FENCE.inc(outcome="rekeyed")
        # 3. injected slow drain (models a wedged backend eating the pack)
        rule = faults.maybe_inject("admission", "drain")
        if rule is not None and rule.kind == "slow_drain" and rule.latency_s > 0:
            time.sleep(rule.latency_s)
        # 4. coalesce: one executor entry per distinct key, arrival order
        groups: Dict[str, List] = {}
        order: List[str] = []
        for t in live:
            if t.key not in groups:
                groups[t.key] = []
                order.append(t.key)
            groups[t.key].append(t)
        bodies = [groups[k][0].body for k in order]
        # 5. watchdog budget: the most generous live deadline (a stricter
        #    per-request budget would abort shared work other waiters still
        #    have time for); deadline-less waiters fall back to the global
        #    OSIM_CALL_DEADLINE_S (0 = unguarded). Resolved through the
        #    admission module so monkeypatched guarded_call intercepts.
        budgets = [t.remaining_s(now) for t in live]
        budget = (
            admission_mod.call_deadline_s()
            if any(b is None for b in budgets)
            else max(budgets)
        )
        try:
            results = admission_mod.guarded_call(
                "serve-simulate",
                lambda: q._execute(bodies),
                budget if budget and budget > 0 else 0.0,
                clock=q._clock,
                poll_s=q._poll_s,
            )
            if len(results) != len(bodies):
                raise RuntimeError(
                    f"batch executor returned {len(results)} results "
                    f"for {len(bodies)} bodies"
                )
        except admission_mod.DeadlineExceeded as e:
            for t in live:
                q._finalize(t, 504, {"error": str(e)})
            return
        except Exception as e:  # executor failure: every waiter gets a 400
            for t in live:
                q._finalize(t, 400, {"error": str(e)})
            return
        # 6. fan each group's one result back out to all of its waiters
        for k, res in zip(order, results):
            waiters = groups[k]
            # mode="fanout": N identical requests served by ONE result.
            # (mode="scenarios" — distinct bodies merged into one batched
            # device call — is observed by the executor, the layer that
            # knows the scenario grouping; see server._execute_bodies.)
            metrics.COALESCED_BATCH.observe(len(waiters), mode="fanout")
            for t in waiters:
                if isinstance(res, BaseException):
                    q._finalize(t, 400, {"error": str(res)})
                else:
                    q._finalize(t, 200, res)
