"""Mid-plan carry checkpoints: make a long commit scan killable anywhere.

The run journal (journal.py, PR 5) commits *run-level* units — a capacity
trial, a bench segment — so a crash between units loses at most one unit.
But the unit that dominates wall-clock is the commit scan itself: a
`plan_1m_100k` sweep is hours inside ONE schedule_scenarios dispatch, and
a SIGKILL there threw all of it away. This module closes that gap for the
chunked commit driver (ops/fast.py, OSIM_COMMIT_CHUNK > 0):

  - after every chunk, a `plan_chunk` journal record commits (chunk index,
    pods committed, the carry's `digest_fold` chain digest) — fsync'd
    before the next chunk dispatches, so the journal always names the last
    chunk that finished;
  - every OSIM_CKPT_EVERY chunks (default 4) the carry and the placement
    prefix are atomically persisted to `<run_dir>/ckpt/` (np.savez via
    tmp + fsync + rename — a torn snapshot is either absent or detected by
    its embedded digest and skipped in favor of the previous one);
  - on resume (`simon runs resume`) the newest snapshot whose recomputed
    digest matches is restored, its chunks are *skipped*, the journal tail
    is replayed — every re-executed chunk's digest is cross-checked against
    the journaled record — and the plan continues mid-scan. The snapshot
    holds plain numpy leaves; ops.fast.carry_from_host re-pins them onto
    whatever mesh the resumed process has NOW (4-dev -> 2-dev -> CPU
    elastic resume), which is safe because the commit arithmetic is
    sharding-independent.

Plan identity: plans are keyed `<seq>:<N>x<P>x<S>c<C>` where `seq` counts
`plan_done` records belonging to *completed* top-level journal units
(trial/sweep/final/segment). A resumed process replays completed units
from their journal records without re-planning, then re-executes the
interrupted unit from its first plan — so its begin_plan calls see the
same seq values the crashed run assigned, and snapshot/journal records
line up by construction.

What is snapshotted: the stacked Carry leaves and the committed placement
prefix (nodes/reasons/takes). What is NOT: the node table, pod batch,
weights and valid masks — those are deterministic re-encodes of the run's
config, and the resumed process rebuilds them (forcing the crashed run's
search shape) before the first chunk.
"""

from __future__ import annotations

import io
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils import flightrec, metrics
from ..utils.tracing import log
from .journal import RunJournal, atomic_write

CKPT_DIR = "ckpt"
OUTPUT_NAMES = ("nodes", "reasons", "gpu_take", "vg_take", "dev_take")
DEFAULT_CKPT_EVERY = 4


class CheckpointError(Exception):
    """A snapshot could not be written, or a re-executed chunk's digest
    contradicts its journaled `plan_chunk` record (non-deterministic replay
    or journal corruption — either way the resume is not byte-identical
    and must not pretend to be)."""


def checkpoint_every() -> int:
    """Chunks between carry snapshots (`OSIM_CKPT_EVERY`, default 4).
    `plan_chunk` journal records are per-chunk regardless; this knob only
    paces the (heavier) atomic carry+prefix snapshot."""
    try:
        return max(1, int(os.environ.get("OSIM_CKPT_EVERY", "") or
                          DEFAULT_CKPT_EVERY))
    except ValueError:
        return DEFAULT_CKPT_EVERY


def _safe(key: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", key)


@dataclass
class PlanRestore:
    """A verified snapshot handed to the chunk loop on resume."""

    chunks_done: int
    pods_done: int
    digest: int
    carry: Dict[str, np.ndarray]
    outputs: Tuple[np.ndarray, ...]


@dataclass
class PlanState:
    """Per-plan bookkeeping between begin_plan and finish_plan."""

    key: str
    n_chunks: int
    restore: Optional[PlanRestore] = None
    # journal-tail digests from a crashed run: chunk -> digest. Re-executed
    # chunks are cross-checked against these and not re-journaled.
    journaled: Dict[int, int] = field(default_factory=dict)
    done_digest: Optional[int] = None
    since_snapshot: int = 0
    snapshots: List[str] = field(default_factory=list)


class PlanCheckpointer:
    """Checkpoint/restore driver for one journaled run's chunked plans.

    Installed around plan_capacity (engine/capacity.py) whenever the run
    has a journal; the chunked commit driver picks it up through
    `active_checkpointer()` so ops/ stays free of durable imports."""

    def __init__(
        self,
        journal: RunJournal,
        resume: bool = False,
        every: Optional[int] = None,
    ) -> None:
        self.journal = journal
        self.run_dir = journal.run_dir
        self.every = every if every else checkpoint_every()
        self._resume = resume
        self._seq = 0
        # plan key -> {"chunks": {i: digest}, "done": digest|None} for the
        # interrupted unit's records only (see module docstring)
        self._tail: Dict[str, Dict[str, Any]] = {}
        if resume:
            self._replay(journal.events())

    # -- resume bookkeeping -------------------------------------------------

    def _replay(self, events: List[Dict[str, Any]]) -> None:
        done_seen = 0
        base = 0
        tail: Dict[str, Dict[str, Any]] = {}
        for e in events:
            ev = e.get("event")
            if ev == "plan_chunk":
                t = tail.setdefault(
                    str(e.get("plan")), {"chunks": {}, "done": None}
                )
                try:
                    t["chunks"][int(e.get("chunk", -1))] = int(
                        str(e.get("digest", "")), 16
                    )
                except ValueError:
                    pass
            elif ev == "plan_done":
                t = tail.setdefault(
                    str(e.get("plan")), {"chunks": {}, "done": None}
                )
                try:
                    t["done"] = int(str(e.get("digest", "")), 16)
                except ValueError:
                    pass
                done_seen += 1
            elif ev in ("trial", "sweep", "final", "segment", "run_end"):
                # a completed top-level unit: everything before it replays
                # from its own record, never through the chunk loop
                base = done_seen
                tail = {}
        self._seq = base
        self._tail = tail

    # -- plan lifecycle -----------------------------------------------------

    def begin_plan(
        self, *, n_nodes: int, p_real: int, s_pad: int, chunk: int,
        n_chunks: int,
    ) -> PlanState:
        key = f"{self._seq}:{n_nodes}x{p_real}x{s_pad}c{chunk}"
        t = self._tail.get(key, {"chunks": {}, "done": None})
        plan = PlanState(
            key=key, n_chunks=n_chunks, journaled=dict(t["chunks"]),
            done_digest=t["done"],
        )
        if self._resume and (plan.journaled or plan.done_digest is not None):
            plan.restore = self._load_restore(key)
        return plan

    def on_chunk(
        self,
        plan: PlanState,
        chunk: int,
        pods_done: int,
        digest: int,
        carry_s,
        outs: List[Tuple[np.ndarray, ...]],
    ) -> Optional[Dict[str, np.ndarray]]:
        """Commit chunk `chunk`'s completion. Returns the host carry leaves
        when this chunk closed a snapshot interval (the caller reuses them
        as its device-loss rollback point), else None."""
        prev = plan.journaled.get(chunk)
        if prev is not None and prev != digest:
            raise CheckpointError(
                f"plan {plan.key} chunk {chunk}: re-executed digest "
                f"{digest:08x} != journaled {prev:08x} — resume is not "
                "byte-identical, refusing to continue"
            )
        if prev is None:
            self.journal.append(
                "plan_chunk", plan=plan.key, chunk=chunk, pods=pods_done,
                digest=f"{digest:08x}",
            )
        flightrec.note(
            "plan-chunk", plan=plan.key, chunk=chunk,
            digest=f"{digest:08x}",
        )
        plan.since_snapshot += 1
        if plan.since_snapshot >= self.every and chunk + 1 < plan.n_chunks:
            plan.since_snapshot = 0
            return self._snapshot(plan, chunk + 1, pods_done, digest,
                                  carry_s, outs)
        return None

    def finish_plan(self, plan: PlanState, digest: int) -> None:
        if plan.done_digest is not None and plan.done_digest != digest:
            raise CheckpointError(
                f"plan {plan.key}: final digest {digest:08x} != journaled "
                f"plan_done {plan.done_digest:08x}"
            )
        if plan.done_digest is None:
            self.journal.append(
                "plan_done", plan=plan.key, chunks=plan.n_chunks,
                digest=f"{digest:08x}",
            )
        self._seq += 1

    # -- snapshot I/O -------------------------------------------------------

    def _snapshot(
        self,
        plan: PlanState,
        chunks_done: int,
        pods_done: int,
        digest: int,
        carry_s,
        outs: List[Tuple[np.ndarray, ...]],
    ) -> Dict[str, np.ndarray]:
        from ..ops import fast as _fast  # lazy: ops must not import durable

        host = _fast.carry_to_host(carry_s)
        arrays: Dict[str, np.ndarray] = {
            f"carry_{k}": v for k, v in host.items()
        }
        for k, name in enumerate(OUTPUT_NAMES):
            arrays[f"out_{name}"] = np.concatenate(
                [o[k] for o in outs], axis=1
            )
        meta = {
            "key": plan.key, "chunks_done": chunks_done,
            "pods_done": pods_done, "digest": f"{digest:08x}",
        }
        arrays["meta"] = np.frombuffer(
            json.dumps(meta, sort_keys=True).encode(), dtype=np.uint8
        ).copy()
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        data = buf.getvalue()
        ckpt_dir = os.path.join(self.run_dir, CKPT_DIR)
        os.makedirs(ckpt_dir, exist_ok=True)
        path = os.path.join(
            ckpt_dir, f"plan-{_safe(plan.key)}-c{chunks_done:06d}.npz"
        )
        atomic_write(path, data)
        metrics.CHECKPOINT_BYTES.inc(len(data))
        flightrec.note(
            "plan-snapshot", plan=plan.key, chunks=chunks_done,
            bytes=len(data), digest=f"{digest:08x}",
        )
        plan.snapshots.append(path)
        # keep the last two snapshots: the previous one is the fallback when
        # the newest turns out torn/corrupt on resume
        while len(plan.snapshots) > 2:
            try:
                os.remove(plan.snapshots.pop(0))
            except OSError:
                pass
        return host

    def _load_restore(self, key: str) -> Optional[PlanRestore]:
        from ..ops import fast as _fast  # lazy: ops must not import durable

        ckpt_dir = os.path.join(self.run_dir, CKPT_DIR)
        try:
            names = sorted(os.listdir(ckpt_dir), reverse=True)
        except OSError:
            return None
        prefix = f"plan-{_safe(key)}-c"
        for name in names:
            if not (name.startswith(prefix) and name.endswith(".npz")):
                continue
            path = os.path.join(ckpt_dir, name)
            restore = self._verify_snapshot(key, path, _fast)
            if restore is not None:
                return restore
            log.warning(
                "checkpoint %s: torn or corrupt snapshot skipped "
                "(falling back to the previous one)", path,
            )
        return None

    def _verify_snapshot(
        self, key: str, path: str, _fast
    ) -> Optional[PlanRestore]:
        """Load + verify one snapshot; None if torn/corrupt/mismatched."""
        try:
            with np.load(path) as z:
                arrays = {k: z[k] for k in z.files}
            meta = json.loads(bytes(arrays.pop("meta").tobytes()).decode())
            if str(meta.get("key")) != key:
                return None
            carry = {
                k[len("carry_"):]: v
                for k, v in arrays.items() if k.startswith("carry_")
            }
            outputs = tuple(
                arrays[f"out_{name}"] for name in OUTPUT_NAMES
            )
            digest = int(str(meta.get("digest", "")), 16)
            if _fast.scenario_carry_digest_host(carry) != digest:
                return None
            return PlanRestore(
                chunks_done=int(meta["chunks_done"]),
                pods_done=int(meta["pods_done"]),
                digest=digest,
                carry=carry,
                outputs=outputs,
            )
        except Exception:
            return None


# ---------------------------------------------------------------------------
# Installation point, mirroring resilience.faults: None = production, and
# the chunk loop's lookup is one attribute read.
# ---------------------------------------------------------------------------

_active: Optional[PlanCheckpointer] = None


def active_checkpointer() -> Optional[PlanCheckpointer]:
    return _active


class installed:
    """Context manager: route chunk checkpoints to `cp` for the duration
    of a block (plan_capacity installs one per journaled call)."""

    def __init__(self, cp: PlanCheckpointer) -> None:
        self.cp = cp
        self._prev: Optional[PlanCheckpointer] = None

    def __enter__(self) -> PlanCheckpointer:
        global _active
        self._prev = _active
        _active = self.cp
        return self.cp

    def __exit__(self, *exc: Any) -> None:
        global _active
        _active = self._prev
