"""Durable runs: journaled checkpoint/resume + watchdog-guarded execution.

`journal` is the per-run write-ahead log (JSONL, fsync per record) that
lets a crashed/preempted capacity sweep or bench ladder resume from its
committed trials; `watchdog` puts hard deadlines around backend
acquisition and blocking device calls and degrades TPU→CPU with honest
top-level provenance instead of hanging. See docs/durability.md.
"""

from .checkpoint import (  # noqa: F401
    CheckpointError,
    PlanCheckpointer,
    active_checkpointer,
    checkpoint_every,
)
from .journal import (  # noqa: F401
    JournalError,
    RunJournal,
    atomic_write,
    completed_segments,
    default_runs_root,
    list_runs,
    replay,
    summarize_run,
)
from .watchdog import (  # noqa: F401
    DeadlineExceeded,
    acquire_backend,
    backend_deadline_s,
    call_deadline_s,
    guarded_call,
)
