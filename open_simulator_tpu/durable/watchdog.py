"""Watchdogs: hard deadlines around the calls that have actually hung.

Every bench round since r03 wedged the same way: backend init against the
TPU tunnel blocked forever, the process sat silent, and the round was
eventually killed by a human — losing every completed trial and (worse)
sometimes banking a CPU capture under a TPU label. The fix is the classic
host-side watchdog: run the blocking call in a worker thread, poll a
monotonic clock, and when the deadline passes raise `DeadlineExceeded` in
the *caller* so the run can degrade deliberately instead of hanging.

Two deadlines, both env-tunable (see docs/durability.md):

    OSIM_BACKEND_DEADLINE_S  (default 90)  backend acquisition / first
                                           device contact
    OSIM_CALL_DEADLINE_S     (default 0)   any guarded compile/execute
                                           call; 0 disables

`acquire_backend` is the degradation ladder in code form:

    probe backend under deadline
      └─ timeout/error → journal `backend_retry`, warm the persistent
         compile cache, probe once more under a fresh deadline
           └─ timeout/error → pin JAX_PLATFORMS=cpu (jax.config.update,
              authoritative over the site hook), journal
              `backend_fallback`, stamp device/fallback/fallback_reason

The stamped dict is what bench/apply merge as *top-level* output fields —
the honest-provenance contract that kills the silent-mislabel class
(ADVICE.md): a CPU-fallback result can no longer masquerade as TPU.

Caveat shared by every host-side watchdog: an abandoned worker thread may
still hold the GIL-released blocking call (XLA compile, RPC). We cannot
kill it — we *can* stop waiting, record the timeout durably, and hand the
run a working (CPU) backend. The daemon flag keeps the zombie from
blocking interpreter exit.

Tests inject `clock`/`poll_s` (and a fake probe) so deadline behavior is
provable without sleeping — same idiom as resilience/policy.py.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..resilience import faults
from ..utils import metrics
from ..utils.platform import enable_compilation_cache, ensure_platform
from ..utils.tracing import log, span

DEFAULT_BACKEND_DEADLINE_S = 90.0


class DeadlineExceeded(Exception):
    """A guarded call outlived its deadline. The worker may still be
    running (blocking native code is unkillable from the host); the caller
    must treat the backend/call as lost and degrade."""

    def __init__(self, stage: str, deadline_s: float) -> None:
        super().__init__(f"{stage} exceeded {deadline_s:g}s deadline")
        self.stage = stage
        self.deadline_s = deadline_s


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        log.warning("%s=%r is not a number; using %g", name, raw, default)
        return default


def backend_deadline_s() -> float:
    return _env_float("OSIM_BACKEND_DEADLINE_S", DEFAULT_BACKEND_DEADLINE_S)


def call_deadline_s() -> float:
    """Deadline for guarded compile/execute calls; 0 = watchdog off."""
    return _env_float("OSIM_CALL_DEADLINE_S", 0.0)


def guarded_call(
    stage: str,
    fn: Callable[[], Any],
    deadline_s: float,
    *,
    clock: Callable[[], float] = time.monotonic,
    poll_s: float = 0.25,
    journal: Any = None,
) -> Any:
    """Run `fn()` in a watchdog-guarded worker; raise DeadlineExceeded if it
    doesn't finish within `deadline_s` (<=0 runs `fn` inline, unguarded).

    The heartbeat is the poll loop itself: the host wakes every `poll_s`,
    re-reads the clock, and decides liveness — so a wedged native call
    can't take the supervising thread down with it."""
    if deadline_s <= 0:
        return fn()

    result: List[Any] = []
    error: List[BaseException] = []
    done = threading.Event()

    def _worker() -> None:
        try:
            result.append(fn())
        except BaseException as e:  # noqa: B036 - must forward KeyboardInterrupt etc.
            error.append(e)
        finally:
            done.set()

    with span("watchdog", stage=stage, deadline_s=deadline_s):
        t = threading.Thread(target=_worker, name=f"osim-guarded-{stage}", daemon=True)
        start = clock()
        t.start()
        while not done.is_set():
            remaining = deadline_s - (clock() - start)
            if remaining <= 0 and not done.is_set():
                metrics.WATCHDOG_FIRED.inc(stage=stage)
                log.error("watchdog: %s exceeded %gs deadline", stage, deadline_s)
                if journal is not None:
                    journal.append("watchdog", stage=stage, deadline_s=deadline_s)
                try:
                    # flight recorder (utils/flightrec.py): dump the
                    # recent-span/metric/journal ring next to the journal —
                    # the wedge evidence a post-mortem needs, captured at
                    # the moment of the fire, never able to worsen it
                    from ..utils import flightrec

                    flightrec.dump(
                        "watchdog",
                        run_dir=getattr(journal, "run_dir", None),
                        error=f"{stage} exceeded {deadline_s:g}s deadline",
                    )
                except Exception:
                    pass
                raise DeadlineExceeded(stage, deadline_s)
            done.wait(min(poll_s, max(remaining, 0.001)))
    if error:
        raise error[0]
    return result[0]


# ---------------------------------------------------------------------------
# Backend acquisition ladder.
# ---------------------------------------------------------------------------

def _default_probe() -> str:
    """First device contact: honor JAX_PLATFORMS, touch a device, return its
    name. This is exactly the call that wedged rounds r03–r05, so it is the
    fault-injection point for backend hangs (target=backend, op=acquire)."""
    rule = faults.maybe_inject("backend", "acquire")
    if rule is not None:
        faults.apply_backend_fault(rule)
    ensure_platform()
    import jax
    import jax.numpy as jnp

    jnp.zeros(4).block_until_ready()
    return str(jax.devices()[0])


def warmup_requested() -> bool:
    """OSIM_WARMUP=1 opts runs into the pre-acquisition warmup phase."""
    return os.environ.get("OSIM_WARMUP", "").strip().lower() in (
        "1", "true", "yes", "on",
    )


def _warm_phase(deadline_s: float, journal: Any, info: Dict[str, Any]) -> None:
    """Best-effort AOT warmup right after first device contact: bank every
    audited jit entry + the sweep rehearsal into the persistent compile
    cache while nothing is being timed, and journal the outcome so a warm
    cache is recorded provenance, not luck. A timeout or error is journaled
    and swallowed — the run proceeds cold rather than dying here (the
    watchdog still guards every later compile)."""
    from ..engine.warmup import run_warmup

    try:
        report = guarded_call(
            "warmup", run_warmup, deadline_s, journal=journal
        )
    except Exception as e:
        log.warning("warmup phase failed (%s); continuing cold", e)
        info["warmup"] = {"ok": False, "error": str(e)}
        if journal is not None:
            journal.append("warmup_error", error=str(e))
        return
    info["warmup"] = {
        "ok": report.ok,
        "entries": len(report.entries),
        "seconds": round(report.seconds, 3),
        "cold_compiles": report.cold_compiles,
        "cache_dir": report.cache_dir,
    }
    if journal is not None:
        journal.append(
            "warmup",
            ok=report.ok,
            entries=len(report.entries),
            seconds=round(report.seconds, 3),
            cold_compiles=report.cold_compiles,
            cache_dir=report.cache_dir,
        )


def acquire_backend(
    deadline_s: Optional[float] = None,
    journal: Any = None,
    *,
    probe: Optional[Callable[[], str]] = None,
    clock: Callable[[], float] = time.monotonic,
    poll_s: float = 0.25,
    warmup: Optional[bool] = None,
) -> Dict[str, Any]:
    """Acquire a working JAX backend under a hard deadline, degrading
    TPU→CPU rather than hanging or lying.

    `warmup` (default: OSIM_WARMUP env) runs the AOT warmup phase
    (engine/warmup.run_warmup) right after first device contact, under its
    own watchdog deadline, journaling a `warmup` event — so downstream
    capture windows open against a provably banked compile cache.

    Returns a provenance dict — `{"device": ...}` plus, after degradation,
    `{"fallback": "cpu", "fallback_reason": ...}` (and `{"warmup": ...}`
    when the phase ran) — that callers must merge as TOP-LEVEL fields of
    their output JSON."""
    if deadline_s is None:
        deadline_s = backend_deadline_s()
    if warmup is None:
        warmup = warmup_requested()
    if warmup:
        # the cache dir must be configured before the FIRST compile (the
        # probe's device touch): jax initializes its persistent-cache
        # singleton once, and a cache configured after that never serves
        # hits in this process
        enable_compilation_cache()
    probe_fn = probe or _default_probe
    info: Dict[str, Any] = {}

    def _try(stage: str) -> str:
        return guarded_call(
            stage, probe_fn, deadline_s, clock=clock, poll_s=poll_s, journal=journal
        )

    try:
        device = _try("backend-acquire")
        info["device"] = device
        if journal is not None:
            journal.append("backend", device=device)
        if warmup:
            _warm_phase(deadline_s, journal, info)
        return info
    except Exception as first_err:  # DeadlineExceeded or a real probe error
        # One journaled retry from the persistent compile cache: warm-cache
        # init skips the compile window that eats most of the deadline
        # (76 s compile in BENCH_r02).
        cache_dir = enable_compilation_cache()
        if journal is not None:
            journal.append(
                "backend_retry",
                error=str(first_err),
                compile_cache=str(cache_dir or ""),
            )
        log.warning(
            "backend acquisition failed (%s); retrying once with persistent "
            "compile cache", first_err,
        )
        try:
            device = _try("backend-retry")
            info["device"] = device
            if journal is not None:
                journal.append("backend", device=device, retried=True)
            if warmup:
                _warm_phase(deadline_s, journal, info)
            return info
        except Exception as second_err:
            reason = (
                f"backend acquisition timed out/failed twice: "
                f"{first_err}; retry: {second_err}"
            )
            log.error("degrading to CPU: %s", reason)
            os.environ["JAX_PLATFORMS"] = "cpu"
            try:
                import jax

                jax.config.update("jax_platforms", "cpu")
                device = str(jax.devices()[0])
            except Exception as cpu_err:
                raise RuntimeError(
                    f"CPU fallback failed after: {reason} ({cpu_err})"
                )
            info.update(device=device, fallback="cpu", fallback_reason=reason)
            if journal is not None:
                journal.append(
                    "backend_fallback", device=device, fallback="cpu",
                    fallback_reason=reason,
                )
            return info
