"""Run journal: an append-only JSONL write-ahead log per run directory.

Long capacity sweeps and bench ladders are the runs that matter most and
the runs most likely to die: the TPU tunnel wedges backend init (BENCH
r03–r05), a preemptible host disappears mid-bisection, a deadline kills the
process. Before this journal existed a wedged 100k-pod sweep lost *all* of
its completed trials. The WAL discipline here is the same one a training
stack applies to checkpoints: commit every unit of proved work (a capacity
trial, a bench segment, a backend acquisition) to durable storage *before*
moving on, so a crashed run resumes from what it already proved instead of
starting over.

Format: `<run_dir>/journal.jsonl`, one JSON object per line, in append
order. Every record carries `seq` (monotonic), `ts` (epoch seconds) and
`event` (the record type); everything else is event payload. Well-known
events (see docs/durability.md for the full schema):

    run_start / run_resume / run_end   run lifecycle + metadata
    backend / backend_retry / backend_fallback   acquisition ladder
    trial                               one committed capacity probe
    final                               the plan-materializing replay
    segment                             one completed bench segment
    watchdog                            a deadline fired

Durability: appends are `write + flush + fsync` per record — a SIGKILL
after `append()` returns can never lose that record. Readers tolerate the
one failure mode fsync-per-line leaves open: a torn final line (crash
mid-append) is discarded, not fatal, and `RunJournal.open` truncates the
torn tail so subsequent appends produce a valid file. Whole-file artifacts
(e.g. the run's `outcome.json`) go through `atomic_write` (tmp + fsync +
rename) instead, so they are either absent or complete.

Every append is mirrored into the observability stack: a
`journal-append` tracing span (so journal activity shows up in
OSIM_TRACE_FILE timelines) and the `osim_journal_events_total{event=}`
counter.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, TextIO, Tuple

from ..resilience import faults
from ..utils import metrics
from ..utils.tracing import log, span

JOURNAL_NAME = "journal.jsonl"


class JournalError(Exception):
    """A journal could not be opened or appended to."""


def atomic_write(path: str, data: "str | bytes") -> None:
    """Write a whole file atomically: tmp + fsync + rename (+ best-effort
    directory fsync). Readers see either the old content or the new,
    never a torn mix — the discipline every non-append run artifact
    (outcome.json, bench JSON snapshots) goes through."""
    if isinstance(data, str):
        data = data.encode()
    path = os.path.abspath(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
    try:
        os.write(fd, data)
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, path)
    try:
        dfd = os.open(os.path.dirname(path), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass  # directory fsync is belt-and-braces; not all filesystems allow it


def _scan(path: str) -> Tuple[List[Dict[str, Any]], int]:
    """Parse a journal file. Returns (events, good_bytes) where good_bytes
    is the file offset just past the last intact record. A torn/corrupt
    line and everything after it are discarded (conservative prefix): a
    WAL's guarantees only hold up to the first broken record."""
    events: List[Dict[str, Any]] = []
    good = 0
    try:
        with open(path, "rb") as fh:
            raw = fh.read()
    except FileNotFoundError:
        return events, 0
    offset = 0
    for line in raw.split(b"\n"):
        consumed = len(line) + 1  # +1 for the newline split removed
        stripped = line.strip()
        if stripped:
            # a record is intact only if it parsed AND its newline made it
            # to disk (offset + len(line) < len(raw)); a crash mid-append
            # can leave a syntactically-complete JSON prefix with no
            # terminator, which the next append would otherwise corrupt
            terminated = offset + len(line) < len(raw)
            try:
                rec = json.loads(stripped)
            except ValueError:
                rec = None
            if not terminated or not isinstance(rec, dict) or "event" not in rec:
                log.warning(
                    "journal %s: discarding torn/invalid record at byte %d "
                    "(and any records after it)", path, offset,
                )
                break
            events.append(rec)
            good = offset + consumed
        offset += consumed
    return events, good


def replay(run_dir: str) -> List[Dict[str, Any]]:
    """Read-only replay of a run directory's journal, oldest record first.
    Torn tails are discarded, never fatal; a missing journal is []."""
    events, _ = _scan(os.path.join(run_dir, JOURNAL_NAME))
    return events


class RunJournal:
    """Append handle + replayed history for one run directory.

    Not safe for concurrent writers from multiple processes (a run owns its
    directory); appends from multiple threads of one process are fine."""

    run_dir: str
    path: str
    _events: List[Dict[str, Any]]
    _seq: int
    _lock: threading.Lock
    _fh: TextIO

    def __init__(self, run_dir: str) -> None:
        raise TypeError("use RunJournal.open(run_dir)")

    @classmethod
    def open(cls, run_dir: str) -> "RunJournal":
        run_dir = os.path.abspath(run_dir)
        try:
            os.makedirs(run_dir, exist_ok=True)
        except OSError as e:
            raise JournalError(f"cannot create run dir {run_dir}: {e}")
        path = os.path.join(run_dir, JOURNAL_NAME)
        events, good = _scan(path)
        if os.path.exists(path) and good < os.path.getsize(path):
            # repair the torn tail in place so future appends start on a
            # record boundary (the discarded bytes were never acknowledged)
            with open(path, "rb+") as fh:
                fh.truncate(good)
        self = object.__new__(cls)
        self.run_dir = run_dir
        self.path = path
        self._events = events
        self._seq = (events[-1]["seq"] + 1) if events else 0
        self._lock = threading.Lock()
        try:
            self._fh = open(path, "a", encoding="utf-8")
        except OSError as e:
            raise JournalError(f"cannot open journal {path}: {e}")
        return self

    # -- write path ---------------------------------------------------------

    def append(self, event: str, **payload: Any) -> Dict[str, Any]:
        """Durably commit one record (write + flush + fsync) and return it.
        The record is on disk when this returns — a crash immediately after
        cannot lose it."""
        rule = faults.maybe_inject("journal", event)
        if rule is not None:
            faults.apply_journal_fault(rule)
        with self._lock:
            rec: Dict[str, Any] = {
                "seq": self._seq,
                "ts": round(time.time(), 6),
                "event": event,
            }
            rec.update(payload)
            with span("journal-append", event=event):
                try:
                    self._fh.write(json.dumps(rec, sort_keys=True) + "\n")
                    self._fh.flush()
                    os.fsync(self._fh.fileno())
                except (OSError, ValueError) as e:
                    raise JournalError(f"journal append failed: {e}")
            self._seq += 1
            self._events.append(rec)
        metrics.JOURNAL_EVENTS.inc(event=event)
        try:
            # flight recorder breadcrumb (utils/flightrec.py): the event key
            # + seq + current trace id, so a post-crash dump correlates its
            # span ring to this journal's records. Never on the durability
            # path — an import/ring failure cannot fail the append.
            from ..utils import flightrec

            flightrec.record_journal(event, rec["seq"], self.run_dir)
        except Exception:
            pass
        return rec

    def close(self) -> None:
        try:
            self._fh.close()
        except OSError:
            pass

    def __enter__(self) -> "RunJournal":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # -- read path ----------------------------------------------------------

    def events(self, event: Optional[str] = None) -> List[Dict[str, Any]]:
        """Committed records, oldest first (optionally one event type)."""
        with self._lock:
            evs = list(self._events)
        if event is not None:
            evs = [e for e in evs if e.get("event") == event]
        return evs

    def has(self, event: str) -> bool:
        return any(e.get("event") == event for e in self.events())


# ---------------------------------------------------------------------------
# Replay helpers shared by the capacity planner, bench, and `simon runs`.
# ---------------------------------------------------------------------------

def completed_segments(events: List[Dict[str, Any]]) -> Dict[str, Dict]:
    """segment name -> journaled result dict (last write wins)."""
    out: Dict[str, Dict] = {}
    for e in events:
        if e.get("event") == "segment" and e.get("segment"):
            out[str(e["segment"])] = e.get("result") or {}
    return out


def default_runs_root() -> str:
    """Where `simon runs` looks by default (OSIM_RUNS_DIR overrides)."""
    return os.environ.get("OSIM_RUNS_DIR", "").strip() or os.path.join(
        os.path.expanduser("~"), ".cache", "open-simulator-tpu", "runs"
    )


def summarize_run(run_dir: str) -> Dict[str, Any]:
    """One run directory -> a flat summary row for `simon runs list/show`."""
    events = replay(run_dir)
    by = {}
    for e in events:
        by.setdefault(e.get("event"), []).append(e)
    start = (by.get("run_start") or [{}])[0]
    status = "in-flight/crashed"
    outcome = ""
    if by.get("run_end"):
        status = "completed"
        outcome = str(by["run_end"][-1].get("outcome", ""))
    backend = (by.get("backend") or by.get("backend_fallback") or [{}])[-1]
    return {
        "run_dir": os.path.abspath(run_dir),
        "name": os.path.basename(os.path.abspath(run_dir)),
        "started": start.get("ts"),
        "kind": start.get("kind", ""),
        "config": start.get("simon_config", ""),
        "status": status,
        "outcome": outcome,
        "events": len(events),
        "trials": len(by.get("trial") or []),
        "segments": len(completed_segments(events)),
        "resumes": len(by.get("run_resume") or []),
        "watchdogs": len(by.get("watchdog") or []),
        "device": backend.get("device", "")
        or ("cpu" if backend.get("fallback") == "cpu" else ""),
        "fallback": backend.get("fallback", ""),
    }


def list_runs(root: str) -> List[Dict[str, Any]]:
    """Summaries for every journaled run directory under `root`, newest
    first (by run_start timestamp, unknown timestamps last)."""
    out = []
    try:
        entries = sorted(os.listdir(root))
    except OSError:
        return out
    for name in entries:
        run_dir = os.path.join(root, name)
        if os.path.isfile(os.path.join(run_dir, JOURNAL_NAME)):
            out.append(summarize_run(run_dir))
    out.sort(key=lambda r: -(r["started"] or 0.0))
    return out
