"""Conflict-parallel wave commit: the round body (ROADMAP item 1).

The serial engines funnel every pod through one `lax.scan` step — N pods
means N sequential heavy filter/score sweeps, so `plan_200k_20k` is
wall-hours on CPU and a 1M-pod plan is 1M device steps no matter how many
chips the mesh has. The wave engine replaces that chain with a
Jacobi-style fixpoint over a *wave* of W pods:

  round r:
    1. REPLAY (cheap): scan the round r-1 choices (i32[W], -1 = no
       commit) through `commit_choice` — the row-wise O(row) form of the
       serial scan's commit arithmetic, ~1-2% of a schedule_step —
       emitting each pod's PRE-commit carry, the allocation takes, and
       the wave's exit carry.
    2. PROBE (heavy, data-parallel): re-decide every pod at its own
       prefix carry with the exact `schedule_step` filter/score/argmax/
       reason formulas, all W pods in one vmapped sweep.

  converged when the probe reproduces its own input choices; that
  round's replay outputs are then byte-identical to the serial scan.

Why the fixpoint is exact and always terminates: pod 0's prefix carry is
the wave-input carry in every round, so its choice is correct and stable
after round 1; inductively pod i's prefix depends only on choices
0..i-1, so it is correct and stable after round i+1 — at most W+1 rounds
(realistic waves converge in 2-3: round 1 decides, round 2 confirms).
Any fixpoint IS the serial solution, so convergence can never mask a
divergence. A naive "commit all non-colliding argmax winners" auction is
NOT serial-equivalent — score normalizations are global, several plugins
are carry-coupled, and two pods may legally pile onto one node — which
is why the probe re-decides against exact prefix carries instead.

Bit-identity: the replay applies `commit_choice` — bitwise equal to
`commit_onehot` by the row-extraction argument documented on it — to the
same (carry, pod, choice) inputs in the same order as the serial scan
(a -1 choice is a dropped scatter, exactly the all-False-onehot no-op
schedule_step produces for an unschedulable pod), and the probe is
schedule_step's own expression sequence, so no float is ever produced
by a different op sequence.
`simon prove --contract` replays all 151,875 small-scope universes
through the wave engine and must reproduce the banked placement digest
(budgets/commit_contract.json) bit-for-bit — that artifact, not this
docstring, is the admission proof the commit-order contract demands.

Knobs (all read per call, so tests can flip them):
  OSIM_WAVE_COMMIT  ""/unset = auto (wave when the plan is large enough
                    to amortize the rounds), "1" = force on, "0" = off —
                    the escape hatch back to the serial oracle.
  OSIM_WAVE_SIZE    pods per wave (default: OSIM_COMMIT_CHUNK if set,
                    else 256). Following the chunk size keeps the
                    checkpoint plan key and `plan_chunk` digest chain
                    identical to a serial chunked run (docs/durability).
  OSIM_WAVE_ROUNDS  fallback bound: a wave that has not converged after
                    this many rounds is re-run through the serial
                    chunked kernel (metric reason="max_rounds"). 0 =
                    no bound (the W+1 guarantee is the bound).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from .kernels import (
    NUM_FILTERS,
    commit_choice,
    run_filters,
    run_scores,
)

# Auto mode enables the wave engine only above this many pods: small
# plans (tier-1 tests, single-batch simulate calls) stay on the serial
# scan so they never pay wave compiles, while capacity-scale plans
# (10k+) get the conflict-parallel path without any opt-in.
WAVE_AUTO_MIN_PODS = 512

DEFAULT_WAVE_SIZE = 256
DEFAULT_MAX_ROUNDS = 24


def wave_mode() -> str:
    """'off' | 'on' | 'auto' from OSIM_WAVE_COMMIT."""
    raw = os.environ.get("OSIM_WAVE_COMMIT", "").strip()
    if raw == "0":
        return "off"
    if raw == "":
        return "auto"
    return "on"


def wave_size() -> int:
    """Pods per wave. Defaults to OSIM_COMMIT_CHUNK when chunking is on,
    so one wave = one checkpoint chunk and the `plan_chunk` digest chain
    (and the plan key itself) matches a serial chunked run of the same
    plan — resume interops in both directions."""
    for var in ("OSIM_WAVE_SIZE", "OSIM_COMMIT_CHUNK"):
        raw = os.environ.get(var, "").strip()
        if raw:
            try:
                v = int(raw)
            except ValueError:
                continue
            if v > 0:
                return v
    return DEFAULT_WAVE_SIZE


def wave_max_rounds() -> int:
    try:
        return max(
            0, int(os.environ.get("OSIM_WAVE_ROUNDS", "") or DEFAULT_MAX_ROUNDS)
        )
    except ValueError:
        return DEFAULT_MAX_ROUNDS


def _parallel_backend() -> bool:
    """Auto mode only helps where probes actually run in parallel: an
    accelerator backend, or a CPU with enough cores that the vmapped
    probe beats the serial chain on throughput, not just on dispatch
    count. On a 1-2 core CPU the serial scan is element-throughput-bound
    and a full-wave probe round costs about as much as serially scanning
    the whole wave, so auto stays off there (force with
    OSIM_WAVE_COMMIT=1 — still bit-identical, just not faster)."""
    try:
        if jax.default_backend() != "cpu":
            return True
    except Exception:
        pass
    return (os.cpu_count() or 1) >= 8


def wave_enabled(p_real: int) -> bool:
    """Should schedule_scenarios_host route this plan to the wave driver?"""
    mode = wave_mode()
    if mode == "off":
        return False
    if mode == "on":
        return True
    if not _parallel_backend():
        return False
    return int(p_real) >= max(WAVE_AUTO_MIN_PODS, 2 * wave_size())


def probe_choice(ns, weights, carry, pod, filter_on=None):
    """schedule_step minus the commit: decide ONE pod against `carry`
    exactly as the serial scan would — same mask, same -inf fold, same
    first-max argmax, same pod.valid gate, same reason histogram.
    Returns (node i32 scalar, -1 = unschedulable; reasons i32[F])."""
    mask, first_fail = run_filters(ns, carry, pod, filter_on)
    score = run_scores(ns, carry, pod, weights)
    score = jnp.where(mask, score, -jnp.inf)
    node = jnp.argmax(score)  # first max => lowest node index tie-break
    ok = jnp.any(mask) & pod.valid
    node_out = jnp.where(ok, node, -1)
    reasons = jnp.zeros(NUM_FILTERS, jnp.int32).at[
        jnp.clip(first_fail, 0, NUM_FILTERS - 1)
    ].add(jnp.where((first_fail < NUM_FILTERS) & ns.valid, 1, 0))
    reasons = jnp.where(ok, jnp.zeros_like(reasons), reasons)
    return node_out.astype(jnp.int32), reasons


def wave_round(ns, weights, carry, pods, choices, count, filter_on=None):
    """One Jacobi round for ONE lane (vmapped by ops/fast.py entries).

    `choices` i32[W] are the previous round's decisions (-1 initially and
    for no-commit pods). `count` is the live-pod gate (traced i32 scalar;
    None = every pod live, the universes variant). Returns
    (exit_carry, new_choices i32[W], reasons i32[W,F],
     gpu_take i32[W,G], vg_take f32[W,V], dev_take f32[W,DV])
    where the takes/exit carry replay THIS round's input choices — on the
    converged round (new_choices == choices) they are the serial scan's
    outputs bitwise.
    """
    w = choices.shape[0]
    idx = jnp.arange(w, dtype=jnp.int32)
    if count is not None:
        # gate dead (pad) steps by pinning their choice to -1: a -1
        # choice is a dropped scatter inside commit_choice, which leaves
        # the carry bitwise untouched — the same result as the serial
        # chunked kernel's per-leaf live gate, with zero extra work.
        choices = jnp.where(idx < count, choices, jnp.int32(-1))

    def replay(c, xs):
        pod, choice = xs
        c2, gpu_take, vg_take, dev_take = commit_choice(ns, c, pod, choice)
        return c2, (c, gpu_take.astype(jnp.int32), vg_take, dev_take)

    final, (pre, gpu_take, vg_take, dev_take) = jax.lax.scan(
        replay, carry, (pods, choices)
    )

    def probe(c, pod):
        return probe_choice(ns, weights, c, pod, filter_on)

    new_choices, reasons = jax.vmap(probe)(pre, pods)
    if count is not None:
        # pad steps pin to -1 so they can never block convergence (their
        # replay is a no-op commit and their outputs are trimmed anyway)
        new_choices = jnp.where(idx < count, new_choices, jnp.int32(-1))
    return final, new_choices, reasons, gpu_take, vg_take, dev_take
