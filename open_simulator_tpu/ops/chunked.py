"""Chunked batch scheduling: bound each device execution.

A 100k-step scan is one ~60s device execution — long enough to trip execution
watchdogs (observed as TPU worker restarts over the axon tunnel) and to starve
any interleaved work. Splitting the pod batch into fixed-size chunks and
threading the carry through keeps results bit-identical (the scan carry IS the
entire cluster state) while bounding each execution to a few seconds, giving
progress callbacks, and reusing one compiled executable for every chunk.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import jax
import numpy as np

from .encode import PodBatch
from .kernels import Carry, NodeStatic, schedule_batch
from .state import pod_rows_from_batch

DEFAULT_CHUNK = 8192


def _slice_batch(batch: PodBatch, start: int, chunk: int) -> PodBatch:
    """Fixed-size window [start, start+chunk) of the batch arrays, zero-padded
    past the end so every chunk compiles to the same shapes."""
    from dataclasses import fields, replace

    stop = min(start + chunk, batch.p)
    updates = {}
    for f in fields(batch):
        if f.name == "keys":
            continue
        arr = getattr(batch, f.name)
        window = arr[start:stop]
        if window.shape[0] < chunk:
            pad = np.zeros((chunk - window.shape[0],) + arr.shape[1:], arr.dtype)
            window = np.concatenate([window, pad], axis=0)
        updates[f.name] = window
    updates["keys"] = batch.keys[start:stop]
    return replace(batch, **updates)


def schedule_batch_chunked(
    ns: NodeStatic,
    carry: Carry,
    batch: PodBatch,
    weights,
    chunk: int = DEFAULT_CHUNK,
    progress: Optional[Callable[[int, int], None]] = None,
) -> Tuple[Carry, np.ndarray, np.ndarray]:
    """schedule_batch semantics over arbitrarily large batches.

    Returns (final carry, placements i32[batch.p], reasons i32[batch.p, F]).
    """
    total = batch.p
    if total <= chunk:
        rows = pod_rows_from_batch(batch)
        carry, nodes, reasons = schedule_batch(ns, carry, rows, weights)
        return carry, np.asarray(nodes), np.asarray(reasons)

    nodes_out: List[np.ndarray] = []
    reasons_out: List[np.ndarray] = []
    done = 0
    for start in range(0, total, chunk):
        rows = pod_rows_from_batch(_slice_batch(batch, start, chunk))
        carry, nodes, reasons = schedule_batch(ns, carry, rows, weights)
        # materialize per chunk: bounds device-queue depth and surfaces errors
        n = min(chunk, total - start)
        nodes_out.append(np.asarray(nodes)[:n])
        reasons_out.append(np.asarray(reasons)[:n])
        done += n
        if progress is not None:
            progress(done, total)
    return carry, np.concatenate(nodes_out), np.concatenate(reasons_out)
