"""Runtime sanitizer mode for the jit entry points (OSIM_SANITIZE=1).

`@sanitizable(name, ...)` stacks ABOVE the `jax.jit` decorator on every
production entry point (ops/fast.py, ops/grouped.py, ops/kernels.py,
ops/delta.py). With the env knob off the wrapper is a single dict
lookup + call-through to the jitted function, so the fast path stays the
fast path. With `OSIM_SANITIZE=1` the same entry runs under
`jax.experimental.checkify` with NaN, out-of-bounds-index and
division-by-zero checks: fuzz and chaos runs execute with lane-level
assertions armed, and any violation increments
`osim_sanitizer_violations_total{entry=}` and raises SanitizerViolation
with checkify's first-failure message.

The decorator deliberately does NOT replace the `jax.jit` spelling —
analysis/lint.py detects jit roots syntactically, and the jaxpr auditor
calls `.trace()` on the module attribute — so the wrapper delegates
`trace`/`lower` to the underlying jit Function and keeps the original
decorator line intact underneath.

Checkify errors caught (the ISSUE's "NaN/OOB/div" set):

  * checkify.nan_checks   — a primitive *produced* a NaN. Note this does
    not flag infinities, so the deliberate -inf sentinels in fast.py's
    score lanes pass; only a genuine -inf * 0.0 style poisoning trips it.
  * checkify.index_checks — out-of-bounds gather/scatter/dynamic-slice.
  * checkify.div_checks   — integer division by zero.
"""

from __future__ import annotations

import functools
import os
import threading
from typing import Any, Callable, Sequence

from ..utils import metrics

SANITIZE_ENV = "OSIM_SANITIZE"


class SanitizerViolation(RuntimeError):
    """A checkify error (NaN/OOB/div) fired inside a sanitized jit entry."""

    def __init__(self, entry: str, message: str) -> None:
        super().__init__(f"{entry}: {message}")
        self.entry = entry
        self.check_message = message


def sanitize_enabled() -> bool:
    """True when OSIM_SANITIZE is set to anything but ''/'0'/'false'/'no'.
    Read per call, so tests and chaos runs can flip it without reimports.

    Lint sees this as jit-reachable only through the decorator expression
    on the entry points; it runs on the host before dispatch, never inside
    a trace."""
    return os.environ.get(SANITIZE_ENV, "").strip().lower() not in (  # osim: lint-ok[impure-read]
        "",
        "0",
        "false",
        "no",
    )


def _errors():
    from jax.experimental import checkify

    return checkify.nan_checks | checkify.index_checks | checkify.div_checks


def _has_tracer(args: tuple, kwargs: dict) -> bool:
    import jax

    return any(
        isinstance(leaf, jax.core.Tracer)
        for leaf in jax.tree_util.tree_leaves((args, kwargs))
    )


def sanitizable(
    name: str,
    static_argnames: Sequence[str] = (),
    skip_kwargs: Sequence[str] = (),
    donate_argnums: Sequence[int] = (),
) -> Callable:
    """Wrap a jitted entry point with an opt-in checkify layer.

    `name` keys the osim_sanitizer_violations_total{entry=} counter and
    matches jaxpr_audit's entry naming ("ops.fast:light_scan").
    `static_argnames` must repeat the underlying jit's static args so the
    checkified re-jit treats them identically. A truthy arg named in
    `skip_kwargs` (e.g. domain_select's use_pallas) bypasses sanitization
    whether it arrives by keyword or positionally: checkify cannot thread
    pallas_call's state effects (`JaxprInputEffect ... does not have
    corresponding input`), and the plain path already covers the shared
    math.

    `donate_argnums` must repeat the underlying jit's donated positional
    args. It is declarative: the jaxpr auditor reads it (as
    ``__osim_donate_argnums__``) to prove no donated arg aliases another
    arg of the same call, and callers/tests use it to know which inputs a
    call consumes. The checkified re-jit deliberately does NOT donate —
    sanitize mode trades the buffer reuse for intact inputs in checkify's
    failure reports; results are bit-identical either way.
    """
    static = tuple(static_argnames)
    skips = tuple(skip_kwargs)
    donated = tuple(donate_argnums)

    def deco(jitted: Callable) -> Callable:
        import inspect

        cache: dict = {}
        cache_lock = threading.Lock()
        params = list(
            inspect.signature(inspect.unwrap(jitted)).parameters
        )
        skip_pos = {k: params.index(k) for k in skips if k in params}

        def _checked() -> Callable:
            fn = cache.get("fn")
            if fn is None:
                with cache_lock:
                    fn = cache.get("fn")
                    if fn is None:
                        import jax
                        from jax.experimental import checkify

                        # checkify the raw function beneath the jit, not the
                        # jit Function: nesting jits would hand the inner one
                        # tracers for its static args. wraps() restores the
                        # original signature so static_argnames still binds
                        # args passed positionally (checkify's own wrapper
                        # is (*args, **kwargs)-opaque).
                        inner = inspect.unwrap(jitted)
                        checked = functools.wraps(inner)(
                            checkify.checkify(inner, errors=_errors())
                        )
                        fn = cache["fn"] = jax.jit(
                            checked, static_argnames=static
                        )
            return fn

        @functools.wraps(jitted)
        def wrapper(*args: Any, **kwargs: Any):
            if not sanitize_enabled():
                return jitted(*args, **kwargs)
            for k in skips:
                i = skip_pos.get(k, len(args))
                if kwargs.get(k) or (i < len(args) and args[i]):
                    return jitted(*args, **kwargs)
            if _has_tracer(args, kwargs):
                # already inside someone else's trace: the outer entry owns
                # the checkify scope
                return jitted(*args, **kwargs)
            err, out = _checked()(*args, **kwargs)
            msg = err.get()
            if msg:
                metrics.SANITIZER_VIOLATIONS.inc(entry=name)
                raise SanitizerViolation(name, msg)
            return out

        # jaxpr_audit captures the module attribute and calls .trace()/.lower()
        wrapper.trace = jitted.trace  # type: ignore[attr-defined]
        wrapper.lower = jitted.lower  # type: ignore[attr-defined]
        wrapper.__osim_sanitizable__ = name  # type: ignore[attr-defined]
        wrapper.__osim_donate_argnums__ = donated  # type: ignore[attr-defined]
        return wrapper

    return deco


def sanitized_entries(*modules) -> dict:
    """name -> wrapper for every @sanitizable attribute in `modules`
    (test/bench helper; mirrors jaxpr_audit.AUDIT_TARGETS coverage)."""
    out = {}
    for mod in modules:
        for attr in dir(mod):
            fn = getattr(mod, attr)
            tag = getattr(fn, "__osim_sanitizable__", None)
            if tag is not None:
                out[tag] = fn
    return out
