"""Device-side scheduling kernels: the pod×node Filter/Score/Select loop.

This is the TPU-native replacement for the reference's per-pod scheduleOne
cycle (`vendor/k8s.io/kubernetes/pkg/scheduler/core/generic_scheduler.go:131-175`,
16-goroutine fan-out at `internal/parallelize/parallelism.go:57`):

  - every Filter plugin is a vectorized boolean mask over all N nodes at once;
  - every Score plugin is an f32[N] kernel + its own normalize, combined by the
    profile's weights (`algorithmprovider/registry.go:71-148` defaults + the
    Simon plugin from `pkg/simulator/plugin/simon.go:45-101`);
  - host selection is a deterministic masked argmax (lowest node index wins
    ties — the reference's selectHost randomizes, we pin for reproducibility);
  - the sequential one-pod-at-a-time commit semantics of kube-scheduler are
    preserved by a `lax.scan` whose carry is the mutable cluster state
    (free resources + per-selector placement counts), so an entire pod batch
    schedules in ONE device computation with no host round-trips.

Everything here is shape-static and jit-safe; dynamic control flow is
expressed with lax.scan / jnp.where only.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .encode import (
    GPU_COUNT_IDX,
    OP_GT,
    OP_IN,
    OP_LT,
    OP_NOT_EXISTS,
    OP_NOT_IN,
    OP_PAD,
    OP_EXISTS,
)
from .sanitize import sanitizable

# Filter indices — order mirrors the kube filter plugin order so the
# first-failure reason attribution matches the reference's diagnostics.
F_UNSCHEDULABLE = 0
F_NODE_NAME = 1
F_TAINT = 2
F_NODE_AFFINITY = 3
F_NODE_PORTS = 4
F_RESOURCES = 5
F_SPREAD = 6
F_POD_AFFINITY = 7
F_STORAGE = 8
F_GPU = 9
F_EXTRA = 10  # out-of-tree device plugins (plugins/ registry)
NUM_FILTERS = 11

# Kube filter-plugin name -> filter index, for KubeSchedulerConfiguration
# enable/disable fidelity (utils.go:304-381 builds the full Filter plugin
# set; a user config may disable in-tree filters). Open-Local/Open-Gpu-Share
# are NOT listed: the reference injects them after the user config merge, so
# disabling them never takes effect (utils.go:337-347).
FILTER_PLUGIN_MAP = {
    "NodeUnschedulable": F_UNSCHEDULABLE,
    "NodeName": F_NODE_NAME,
    "TaintToleration": F_TAINT,
    "NodeAffinity": F_NODE_AFFINITY,
    "NodePorts": F_NODE_PORTS,
    "NodeResourcesFit": F_RESOURCES,
    "PodTopologySpread": F_SPREAD,
    "InterPodAffinity": F_POD_AFFINITY,
    # The volume filter family of the default provider — VolumeBinding,
    # VolumeRestrictions, NodeVolumeLimits (EBS/GCE/CSI/Azure), VolumeZone
    # (vendored algorithmprovider/registry.go:88-106) — is INERT in the
    # reference and therefore not implemented: MakeValidPod rewrites every
    # PVC volume to a hostPath volume before any pod reaches the scheduler
    # (utils.go:378-463, the `vol.PersistentVolumeClaim != nil` branch), so
    # those filters never see a PVC/bound-volume to act on, and open-local
    # storage runs through its own plugin instead (ops/kernels.py
    # local_storage_*). Config files naming them parse cleanly and their
    # enable/disable is a no-op, matching observable reference behavior.
}

FILTER_MESSAGES = (
    "node(s) were unschedulable",
    "node(s) didn't match the requested node name",
    "node(s) had taint that the pod didn't tolerate",
    "node(s) didn't match Pod's node affinity/selector",
    "node(s) didn't have free ports for the requested pod ports",
    "Insufficient resources",
    "node(s) didn't match pod topology spread constraints",
    "node(s) didn't match pod affinity/anti-affinity rules",
    "node(s) didn't have enough local storage",
    "node(s) didn't have enough free GPU memory",
    "node(s) were rejected by an out-of-tree filter plugin",
)

# Score weights, matching the default v1beta1 provider weights
# (SURVEY §2.2: registry.go:71-148) plus Simon at weight 1.
DEFAULT_WEIGHTS = {
    "balanced_allocation": 1.0,
    "least_allocated": 1.0,
    "node_affinity": 1.0,
    "taint_toleration": 1.0,
    "topology_spread": 2.0,
    "inter_pod_affinity": 1.0,
    "prefer_avoid_pods": 10000.0,
    "simon": 1.0,
    "gpu_share": 1.0,
    "open_local": 1.0,
}
# Fold order: the two carry-coupled terms come LAST (inter_pod_affinity,
# then topology_spread) so the fast paths' partial-sum prefix splits are
# exact left-fold prefixes (ops/fast.py: partial8 + w_ipa*ipa + w_sp*sp);
# node-local terms keep alphabetical order among themselves. Every path —
# naive scan, grouped, sort/micro/domain — folds in this one order, so the
# f32 summation (and every tie-break) stays internally consistent.
WEIGHT_ORDER = tuple(
    sorted(k for k in DEFAULT_WEIGHTS
           if k not in ("inter_pod_affinity", "topology_spread"))
) + ("inter_pod_affinity", "topology_spread")


def weights_array(weights: dict = DEFAULT_WEIGHTS) -> jnp.ndarray:
    return jnp.array([float(weights.get(k, 0.0)) for k in WEIGHT_ORDER], jnp.float32)


def combine_scores(by_name: dict, weights: jnp.ndarray, order=WEIGHT_ORDER):
    """Weighted score combination as an EXPLICIT left fold over `order`:
    ((w0*s0 + w1*s1) + w2*s2) + ... — every scheduling path (naive scan,
    grouped, light/sort/micro fast paths) uses this one function, so partial
    sums split exactly: fold(order) == fold(order[:-1]) + w_last*s_last by
    construction, with no reliance on XLA's reduce lowering."""
    total = None
    for i, k in enumerate(order):
        term = weights[i] * by_name[k]
        total = term if total is None else total + term
    return total


class NodeStatic(NamedTuple):
    """Immutable per-node tensors (device resident for a whole simulation)."""
    alloc: jnp.ndarray        # f32[N,R]
    label_pair: jnp.ndarray   # i32[N,L]
    label_key: jnp.ndarray    # i32[N,L]
    label_num: jnp.ndarray    # f32[N,L]
    taint_key: jnp.ndarray    # i32[N,T]
    taint_val: jnp.ndarray    # i32[N,T]
    taint_effect: jnp.ndarray  # i32[N,T]
    name_id: jnp.ndarray      # i32[N]
    unsched: jnp.ndarray      # bool[N]
    avoid_pods: jnp.ndarray   # bool[N]
    topo: jnp.ndarray         # i32[N,K] domain id or -1
    valid: jnp.ndarray        # bool[N]
    gpu_total: jnp.ndarray    # f32[N,G] per-device total GPU mem MiB (0=none)
    vg_cap: jnp.ndarray       # f32[N,V] open-local VG capacity MiB (0=pad)
    vg_name: jnp.ndarray      # i32[N,V] VG name id (0=pad)
    dev_cap: jnp.ndarray      # f32[N,DV] exclusive-device capacity MiB (0=pad)
    dev_ssd: jnp.ndarray      # bool[N,DV] device media is SSD
    has_storage: jnp.ndarray  # bool[N] node carries local storage
    domain_key: jnp.ndarray   # i32[D] topo-key index per domain id (-1 pad)
    topo_onehot: jnp.ndarray  # f32[K,D,N] domain membership (0 for missing key)
    unsched_key_id: jnp.ndarray  # i32 scalar: key id of node.kubernetes.io/unschedulable
    empty_val_id: jnp.ndarray    # i32 scalar: value id of ""
    anti_topo: jnp.ndarray    # i32[AT] topo-key index per registered required
                              # anti-affinity term (-1 pad) — IPA symmetry


class Carry(NamedTuple):
    """Mutable cluster state threaded through the scan."""
    free: jnp.ndarray        # f32[N,R]
    sel_counts: jnp.ndarray  # f32[S,N]
    gpu_free: jnp.ndarray    # f32[N,G] per-device free GPU mem MiB
                             # (tracks annotation pods only, like the
                             # reference's SchedulerCache)
    vg_free: jnp.ndarray     # f32[N,V] VG capacity - requested, MiB
    dev_free: jnp.ndarray    # f32[N,DV] 1.0 = device free, 0.0 = allocated
    port_any: jnp.ndarray    # f32[PID,N] host-port uses per (proto,port)
    port_wild: jnp.ndarray   # f32[PID,N] ... with wildcard hostIP only
    port_ipc: jnp.ndarray    # f32[PIP,N] uses per specific (proto,port,ip)
    anti_counts: jnp.ndarray  # f32[AT,N] placed pods carrying each
                              # required-anti-affinity term (IPA symmetry)


class PodRow(NamedTuple):
    """One pod's features (a slice of the PodBatch arrays)."""
    req: jnp.ndarray
    has_req: jnp.ndarray
    node_name_id: jnp.ndarray
    gpu_mem: jnp.ndarray
    gpu_num: jnp.ndarray
    sel_op: jnp.ndarray
    sel_key: jnp.ndarray
    sel_val: jnp.ndarray
    sel_num: jnp.ndarray
    has_terms: jnp.ndarray
    ns_pair: jnp.ndarray
    pref_weight: jnp.ndarray
    pref_op: jnp.ndarray
    pref_key: jnp.ndarray
    pref_val: jnp.ndarray
    pref_num: jnp.ndarray
    tol_key: jnp.ndarray
    tol_val: jnp.ndarray
    tol_exists: jnp.ndarray
    tol_effect: jnp.ndarray
    tol_valid: jnp.ndarray
    spread_topo: jnp.ndarray
    spread_sel: jnp.ndarray
    spread_skew: jnp.ndarray
    spread_hard: jnp.ndarray
    aff_topo: jnp.ndarray
    aff_sel: jnp.ndarray
    aff_anti: jnp.ndarray
    aff_required: jnp.ndarray
    aff_weight: jnp.ndarray
    lvm_req: jnp.ndarray
    lvm_vg: jnp.ndarray
    dev_req: jnp.ndarray
    dev_media_ssd: jnp.ndarray
    has_local: jnp.ndarray
    match_sel: jnp.ndarray
    owned_by_rs: jnp.ndarray
    hp_pid: jnp.ndarray
    hp_wild: jnp.ndarray
    hp_ipid: jnp.ndarray
    match_anti: jnp.ndarray
    own_anti: jnp.ndarray
    valid: jnp.ndarray


_EPS = 1e-3  # absolute slack for f32 resource comparisons (units: milli / MiB)


# ---------------------------------------------------------------------------
# node-selector term matching (shared by NodeAffinity filter + score)
# ---------------------------------------------------------------------------

def _expr_matches(ns: NodeStatic, op, key, val, num):
    """One expression vs all nodes. op/key scalar, val i32[VAL]. -> bool[N]"""
    has_key = jnp.any((ns.label_key == key) & (key != 0), axis=1)          # [N]
    pair_hit = jnp.any(
        (ns.label_pair[:, :, None] == val[None, None, :]) & (val != 0)[None, None, :],
        axis=(1, 2),
    )                                                                       # [N]
    key_rows = ns.label_key == key                                          # [N,L]
    gt = jnp.any(key_rows & (ns.label_num > num), axis=1)
    lt = jnp.any(key_rows & (ns.label_num < num), axis=1)
    return jnp.select(
        [op == OP_IN, op == OP_NOT_IN, op == OP_EXISTS, op == OP_NOT_EXISTS,
         op == OP_GT, op == OP_LT],
        [pair_hit, ~pair_hit, has_key, ~has_key, gt, lt],
        default=jnp.ones_like(has_key),  # OP_PAD: neutral inside an AND
    )


def _term_matches(ns: NodeStatic, ops, keys, vals, nums):
    """One term (AND of EXPR expressions) vs all nodes -> bool[N].
    A term with no real expressions matches nothing (upstream semantics)."""
    per_expr = jax.vmap(
        lambda o, k, v, n: _expr_matches(ns, o, k, v, n),
        in_axes=(0, 0, 0, 0),
        out_axes=1,
    )(ops, keys, vals, nums)                                  # [N,EXPR]
    non_empty = jnp.any(ops != OP_PAD)
    return jnp.all(per_expr, axis=1) & non_empty


def node_affinity_mask(ns: NodeStatic, pod: PodRow) -> jnp.ndarray:
    """NodeAffinity filter: plain nodeSelector AND required affinity terms
    (OR over terms). Parity: plugins/nodeaffinity + nodeSelector matching."""
    # nodeSelector: every listed pair must be present on the node
    wanted = pod.ns_pair                                       # [NS]
    present = jnp.any(
        ns.label_pair[:, :, None] == wanted[None, None, :], axis=1
    )                                                          # [N,NS]
    ns_ok = jnp.all(present | (wanted == 0)[None, :], axis=1)  # [N]
    term_hits = jax.vmap(
        lambda o, k, v, n: _term_matches(ns, o, k, v, n),
        in_axes=(0, 0, 0, 0),
        out_axes=1,
    )(pod.sel_op, pod.sel_key, pod.sel_val, pod.sel_num)       # [N,TERM]
    terms_ok = jnp.any(term_hits, axis=1) | ~pod.has_terms
    return ns_ok & terms_ok


def taint_mask(ns: NodeStatic, pod: PodRow) -> jnp.ndarray:
    """TaintToleration filter: every NoSchedule/NoExecute taint tolerated."""
    tk, tv, te = ns.taint_key, ns.taint_val, ns.taint_effect   # [N,T]
    # toleration axis -> [N,T,TOL]
    eff_ok = (pod.tol_effect[None, None, :] == 0) | (pod.tol_effect[None, None, :] == te[:, :, None])
    key_ok = (pod.tol_key[None, None, :] == 0) | (pod.tol_key[None, None, :] == tk[:, :, None])
    val_ok = pod.tol_exists[None, None, :] | (pod.tol_val[None, None, :] == tv[:, :, None])
    tolerated = jnp.any(
        pod.tol_valid[None, None, :] & eff_ok & key_ok & val_ok, axis=2
    )                                                          # [N,T]
    hard = (te == 1) | (te == 3)                               # NoSchedule/NoExecute
    return jnp.all(tolerated | ~hard, axis=1)


HOSTNAME_KEY_IDX = 0  # Encoder pins kubernetes.io/hostname at topo index 0


def _domain_counts(
    ns: NodeStatic,
    counts_node: jnp.ndarray,
    k: jnp.ndarray,
    elig: jnp.ndarray = None,
):
    """Per-domain sums + their per-node broadcast for topology key k.

    Two representations (TPU scatters serialize, so neither path scatters):
      - hostname (k==0): domains ≡ nodes 1:1, so the per-node count IS the
        input and no [D,N] matrix is ever materialized (a dense one-hot for
        hostname would be O(N²) memory).
      - low-cardinality keys (zone/region/...): matvec against the precomputed
        one-hot membership (f32-exact precision — bf16 MXU rounding would
        corrupt integer counts above 256), then an exact gather back to nodes.

    `elig` bool[N] restricts which nodes participate (PodTopologySpread counts
    and min only consider nodes passing the pod's node affinity/selector —
    vendored podtopologyspread/common.go calPreFilterState skips other nodes);
    None means all valid nodes.

    Returns (dom f32[D] — hostname slot returns zeros, use the host outputs —,
    cnt_n f32[N], min_count f32, total f32) where min_count is the minimum
    count over eligible domains of key k and total the sum over them."""
    elig = ns.valid if elig is None else (elig & ns.valid)
    counts = jnp.where(elig, counts_node, 0.0)
    is_host = k == HOSTNAME_KEY_IDX

    onehot = ns.topo_onehot[k]                                  # [D,N]
    dom = jax.lax.dot_general(
        onehot, counts, (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
    )                                                           # [D]
    dom_elig = jax.lax.dot_general(
        onehot, elig.astype(jnp.float32), (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
    ) > 0.0                                                     # [D]
    topo_k = ns.topo[:, k]
    D = dom.shape[0]
    cnt_gather = jnp.where(
        topo_k >= 0, dom[jnp.clip(topo_k, 0, D - 1)], 0.0
    )
    cnt_n = jnp.where(is_host, counts, cnt_gather)

    in_key = (ns.domain_key == k) & dom_elig                    # [D]
    min_dom = jnp.min(jnp.where(in_key, dom, jnp.inf))
    min_host = jnp.min(jnp.where(elig, counts_node, jnp.inf))
    min_count = jnp.where(is_host, min_host, min_dom)
    min_count = jnp.where(jnp.isfinite(min_count), min_count, 0.0)

    total = jnp.where(is_host, jnp.sum(counts), jnp.sum(jnp.where(in_key, dom, 0.0)))
    return dom, cnt_n, min_count, total


def spread_mask(
    ns: NodeStatic, carry: Carry, pod: PodRow, na_ok: jnp.ndarray = None
) -> jnp.ndarray:
    """PodTopologySpread hard constraints.

    skew(node) = count(domain(node)) + 1 - min over eligible domains of the
    topology key, where eligibility (`na_ok`, defaults to recomputing the
    pod's node affinity/selector) restricts both the counts and the min —
    matching calPreFilterState, which skips nodes failing the pod's
    nodeSelector/required node affinity entirely."""
    elig = node_affinity_mask(ns, pod) if na_ok is None else na_ok

    def one(topo_idx, sel_idx, max_skew, hard):
        active = (topo_idx >= 0) & hard
        k = jnp.maximum(topo_idx, 0)
        has_key = ns.topo[:, k] >= 0                            # [N]
        _, cnt_n, min_count, _ = _domain_counts(
            ns, carry.sel_counts[sel_idx], k, elig
        )
        ok = (cnt_n + 1.0 - min_count) <= max_skew + _EPS
        ok = ok & has_key
        return jnp.where(active, ok, jnp.ones_like(ok))

    per_c = jax.vmap(one, in_axes=(0, 0, 0, 0), out_axes=1)(
        pod.spread_topo, pod.spread_sel, pod.spread_skew, pod.spread_hard
    )                                                           # [N,C]
    return jnp.all(per_c, axis=1)


def pod_affinity_mask(ns: NodeStatic, carry: Carry, pod: PodRow) -> jnp.ndarray:
    """InterPodAffinity required terms.

    affinity: candidate node's domain must already hold a matching pod — OR the
    incoming pod matches its own selector and no match exists anywhere (the
    upstream first-pod-of-a-group special case).
    anti-affinity: candidate node's domain must hold none.
    symmetry: existing pods' required anti-affinity repels matching incomers —
    for every registered anti term (ns.anti_topo) the pod's labels match
    (pod.match_anti), domains already holding a carrier (carry.anti_counts)
    are infeasible (the vendored plugin's existingAntiAffinityCounts).
    """

    def one(topo_idx, sel_idx, anti, required):
        active = (topo_idx >= 0) & required
        k = jnp.maximum(topo_idx, 0)
        has_key = ns.topo[:, k] >= 0
        _, cnt, _, total = _domain_counts(ns, carry.sel_counts[sel_idx], k)
        self_match = pod.match_sel[sel_idx]
        aff_ok = (cnt > 0) | (self_match & (total == 0))
        aff_ok = aff_ok & has_key
        anti_ok = cnt == 0
        ok = jnp.where(anti, anti_ok, aff_ok)
        return jnp.where(active, ok, jnp.ones(ns.valid.shape, bool))

    per_a = jax.vmap(one, in_axes=(0, 0, 0, 0), out_axes=1)(
        pod.aff_topo, pod.aff_sel, pod.aff_anti, pod.aff_required
    )

    def one_sym(topo_idx, cnt_row, match):
        active = (topo_idx >= 0) & match
        k = jnp.maximum(topo_idx, 0)
        has_key = ns.topo[:, k] >= 0
        _, cnt, _, _ = _domain_counts(ns, cnt_row, k)
        ok = (cnt == 0) | ~has_key
        return jnp.where(active, ok, jnp.ones(ns.valid.shape, bool))

    per_sym = jax.vmap(one_sym, in_axes=(0, 0, 0), out_axes=1)(
        ns.anti_topo, carry.anti_counts, pod.match_anti
    )                                                           # [N,AT]
    return jnp.all(per_a, axis=1) & jnp.all(per_sym, axis=1)


# ---------------------------------------------------------------------------
# Open-Gpu-Share: per-device GPU memory packing
# (parity: pkg/simulator/plugin/open-gpu-share.go + AllocateGpuId,
#  pkg/type/open-gpu-share/cache/gpunodeinfo.go:232-290)
# ---------------------------------------------------------------------------

def _gpu_device_caps(ns: NodeStatic, carry: Carry, pod: PodRow) -> jnp.ndarray:
    """floor(free_d / mem) shares each device can still hold -> f32[N,G]."""
    mem = jnp.maximum(pod.gpu_mem, 1e-9)
    caps = jnp.floor((carry.gpu_free + _EPS) / mem)
    return jnp.where(ns.gpu_total > 0, caps, 0.0)


def allocatable_gpus(ns: NodeStatic, carry: Carry) -> jnp.ndarray:
    """Number of not-fully-used devices per node -> f32[N]. This is the
    DYNAMIC value the reference writes back into node allocatable
    `alibabacloud.com/gpu-count` on every Reserve (open-gpu-share.go:183-190):
    GpuAllocatable = gpuCount - #(used >= total), so a partially-used device
    still counts (gpunodeinfo.go:355-362)."""
    usable = (carry.gpu_free > _EPS) & (ns.gpu_total > 0)
    return jnp.sum(usable.astype(jnp.float32), axis=1)


def gpu_mask(ns: NodeStatic, carry: Carry, pod: PodRow) -> jnp.ndarray:
    """Open-Gpu-Share Filter: non-GPU pods pass everywhere; GPU pods need a
    feasible device packing. The two-pointer greedy of AllocateGpuId succeeds
    iff sum_d floor(free_d/mem) >= num (it never strands capacity: a device is
    only abandoned when it can't hold another share)."""
    is_gpu = pod.gpu_mem > 0
    caps = _gpu_device_caps(ns, carry, pod)
    feasible = (pod.gpu_num >= 1) & (jnp.sum(caps, axis=1) >= pod.gpu_num)
    return jnp.where(is_gpu, feasible, jnp.ones_like(feasible))


def gpu_allocate_rowwise(
    ns: NodeStatic, gpu_free: jnp.ndarray, pod: PodRow
) -> jnp.ndarray:
    """gpu_allocate's take, evaluated on EVERY node independently -> f32[N,G].

    Row n is bit-identical to `gpu_allocate(..., onehot=e_n)[0]`: the einsum
    projection there extracts the row exactly (one 1.0 times f32 values), and
    every subsequent op here is the same op applied along axis 1."""
    mem = pod.gpu_mem
    free_d = gpu_free                                    # [N,G]
    total_d = ns.gpu_total
    G = free_d.shape[1]

    elig = (total_d > 0) & (free_d >= mem - _EPS)
    tight = jnp.argmin(jnp.where(elig, free_d, jnp.inf), axis=1)    # [N]
    take_single = (
        (jnp.arange(G)[None, :] == tight[:, None]) & jnp.any(elig, axis=1)[:, None]
    ).astype(jnp.float32)

    caps = jnp.where(
        total_d > 0, jnp.floor((free_d + _EPS) / jnp.maximum(mem, 1e-9)), 0.0
    )
    prefix = jnp.cumsum(caps, axis=1) - caps
    take_multi = jnp.clip(pod.gpu_num - prefix, 0.0, caps)
    take_multi = jnp.where(
        (jnp.sum(caps, axis=1) >= pod.gpu_num)[:, None], take_multi, 0.0
    )

    take = jnp.where(pod.gpu_num == 1, take_single, take_multi)
    return jnp.where((mem > 0) & (pod.gpu_num >= 1), take, 0.0)


def gpu_allocate(
    ns: NodeStatic, carry: Carry, pod: PodRow, node_onehot: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Allocate devices on the selected node -> (take f32[G] shares per device,
    new gpu_free f32[N,G]).

    num == 1: tightest fit — the device with the least free memory that still
    fits, ties to the lowest id (gpunodeinfo.go:256-270, strict `<` keeps the
    earlier candidate).
    num > 1: the two-pointer greedy packs shares onto the lowest-id devices
    first, reusing a device while it fits (gpunodeinfo.go:271-286); that is
    exactly take_d = clip(num - prefix_d, 0, cap_d) with prefix the exclusive
    cumsum of caps."""
    sel = node_onehot.astype(jnp.float32)
    free_d = jnp.einsum("n,ng->g", sel, carry.gpu_free)
    total_d = jnp.einsum("n,ng->g", sel, ns.gpu_total)
    mem = pod.gpu_mem

    elig = (total_d > 0) & (free_d >= mem - _EPS)
    tight = jnp.argmin(jnp.where(elig, free_d, jnp.inf))
    take_single = (
        (jnp.arange(free_d.shape[0]) == tight) & jnp.any(elig)
    ).astype(jnp.float32)

    caps = jnp.where(
        total_d > 0, jnp.floor((free_d + _EPS) / jnp.maximum(mem, 1e-9)), 0.0
    )
    prefix = jnp.cumsum(caps) - caps
    take_multi = jnp.clip(pod.gpu_num - prefix, 0.0, caps)
    take_multi = jnp.where(jnp.sum(caps) >= pod.gpu_num, take_multi, 0.0)

    take = jnp.where(pod.gpu_num == 1, take_single, take_multi)
    take = jnp.where((mem > 0) & (pod.gpu_num >= 1), take, 0.0)
    gpu_free = carry.gpu_free - sel[:, None] * take[None, :] * mem
    return take, gpu_free


# ---------------------------------------------------------------------------
# Open-Local: LVM volume-group binpack + exclusive-device allocation
# (parity: pkg/simulator/plugin/open-local.go + the vendored algorithms at
#  vendor/github.com/alibaba/open-local/pkg/scheduler/algorithm/algo/common.go —
#  ProcessLVMPVCPredicate :59, ProcessDevicePVC :394, ScoreLVM :660,
#  ScoreDevice :753, and the Bind-side commit open-local.go:175-254)
# ---------------------------------------------------------------------------

def local_storage_eval(ns: NodeStatic, carry: Carry, pod: PodRow):
    """Simulate this pod's storage allocation on EVERY node at once.

    Returns (ok bool[N], vg_take f32[N,V] MiB claimed per VG, dev_take
    f32[N,DV] one-hot devices claimed, raw_score f32[N] — the plugin's
    pre-normalize 0..20 score).

    LVM volumes without an explicit VG follow the default Binpack strategy:
    each request goes to the VG with the least free space that still fits
    (common.go:575-618 sorts ascending and takes the first fit; ties break to
    the lowest VG index here where Go's unstable sort is arbitrary). Explicit
    VG requests must fit that VG (common.go:59-96). Device volumes take the
    smallest free device of the right media type whose capacity covers the
    request — the ascending device walk of CheckExclusiveResourceMeetsPVCSize
    (common.go:290-350) picks exactly that device for requests sorted
    ascending, which the encoder guarantees.
    """
    N, V = ns.vg_cap.shape
    DV = ns.dev_cap.shape[1]
    SV = pod.lvm_req.shape[0]

    def lvm_slot(state, s):
        vg_free, vg_take, ok = state
        req = pod.lvm_req[s]
        active = req > 0
        want = pod.lvm_vg[s]
        fits = (vg_free + _EPS >= req) & (ns.vg_name != 0)       # [N,V]
        elig = jnp.where(want != 0, fits & (ns.vg_name == want), fits)
        free_key = jnp.where(elig, vg_free, jnp.inf)
        choice = jnp.argmin(free_key, axis=1)                     # [N]
        any_elig = jnp.any(elig, axis=1)
        onehot = (
            (jnp.arange(V)[None, :] == choice[:, None])
            & any_elig[:, None]
            & active
        ).astype(jnp.float32)
        return (
            vg_free - onehot * req,
            vg_take + onehot * req,
            ok & (any_elig | ~active),
        ), None

    (_, vg_take, lvm_ok), _ = jax.lax.scan(
        lvm_slot,
        (carry.vg_free, jnp.zeros_like(carry.vg_free), jnp.ones(N, bool)),
        jnp.arange(SV),
    )

    def dev_slot(state, s):
        avail, dev_take, frac_sum, ok = state
        req = pod.dev_req[s]
        active = req > 0
        elig = (
            (avail > 0.5)
            & (ns.dev_ssd == pod.dev_media_ssd[s])
            & (ns.dev_cap + _EPS >= req)
            & (ns.dev_cap > 0)
        )                                                          # [N,DV]
        cap_key = jnp.where(elig, ns.dev_cap, jnp.inf)
        choice = jnp.argmin(cap_key, axis=1)
        any_elig = jnp.any(elig, axis=1)
        onehot = (
            (jnp.arange(DV)[None, :] == choice[:, None])
            & any_elig[:, None]
            & active
        ).astype(jnp.float32)
        cap_chosen = jnp.sum(onehot * ns.dev_cap, axis=1)          # [N]
        frac_sum = frac_sum + jnp.where(
            any_elig & active, req / jnp.maximum(cap_chosen, 1e-9), 0.0
        )
        return (
            avail - onehot,
            dev_take + onehot,
            frac_sum,
            ok & (any_elig | ~active),
        ), None

    (_, dev_take, dev_frac_sum, dev_ok), _ = jax.lax.scan(
        dev_slot,
        (
            carry.dev_free,
            jnp.zeros_like(carry.dev_free),
            jnp.zeros(N, jnp.float32),
            jnp.ones(N, bool),
        ),
        jnp.arange(SV),
    )

    ok = jnp.where(
        pod.has_local,
        lvm_ok & dev_ok & ns.has_storage,
        jnp.ones(N, bool),
    )

    # ScoreLVM (Binpack): mean over the VGs this pod uses of used/capacity,
    # ×10, floor'd (common.go:660-684). ScoreDevice: mean over units of
    # requested/allocated-capacity, ×10, floor'd (common.go:753-762). The
    # plugin returns their sum (open-local.go:136) before its min-max
    # NormalizeScore maps the batch to 0..100 (open-local.go:145-170).
    used = vg_take > 0
    vg_frac = jnp.where(used, vg_take / jnp.maximum(ns.vg_cap, 1e-9), 0.0)
    lvm_cnt = jnp.sum(used.astype(jnp.float32), axis=1)
    lvm_score = jnp.floor(
        jnp.where(
            lvm_cnt > 0,
            jnp.sum(vg_frac, axis=1) / jnp.maximum(lvm_cnt, 1.0) * 10.0,
            0.0,
        )
    )
    dev_cnt = jnp.sum((pod.dev_req > 0).astype(jnp.float32))
    dev_score = jnp.floor(
        jnp.where(dev_cnt > 0, dev_frac_sum / jnp.maximum(dev_cnt, 1.0) * 10.0, 0.0)
    )
    raw = jnp.where(ok & pod.has_local, lvm_score + dev_score, 0.0)
    return ok, vg_take, dev_take, raw


def local_storage_mask(ns: NodeStatic, carry: Carry, pod: PodRow) -> jnp.ndarray:
    ok, _, _, _ = local_storage_eval(ns, carry, pod)
    return ok


def score_open_local(ns: NodeStatic, carry: Carry, pod: PodRow) -> jnp.ndarray:
    """Open-Local Score + its NormalizeScore. Pods without storage volumes get
    MinScore everywhere (open-local.go:113-119), which normalizes to 0."""
    _, _, _, raw = local_storage_eval(ns, carry, pod)
    return jnp.where(pod.has_local, _minmax_normalize(raw, ns.valid), 0.0)


def local_storage_commit(
    ns: NodeStatic, carry: Carry, pod: PodRow, node_onehot: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Commit the chosen node's storage allocation (the Bind-side annotation
    rewrite, open-local.go:221-247): VG requested += size, device allocated.

    Returns (vg_free f32[N,V], dev_free f32[N,DV], vg_take f32[V], dev_take
    f32[DV]) — the takes are the selected node's slice, recorded per pod so an
    eviction can reverse the allocation exactly."""
    _, vg_take_all, dev_take_all, _ = local_storage_eval(ns, carry, pod)
    sel = node_onehot.astype(jnp.float32)
    vg_take = jnp.einsum("n,nv->v", sel, vg_take_all)
    dev_take = jnp.einsum("n,nd->d", sel, dev_take_all)
    return (
        carry.vg_free - sel[:, None] * vg_take_all,
        carry.dev_free - sel[:, None] * dev_take_all,
        vg_take,
        dev_take,
    )


def ports_mask(carry: Carry, pod: PodRow) -> jnp.ndarray:
    """NodePorts filter (vendored plugins/nodeports): a requested host port
    conflicts on a node iff the same (protocol, port) is already used there
    with an overlapping hostIP — wildcard overlaps everything, specific IPs
    only themselves. Row 0 of the count tables is the pad row (all zeros), so
    padded hp slots are harmless; the explicit pid>0 guard keeps them inert
    even after carry updates."""
    any_tbl = carry.port_any[pod.hp_pid]                       # [HP,N]
    wild_tbl = carry.port_wild[pod.hp_pid]                     # [HP,N]
    ip_tbl = carry.port_ipc[pod.hp_ipid]                       # [HP,N]
    conf_wild = any_tbl > 0.0
    conf_spec = (wild_tbl > 0.0) | (ip_tbl > 0.0)
    conf = jnp.where(pod.hp_wild[:, None], conf_wild, conf_spec)
    conf = conf & (pod.hp_pid > 0)[:, None]
    return ~jnp.any(conf, axis=0)


def port_adds(pid_rows: int, pip_rows: int, pod: PodRow):
    """Per-commit increments to the port count tables for one pod ->
    (add_any f32[PID], add_wild f32[PID], add_ipc f32[PIP])."""
    active = (pod.hp_pid > 0).astype(jnp.float32)              # [HP]
    add_any = jnp.zeros(pid_rows, jnp.float32).at[pod.hp_pid].add(
        active, mode="drop"
    )
    add_wild = jnp.zeros(pid_rows, jnp.float32).at[pod.hp_pid].add(
        active * pod.hp_wild.astype(jnp.float32), mode="drop"
    )
    add_ipc = jnp.zeros(pip_rows, jnp.float32).at[pod.hp_ipid].add(
        active * (~pod.hp_wild).astype(jnp.float32) * (pod.hp_ipid > 0), mode="drop"
    )
    # never count into the pad row — keep row 0 identically zero
    add_any = add_any.at[0].set(0.0)
    add_wild = add_wild.at[0].set(0.0)
    add_ipc = add_ipc.at[0].set(0.0)
    return add_any, add_wild, add_ipc


def ports_commit(carry: Carry, pod: PodRow, onehot: jnp.ndarray):
    """Record the committed pod's host ports into the selected node's counts.
    Returns (port_any, port_wild, port_ipc). The HP-sized scatters serialize
    on device but HP is tiny (max ports per pod)."""
    sel = onehot.astype(jnp.float32)                           # [N]
    add_any, add_wild, add_ipc = port_adds(
        carry.port_any.shape[0], carry.port_ipc.shape[0], pod
    )
    return (
        carry.port_any + add_any[:, None] * sel[None, :],
        carry.port_wild + add_wild[:, None] * sel[None, :],
        carry.port_ipc + add_ipc[:, None] * sel[None, :],
    )


def resource_fail(ns: NodeStatic, carry: Carry, pod: PodRow) -> jnp.ndarray:
    """NodeResourcesFit failure -> bool[N]. The whole-GPU extended resource
    (alibabacloud.com/gpu-count) is checked against its DYNAMIC allocatable —
    the number of not-fully-used devices minus already-committed whole-GPU
    requests — because the reference rewrites that allocatable on every
    Reserve (open-gpu-share.go:183-190)."""
    static_fail = jnp.any(pod.req[None, :] > carry.free + _EPS, axis=1)
    whole_req = pod.req[GPU_COUNT_IDX]
    whole_used = ns.alloc[:, GPU_COUNT_IDX] - carry.free[:, GPU_COUNT_IDX]
    whole_fail = whole_req > allocatable_gpus(ns, carry) - whole_used + _EPS
    return static_fail | whole_fail


def run_filters(
    ns: NodeStatic, carry: Carry, pod: PodRow, filter_on=None, extra_filters=()
):
    """All filter plugins -> (mask bool[N], first_fail i32[N]).

    first_fail is the index of the first failing filter per node (kube stops a
    node's filter chain at the first failure), or NUM_FILTERS when feasible.
    `filter_on` (bool[NUM_FILTERS] or None = all on) disables filter plugins
    per the scheduler profile: a disabled filter never fails a node.
    `extra_filters` is the out-of-tree registry (plugins/): jax-traceable
    `f(ns, carry, pod) -> bool[N]` predicates AND-ed into the F_EXTRA slot
    (the extraRegistry analog, simulator.go:190-203).
    """
    # NodeUnschedulable filter admits pods tolerating the synthetic
    # node.kubernetes.io/unschedulable:NoSchedule taint (plugin parity);
    # Equal with empty value tolerates it too (taint value is "").
    unsched_tolerated = jnp.any(
        pod.tol_valid
        & ((pod.tol_key == 0) | (pod.tol_key == ns.unsched_key_id))
        & (pod.tol_exists | (pod.tol_val == ns.empty_val_id))
        & ((pod.tol_effect == 0) | (pod.tol_effect == 1)),
    )
    na_ok = node_affinity_mask(ns, pod)
    extra_fail = jnp.zeros(ns.valid.shape[0], bool)
    for f in extra_filters:
        extra_fail = extra_fail | ~f(ns, carry, pod)
    fails = jnp.stack(
        [
            ns.unsched & ~unsched_tolerated,
            (pod.node_name_id != 0) & (ns.name_id != pod.node_name_id),
            ~taint_mask(ns, pod),
            ~na_ok,
            ~ports_mask(carry, pod),
            resource_fail(ns, carry, pod),
            ~spread_mask(ns, carry, pod, na_ok),
            ~pod_affinity_mask(ns, carry, pod),
            ~local_storage_mask(ns, carry, pod),
            ~gpu_mask(ns, carry, pod),
            extra_fail,
        ],
        axis=1,
    )                                                           # [N,F]
    if filter_on is not None:
        fails = fails & filter_on[None, :]
    mask = ~jnp.any(fails, axis=1) & ns.valid
    first_fail = jnp.where(
        jnp.any(fails, axis=1), jnp.argmax(fails, axis=1), NUM_FILTERS
    )
    return mask, first_fail


# ---------------------------------------------------------------------------
# Score plugins
# ---------------------------------------------------------------------------

def _minmax_normalize(score: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Simon's NormalizeScore (simon.go:76-101): min-max to 0..100; constant
    scores collapse to 0."""
    lo = jnp.min(jnp.where(valid, score, jnp.inf))
    hi = jnp.max(jnp.where(valid, score, -jnp.inf))
    rng = hi - lo
    out = jnp.where(rng > 0, (score - lo) * 100.0 / jnp.maximum(rng, 1e-9), 0.0)
    # Exact no-op for valid lanes (fl((score-lo)*100/rng) <= 100 by monotone
    # rounding when score <= hi); pins invalid lanes so the plugin contract
    # score in [0,100] (framework's checkPluginScores) holds for every lane.
    return jnp.clip(out, 0.0, 100.0)


def score_least_allocated(ns: NodeStatic, carry: Carry, pod: PodRow) -> jnp.ndarray:
    """NodeResourcesLeastAllocated over cpu+memory (dims 0,1 by construction)."""
    alloc = ns.alloc[:, :2]
    free_after = carry.free[:, :2] - pod.req[None, :2]
    frac = jnp.where(alloc > 0, free_after / jnp.maximum(alloc, 1e-9), 0.0)
    return jnp.clip(jnp.mean(frac, axis=1), 0.0, 1.0) * 100.0


def score_balanced(ns: NodeStatic, carry: Carry, pod: PodRow) -> jnp.ndarray:
    """NodeResourcesBalancedAllocation: 100 - |cpuFrac - memFrac|*100."""
    alloc = ns.alloc[:, :2]
    used_after = ns.alloc[:, :2] - carry.free[:, :2] + pod.req[None, :2]
    frac = jnp.where(alloc > 0, used_after / jnp.maximum(alloc, 1e-9), 0.0)
    frac = jnp.clip(frac, 0.0, 1.0)
    return (1.0 - jnp.abs(frac[:, 0] - frac[:, 1])) * 100.0


def score_simon(ns: NodeStatic, carry: Carry, pod: PodRow) -> jnp.ndarray:
    """Simon worst-fit score (simon.go:45-68): max over resources of
    share(req, allocatable - req), truncated to int, then min-max normalized.
    Note the reference deliberately uses static allocatable, not current free.
    """
    req = pod.req[None, :]                       # [1,R]
    avail = ns.alloc - req                       # [N,R]
    share = jnp.where(
        req == 0,
        0.0,
        jnp.where(avail == 0, 1.0, req / jnp.where(avail == 0, 1.0, avail)),
    )
    share = jnp.where(avail < 0, 1.0, share)     # negative headroom: saturate
    raw = jnp.floor(jnp.max(share, axis=1) * 100.0)
    raw = jnp.where(pod.has_req, raw, 100.0)     # empty requests => MaxNodeScore
    return _minmax_normalize(raw, ns.valid)


def score_taint_toleration(ns: NodeStatic, pod: PodRow) -> jnp.ndarray:
    """TaintToleration score: fewer intolerable PreferNoSchedule taints is
    better; reverse-normalized like plugin DefaultNormalizeScore(reverse)."""
    tk, tv, te = ns.taint_key, ns.taint_val, ns.taint_effect
    eff_ok = (pod.tol_effect[None, None, :] == 0) | (pod.tol_effect[None, None, :] == te[:, :, None])
    key_ok = (pod.tol_key[None, None, :] == 0) | (pod.tol_key[None, None, :] == tk[:, :, None])
    val_ok = pod.tol_exists[None, None, :] | (pod.tol_val[None, None, :] == tv[:, :, None])
    tolerated = jnp.any(pod.tol_valid[None, None, :] & eff_ok & key_ok & val_ok, axis=2)
    cnt = jnp.sum(((te == 2) & ~tolerated).astype(jnp.float32), axis=1)
    max_cnt = jnp.max(jnp.where(ns.valid, cnt, 0.0))
    return jnp.clip(
        jnp.where(max_cnt > 0, (max_cnt - cnt) * 100.0 / jnp.maximum(max_cnt, 1e-9), 100.0),
        0.0,
        100.0,
    )


def score_node_affinity(ns: NodeStatic, pod: PodRow) -> jnp.ndarray:
    """NodeAffinity score: sum of matching preferred term weights, normalized
    by the max (DefaultNormalizeScore)."""
    hits = jax.vmap(
        lambda o, k, v, n: _term_matches(ns, o, k, v, n),
        in_axes=(0, 0, 0, 0),
        out_axes=1,
    )(pod.pref_op, pod.pref_key, pod.pref_val, pod.pref_num)    # [N,PREF]
    raw = jnp.sum(hits * pod.pref_weight[None, :], axis=1)
    mx = jnp.max(jnp.where(ns.valid, raw, 0.0))
    return jnp.clip(
        jnp.where(mx > 0, raw * 100.0 / jnp.maximum(mx, 1e-9), 0.0), 0.0, 100.0
    )


def score_prefer_avoid(ns: NodeStatic, pod: PodRow) -> jnp.ndarray:
    """NodePreferAvoidPods: 0 on annotated nodes for RS/RC-owned pods."""
    avoided = ns.avoid_pods & pod.owned_by_rs
    return jnp.where(avoided, 0.0, 100.0)


def score_topology_spread(
    ns: NodeStatic, carry: Carry, pod: PodRow, na_ok: jnp.ndarray = None
) -> jnp.ndarray:
    """PodTopologySpread soft constraints: lower matching-count domains score
    higher (reverse-normalized sum over ScheduleAnyway constraints). Counting
    only spans nodes passing the pod's node affinity/selector, like the
    upstream PreScore (scoring.go:146-149)."""
    elig = node_affinity_mask(ns, pod) if na_ok is None else na_ok

    def one(topo_idx, sel_idx, hard):
        active = (topo_idx >= 0) & ~hard
        k = jnp.maximum(topo_idx, 0)
        _, cnt, _, _ = _domain_counts(ns, carry.sel_counts[sel_idx], k, elig)
        return jnp.where(active, cnt, 0.0)

    raw = jnp.sum(
        jax.vmap(one, in_axes=(0, 0, 0), out_axes=1)(
            pod.spread_topo, pod.spread_sel, pod.spread_hard
        ),
        axis=1,
    )
    mx = jnp.max(jnp.where(ns.valid, raw, 0.0))
    return jnp.clip(
        jnp.where(mx > 0, (mx - raw) * 100.0 / jnp.maximum(mx, 1e-9), 100.0),
        0.0,
        100.0,
    )


def score_inter_pod_affinity(ns: NodeStatic, carry: Carry, pod: PodRow) -> jnp.ndarray:
    """InterPodAffinity preferred terms: +weight per matching pod in domain for
    affinity, -weight for anti-affinity; min-max normalized to 0..100."""

    def one(topo_idx, sel_idx, anti, required, weight):
        active = (topo_idx >= 0) & ~required
        k = jnp.maximum(topo_idx, 0)
        _, cnt, _, _ = _domain_counts(ns, carry.sel_counts[sel_idx], k)
        signed = jnp.where(anti, -weight, weight) * cnt
        return jnp.where(active, signed, 0.0)

    raw = jnp.sum(
        jax.vmap(one, in_axes=(0, 0, 0, 0, 0), out_axes=1)(
            pod.aff_topo, pod.aff_sel, pod.aff_anti, pod.aff_required, pod.aff_weight
        ),
        axis=1,
    )
    any_active = jnp.any((pod.aff_topo >= 0) & ~pod.aff_required)
    normalized = _minmax_normalize(raw, ns.valid)
    return jnp.where(any_active, normalized, 0.0)


def gpu_share_raw(ns: NodeStatic, carry: Carry, pod: PodRow) -> jnp.ndarray:
    """Open-Gpu-Share raw score before its NormalizeScore -> f32[N]."""
    req = pod.req[None, :]                                    # [1,R]
    alloc = ns.alloc
    R = alloc.shape[1]
    dyn = allocatable_gpus(ns, carry)                          # [N]
    alloc = jnp.where(
        (jnp.arange(R) == GPU_COUNT_IDX)[None, :], dyn[:, None], alloc
    )
    avail = alloc - req
    share = jnp.where(
        req == 0,
        0.0,
        jnp.where(avail == 0, 1.0, req / jnp.where(avail == 0, 1.0, avail)),
    )
    share = jnp.where(avail < 0, 1.0, share)
    raw = jnp.max(share, axis=1) * 100.0
    return jnp.where(pod.has_req, raw, 100.0)                 # empty req => Max


def score_gpu_share(ns: NodeStatic, carry: Carry, pod: PodRow) -> jnp.ndarray:
    """Open-Gpu-Share Score (open-gpu-share.go:85-110): the same worst-fit
    share as Simon but over the node's CURRENT allocatable — where the
    whole-GPU count dimension is the dynamic allocatable-device count — then
    min-max normalized by the plugin's own NormalizeScore."""
    return _minmax_normalize(gpu_share_raw(ns, carry, pod), ns.valid)


def run_scores(
    ns: NodeStatic,
    carry: Carry,
    pod: PodRow,
    weights: jnp.ndarray,
    extra_scores=(),
) -> jnp.ndarray:
    """Weighted sum of all normalized score plugins -> f32[N]. `extra_scores`
    is the out-of-tree registry: (fn, weight) pairs of jax-traceable
    `fn(ns, carry, pod) -> f32[N]` kernels added after the in-tree sum."""
    na_ok = node_affinity_mask(ns, pod)  # CSE-merged with run_filters' copy
    by_name = {
        "balanced_allocation": score_balanced(ns, carry, pod),
        "least_allocated": score_least_allocated(ns, carry, pod),
        "node_affinity": score_node_affinity(ns, pod),
        "taint_toleration": score_taint_toleration(ns, pod),
        "topology_spread": score_topology_spread(ns, carry, pod, na_ok),
        "inter_pod_affinity": score_inter_pod_affinity(ns, carry, pod),
        "prefer_avoid_pods": score_prefer_avoid(ns, pod),
        "simon": score_simon(ns, carry, pod),
        "gpu_share": score_gpu_share(ns, carry, pod),
        "open_local": score_open_local(ns, carry, pod),
    }
    score = combine_scores(by_name, weights)
    for fn, w in extra_scores:
        score = score + w * fn(ns, carry, pod)
    return score


# ---------------------------------------------------------------------------
# The scan: sequential commit of a pod batch in one device computation
# ---------------------------------------------------------------------------

def commit_onehot(ns: NodeStatic, carry: Carry, pod: PodRow, onehot):
    """Apply one pod's placement (onehot bool[N], all-False = no commit) to
    the carry. The single commit implementation shared by the naive scan and
    the extender per-pod path — placements must mutate state identically on
    both."""
    free = carry.free - onehot[:, None] * pod.req[None, :]
    sel_counts = carry.sel_counts + (
        pod.match_sel.astype(jnp.float32)[:, None] * onehot.astype(jnp.float32)[None, :]
    )
    gpu_take, gpu_free = gpu_allocate(ns, carry, pod, onehot)
    vg_free, dev_free, vg_take, dev_take = local_storage_commit(
        ns, carry, pod, onehot
    )
    port_any, port_wild, port_ipc = ports_commit(carry, pod, onehot)
    anti_counts = carry.anti_counts + (
        pod.own_anti[:, None] * onehot.astype(jnp.float32)[None, :]
    )
    new_carry = Carry(
        free=free, sel_counts=sel_counts, gpu_free=gpu_free,
        vg_free=vg_free, dev_free=dev_free,
        port_any=port_any, port_wild=port_wild, port_ipc=port_ipc,
        anti_counts=anti_counts,
    )
    return new_carry, gpu_take, vg_take, dev_take


def _gpu_allocate_row(free_d, total_d, pod: PodRow):
    """gpu_allocate's take for ONE node row (free_d f32[G], total_d f32[G]).
    Bit-identical to gpu_allocate's einsum-projected result for that row:
    the projection is one 1.0 times f32 values plus exact +0.0 terms, and
    every op here is the dense op applied to the extracted row (the
    gpu_allocate_rowwise argument, one row at a time)."""
    mem = pod.gpu_mem
    g = free_d.shape[0]

    elig = (total_d > 0) & (free_d >= mem - _EPS)
    tight = jnp.argmin(jnp.where(elig, free_d, jnp.inf))
    take_single = (
        (jnp.arange(g) == tight) & jnp.any(elig)
    ).astype(jnp.float32)

    caps = jnp.where(
        total_d > 0, jnp.floor((free_d + _EPS) / jnp.maximum(mem, 1e-9)), 0.0
    )
    prefix = jnp.cumsum(caps) - caps
    take_multi = jnp.clip(pod.gpu_num - prefix, 0.0, caps)
    take_multi = jnp.where(jnp.sum(caps) >= pod.gpu_num, take_multi, 0.0)

    take = jnp.where(pod.gpu_num == 1, take_single, take_multi)
    return jnp.where((mem > 0) & (pod.gpu_num >= 1), take, 0.0)


def _local_storage_take_row(vg_cap, vg_name, dev_cap, dev_ssd,
                            vg_free, dev_free, pod: PodRow):
    """local_storage_eval's takes for ONE node row (all args are that
    node's [V]/[DV] slices). Each slot step is the dense step's arithmetic
    with the node axis removed — the eval is node-local by construction
    (every op there maps axis 1 independently per row), so the takes are
    bit-identical to the dense eval's row."""
    v = vg_cap.shape[0]
    dv = dev_cap.shape[0]
    sv = pod.lvm_req.shape[0]

    def lvm_slot(state, s):
        free, take = state
        req = pod.lvm_req[s]
        active = req > 0
        want = pod.lvm_vg[s]
        fits = (free + _EPS >= req) & (vg_name != 0)
        elig = jnp.where(want != 0, fits & (vg_name == want), fits)
        free_key = jnp.where(elig, free, jnp.inf)
        choice = jnp.argmin(free_key)
        any_elig = jnp.any(elig)
        onehot = (
            (jnp.arange(v) == choice) & any_elig & active
        ).astype(jnp.float32)
        return (free - onehot * req, take + onehot * req), None

    (_, vg_take), _ = jax.lax.scan(
        lvm_slot, (vg_free, jnp.zeros_like(vg_free)), jnp.arange(sv)
    )

    def dev_slot(state, s):
        avail, take = state
        req = pod.dev_req[s]
        active = req > 0
        elig = (
            (avail > 0.5)
            & (dev_ssd == pod.dev_media_ssd[s])
            & (dev_cap + _EPS >= req)
            & (dev_cap > 0)
        )
        cap_key = jnp.where(elig, dev_cap, jnp.inf)
        choice = jnp.argmin(cap_key)
        any_elig = jnp.any(elig)
        onehot = (
            (jnp.arange(dv) == choice) & any_elig & active
        ).astype(jnp.float32)
        return (avail - onehot, take + onehot), None

    (_, dev_take), _ = jax.lax.scan(
        dev_slot, (dev_free, jnp.zeros_like(dev_free)), jnp.arange(sv)
    )
    return vg_take, dev_take


def commit_choice(ns: NodeStatic, carry: Carry, pod: PodRow, choice):
    """commit_onehot for a known node index (i32 scalar, -1 = no commit),
    in O(row) work instead of O(N): only the chosen node's row/column of
    each carry plane changes, so gather that slice, apply the dense
    commit's row arithmetic, and scatter it back (a -1/invalid choice
    scatters out of bounds and is dropped — the carry is returned
    untouched, bitwise, exactly like an all-False onehot).

    Bit-identity to commit_onehot(..., onehot=(arange(N)==choice)&ok):
    dense planes update as `x - onehot*delta` / `x + delta*onehot` —
    unchosen entries add or subtract an exact +0.0 (every delta is
    nonnegative, so no -0.0 products), which is bitwise identity, and the
    chosen row sees `1.0 * delta` which is bitwise `delta`; the gpu and
    storage takes follow the gpu_allocate_rowwise row-extraction
    argument. This is the wave engine's replay step (ops/wave.py) and the
    commit phase of `ops.fast:commit_choices`; `simon prove` holds it to
    the banked digest over the full small-scope corpus."""
    n = ns.valid.shape[0]
    ok = (choice >= 0) & pod.valid
    row = jnp.where(ok, choice, 0)        # safe gather index
    idx = jnp.where(ok, choice, n)        # out-of-bounds scatters drop

    free = carry.free.at[idx].set(
        carry.free[row] - pod.req, mode="drop"
    )
    sel_counts = carry.sel_counts.at[:, idx].set(
        carry.sel_counts[:, row] + pod.match_sel.astype(jnp.float32),
        mode="drop",
    )
    anti_counts = carry.anti_counts.at[:, idx].set(
        carry.anti_counts[:, row] + pod.own_anti, mode="drop"
    )

    gpu_take = jnp.where(
        ok,
        _gpu_allocate_row(carry.gpu_free[row], ns.gpu_total[row], pod),
        jnp.zeros(carry.gpu_free.shape[1], jnp.float32),
    )
    gpu_free = carry.gpu_free.at[idx].set(
        carry.gpu_free[row] - gpu_take * pod.gpu_mem, mode="drop"
    )

    vg_take_row, dev_take_row = _local_storage_take_row(
        ns.vg_cap[row], ns.vg_name[row], ns.dev_cap[row], ns.dev_ssd[row],
        carry.vg_free[row], carry.dev_free[row], pod,
    )
    vg_take = jnp.where(ok, vg_take_row, jnp.zeros_like(vg_take_row))
    dev_take = jnp.where(ok, dev_take_row, jnp.zeros_like(dev_take_row))
    vg_free = carry.vg_free.at[idx].set(
        carry.vg_free[row] - vg_take_row, mode="drop"
    )
    dev_free = carry.dev_free.at[idx].set(
        carry.dev_free[row] - dev_take_row, mode="drop"
    )

    add_any, add_wild, add_ipc = port_adds(
        carry.port_any.shape[0], carry.port_ipc.shape[0], pod
    )
    port_any = carry.port_any.at[:, idx].set(
        carry.port_any[:, row] + add_any, mode="drop"
    )
    port_wild = carry.port_wild.at[:, idx].set(
        carry.port_wild[:, row] + add_wild, mode="drop"
    )
    port_ipc = carry.port_ipc.at[:, idx].set(
        carry.port_ipc[:, row] + add_ipc, mode="drop"
    )

    new_carry = Carry(
        free=free, sel_counts=sel_counts, gpu_free=gpu_free,
        vg_free=vg_free, dev_free=dev_free,
        port_any=port_any, port_wild=port_wild, port_ipc=port_ipc,
        anti_counts=anti_counts,
    )
    return new_carry, gpu_take, vg_take, dev_take


def schedule_step(
    ns: NodeStatic,
    weights: jnp.ndarray,
    carry: Carry,
    pod: PodRow,
    filter_on=None,
    extra_filters=(),
    extra_scores=(),
):
    mask, first_fail = run_filters(ns, carry, pod, filter_on, extra_filters)
    score = run_scores(ns, carry, pod, weights, extra_scores)
    score = jnp.where(mask, score, -jnp.inf)
    node = jnp.argmax(score)  # first max => lowest node index tie-break
    ok = jnp.any(mask) & pod.valid
    node_out = jnp.where(ok, node, -1)

    onehot = (jnp.arange(ns.valid.shape[0]) == node) & ok
    new_carry, gpu_take, vg_take, dev_take = commit_onehot(
        ns, carry, pod, onehot
    )

    reason_counts = jnp.zeros(NUM_FILTERS, jnp.int32).at[
        jnp.clip(first_fail, 0, NUM_FILTERS - 1)
    ].add(jnp.where((first_fail < NUM_FILTERS) & ns.valid, 1, 0))
    reason_counts = jnp.where(ok, jnp.zeros_like(reason_counts), reason_counts)

    return new_carry, (
        node_out.astype(jnp.int32),
        reason_counts,
        gpu_take.astype(jnp.int32),
        vg_take,
        dev_take,
    )


@sanitizable(
    "ops.kernels:probe_step", static_argnames=("extra_filters", "extra_scores")
)
@functools.partial(jax.jit, static_argnames=("extra_filters", "extra_scores"))
def probe_step(
    ns: NodeStatic,
    carry: Carry,
    pod: PodRow,
    weights: jnp.ndarray,
    filter_on=None,
    extra_filters=(),
    extra_scores=(),
):
    """Filter + score ONE pod without committing: (mask bool[N], score f32[N]
    with -inf on infeasible nodes, first_fail i32[N]). The extender path pulls
    these to the host, folds in extender filter/prioritize results, then
    commits via commit_step — the split point generic_scheduler.go sits at
    between findNodesThatPassExtenders (:263) and prioritizeNodes (:521)."""
    mask, first_fail = run_filters(ns, carry, pod, filter_on, extra_filters)
    score = run_scores(ns, carry, pod, weights, extra_scores)
    score = jnp.where(mask, score, -jnp.inf)
    return mask & ns.valid, score, first_fail


@sanitizable("ops.kernels:commit_step")
@jax.jit
def commit_step(ns: NodeStatic, carry: Carry, pod: PodRow, node):
    """Commit ONE pod to node index `node` (i32 scalar; -1 = no commit).
    Same state transition as the scan's schedule_step for the same choice."""
    ok = (node >= 0) & pod.valid
    onehot = (jnp.arange(ns.valid.shape[0]) == node) & ok
    new_carry, gpu_take, vg_take, dev_take = commit_onehot(
        ns, carry, pod, onehot
    )
    return new_carry, gpu_take.astype(jnp.int32), vg_take, dev_take


@sanitizable(
    "ops.kernels:probe_many", static_argnames=("extra_filters", "extra_scores")
)
@functools.partial(jax.jit, static_argnames=("extra_filters", "extra_scores"))
def probe_many(
    ns: NodeStatic,
    carry: Carry,
    rows: PodRow,
    weights: jnp.ndarray,
    filter_on=None,
    extra_filters=(),
    extra_scores=(),
):
    """probe_step vmapped over a pod-wave axis: filter + score W pods against
    ONE carry in a single device call — (mask bool[W,N], score f32[W,N] with
    -inf on infeasible nodes, first_fail i32[W,N]). The extender wave engine
    probes a whole wave up front so the per-pod HTTP chains can run
    concurrently; callers pad W to `wave_bucket` (ops/fast.py scenario
    bucketing discipline) so the jit cache stays at a handful of shapes."""

    def one(pod):
        mask, first_fail = run_filters(ns, carry, pod, filter_on, extra_filters)
        score = run_scores(ns, carry, pod, weights, extra_scores)
        score = jnp.where(mask, score, -jnp.inf)
        return mask & ns.valid, score, first_fail

    return jax.vmap(one)(rows)


@sanitizable(
    "ops.kernels:commit_wave", static_argnames=("extra_filters", "extra_scores")
)
@functools.partial(jax.jit, static_argnames=("extra_filters", "extra_scores"))
def commit_wave(
    ns: NodeStatic,
    carry: Carry,
    rows: PodRow,
    weights: jnp.ndarray,
    expected_mask: jnp.ndarray,
    expected_ff: jnp.ndarray,
    ext_allowed: jnp.ndarray,
    ext_score: jnp.ndarray,
    want_commit: jnp.ndarray,
    filter_on=None,
    extra_filters=(),
    extra_scores=(),
):
    """Pod-order commit scan for one extender wave, with conflict recheck.

    The wave's HTTP filter/prioritize calls were issued against masks probed
    at the wave-start carry (probe_many). By the time pod i commits, pods
    0..i-1 of the same wave have already mutated the carry — so each step
    re-runs the filters against the LIVE carry and compares with the mask the
    HTTP chain actually saw (`expected_mask`, plus `expected_ff` so failure
    reasons stay identical). A match proves the serial per-pod path would
    have issued byte-identical extender requests, so committing
    argmax(score' + ext_score) here IS the serial placement: score' is
    recomputed on the live carry, exactly what serial probe_step would have
    produced at this point. The first mismatch flips a sticky `blocked` flag
    — that pod and every later pod in the wave respill to the next wave
    (their serial outcome depends on commits that must land first).

    Inputs per wave lane: `ext_allowed` bool[W,N] nodes surviving the
    extender filter chain; `ext_score` f32[W,N] combined extender priority ×
    weight × scale per node (0 elsewhere); `want_commit` bool[W] lanes whose
    extender chain succeeded with a non-empty feasible set (False = failed /
    pad lanes, which only recheck). Returns (carry', nodes i32[W] (-1 = no
    commit), respill bool[W], gpu_take, vg_take, dev_take)."""

    def step(c, xs):
        carry_c, blocked = c
        pod, exp_mask, exp_ff, allowed, escore, want = xs
        mask, first_fail = run_filters(
            ns, carry_c, pod, filter_on, extra_filters
        )
        mask = mask & ns.valid
        match = jnp.all(mask == exp_mask) & jnp.all(first_fail == exp_ff)
        respill = blocked | ~match
        score = run_scores(ns, carry_c, pod, weights, extra_scores)
        allow = mask & allowed
        total = jnp.where(allow, score + escore, -jnp.inf)
        node = jnp.argmax(total)  # first max => lowest node index tie-break
        ok = want & ~respill & jnp.any(allow) & pod.valid
        node_out = jnp.where(ok, node, -1).astype(jnp.int32)
        onehot = (jnp.arange(ns.valid.shape[0]) == node) & ok
        new_carry, gpu_take, vg_take, dev_take = commit_onehot(
            ns, carry_c, pod, onehot
        )
        return (new_carry, respill), (
            node_out, respill, gpu_take.astype(jnp.int32), vg_take, dev_take
        )

    (final_carry, _), (nodes, respill, gpu_take, vg_take, dev_take) = (
        jax.lax.scan(
            step,
            (carry, jnp.bool_(False)),
            (rows, expected_mask, expected_ff, ext_allowed, ext_score,
             want_commit),
        )
    )
    return final_carry, nodes, respill, gpu_take, vg_take, dev_take


@sanitizable(
    "ops.kernels:schedule_batch",
    static_argnames=("extra_filters", "extra_scores"),
)
@functools.partial(jax.jit, static_argnames=("extra_filters", "extra_scores"))
def schedule_batch(
    ns: NodeStatic,
    carry: Carry,
    pods: PodRow,
    weights: jnp.ndarray,
    filter_on=None,
    extra_filters=(),
    extra_scores=(),
):
    """Schedule a whole PodBatch sequentially on device.

    Returns (final_carry, nodes i32[P] (-1 = unschedulable), reasons i32[P,F],
    gpu_take i32[P,G] — shares allocated per device on the chosen node,
    vg_take f32[P,V] — MiB claimed per VG slot of the chosen node,
    dev_take f32[P,DV] — devices claimed on the chosen node).
    """

    def step(c, pod):
        return schedule_step(
            ns, weights, c, pod, filter_on, extra_filters, extra_scores
        )

    final_carry, (nodes, reasons, gpu_take, vg_take, dev_take) = jax.lax.scan(
        step, carry, pods
    )
    return final_carry, nodes, reasons, gpu_take, vg_take, dev_take
