"""Grouped scheduling: static-part hoisting for runs of identical pods.

Real batches are a few workload templates × thousands of replicas. For one
template, most of the per-step work in the naive scan is invariant:

  static per group (computed ONCE):
    - NodeUnschedulable / NodeName / TaintToleration / NodeAffinity masks
      (depend only on the pod spec and immutable node attributes)
    - Simon worst-fit score (uses static allocatable — simon.go:45-68),
      NodeAffinity-preferred, TaintToleration and NodePreferAvoidPods scores
  dynamic per step (recomputed in the inner scan):
    - NodeResourcesFit vs the free matrix
    - PodTopologySpread / InterPodAffinity masks + scores vs sel_counts
    - LeastAllocated / BalancedAllocation vs the free matrix

The inner scan step is ~5x fewer ops than the full scan step, and results are
bit-identical to `schedule_batch` because every dynamic quantity is recomputed
exactly as the naive kernel does (the hoisted parts are genuinely invariant:
per-node scores with no cross-step dependence, and normalizations whose inputs
are all static for a fixed pod spec).
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .encode import PodBatch, round_up
from .kernels import (
    Carry,
    F_EXTRA,
    F_GPU,
    F_NODE_PORTS,
    F_POD_AFFINITY,
    F_RESOURCES,
    F_SPREAD,
    F_STORAGE,
    NUM_FILTERS,
    NodeStatic,
    PodRow,
    _minmax_normalize,
    combine_scores,
    gpu_allocate,
    gpu_mask,
    local_storage_commit,
    local_storage_eval,
    node_affinity_mask,
    pod_affinity_mask,
    ports_commit,
    ports_mask,
    resource_fail,
    score_balanced,
    score_gpu_share,
    score_inter_pod_affinity,
    score_least_allocated,
    score_node_affinity,
    score_prefer_avoid,
    score_simon,
    score_taint_toleration,
    score_topology_spread,
    spread_mask,
    taint_mask,
)
from .sanitize import sanitizable
from .state import pod_rows_from_batch

# Default cap on per-group device-program length (scan steps per dispatch) —
# one 100k-step scan trips the TPU worker's watchdog, so dispatches are
# bounded. Shared by schedule_batch_grouped, schedule_batch_fast and
# bench.py's OSIM_HEADLINE_CHUNK default/stamp so the sites cannot drift.
DEFAULT_GROUP_CHUNK = 16384


def _static_parts(ns: NodeStatic, pod: PodRow, weights: jnp.ndarray, filter_on=None):
    """Masks/scores that do not depend on the scan carry. `filter_on`
    (bool[NUM_FILTERS] or None) disables filters per the scheduler profile —
    note na_ok itself stays unmasked: PodTopologySpread eligibility reads the
    pod spec directly regardless of the NodeAffinity plugin's enablement."""
    unsched_tolerated = jnp.any(
        pod.tol_valid
        & ((pod.tol_key == 0) | (pod.tol_key == ns.unsched_key_id))
        & (pod.tol_exists | (pod.tol_val == ns.empty_val_id))
        & ((pod.tol_effect == 0) | (pod.tol_effect == 1)),
    )
    na_ok = node_affinity_mask(ns, pod)
    static_fails = jnp.stack(
        [
            ns.unsched & ~unsched_tolerated,
            (pod.node_name_id != 0) & (ns.name_id != pod.node_name_id),
            ~taint_mask(ns, pod),
            ~na_ok,
        ],
        axis=1,
    )                                                   # [N,4]
    if filter_on is not None:
        static_fails = static_fails & filter_on[None, :4]
    static_ok = ~jnp.any(static_fails, axis=1)
    static_first_fail = jnp.where(
        jnp.any(static_fails, axis=1),
        jnp.argmax(static_fails, axis=1),
        NUM_FILTERS,
    )
    static_scores = {
        "node_affinity": score_node_affinity(ns, pod),
        "taint_toleration": score_taint_toleration(ns, pod),
        "prefer_avoid_pods": score_prefer_avoid(ns, pod),
        "simon": score_simon(ns, None, pod),
    }
    return static_ok, static_first_fail, static_scores, na_ok


def schedule_group(
    ns: NodeStatic,
    carry: Carry,
    pod: PodRow,
    group_size: int,
    valid_count: jnp.ndarray,
    weights: jnp.ndarray,
    filter_on=None,
    extra_filters=(),
    extra_scores=(),
):
    """Schedule `group_size` copies of one pod spec; only the first
    `valid_count` steps commit. Returns (carry, nodes i32[G], reasons i32[G,F]).
    """
    static_ok, static_ff, static_scores, na_ok = _static_parts(
        ns, pod, weights, filter_on
    )
    fo = (
        jnp.ones(NUM_FILTERS, bool) if filter_on is None else filter_on
    )

    def step(c: Carry, i):
        active = i < valid_count
        port_ok = ports_mask(c, pod) | ~fo[F_NODE_PORTS]
        res_fail = resource_fail(ns, c, pod) & fo[F_RESOURCES]
        spread_ok = spread_mask(ns, c, pod, na_ok) | ~fo[F_SPREAD]
        aff_ok = pod_affinity_mask(ns, c, pod) | ~fo[F_POD_AFFINITY]
        # takes are re-derived inside local_storage_commit below; XLA CSE
        # collapses the two local_storage_eval calls within one jit
        storage_ok, _, _, storage_raw = local_storage_eval(ns, c, pod)
        gpu_ok = gpu_mask(ns, c, pod)
        extra_ok = jnp.ones(ns.valid.shape[0], bool)
        for f in extra_filters:
            extra_ok = extra_ok & f(ns, c, pod)
        mask = (
            static_ok & port_ok & ~res_fail & spread_ok & aff_ok & storage_ok
            & gpu_ok & extra_ok & ns.valid
        )

        # Combine in WEIGHT_ORDER exactly like run_scores so the f32
        # summation order (and therefore every tie-break) matches the naive
        # kernel.
        by_name = {
            "balanced_allocation": score_balanced(ns, c, pod),
            "least_allocated": score_least_allocated(ns, c, pod),
            "topology_spread": score_topology_spread(ns, c, pod, na_ok),
            "inter_pod_affinity": score_inter_pod_affinity(ns, c, pod),
            "gpu_share": score_gpu_share(ns, c, pod),
            "open_local": jnp.where(
                pod.has_local, _minmax_normalize(storage_raw, ns.valid), 0.0
            ),
            **static_scores,
        }
        score = combine_scores(by_name, weights)
        for fn, w in extra_scores:
            score = score + w * fn(ns, c, pod)
        score = jnp.where(mask, score, -jnp.inf)
        node = jnp.argmax(score)
        ok = jnp.any(mask) & active
        node_out = jnp.where(ok, node, -1)

        onehot = (jnp.arange(ns.valid.shape[0]) == node) & ok
        free = c.free - onehot[:, None] * pod.req[None, :]
        sel_counts = c.sel_counts + (
            pod.match_sel.astype(jnp.float32)[:, None]
            * onehot.astype(jnp.float32)[None, :]
        )
        gpu_take, gpu_free = gpu_allocate(ns, c, pod, onehot)
        vg_free, dev_free, vg_take_sel, dev_take_sel = local_storage_commit(
            ns, c, pod, onehot
        )
        port_any, port_wild, port_ipc = ports_commit(c, pod, onehot)
        anti_counts = c.anti_counts + (
            pod.own_anti[:, None] * onehot.astype(jnp.float32)[None, :]
        )

        first_fail = jnp.where(
            static_ff < NUM_FILTERS,
            static_ff,
            jnp.where(
                ~port_ok,
                F_NODE_PORTS,
                jnp.where(
                    res_fail,
                    F_RESOURCES,
                    jnp.where(
                        ~spread_ok,
                        F_SPREAD,
                        jnp.where(
                            ~aff_ok,
                            F_POD_AFFINITY,
                            jnp.where(
                                ~storage_ok,
                                F_STORAGE,
                                jnp.where(
                                    ~gpu_ok,
                                    F_GPU,
                                    jnp.where(
                                        ~extra_ok, F_EXTRA, NUM_FILTERS
                                    ),
                                ),
                            ),
                        ),
                    ),
                ),
            ),
        )
        reason_counts = jnp.zeros(NUM_FILTERS, jnp.int32).at[
            jnp.clip(first_fail, 0, NUM_FILTERS - 1)
        ].add(jnp.where((first_fail < NUM_FILTERS) & ns.valid, 1, 0))
        reason_counts = jnp.where(ok, jnp.zeros_like(reason_counts), reason_counts)

        return Carry(
            free=free, sel_counts=sel_counts, gpu_free=gpu_free,
            vg_free=vg_free, dev_free=dev_free,
            port_any=port_any, port_wild=port_wild, port_ipc=port_ipc,
            anti_counts=anti_counts,
        ), (
            node_out.astype(jnp.int32),
            reason_counts,
            gpu_take.astype(jnp.int32),
            vg_take_sel,
            dev_take_sel,
        )

    return jax.lax.scan(step, carry, jnp.arange(group_size))


_group_jit = jax.jit(
    schedule_group,
    static_argnames=("group_size", "extra_filters", "extra_scores"),
)
# Separate statement: lint's jit-root detection keys off the `jax.jit(...)`
# assignment above, and sanitize delegates .trace back to it.
_group_jit = sanitizable(
    "ops.grouped:_group_jit",
    static_argnames=("group_size", "extra_filters", "extra_scores"),
)(_group_jit)


def _group_call(
    ns, carry, pod, group_size, valid_count, weights, filter_on=None,
    extra_filters=(), extra_scores=(),
):
    """_group_jit with defaults omitted (keeps the plain jit cache entry
    shared with callers that never pass a profile or plugins)."""
    if filter_on is None and not extra_filters and not extra_scores:
        return _group_jit(ns, carry, pod, group_size, valid_count, weights)
    return _group_jit(
        ns, carry, pod, group_size, valid_count, weights, filter_on,
        extra_filters, extra_scores,
    )


def _row_signature(batch: PodBatch) -> np.ndarray:
    """Byte-hash every pod row's feature arrays to detect identical specs.
    Uses the compiled 128-bit row hasher (native/osim_native.cpp) when
    available; blake2b otherwise."""
    from dataclasses import fields

    parts = []
    for f in fields(batch):
        if f.name in ("keys", "valid"):
            continue
        arr = getattr(batch, f.name)
        parts.append(np.ascontiguousarray(arr).reshape(batch.p, -1).view(np.uint8))
    blob = np.concatenate(parts, axis=1)

    from ..native import hash_rows

    hashed = hash_rows(blob)
    if hashed is not None:
        # host-side reinterpretation of a 128-bit digest; never enters a
        # kernel, and the view width must match the digest exactly
        return hashed.view([("a", np.uint64), ("b", np.uint64)]).reshape(-1)  # osim: lint-ok[f64-literal]

    import hashlib

    return np.array(
        [hashlib.blake2b(row.tobytes(), digest_size=8).digest() for row in blob]
    )


def group_runs(batch: PodBatch) -> List[Tuple[int, int]]:
    """(start, length) runs of consecutive identical valid rows."""
    total = int(batch.valid.sum())
    if total == 0:
        return []
    sig = _row_signature(batch)
    # Vectorized boundary detection: per-element comparison of structured
    # rows re-promotes the dtype 100k times (~0.8 s at headline scale).
    # Iterate the signature's fields generically so a digest-width or dtype
    # change in the native hasher can't silently break this.
    if sig.dtype.fields:
        diff = np.zeros(max(total - 1, 0), bool)
        for fname in sig.dtype.fields:
            col = sig[fname][:total]
            diff |= col[1:] != col[:-1]
    else:
        diff = sig[1:total] != sig[: total - 1]
    change = np.nonzero(diff)[0] + 1
    starts = np.concatenate(([0], change))
    ends = np.concatenate((change, [total]))
    return [(int(s), int(e - s)) for s, e in zip(starts, ends)]


def _bucket(n: int) -> int:
    """Scan-length bucket. Floor of 32: distinct lengths below that would
    each trace their own multi-second jit of the full scheduling graph for
    under ~0.3s of wasted inert steps."""
    return round_up(n, 32)


def schedule_batch_grouped(
    ns: NodeStatic,
    carry: Carry,
    batch: PodBatch,
    weights,
    max_group_chunk: int = DEFAULT_GROUP_CHUNK,
    filter_on=None,
    extra_filters=(),
    extra_scores=(),
) -> Tuple[Carry, np.ndarray, np.ndarray, np.ndarray]:
    """schedule_batch semantics via per-group inner scans.

    Returns (carry, nodes i32[batch.p], reasons i32[batch.p, F],
    gpu_take i32[batch.p, G], vg_take f32[batch.p, V], dev_take
    f32[batch.p, DV]) — identical to the naive kernel's output for the same
    batch.
    """
    P = batch.p
    G = ns.gpu_total.shape[1]
    V = ns.vg_cap.shape[1]
    DV = ns.dev_cap.shape[1]
    nodes_out = np.full(P, -1, np.int32)
    reasons_out = np.zeros((P, NUM_FILTERS), np.int32)
    take_out = np.zeros((P, G), np.int32)
    vg_out = np.zeros((P, V), np.float32)
    dev_out = np.zeros((P, DV), np.float32)
    rows_all = pod_rows_from_batch(batch)

    for start, length in group_runs(batch):
        row = jax.tree.map(lambda a: a[start], rows_all)
        done = 0
        while done < length:
            n = min(length - done, max_group_chunk)
            g = _bucket(n)
            carry, (nodes, reasons, take, vg_take, dev_take) = _group_call(
                ns, carry, row, g, jnp.int32(n), weights, filter_on,
                extra_filters, extra_scores,
            )
            sl = slice(start + done, start + done + n)
            nodes_np, reasons_np, take_np, vg_np, dev_np = jax.device_get(
                (nodes, reasons, take, vg_take, dev_take)
            )
            nodes_out[sl] = nodes_np[:n]
            reasons_out[sl] = reasons_np[:n]
            take_out[sl] = take_np[:n]
            vg_out[sl] = vg_np[:n]
            dev_out[sl] = dev_np[:n]
            done += n
    return carry, nodes_out, reasons_out, take_out, vg_out, dev_out
