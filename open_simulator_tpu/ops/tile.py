"""Tiling identical pods: encode one row per distinct workload template, then
replicate. Real workloads are a handful of templates × thousands of replicas
(the reference expands Deployments the same way, one synthesized pod per
replica of the same spec — utils.go:139-152), so this turns O(P) host work
into O(templates)."""

from __future__ import annotations

from dataclasses import fields, replace
from typing import List, Sequence

import numpy as np

from .encode import PodBatch, round_up


def tile_pod_batch(batch: PodBatch, counts: Sequence[int]) -> PodBatch:
    """Expand template batch rows by per-template replica counts.

    batch rows [0..len(counts)) are templates; returns a batch whose first
    sum(counts) rows are the replicas (template order preserved), padded to a
    bucket size.
    """
    t = len(counts)
    assert t <= batch.p
    total = int(sum(counts))
    P = round_up(total)
    reps = list(counts) + [0] * (batch.p - t)

    def grow(arr: np.ndarray) -> np.ndarray:
        tiled = np.repeat(arr[: batch.p], reps, axis=0)
        out = np.zeros((P,) + arr.shape[1:], arr.dtype)
        out[:total] = tiled
        return out

    keys: List[str] = []
    for i, c in enumerate(counts):
        base = batch.keys[i] if i < len(batch.keys) else f"tpl-{i}"
        keys.extend(f"{base}-{j}" for j in range(c))

    grown = {
        f.name: grow(getattr(batch, f.name))
        for f in fields(batch)
        if f.name != "keys"
    }
    return replace(batch, keys=keys, **grown)
