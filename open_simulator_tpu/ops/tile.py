"""Tiling identical pods: encode one row per distinct workload template, then
replicate. Real workloads are a handful of templates × thousands of replicas
(the reference expands Deployments the same way, one synthesized pod per
replica of the same spec — utils.go:139-152), so this turns O(P) host work
into O(templates)."""

from __future__ import annotations

from dataclasses import replace
from typing import List, Sequence

import numpy as np

from .encode import PodBatch, round_up


def tile_pod_batch(batch: PodBatch, counts: Sequence[int]) -> PodBatch:
    """Expand template batch rows by per-template replica counts.

    batch rows [0..len(counts)) are templates; returns a batch whose first
    sum(counts) rows are the replicas (template order preserved), padded to a
    bucket size.
    """
    t = len(counts)
    assert t <= batch.p
    total = int(sum(counts))
    P = round_up(total)
    reps = list(counts) + [0] * (batch.p - t)

    def grow(arr: np.ndarray) -> np.ndarray:
        tiled = np.repeat(arr[: batch.p], reps, axis=0)
        out = np.zeros((P,) + arr.shape[1:], arr.dtype)
        out[:total] = tiled
        return out

    keys: List[str] = []
    for i, c in enumerate(counts):
        base = batch.keys[i] if i < len(batch.keys) else f"tpl-{i}"
        keys.extend(f"{base}-{j}" for j in range(c))

    return replace(
        batch,
        req=grow(batch.req),
        has_req=grow(batch.has_req),
        node_name_id=grow(batch.node_name_id),
        sel_op=grow(batch.sel_op),
        sel_key=grow(batch.sel_key),
        sel_val=grow(batch.sel_val),
        sel_num=grow(batch.sel_num),
        has_terms=grow(batch.has_terms),
        ns_pair=grow(batch.ns_pair),
        pref_weight=grow(batch.pref_weight),
        pref_op=grow(batch.pref_op),
        pref_key=grow(batch.pref_key),
        pref_val=grow(batch.pref_val),
        pref_num=grow(batch.pref_num),
        tol_key=grow(batch.tol_key),
        tol_val=grow(batch.tol_val),
        tol_exists=grow(batch.tol_exists),
        tol_effect=grow(batch.tol_effect),
        tol_valid=grow(batch.tol_valid),
        spread_topo=grow(batch.spread_topo),
        spread_sel=grow(batch.spread_sel),
        spread_skew=grow(batch.spread_skew),
        spread_hard=grow(batch.spread_hard),
        aff_topo=grow(batch.aff_topo),
        aff_sel=grow(batch.aff_sel),
        aff_anti=grow(batch.aff_anti),
        aff_required=grow(batch.aff_required),
        aff_weight=grow(batch.aff_weight),
        match_sel=grow(batch.match_sel),
        owned_by_rs=grow(batch.owned_by_rs),
        valid=grow(batch.valid),
        keys=keys,
    )
